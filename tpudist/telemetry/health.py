"""Run-health: job-level operability on top of per-rank telemetry.

PR 4's telemetry answers "is this RANK healthy?" — every process writes its
own JSONL and nobody correlates them. At multi-host scale the dominant
failures are silent (the TPUv4 pjit experience reports, PAPERS.md): a
straggling host dragging every synchronous step, a hung collective stalling
the job with no output, or replicas silently desyncing so the
"data-parallel" run quietly trains W different models. This module is the
layer that answers "is this JOB healthy, and if it died, why?" — four
pieces, all driven through ``fit()`` via :class:`~tpudist.telemetry
.Telemetry` and all OFF by default (the streams stay byte-identical):

- :class:`CrossProcessAggregator` — rank 0 periodically folds every rank's
  last-seen step / step interval / host-blocked seconds into per-host skew
  stats (a ``fleet`` row) and emits a one-shot ``straggler`` warning when
  one host's host-side share of the step persistently exceeds the fleet
  median. The gather is a tiny compiled all-gather over all devices whose
  result is FETCHED one aggregation later (``copy_to_host_async``) — the
  same delayed pipeline as the loss, so it adds no host↔device sync.
  Synchronous SPMD equalizes ``interval_s`` across ranks (everyone waits
  for the slowest collective), so the skew signal is ``host_s`` — the
  seconds each rank spent blocked in ITS OWN input pipeline and dispatch,
  which is precisely what differs on the straggling host.
- :class:`DivergenceProbe` — drives :func:`tpudist.parallel.dp
  .make_divergence_probe` (per-replica bit-checksums all-gathered over the
  ``data`` axis; psum'd checksum + non-finite count for ZeRO-1-sharded
  state) at a cadence, resolving each probe one cadence later. A mismatch
  writes a ``divergence`` row and fires the NanSentry flight-recorder path
  (arms the on-demand profiler window).
- :class:`HangWatchdog` — a daemon thread with a step deadline, armed at
  the first ``beat()``. On trip it dumps every Python thread's stack,
  writes a ``watchdog`` row (the sink flushes per write), flushes any
  armed profiler window, and writes a structured per-rank crash report
  (``{job}_crash_{rank}.json``: thread stacks, last-N telemetry rows,
  per-rank last-seen steps, anomaly/straggler/divergence history) plus the
  end-of-run report — the forensics a hung job otherwise takes to its
  grave. One-shot; non-fatal (a stall that resolves lets the run finish).
- the **end-of-run report** — ``{job}_report.json`` (rank 0), written on
  normal exit, on the crash path, and from the watchdog: step-time
  percentiles, MFU percentiles, skipped steps, comm byte totals, anomaly /
  straggler / divergence / watchdog history, per-rank last-seen steps, and
  the telemetry segment list (the sink's size-capped rotation).

Enable via :func:`health_config` (what ``main.py --health`` builds) or by
setting the health fields on :class:`~tpudist.telemetry.TelemetryConfig`.
Row kinds and the report schema: docs/OBSERVABILITY.md §7; the stuck-job
recipe: docs/MULTIHOST.md.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Any, Callable

import numpy as np

__all__ = [
    "CrossProcessAggregator",
    "DivergenceProbe",
    "HangWatchdog",
    "RunHealth",
    "health_config",
    "thread_stacks",
]


def health_config(base=None, *, aggregate_every: int = 50,
                  divergence_every: int = 200,
                  hang_timeout_s: float | None = 300.0, **overrides):
    """A :class:`~tpudist.telemetry.TelemetryConfig` with the run-health
    layer ON at production defaults — what ``main.py --health`` passes to
    ``fit(telemetry=...)``. ``base`` seeds the non-health fields
    (``None`` → defaults); keyword overrides win."""
    import dataclasses

    from tpudist.telemetry import TelemetryConfig

    return dataclasses.replace(
        base or TelemetryConfig(),
        aggregate_every=aggregate_every,
        divergence_every=divergence_every,
        hang_timeout_s=hang_timeout_s,
        **overrides,
    )


def thread_stacks() -> dict[str, list[str]]:
    """Formatted Python stacks of every live thread, keyed
    ``"{name} ({ident})"`` — the crash report's view of WHERE each thread
    is stuck (the hung-collective signature: the main thread inside a
    jax value fetch, the prefetch thread inside its queue)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        f"{names.get(ident, 'unknown')} ({ident})":
            traceback.format_stack(frame)
        for ident, frame in sys._current_frames().items()
    }


def _strict_json(obj):
    """The report/crash files keep the sink's strict-JSON contract: a
    NanSentry event carries the literal NaN loss that killed the run, and
    bare ``json.dumps`` would emit a ``NaN`` token that breaks every
    strict consumer of exactly the forensics file written for them.
    Recurses via the sink's serializer (non-finite → null)."""
    from tpudist.telemetry import _json_safe

    return _json_safe(obj)


def _percentiles(xs) -> dict | None:
    if not xs:
        return None
    a = np.asarray(xs, np.float64)
    return {
        "p50": round(float(np.percentile(a, 50)), 6),
        "p90": round(float(np.percentile(a, 90)), 6),
        "p99": round(float(np.percentile(a, 99)), 6),
        "mean": round(float(a.mean()), 6),
        "max": round(float(a.max()), 6),
        "n": int(a.size),
    }


def _observe_bounded(lst: list, v: float, cap: int = 100_000) -> None:
    # multi-day runs must not grow the percentile source unbounded: past
    # the cap, drop every other sample (keeps the distribution's shape at
    # half the resolution — fine for p50/p90/p99)
    lst.append(float(v))
    if len(lst) > cap:
        del lst[::2]


class CrossProcessAggregator:
    """Rank 0's fold of every rank's health scalars (see module doc).

    Every rank calls :meth:`on_step` once per resolved step; collective
    work happens only at the ``every`` cadence, on the same steps on every
    rank — lockstep by construction, like the train step itself. The
    gathered stats per rank: last-seen step, step interval, and ``host_s``
    (data-wait + dispatch seconds — the rank-LOCAL share of the step).

    Straggler rule: at each fold, a rank's host-blocked fraction
    ``rel = host_s / interval_s`` is compared against the fleet median;
    a rank is a candidate when ``rel > max(ratio · median, min_frac)``
    (the ``min_frac`` floor keeps a near-zero healthy median from turning
    measurement noise into ratios). ``patience`` consecutive candidate
    folds fire ONE ``straggler`` row per rank per run — a page, not a
    stream.
    """

    def __init__(self, sink, *, every: int, ratio: float = 1.5,
                 patience: int = 3, min_frac: float = 0.25, rank: int = 0):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.sink = sink
        self.every = max(int(every), 1)
        self.ratio = float(ratio)
        self.patience = max(int(patience), 1)
        self.min_frac = float(min_frac)
        self.rank = rank
        devices = jax.devices()
        self._slot_proc = np.asarray([d.process_index for d in devices])
        self._procs = sorted(set(self._slot_proc.tolist()))
        # the gather rides its own flat 1-D mesh over ALL devices — health
        # is a job-level question, independent of how the training mesh
        # factors them. Steps travel as an int32 channel of their own: a
        # float32 slot rounds past 2^24, and "which rank's last-seen step
        # trails" is exactly the multi-day diagnosis that must stay exact.
        gmesh = Mesh(np.asarray(devices), ("g",))
        self._in_sharding = NamedSharding(gmesh, P("g"))
        out = NamedSharding(gmesh, P())
        self._gather = jax.jit(
            lambda s, f: (s, f), out_shardings=(out, out)
        )
        self._local = jax.local_device_count()
        self._pending: tuple | None = None
        self._streak: dict[int, int] = collections.defaultdict(int)
        self._warned: set[int] = set()
        self.last_seen: dict[int, int] = {}
        self.straggler_events: list[dict] = []
        self.fleet: dict | None = None

    def on_step(self, step: int, interval_s: float, host_s: float) -> None:
        if step % self.every:
            return
        import jax

        if self._pending is not None:
            # resolve LAST cadence's gather — its D2H started right after
            # dispatch, so this is a host-memory read, not a device sync
            self.flush()
        steps_local = np.full((self._local, 1), step, np.int32)
        floats_local = np.tile(
            np.asarray([interval_s, host_s], np.float32), (self._local, 1)
        )
        n = len(self._slot_proc)
        sarr = jax.make_array_from_process_local_data(
            self._in_sharding, steps_local, (n, 1)
        )
        farr = jax.make_array_from_process_local_data(
            self._in_sharding, floats_local, (n, 2)
        )
        gs, gf = self._gather(sarr, farr)
        gs.copy_to_host_async()
        gf.copy_to_host_async()
        self._pending = (step, gs, gf)

    def flush(self) -> None:
        if self._pending is not None:
            at, gs, gf = self._pending
            self._pending = None
            self._fold(np.asarray(gs), np.asarray(gf), at)

    def _fold(self, steps: np.ndarray, floats: np.ndarray,
              at_step: int) -> None:
        # one row per device; every device of a process carries the same
        # stats, so the first slot speaks for it
        per_step = {
            p: int(steps[self._slot_proc == p][0, 0]) for p in self._procs
        }
        per = {p: floats[self._slot_proc == p][0] for p in self._procs}
        for p, s in per_step.items():
            self.last_seen[int(p)] = s
        if self.rank != 0:
            return
        intervals = {p: float(r[0]) for p, r in per.items()}
        host = {p: float(r[1]) for p, r in per.items()}
        rel = {
            p: host[p] / max(intervals[p], 1e-9) for p in self._procs
        }
        med = float(np.median(list(rel.values())))
        self.fleet = {
            "per_rank_step": {str(p): per_step[p] for p in self._procs},
            "per_rank_interval_s": {
                str(p): round(intervals[p], 6) for p in self._procs
            },
            "per_rank_host_s": {
                str(p): round(host[p], 6) for p in self._procs
            },
            "median_host_frac": round(med, 6),
        }
        self.sink.write("fleet", at_step, **self.fleet)
        if len(self._procs) <= 1:
            return  # a one-host fleet has no one to straggle behind
        bar = max(self.ratio * med, self.min_frac)
        for p in self._procs:
            if rel[p] > bar:
                self._streak[p] += 1
                if self._streak[p] >= self.patience and p not in self._warned:
                    self._warned.add(p)
                    event = {
                        "rank": int(p),
                        "host_s": round(host[p], 6),
                        "interval_s": round(intervals[p], 6),
                        "host_frac": round(rel[p], 6),
                        "fleet_median_frac": round(med, 6),
                        "consecutive_folds": self._streak[p],
                        "step": int(at_step),
                    }
                    self.straggler_events.append(event)
                    self.sink.write(
                        "straggler", at_step,
                        **{k: v for k, v in event.items() if k != "step"},
                        hint="this host spends an outsized share of each "
                             "step blocked in its own input pipeline / "
                             "dispatch; check its heartbeat drift, disk, "
                             "and decode load (docs/MULTIHOST.md)",
                    )
            else:
                self._streak[p] = 0


class DivergenceProbe:
    """Host driver for :func:`tpudist.parallel.dp.make_divergence_probe`:
    dispatches the compiled probe every ``every`` steps and resolves each
    result one cadence later (delayed fetch, no sync). A replica mismatch
    or non-finite state writes a ``divergence`` row, records the event,
    and calls ``on_event`` (the flight-recorder arm) — whose return value
    lands in the row as ``profiler_armed``."""

    def __init__(self, sink, mesh, *, every: int, rank: int = 0,
                 on_event: Callable[[dict], bool] | None = None):
        self.sink = sink
        self.mesh = mesh
        self.every = max(int(every), 1)
        self.rank = rank
        self.on_event = on_event
        self._fn = None
        self._disabled = False
        self._pending: tuple | None = None
        self.checks = 0
        self.events: list[dict] = []

    def on_step(self, step: int, state) -> None:
        if self._disabled or step % self.every:
            return
        if self._pending is not None:
            self._resolve()
        if self._fn is None:
            from tpudist.parallel.dp import make_divergence_probe

            self._fn = make_divergence_probe(state, self.mesh)
            if self._fn is None:  # one data replica: nothing to compare
                self._disabled = True
                return
        metrics = self._fn(state)
        for v in metrics.values():
            v.copy_to_host_async()
        self._pending = (step, metrics)

    def flush(self) -> None:
        if self._pending is not None:
            self._resolve()

    def _resolve(self) -> None:
        step, metrics = self._pending
        self._pending = None
        host = {k: int(v) for k, v in metrics.items()}
        self.checks += 1
        diverged = host["replica_divergence"]
        nonfinite = host["state_nonfinite"]
        if diverged == 0 and nonfinite == 0:
            return
        event = {
            "step": int(step),
            "replica_divergence": diverged,
            "state_nonfinite": nonfinite,
            "replica_checksum": host["replica_checksum"],
            "sharded_checksum": host["sharded_checksum"],
        }
        self.events.append(event)
        armed = bool(self.on_event(event)) if self.on_event else False
        # every rank observed the same replicated scalars; one row, rank 0
        if self.rank == 0:
            self.sink.write(
                "divergence", step, profiler_armed=armed,
                **{k: v for k, v in event.items() if k != "step"},
                hint="data-parallel replicas no longer hold identical "
                     "state — a missed collective, bit corruption, or a "
                     "host resumed from the wrong step; the run is "
                     "training divergent models (docs/OBSERVABILITY.md §7)",
            )


class HangWatchdog:
    """Daemon monitor thread with a step deadline (see module doc).

    Armed at the FIRST :meth:`beat` — bring-up (device attach, the first
    compile) legitimately takes minutes and must not trip it. After that,
    a gap of more than ``timeout_s`` between beats calls ``on_trip`` once
    (one-shot: forensics, not a supervisor — pair with the launcher's
    ``--max_restarts`` for recovery). Non-fatal: a stall that resolves
    lets the run finish, with the trip recorded."""

    def __init__(self, timeout_s: float, on_trip: Callable[[dict], None],
                 *, poll_s: float | None = None):
        self.timeout_s = float(timeout_s)
        self._on_trip = on_trip
        self._poll = (
            poll_s if poll_s is not None
            else min(max(self.timeout_s / 4.0, 0.05), 5.0)
        )
        self._beat: tuple[float, int] | None = None
        self._stop = threading.Event()
        self.tripped: dict | None = None
        self._thread = threading.Thread(
            target=self._run, name="tpudist-hang-watchdog", daemon=True
        )
        self._thread.start()

    def beat(self, step: int) -> None:
        self._beat = (time.monotonic(), int(step))

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            b = self._beat
            if b is None:
                continue  # not armed until the first beat
            age = time.monotonic() - b[0]
            if age > self.timeout_s:
                self.tripped = {
                    "last_step": b[1],
                    "age_s": round(age, 3),
                    "timeout_s": self.timeout_s,
                    "t": time.time(),
                }
                try:
                    self._on_trip(dict(self.tripped))
                except Exception:  # forensics must never kill the monitor
                    traceback.print_exc()
                return  # one-shot

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=max(self._poll * 4, 1.0))


class RunHealth:
    """The facade ``fit()`` drives (owned by :class:`~tpudist.telemetry
    .Telemetry`): builds whichever of the four pieces the config turns
    on, accumulates the end-of-run report's inputs, and owns the crash
    paths."""

    def __init__(self, config, sink, *, job_id: str, log_dir: str,
                 mesh=None, rank: int = 0, profiler=None, tel=None,
                 exit_fn: Callable[[int], None] | None = None):
        self.config = config
        self.sink = sink
        self.job_id = job_id
        self.rank = rank
        self.profiler = profiler
        # hang_action="exit" escalation: os._exit, injectable for tests
        # (sys.exit from the watchdog's daemon thread would only kill that
        # thread — the hung main thread is exactly what cannot be asked
        # to exit cleanly)
        self._exit = exit_fn if exit_fn is not None else os._exit
        # bounded pre-exit drain (fit wires the checkpointer's wait here):
        # exit-76's contract is "relaunch from the last checkpoint", and an
        # async Orbax commit still writing when os._exit fires would never
        # finalize — the relaunch would restore an OLDER step than promised
        self._exit_drain: Callable[[], None] | None = None
        out = Path(log_dir)
        self.report_path = out / f"{job_id}_report.json"
        self.crash_path = out / f"{job_id}_crash_{rank}.json"
        self.aggregator = (
            CrossProcessAggregator(
                sink, every=config.aggregate_every,
                ratio=config.straggler_ratio,
                patience=config.straggler_patience, rank=rank,
            )
            if config.aggregate_every else None
        )
        self.probe = (
            DivergenceProbe(
                sink, mesh, every=config.divergence_every, rank=rank,
                on_event=self._on_divergence,
            )
            if config.divergence_every and mesh is not None else None
        )
        self.watchdog = (
            HangWatchdog(config.hang_timeout_s, self._on_trip)
            if config.hang_timeout_s else None
        )
        self.intervals: list[float] = []
        self.mfus: list[float] = []
        self.steps_observed = 0
        self.skipped_steps = 0
        self._last_step = 0
        # set by fit's exception handler BEFORE it flushes the final
        # pending step: once crashing, no path may dispatch or RESOLVE a
        # collective (a fetch queued behind the hung collective the crash
        # interrupted blocks forever — inside the crash handler)
        self.crashing = False
        # the owning Telemetry (sentry-event history and comm stats for
        # the reports) — constructor-injected so no caller depends on a
        # post-hoc private assignment
        self._tel = tel

    # -- per-step drive (main thread) --------------------------------------

    EXIT_DRAIN_TIMEOUT_S = 30.0

    def set_exit_drain(self, fn: Callable[[], None]) -> None:
        """Register a flush to run (bounded) before a ``hang_action="exit"``
        termination — fit passes ``Checkpointer.wait`` so an in-flight
        async save finalizes instead of dying mid-commit."""
        self._exit_drain = fn

    def beat(self, step: int) -> None:
        if self.watchdog is not None:
            self.watchdog.beat(step)

    def observe_state(self, step: int, state) -> None:
        if self.probe is not None and not self.crashing:
            self.probe.on_step(step, state)

    def observe_interval(self, step: int, interval_s: float, *,
                         host_s: float = 0.0, mfu: float | None = None,
                         skipped: int = 0) -> None:
        self.steps_observed += 1
        self.skipped_steps += int(skipped)
        self._last_step = int(step)
        _observe_bounded(self.intervals, interval_s)
        if mfu is not None:
            _observe_bounded(self.mfus, mfu)
        if self.aggregator is not None and not self.crashing:
            # the crash-path final resolve must not touch the aggregator:
            # its on_step would FETCH the previous pending gather, which
            # can sit queued behind the very collective that hung
            self.aggregator.on_step(step, interval_s, host_s)

    # -- flight recorder / crash forensics ---------------------------------

    def _arm_recorder(self, event: dict) -> bool:
        if self.profiler is None or not getattr(
            self.config, "capture_on_anomaly", True
        ):
            return False
        return bool(self.profiler.arm(self.config.capture_steps))

    def _on_divergence(self, event: dict) -> bool:
        """The probe's verdict: arm the flight recorder (the row records
        whether that succeeded) AND publish onto the telemetry event bus
        — the repair loop's SDC trigger subscribes there."""
        armed = self._arm_recorder(event)
        if self._tel is not None:
            self._tel._publish({"detector": "divergence", **event})
        return armed

    def reset_pipelines(self) -> None:
        """Drop in-flight delayed fetches (pending aggregation gather /
        divergence probe) WITHOUT resolving them — the repair loop's
        rollback made their dispatched-on state history; resolving a
        probe of the discarded state would re-trigger the very incident
        the repair just cleared."""
        if self.aggregator is not None:
            self.aggregator._pending = None
        if self.probe is not None:
            self.probe._pending = None

    def _on_trip(self, trip: dict) -> None:
        # runs on the watchdog thread while the main thread is (by
        # definition) stuck — every write here must be host-local, and the
        # ORDER is the forensic priority: when the hang is the filesystem
        # itself, the main thread may be wedged INSIDE sink.write holding
        # the sink lock, so the crash file (tail read with a lock timeout)
        # and the report land on disk BEFORE anything touches the sink
        stacks = thread_stacks()
        crash = {
            "v": 1,
            "job": self.job_id,
            "rank": self.rank,
            "trip": trip,
            "thread_stacks": stacks,
            "last_rows": self.sink.tail(64, lock_timeout=2.0),
            "per_rank_last_seen": self._last_seen(),
            "anomalies": self._anomalies(),
            "straggler_events": (
                self.aggregator.straggler_events if self.aggregator else []
            ),
            "divergence_events": self.probe.events if self.probe else [],
        }
        self.crash_path.write_text(json.dumps(_strict_json(crash), indent=1))
        self._write_report("watchdog")
        if self.profiler is not None:
            # an armed anomaly window dies unwritten with a hung process;
            # flush what the runtime has
            self.profiler.flush_armed()
        self.sink.write(
            "watchdog", step=trip["last_step"], age_s=trip["age_s"],
            timeout_s=trip["timeout_s"],
            hint="no step completed inside the deadline — hung collective "
                 "or dead input pipeline; crash report at "
                 f"{self.crash_path} (docs/MULTIHOST.md: Diagnosing a "
                 "stuck job)",
        )
        if getattr(self.config, "hang_action", "report") == "exit":
            # escalation (detection → forensics → recovery): everything
            # above is on disk, so terminate with the restartable hang
            # code and let the supervisor relaunch from the last
            # checkpoint. os._exit, not sys.exit: the main thread is by
            # definition wedged and atexit/finally would hang behind it.
            from tpudist.resilience import EXIT_HANG

            if self._exit_drain is not None:
                # give an in-flight async checkpoint commit a bounded
                # window to finalize (its writer threads are NOT the hung
                # ones, usually) — on a side thread with a join timeout,
                # because when the hang IS the filesystem the drain would
                # wedge this monitor thread too and the escalation would
                # never fire
                drainer = threading.Thread(
                    target=self._exit_drain, daemon=True,
                    name="tpudist-exit-drain",
                )
                drainer.start()
                drainer.join(timeout=self.EXIT_DRAIN_TIMEOUT_S)
            print(
                f"tpudist: hang watchdog exiting rc={EXIT_HANG} "
                f"(hang_action='exit'; forensics at {self.crash_path})",
                file=sys.stderr, flush=True,
            )
            self._exit(EXIT_HANG)

    # -- report ------------------------------------------------------------

    def _last_seen(self) -> dict:
        if self.aggregator is not None and self.aggregator.last_seen:
            return {
                str(k): v for k, v in sorted(self.aggregator.last_seen.items())
            }
        return {str(self.rank): self._last_step}

    def _anomalies(self) -> list:
        tel = self._tel
        if tel is not None and tel.sentry is not None:
            return list(tel.sentry.events)
        return []

    def finish(self, status: str = "completed", *,
               optimizer_skips: int | None = None,
               drain: bool = True) -> None:
        """Drain the delayed pipelines and write the report. Called on all
        ranks (the flushes resolve already-dispatched collectives); the
        report file itself is rank 0's. The crash path passes
        ``drain=False``: resolving a pending gather/probe means fetching a
        collective's value, and when the crash IS an interrupt of a hung
        collective that fetch would block forever — the crash report must
        come from host-side state only."""
        if drain:
            if self.aggregator is not None:
                self.aggregator.flush()
            if self.probe is not None:
                self.probe.flush()
        self._write_report(status, optimizer_skips=optimizer_skips)

    def shutdown(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()

    def _write_report(self, status: str,
                      optimizer_skips: int | None = None) -> dict | None:
        if self.rank != 0 or not getattr(self.config, "run_report", True):
            return None
        tel = self._tel
        comm = getattr(tel, "_comm", None) if tel is not None else None
        report = {
            "v": 1,
            "job": self.job_id,
            "status": status,
            "t": round(time.time(), 3),
            "steps_observed": self.steps_observed,
            "step_time_s": _percentiles(self.intervals),
            "mfu": _percentiles(self.mfus),
            "skipped_steps": self.skipped_steps,
            "optimizer_nonfinite_skips": optimizer_skips,
            "anomaly_events": self._anomalies(),
            "straggler_events": (
                self.aggregator.straggler_events if self.aggregator else []
            ),
            "divergence_events": self.probe.events if self.probe else [],
            "divergence_checks": self.probe.checks if self.probe else 0,
            "watchdog": self.watchdog.tripped if self.watchdog else None,
            "per_rank_last_seen": self._last_seen(),
            "fleet": self.aggregator.fleet if self.aggregator else None,
            "comm": comm,
            "comm_bytes_total": (
                comm["bytes_per_step"] * self.steps_observed
                if comm and "bytes_per_step" in comm else None
            ),
            "telemetry_segments": [str(p) for p in self.sink.segments()],
        }
        # resilience fields ride APPENDED after the existing keys (the
        # heartbeat discipline): exit_reason is the operator-facing
        # disposition ("watchdog" status → "hang" — the condition, not
        # the detector), generation attributes this report to one life of
        # the job, goodput is the wall-time partition aggregated across
        # lives (tpudist.resilience.goodput)
        exit_reason = "hang" if status == "watchdog" else status
        report["exit_reason"] = exit_reason
        report["generation"] = getattr(tel, "generation", 0)
        goodput = getattr(tel, "goodput", None) if tel is not None else None
        report["goodput"] = (
            goodput.summary(exit_reason) if goodput is not None else None
        )
        # self-healing record (tpudist.resilience.repair), appended after
        # the existing keys like every resilience field: the controller's
        # durable CROSS-GENERATION history when fit attached it, else
        # this generation's repair rows; plus the supervisor's
        # per-generation exit codes (TPUDIST_EXIT_HISTORY) — one file
        # reconstructs the full incident timeline across the job's lives
        repair_history = getattr(tel, "repair_history", None)
        if repair_history is None:
            repair_history = getattr(tel, "repair_events", []) or []
        report["repairs"] = list(repair_history)
        from tpudist.resilience.exitcodes import exit_history

        report["supervisor_exit_history"] = exit_history()
        # the sink's stable run id, appended after existing keys (the same
        # append-only discipline as the JSONL rows) so tracelens can match
        # this report to its telemetry segments without filename heuristics
        report["run_id"] = getattr(self.sink, "run_id", None)
        report = _strict_json(report)
        self.report_path.write_text(json.dumps(report, indent=1))
        return report
