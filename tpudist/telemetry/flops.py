"""Analytic per-model training-step FLOPs counters — the MFU numerators.

MFU (model FLOPs utilization) is the headline efficiency metric of
"Scalable Training of Language Models using JAX pjit and TPUv4"
(arXiv:2204.06514): analytic model FLOPs per step divided by step time and
chip peak. This module is the ONE home for the analytic counters that were
previously duplicated across ``bench.py``'s per-leg hand math and
``examples/mfu_probe.py``'s GEMM tables — both now import from here, and
``fit()``'s telemetry MFU rows use the same numbers, so a bench leg, the
probe, and a live training run can never disagree about the numerator.

Accounting convention (docs/PERF.md §4, kept bit-identical to the bench
legs it replaced): weight GEMMs count forward + dgrad + wgrad
(``6 · tokens · matmul_params``); attention counts 6 matmuls per layer
(QKᵀ and AV, forward + two backward passes: ``12 · tokens · seq · hidden``
with the causal factor folded into the convention, not halved); embedding
lookups, norms, and elementwise work are excluded (sub-1% at these
shapes). These are MODEL FLOPs — recompute from remat does NOT count,
which is what makes the metric comparable across memory policies.

Dispatch is duck-typed: a model advertises its counter family via a
``flops_counter`` property (``"gpt2"``/``"llama"``/``"gpt2_moe"``/
``"llama_moe"``/``"t5"``/``"bert"``/``"vit"``/``"resnet"``);
:func:`train_step_flops` reads the model's own
geometry fields and the batch's shapes. Models without the attribute (or
geometries without a counter, e.g. a non-50-layer ResNet) return ``None``
— no MFU row is ever fabricated from a guessed numerator.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

# TPU v5e bf16 peak — the single source of truth for the MFU denominator
# (bench.py's V5E_BF16_PEAK and examples/mfu_probe.py's --peak default both
# alias this). Override per-chip via TelemetryConfig.peak_flops / --peak.
DEFAULT_PEAK_FLOPS = 197e12


def mfu(flops_per_step: float, step_seconds: float, *,
        peak: float = DEFAULT_PEAK_FLOPS, n_chips: int = 1) -> float:
    """Fraction of aggregate peak the step achieved; 0.0 on a degenerate
    (non-positive) step time rather than a ZeroDivisionError — the same
    coarse-clock guard as ``MetricsLogger.log_step``.

    ``n_chips`` must be the FULL chip count of the mesh the program spans
    (:func:`mesh_chips`), model axes included: the numerator is total
    MODEL FLOPs for the global batch, so dividing by every chip is
    correct whether each chip holds the whole model (pure DP) or
    ``1/(tensor·pipe)`` of it (a composed plan) — per-chip work is
    ``total/chips`` either way. Counting only the data replicas (the
    whole-model-per-chip assumption) would overstate MFU by exactly
    ``tensor·pipe`` on a composed mesh."""
    if step_seconds <= 0.0:
        return 0.0
    return flops_per_step / step_seconds / (peak * max(n_chips, 1))


def mesh_chips(mesh) -> int:
    """The MFU denominator's chip count for ``mesh``: every device the
    compiled program spans — data, fsdp, pipe, and tensor axes alike, and
    ONLY those (a sub-mesh on a shared attach must not divide by chips it
    never used). ``fit()``'s telemetry, ``ParallelPlan.n_chips``, and the
    bench legs all route through this one function so a composed-plan MFU
    row can never disagree with a bench record about the denominator."""
    return int(mesh.size)


# -- decoder / encoder LM counters (per GLOBAL step: pass global tokens) ----


def gpt2_train_flops(tokens: float, *, hidden: int, depth: int, vocab: int,
                     seq: int) -> float:
    """GPT-2 geometry: 12·H² weight-GEMM params per block (qkv 3H² + out H²
    + mlp 4H²+4H²), weight-tied head V·H."""
    weight_matmul_params = depth * 12 * hidden * hidden + vocab * hidden
    return 6.0 * tokens * weight_matmul_params + depth * 12.0 * tokens * seq * hidden


def llama_train_flops(tokens: float, *, hidden: int, depth: int, ffn_dim: int,
                      vocab: int, seq: int, num_heads: int,
                      num_kv_heads: int) -> float:
    """Llama geometry: GQA qkv (2H² q+o, 2·H·kv_heads·dh k+v), SwiGLU MLP
    (3·H·ffn), un-tied head V·H."""
    dh = hidden // num_heads
    layer_p = (2 * hidden * hidden + 2 * hidden * (num_kv_heads * dh)
               + 3 * hidden * ffn_dim)
    return (6.0 * tokens * (depth * layer_p + vocab * hidden)
            + depth * 12.0 * tokens * seq * hidden)


def gpt2_moe_train_flops(tokens: float, *, hidden: int, depth: int,
                         vocab: int, seq: int, num_experts: int,
                         moe_every: int, top_k: int,
                         moe_ffn_dim: int | None = None) -> float:
    """Sparse GPT-2 (tpudist.parallel.ep): ACTIVE-param accounting — each
    token pays its dense blocks (12·H²), plus per MoE block the attention
    4·H², the fp32 router GEMM H·E, and ``top_k`` gelu expert FFNs of
    2·H·ffn params each. Capacity drops are NOT subtracted (the dispatch
    einsums/gathers still move full-capacity slots, and an MFU that rose
    when the router dropped tokens would reward imbalance); ``moe_every``
    follows the models' placement rule (every moe_every-th block,
    ``depth // moe_every`` MoE blocks total)."""
    ffn = moe_ffn_dim or 4 * hidden
    n_moe = depth // moe_every
    moe_layer_p = (4 * hidden * hidden + hidden * num_experts
                   + top_k * 2 * hidden * ffn)
    weight_matmul_params = ((depth - n_moe) * 12 * hidden * hidden
                            + n_moe * moe_layer_p + vocab * hidden)
    return (6.0 * tokens * weight_matmul_params
            + depth * 12.0 * tokens * seq * hidden)


def llama_moe_train_flops(tokens: float, *, hidden: int, depth: int,
                          ffn_dim: int, vocab: int, seq: int, num_heads: int,
                          num_kv_heads: int, num_experts: int,
                          moe_every: int, top_k: int) -> float:
    """Sparse Llama (Mixtral-style): GQA attention as the dense counter,
    per MoE block the router H·E plus ``top_k`` active SwiGLU experts
    (3·H·ffn each) instead of the dense MLP. Same active-param convention
    as :func:`gpt2_moe_train_flops`."""
    dh = hidden // num_heads
    attn_p = 2 * hidden * hidden + 2 * hidden * (num_kv_heads * dh)
    n_moe = depth // moe_every
    dense_layer_p = attn_p + 3 * hidden * ffn_dim
    moe_layer_p = (attn_p + hidden * num_experts
                   + top_k * 3 * hidden * ffn_dim)
    return (6.0 * tokens * ((depth - n_moe) * dense_layer_p
                            + n_moe * moe_layer_p + vocab * hidden)
            + depth * 12.0 * tokens * seq * hidden)


def bert_train_flops(tokens: float, *, hidden: int, depth: int, vocab: int,
                     seq: int) -> float:
    """BERT MLM: 12·H² encoder blocks + the MLM head's H² transform and
    tied V·H projection."""
    return (6.0 * tokens * (depth * 12 * hidden * hidden + hidden * hidden
                            + vocab * hidden)
            + depth * 12.0 * tokens * seq * hidden)


def vit_train_flops(tokens: float, *, hidden: int, depth: int,
                    seq: int) -> float:
    """ViT encoder blocks only (12·H² per block); the patch embed and
    classifier head are sub-1% at ImageNet shapes and excluded."""
    return (6.0 * tokens * depth * 12 * hidden * hidden
            + depth * 12.0 * tokens * seq * hidden)


def t5_train_flops(enc_tokens: float, dec_tokens: float, *, hidden: int,
                   ffn_dim: int, enc_depth: int, dec_depth: int, vocab: int,
                   enc_len: int, dec_len: int) -> float:
    """T5 v1.1 geometry: self-attn 4H² + gated-GELU MLP 3·H·ffn per block,
    decoder cross-attn q/o on dec tokens and k/v on enc tokens, un-tied
    head. Bit-identical to the bench_t5 hand model it replaced."""
    h, ffn = hidden, ffn_dim
    te, td = enc_tokens, dec_tokens
    attn_p, mlp_p = 4 * h * h, 3 * h * ffn
    gemm = 3.0 * 2.0 * (
        te * enc_depth * (attn_p + mlp_p)
        + td * dec_depth * (attn_p + mlp_p)
        + dec_depth * (2 * h * h * td + 2 * h * h * te)
        + td * vocab * h
    )
    attn = 6.0 * 2.0 * (
        te * enc_len * h * enc_depth
        + td * dec_len * h * dec_depth
        + td * enc_len * h * dec_depth
    )
    return gemm + attn


# ResNet-50 at 224×224: ~4.1 GFLOPs forward per image (the standard
# multiply+add count); backward ≈ 2× forward, same as the transformer
# convention above. Other ResNet geometries return None (no counter) —
# a guessed constant is worse than an absent row.
RESNET50_FWD_FLOPS_224 = 4.1e9
_RESNET50_STAGES = (3, 4, 6, 3)


def resnet_train_flops(images: float, *, stage_sizes, image_size: int = 224,
                       bottleneck: bool = True) -> float | None:
    if not bottleneck or tuple(stage_sizes) != _RESNET50_STAGES:
        return None
    scale = (image_size / 224.0) ** 2
    return 3.0 * RESNET50_FWD_FLOPS_224 * scale * images


# -- the dispatcher ----------------------------------------------------------


def _rows(shape, trailing: int) -> int:
    """Flat example count of a batch leaf: product of all dims before the
    ``trailing`` content dims — handles both the loader's flat [B, ...] and
    the grad-accum staged [accum, micro, ...] layouts."""
    lead = shape[: len(shape) - trailing]
    return int(math.prod(lead)) if lead else 1


def train_step_flops(model: Any, batch: Mapping[str, Any], *,
                     input_key: str = "tokens") -> float | None:
    """Analytic model FLOPs of ONE training step of ``model`` on ``batch``
    (shapes only — works on host arrays, staged ``jax.Array``s, or
    ``jax.eval_shape`` results). Returns ``None`` when the model doesn't
    advertise a counter (``flops_counter``), the batch is missing the
    expected keys (e.g. an index-only DeviceCachedLoader batch), or the
    geometry has no counter — callers must treat ``None`` as "no MFU row",
    never as zero.
    """
    family = getattr(model, "flops_counter", None)
    if family is None:
        return None
    try:
        if family == "t5":
            enc, dec = batch["enc_tokens"].shape, batch["dec_tokens"].shape
            return t5_train_flops(
                _rows(enc, 1) * enc[-1], _rows(dec, 1) * dec[-1],
                hidden=model.hidden_dim, ffn_dim=model.ffn_dim,
                enc_depth=model.enc_depth, dec_depth=model.dec_depth,
                vocab=model.vocab_size, enc_len=enc[-1], dec_len=dec[-1],
            )
        shape = batch[input_key].shape
    except (KeyError, AttributeError):
        return None
    if family == "gpt2":
        seq = shape[-1]
        return gpt2_train_flops(
            _rows(shape, 1) * seq, hidden=model.hidden_dim,
            depth=model.depth, vocab=model.vocab_size, seq=seq,
        )
    if family == "gpt2_moe":
        seq = shape[-1]
        return gpt2_moe_train_flops(
            _rows(shape, 1) * seq, hidden=model.hidden_dim,
            depth=model.depth, vocab=model.vocab_size, seq=seq,
            num_experts=model.num_experts, moe_every=model.moe_every,
            top_k=model.moe_top_k,
        )
    if family == "llama_moe":
        seq = shape[-1]
        from tpudist.models.llama import default_ffn_dim

        ffn = model.ffn_dim or default_ffn_dim(model.hidden_dim)
        return llama_moe_train_flops(
            _rows(shape, 1) * seq, hidden=model.hidden_dim,
            depth=model.depth, ffn_dim=ffn, vocab=model.vocab_size, seq=seq,
            num_heads=model.num_heads,
            num_kv_heads=model.num_kv_heads or model.num_heads,
            num_experts=model.num_experts, moe_every=model.moe_every,
            top_k=model.moe_top_k,
        )
    if family == "llama":
        seq = shape[-1]
        from tpudist.models.llama import default_ffn_dim

        ffn = model.ffn_dim or default_ffn_dim(model.hidden_dim)
        return llama_train_flops(
            _rows(shape, 1) * seq, hidden=model.hidden_dim,
            depth=model.depth, ffn_dim=ffn, vocab=model.vocab_size, seq=seq,
            num_heads=model.num_heads,
            num_kv_heads=model.num_kv_heads or model.num_heads,
        )
    if family == "bert":
        seq = shape[-1]
        return bert_train_flops(
            _rows(shape, 1) * seq, hidden=model.hidden_dim,
            depth=model.depth, vocab=model.vocab_size, seq=seq,
        )
    if family == "vit":
        patches = (shape[-3] // model.patch_size) * (shape[-2] // model.patch_size)
        seq = patches + 1  # the CLS token
        return vit_train_flops(
            _rows(shape, 3) * seq, hidden=model.hidden_dim,
            depth=model.depth, seq=seq,
        )
    if family == "resnet":
        block_cls = getattr(model, "block_cls", None)
        return resnet_train_flops(
            _rows(shape, 3), stage_sizes=model.stage_sizes,
            image_size=shape[-3],
            bottleneck=getattr(block_cls, "__name__", "") == "BottleneckBlock",
        )
    return None


def tokens_per_step(model: Any, batch: Mapping[str, Any], *,
                    input_key: str = "tokens") -> int | None:
    """The throughput denominator matching :func:`train_step_flops`'s
    numerator: total tokens (LMs; enc+dec for T5) or images (vision) per
    step, or ``None`` for the same cases the counter returns ``None``."""
    family = getattr(model, "flops_counter", None)
    if family is None:
        return None
    try:
        if family == "t5":
            enc, dec = batch["enc_tokens"].shape, batch["dec_tokens"].shape
            return _rows(enc, 1) * enc[-1] + _rows(dec, 1) * dec[-1]
        shape = batch[input_key].shape
    except (KeyError, AttributeError):
        return None
    if family in ("gpt2", "llama", "bert", "gpt2_moe", "llama_moe"):
        return _rows(shape, 1) * shape[-1]
    if family in ("vit", "resnet"):
        return _rows(shape, 3)
    return None


def gpt2_step_shapes(tokens: int, hidden: int, vocab: int = 50257,
                     ce_chunk_rows: int = 4096) -> list[tuple[str, int, int, int]]:
    """The GEMM shapes of one GPT-2 block + tied head, forward and the two
    backward passes (dgrad/wgrad) per GEMM, at ``tokens`` rows — the
    per-GEMM table behind ``examples/mfu_probe.py`` (docs/PERF.md §4b)."""
    t, d = tokens, hidden
    fwd = [
        ("qkv", t, d, 3 * d),
        ("attn_out", t, d, d),
        ("mlp_fc", t, d, 4 * d),
        ("mlp_proj", t, 4 * d, d),
        ("lm_head(chunk)", ce_chunk_rows, d, vocab),
    ]
    shapes = []
    for name, m, k, n in fwd:
        shapes.append((f"{name} fwd", m, k, n))
        shapes.append((f"{name} dgrad", m, n, k))
        shapes.append((f"{name} wgrad", k, m, n))
    return shapes
