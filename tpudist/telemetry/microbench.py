"""Differential on-device microbenchmark timing — the measurement skeleton
behind ``examples/mfu_probe.py`` (docs/PERF.md §4b) and
``examples/kernel_probe.py``, factored here so every probe measures the
same way.

The problem it solves: on a remote/tunnel attach each device call carries
~100 ms ± 100 ms of RTT, which swamps sub-millisecond kernels — a naive
``time(run(n))/n`` under-read small GEMMs 30× (§4b's history). Three
ingredients fix it:

- **differential timing** — ``(t(4n) − t(n)) / 3n`` cancels every
  per-call fixed cost (dispatch, the tunnel RTT, the value-fetch sync);
- **adaptive iteration counts** — sized from an optimistic per-iteration
  estimate so the differential itself spans ~1.5 s of device time, far
  above the tunnel's jitter;
- **plausibility retries** — a non-positive or faster-than-physics
  differential is jitter, not measurement: retry with a doubled budget,
  and return NaN (never a fake number) if it stays noisy.

Callers provide ``timed(n) -> seconds`` (median wall time for ``n``
iterations, compiled and synchronized by a VALUE fetch — ``float(out)`` —
because ``block_until_ready`` on a remote attach returns at the stub, not
the device). :func:`anti_hoist_scan` builds the standard iteration body:
one jitted ``lax.scan`` whose operand is scaled per-iteration (defeats
loop-invariant hoisting) and whose result feeds an accumulator (defeats
dead-code elimination).
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np


def adaptive_iters(est_iter_s: float, *, budget_s: float = 0.5,
                   lo: int = 64, hi: int = 8192) -> int:
    """Iteration count whose single-``n`` timing is ~``budget_s`` of device
    time under the caller's optimistic per-iteration estimate (the
    differential then spans ``3n`` ≈ 3 budgets)."""
    if est_iter_s <= 0:
        return hi
    return int(np.clip(budget_s / est_iter_s, lo, hi))


def differential_iter_seconds(timed: Callable[[int], float],
                              iters: int) -> float:
    """One differential sample: ``(timed(4n) − timed(n)) / 3n``."""
    return (timed(4 * iters) - timed(iters)) / (3 * iters)


def measure_iter_seconds(
    timed: Callable[[int], float],
    est_iter_s: float,
    *,
    budget_s: float = 0.5,
    floor_s: float | None = None,
    attempts: int = 3,
    lo: int = 64,
    hi: int = 8192,
    max_iters: int = 16384,
) -> float:
    """Robust seconds-per-iteration via the differential method.

    ``floor_s``: the fastest physically-plausible per-iteration time
    (e.g. ``flops / (1.05·peak)`` or ``bytes / (1.05·peak_bw)``); a
    differential below it — or non-positive — is attach jitter and
    triggers a doubled-budget retry. Returns NaN after ``attempts``
    persistently-noisy tries: a missing number, never a fake one.
    """
    iters = adaptive_iters(est_iter_s, budget_s=budget_s, lo=lo, hi=hi)
    for _ in range(attempts):
        dt = differential_iter_seconds(timed, iters)
        if dt > 0 and (floor_s is None or dt >= floor_s):
            return dt
        iters = min(iters * 2, max_iters)
    return float("nan")


def anti_hoist_scan(body: Callable, operand, *, reps: int = 5):
    """Build ``timed(n)`` for :func:`measure_iter_seconds` from a kernel
    invocation.

    ``body(scaled_operand) -> array`` is the work to time; it runs inside
    one jitted ``lax.scan`` of ``n`` iterations with the operand scaled
    per-iteration (``×(1 + i·1e-6)`` — no hoisting) and the FULL result
    accumulated as the scan carry (a scalar carry would let XLA slice the
    work down to one element — the whole output must stay live). One
    element of the accumulator is fetched at the end. ``timed(n)``
    compiles once per distinct ``n``, then returns the median of ``reps``
    runs, each synchronized by the value fetch.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x, scales):
        shape = jax.eval_shape(body, x)

        def step(acc, s):
            out = body(x * s.astype(x.dtype))
            return acc + out.astype(jnp.float32), None

        acc, _ = jax.lax.scan(
            step, jnp.zeros(shape.shape, jnp.float32), scales
        )
        return jnp.ravel(acc)[0]

    def timed(n_iters: int) -> float:
        scales = jnp.asarray(1.0 + np.arange(n_iters) * 1e-6, jnp.float32)
        run(operand, scales).block_until_ready()  # compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(operand, scales))  # value fetch = real sync on remote
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    return timed
