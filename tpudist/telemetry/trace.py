"""The span layer: structured timeline rows riding :class:`TelemetrySink`,
plus the live Prometheus exporter — the two observability surfaces PR 19
adds on top of the existing per-rank JSONL streams (docs/OBSERVABILITY.md
§8).

Everything the subsystem already measures is an *aggregate* — percentile
rows, breakdown averages, heartbeat intervals. A span row is the same
measurement kept *attributed*: one row per interval (or event) with a
start, a duration, and the identity of the thing that spent the time, so
``tools/tracelens.py`` can stitch the per-rank streams into a Chrome/
Perfetto timeline and a per-request latency decomposition.

One row schema for every span (kind ``span``, docs/OBSERVABILITY.md §8)::

    {"v": 1, "t": <wall>, "kind": "span", "rank": R, ["step": S,]
     "name": ..., "cat": "train"|"serve", "ph": "X"|"i",
     "t0": <span-clock start>, "dur_s": <seconds>, <tags...>}

``ph`` follows the Chrome trace-event phases: ``"X"`` is a complete span,
``"i"`` an instant event (``dur_s`` 0). ``t0``/``dur_s`` are on the
emitter's *span clock* — ``time.monotonic`` for train spans (the heartbeat
``mono`` domain) and the :class:`~tpudist.serve.stats.ServeStats` clock
(``time.perf_counter``) for serve spans. Span clocks are never wall time;
the row's own ``t`` (written at span close) is the wall anchor tracelens
uses to place each clock domain on a shared timeline.

Span values are NOT rounded: the serve tracer reuses the exact clock
readings :class:`ServeStats` sampled, so TTFT/TPOT derived from the spans
are bit-equal to the SLO samples (the parity test pins this), and a
request's phase spans telescope exactly — ``queued + prefill + decode +
preempted == total`` to float addition error.

Both features are strictly opt-in: with ``trace`` off and no
``metrics_port``, no object here is constructed and every existing stream
stays byte-identical (the standing telemetry contract).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Mapping

__all__ = ["Tracer", "ServeTracer", "MetricsExporter"]


class Tracer:
    """Span emitter for the training loop (and any host-side code that
    thinks in intervals): ``span`` writes a completed interval, ``instant``
    a point event. Spans are stamped with ``process_index``/``generation``
    so multi-rank, multi-generation streams align (the same identity pair
    heartbeat rows carry), and ``t0`` is on ``time.monotonic`` — wall
    clocks skew across hosts, monotonic deltas do not."""

    def __init__(self, sink, *, cat: str = "train", process_index: int = 0,
                 generation: int = 0, clock=time.monotonic):
        self.sink = sink
        self.cat = cat
        self.process_index = int(process_index)
        self.generation = int(generation)
        self._clock = clock

    def span(self, name: str, dur_s: float, *, t0: float | None = None,
             step: int | None = None, **tags) -> dict:
        """One completed interval. ``t0`` defaults to ``now - dur_s`` —
        the caller measured a duration and is reporting it at close, the
        common shape in ``fit()`` (interval_s, checkpoint save time)."""
        if t0 is None:
            t0 = self._clock() - dur_s
        return self.sink.write(
            "span", step, name=name, cat=self.cat, ph="X",
            t0=float(t0), dur_s=float(dur_s),
            process_index=self.process_index, generation=self.generation,
            **tags,
        )

    def instant(self, name: str, *, step: int | None = None, **tags) -> dict:
        """One point event (repair, reshard, anomaly, probe)."""
        return self.sink.write(
            "span", step, name=name, cat=self.cat, ph="i",
            t0=float(self._clock()), dur_s=0.0,
            process_index=self.process_index, generation=self.generation,
            **tags,
        )


class _Req:
    """Per-request span state: the open phase boundaries and the tag
    accumulators the terminal ``request`` span reports."""

    __slots__ = (
        "lane", "t_submit", "t_admit", "t_first", "t_preempt", "seg_t0",
        "decode_s", "preempt_s", "slot", "preempts", "prefix_hit",
        "prefix_lookup", "spec_drafted", "spec_accepted",
    )

    def __init__(self, lane: int, t_submit: float):
        self.lane = lane
        self.t_submit = t_submit
        self.t_admit: float | None = None
        self.t_first: float | None = None
        self.t_preempt: float | None = None
        self.seg_t0: float | None = None  # open decode segment's start
        self.decode_s = 0.0
        self.preempt_s = 0.0
        self.slot: int | None = None
        self.preempts = 0
        self.prefix_hit: int | None = None
        self.prefix_lookup: int | None = None
        self.spec_drafted = 0
        self.spec_accepted = 0


class ServeTracer:
    """Per-request lifecycle spans for :class:`tpudist.serve.ServeEngine`.

    The engine drives one hook per scheduler transition, passing the EXACT
    clock reading its :class:`ServeStats` call returned — the tracer never
    reads the clock for a phase boundary itself, so span-derived TTFT/TPOT
    reconcile bit-equal with the SLO samples.

    A request's phases telescope over its lifetime::

        queued    submit → first admission (prefill dispatch)
        prefill   first admission → first token
        decode    first token → retire, minus the preempted gaps
        preempted each eviction → its re-admission (the queue wait the
                  preemption cost; the replay prefill compute lands in
                  the decode segment that follows — it produces tokens)

    so ``queued + prefill + decode + preempted == retire - submit``
    exactly. Each closed phase is a ``span`` row; retire additionally
    emits the terminal ``request`` span carrying the full decomposition
    plus the request's identity tags (lane, slot, prefix-cache outcome,
    speculative counts, preempt count)."""

    def __init__(self, sink, *, rank: int = 0):
        self.sink = sink
        self.rank = rank
        self._req: dict[int, _Req] = {}

    # -- emission ---------------------------------------------------------

    def _span(self, name: str, t0: float, t1: float, *, step=None, **tags):
        self.sink.write(
            "span", step, name=name, cat="serve", ph="X",
            t0=float(t0), dur_s=float(t1 - t0), **tags,
        )

    def _instant(self, name: str, t: float, *, step=None, **tags):
        self.sink.write(
            "span", step, name=name, cat="serve", ph="i",
            t0=float(t), dur_s=0.0, **tags,
        )

    # -- request lifecycle (engine-driven) --------------------------------

    def on_submit(self, rid: int, t: float, *, lane: int = 0) -> None:
        self._req[rid] = _Req(lane, t)

    def on_admit(self, rid: int, t: float, *,
                 pool_occupancy: float | None = None) -> None:
        """First admission: the queued phase closes, prefill begins."""
        st = self._req.get(rid)
        if st is None or st.t_admit is not None:
            return
        st.t_admit = t
        self._span("queued", st.t_submit, t, rid=rid, lane=st.lane,
                   pool_occupancy=pool_occupancy)

    def on_first_token(self, rid: int, t: float, *,
                       slot: int | None = None,
                       prefix_hit: int | None = None,
                       prefix_lookup: int | None = None) -> None:
        """Prefill produced the first token; the decode phase opens."""
        st = self._req.get(rid)
        if st is None or st.t_first is not None:
            return
        st.t_first = t
        st.slot = slot
        st.prefix_hit = prefix_hit
        st.prefix_lookup = prefix_lookup
        st.seg_t0 = t
        self._span("prefill", st.t_admit if st.t_admit is not None else t, t,
                   rid=rid, slot=slot, prefix_hit_blocks=prefix_hit,
                   prefix_lookup_blocks=prefix_lookup)

    def on_preempt(self, rid: int, t: float, *,
                   pool_occupancy: float | None = None) -> None:
        """Eviction back to the queue: the open decode segment closes,
        the preempted phase opens."""
        st = self._req.get(rid)
        if st is None:
            return
        if st.seg_t0 is not None:
            st.decode_s += t - st.seg_t0
            self._span("decode", st.seg_t0, t, rid=rid, slot=st.slot)
            st.seg_t0 = None
        st.t_preempt = t
        st.preempts += 1
        self._instant("preempt", t, rid=rid, slot=st.slot,
                      pool_occupancy=pool_occupancy)
        st.slot = None

    def on_resume(self, rid: int, t: float, *, slot: int | None = None,
                  pool_occupancy: float | None = None) -> None:
        """Re-admission of a preempted request: the preempted phase
        closes, decode resumes (the replay prefill runs inside the new
        decode segment — it is re-producing the request's progress)."""
        st = self._req.get(rid)
        if st is None or st.t_preempt is None:
            return
        st.preempt_s += t - st.t_preempt
        self._span("preempted", st.t_preempt, t, rid=rid,
                   pool_occupancy=pool_occupancy)
        st.t_preempt = None
        st.seg_t0 = t
        st.slot = slot

    def set_slot(self, rid: int, slot: int) -> None:
        """The pool assigned (or reassigned) the request's slot — recorded
        after the first-token hook, which fires before insertion."""
        st = self._req.get(rid)
        if st is not None:
            st.slot = slot

    def on_spec(self, rid: int, drafted: int, accepted: int) -> None:
        """One verify sweep's outcome for THIS request (the per-request
        split of ``ServeStats.on_spec``'s batch totals)."""
        st = self._req.get(rid)
        if st is not None:
            st.spec_drafted += int(drafted)
            st.spec_accepted += int(accepted)

    def on_done(self, rid: int, t: float, n_tokens: int, *,
                pool_occupancy: float | None = None) -> None:
        """Retire: close the open decode segment and emit the terminal
        ``request`` span with the exact phase decomposition."""
        st = self._req.pop(rid, None)
        if st is None:
            return
        if st.seg_t0 is not None:
            st.decode_s += t - st.seg_t0
            self._span("decode", st.seg_t0, t, rid=rid, slot=st.slot,
                       tokens=n_tokens)
        queued_s = (
            (st.t_admit - st.t_submit) if st.t_admit is not None else 0.0
        )
        prefill_s = (
            (st.t_first - st.t_admit)
            if (st.t_first is not None and st.t_admit is not None) else 0.0
        )
        ttft_s = (
            (st.t_first - st.t_submit) if st.t_first is not None else None
        )
        tpot_s = (
            (t - st.t_first) / (n_tokens - 1)
            if (st.t_first is not None and n_tokens > 1) else None
        )
        self._span(
            "request", st.t_submit, t,
            rid=rid, lane=st.lane, slot=st.slot, tokens=n_tokens,
            queued_s=queued_s, prefill_s=prefill_s,
            decode_s=st.decode_s, preempt_s=st.preempt_s,
            ttft_s=ttft_s, tpot_s=tpot_s, preempts=st.preempts,
            prefix_hit_blocks=st.prefix_hit,
            prefix_lookup_blocks=st.prefix_lookup,
            spec_drafted=st.spec_drafted, spec_accepted=st.spec_accepted,
            pool_occupancy=pool_occupancy,
        )

    # -- scheduler ticks --------------------------------------------------

    def on_tick(self, step: int, t0: float, t1: float, *, active: int,
                queue_depth: int, emitted: int) -> None:
        """One scheduler tick (admit + dispatch + process): the decode
        timeline's backbone — token counts per tick, batch occupancy."""
        self._span("tick", t0, t1, step=step, active=active,
                   queue_depth=queue_depth, tokens=emitted)


def _metric_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    return ("_" + s) if s[:1].isdigit() else s


class MetricsExporter:
    """Opt-in live scrape surface: a stdlib ``ThreadingHTTPServer`` on a
    daemon thread serving Prometheus text exposition at ``/metrics``.

    Two sources, both host-side only (never a device sync):

    - **pushed gauges** — ``set(step=..., mfu=...)``; the training loop
      pushes the scalars it already fetched for its telemetry rows.
    - **pull collectors** — ``add_collector(fn)``; ``fn()`` runs AT SCRAPE
      TIME and returns a mapping (the serving engine registers a
      ``ServeStats.snapshot()`` reader, so request traffic pays zero
      per-token cost for the endpoint).

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``self.port``. ``None`` values are skipped (a metric with no sample
    yet is absent, not 0 — absence is what alerting rules can see).
    Metrics are namespaced ``tpudist_``; names ending ``_total`` are typed
    ``counter``, everything else ``gauge``."""

    def __init__(self, port: int = 0, *, host: str = "0.0.0.0",
                 namespace: str = "tpudist"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.namespace = namespace
        self._lock = threading.Lock()
        self._gauges: dict[str, float] = {}
        self._collectors: list[Callable[[], Mapping]] = []
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server's contract
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr spam
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpudist-metrics",
            daemon=True,
        )
        self._thread.start()

    def set(self, **gauges) -> None:
        """Merge pushed gauge values (``None`` clears a key)."""
        with self._lock:
            for k, v in gauges.items():
                if v is None:
                    self._gauges.pop(k, None)
                else:
                    self._gauges[k] = v

    def add_collector(self, fn: Callable[[], Mapping]) -> None:
        """Register a scrape-time reader; later collectors win key ties."""
        self._collectors.append(fn)

    def render(self) -> str:
        with self._lock:
            merged: dict[str, float] = dict(self._gauges)
        for fn in list(self._collectors):
            try:
                merged.update({
                    k: v for k, v in dict(fn()).items() if v is not None
                })
            except Exception:
                continue  # a scrape must never take the server down
        lines = []
        for key in sorted(merged):
            v = merged[key]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            name = f"{self.namespace}_{_metric_name(key)}"
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# HELP {name} tpudist live metric: {key}")
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {float(v):g}")
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
