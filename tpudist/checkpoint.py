"""Checkpoint / resume — sharding-aware training-state persistence.

The reference has NO checkpointing (verified in SURVEY.md §5: no
``state_dict``/``torch.save`` anywhere; training always starts from random
init, /root/reference/main.py:40, and the process exits without persisting).
tpudist adds it as a capability extension because on TPU pods it is the
failure-recovery story (SURVEY.md §5 notes fail-fast is the reference's only
answer): the launcher restarts a dead world and training resumes from the
last saved step.

Built on Orbax, the TPU-native checkpoint layer: saves are async (the step
loop keeps running while the previous checkpoint flushes), every process
writes only its own shards of sharded arrays (TP/FSDP states don't gather),
and restore places leaves directly onto the mesh according to a target
sharding tree.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tpudist.train import TrainState

#: subdirectory old-geometry step dirs are moved under while an elastic
#: reshard commits (``quarantine_steps``): the per-step renames are atomic
#: and reversible, so a crash mid-commit can always roll back to a state
#: that restores — either the fresh new-world step (if its save landed)
#: or the quarantined old-world steps (``recover_interrupted_reshard``).
#: A digit-free name: orbax's step scan parses any trailing integer, so a
#: sibling like ``stale_4`` would read as step 4 and crash the manager.
QUARANTINE_DIR = "_pre_reshard"

#: where restore's fallback walk sets aside step dirs that failed to
#: deserialize — moved, never deleted (the failure may be transient I/O
#: and the dir may still hold the healthy newest state), but out of the
#: step namespace so latest_step and orbax's monotonic save order stop
#: seeing them. A digit-free name, same rule as QUARANTINE_DIR.
FAILED_DIR = "_failed"

#: the last-known-good marker (``tpudist.resilience.repair``): a step is
#: recorded here only after K subsequent steps with clean health metrics
#: promoted it, so the repair loop's rollback target is never a
#: checkpoint written mid-incubating-spike. Anchored steps are exempt
#: from ``keep_last`` pruning.
ANCHOR_FILE = "tpudist_anchor.json"


def atomic_write_json(directory: Path, name: str, obj) -> None:
    """Write ``obj`` as JSON at ``directory/name`` atomically (sibling
    tmp + fsync + ``os.replace``): a preemption landing mid-write must
    never leave a torn half-JSON that poisons the next generation's
    bring-up. The one write discipline every run-metadata file here
    (geometry meta, anchor, repair state) shares."""
    import json

    directory = Path(directory)
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=f".{name}.", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(obj))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, directory / name)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


@dataclasses.dataclass
class Checkpointer:
    """Manages a directory of step-numbered TrainState checkpoints.

    >>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(state)                        # async; step from state.step
    >>> state = ckpt.restore(like=state)        # latest, onto state's shardings
    >>> ckpt.latest_step()
    """

    directory: str | Path
    max_to_keep: int = 3
    #: retention knob (``fit(keep_last=)`` / ``main.py --keep_last``):
    #: when set, orbax's own max_to_keep is DISABLED and this class
    #: prunes after each save instead, keeping the newest ``keep_last``
    #: step dirs PLUS the health-anchored step (``read_anchor``) — the
    #: repair loop's rollback target must survive retention, which
    #: orbax's purely-newest policy cannot express. ``None`` keeps the
    #: legacy orbax ``max_to_keep`` behavior byte-identical.
    keep_last: int | None = None
    #: optional callable returning extra step numbers ``_prune`` must
    #: keep. fit wires the repair controller's ``protected_steps`` here:
    #: anchor CANDIDATES (saves still inside their clean-step promotion
    #: window) must survive retention, or a promotion at step S+K would
    #: stamp the anchor file with a step dir ``keep_last`` newer saves
    #: already deleted — and the first rollback would die on a missing
    #: checkpoint instead of self-healing.
    protect_steps: object = None

    def __post_init__(self):
        self.directory = Path(self.directory).absolute()
        self._mgr = self._make_manager()

    def _make_manager(self) -> ocp.CheckpointManager:
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=(
                    None if self.keep_last is not None else self.max_to_keep
                ),
                enable_async_checkpointing=True,
            ),
            # registers the standard handler at construction: a FRESH
            # manager (a relaunched generation) can then serve
            # item_metadata() — the elastic reshard's shape source —
            # before any save/restore call has lazily registered it
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    # -- write ------------------------------------------------------------
    def save(self, state: TrainState, step: int | None = None,
             wait: bool = False) -> bool:
        """Persist ``state`` (async by default). Returns False if this step
        is already saved.

        ``wait=True`` is the EMERGENCY-SAVE contract
        (``tpudist.resilience``): it blocks until the checkpoint — and any
        earlier in-flight async save — is durable on disk, which is what
        fit()'s graceful-preemption path calls before exiting 75. The
        supervisor may relaunch the moment this process dies; only a
        synchronous save guarantees the next generation finds the step it
        was promised."""
        if step is None:
            step = int(state.step)
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()
        if saved and self.keep_last is not None:
            self._prune()
        return saved

    def _prune(self) -> None:
        """keep_last retention: delete everything but the newest
        ``keep_last`` steps and the anchored step. Fail-soft — retention
        must never kill training over a racing delete or a permission
        hiccup — and orbax's own ``delete`` does the multi-process
        coordination (primary-host surgery)."""
        keep = max(int(self.keep_last), 1)
        steps = self.all_steps()
        protect = set(steps[-keep:])
        anchor = self.read_anchor()
        if anchor is not None:
            protect.add(int(anchor))
        if self.protect_steps is not None:
            try:
                protect.update(int(s) for s in self.protect_steps())
            except Exception:
                pass
        for s in steps:
            if s in protect:
                continue
            try:
                self._mgr.delete(s)
            except Exception:
                pass

    def wait(self) -> None:
        """Block until in-flight async saves are durable."""
        self._mgr.wait_until_finished()

    def quarantine_failed_step(self, step: int) -> bool:
        """Set aside one saved step that failed to deserialize (the
        corrupt-fallback cleanup): the dir moves into ``_failed/`` so it
        stops blocking orbax's monotonic save order and shadowing
        latest_step for the next resume — but is NEVER deleted, because
        the failure may have been transient I/O (an NFS/GCS hiccup) and
        the "torn" checkpoint may in fact be the healthy newest state an
        operator can still recover by moving it back.

        Multi-process discipline (same shape as
        ``recover_interrupted_reshard``): the early return reads
        PRE-mutation state — stable because rank 0's surgery sits BEHIND
        the entry barrier, which it cannot pass until every rank has
        taken the same branch — so every rank runs the same collective
        sequence; the rank-0 filesystem surgery alone is fail-soft (a
        cleanup must never kill a resume that already succeeded), never
        the barrier."""
        step = int(step)
        src = self.directory / str(step)
        if not src.is_dir():
            return False
        self._sync("failed-step-enter")
        if jax.process_index() == 0:
            try:
                import shutil

                d = self.directory / FAILED_DIR
                d.mkdir(exist_ok=True)
                target = d / str(step)
                if target.exists():
                    shutil.rmtree(target, ignore_errors=True)
                os.replace(src, target)
            except OSError:
                pass
        self._sync("failed-step")
        self._reopen()
        return (self.directory / FAILED_DIR / str(step)).is_dir()

    # -- read -------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def saved_metadata(self, step: int):
        """The SAVED tree's per-leaf metadata (shapes/dtypes/old shardings)
        as orbax recorded it — what the elastic reshard aligns the live
        state against (``tpudist.resilience.elastic``)."""
        return self._mgr.item_metadata(step)

    def raw_restore(self, step: int, abstract):
        """Restore ``step`` onto an explicit abstract tree — the reshard
        path's escape hatch, where the abstract shapes are the checkpoint's
        own (old-world) shapes rather than the live state's."""
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore(
        self,
        like: TrainState,
        step: int | None = None,
        *,
        reshard: bool = False,
        run_meta: dict | None = None,
        mesh=None,
        fallback: bool = False,
        on_event=None,
    ) -> TrainState:
        """Restore a checkpoint onto the placement of ``like``.

        ``like`` supplies the tree structure, dtypes, and shardings (it can
        be a freshly-initialized state); leaves are created directly on the
        devices that own them — no host-side gather.

        ``reshard=True`` is the elastic-restart mode (``fit(elastic=True)``,
        docs/MULTIHOST.md "Resuming on a different world size"): when the
        saved ``tpudist_meta.json`` geometry disagrees with ``run_meta``,
        the mismatch is validated as a pure world resize and the
        world-bound leaves (ZeRO-1 pad-and-reshape optimizer shards) are
        re-laid onto the live ``mesh``; the error-feedback residual
        restarts zeroed and ``state.step`` comes back remapped into the
        new world's step units (:mod:`tpudist.resilience.elastic`). Any
        mismatch that is NOT a world resize still refuses loudly.

        ``fallback=True`` walks back to the previous saved step when the
        newest fails to deserialize (a preemption landing mid-save can
        leave a truncated step dir) — each failed step emits a
        ``checkpoint_fallback`` event through ``on_event`` and the walk
        continues oldest-ward; only when every step fails does the last
        error propagate.
        """
        saved_meta = self.read_meta() if reshard else None
        if reshard and run_meta is not None and saved_meta is not None:
            from tpudist.resilience import elastic

            do_reshard = not elastic.meta_matches(saved_meta, run_meta)
        else:
            do_reshard = False
        if step is not None:
            steps = [int(step)]
        else:
            steps = sorted(self.all_steps(), reverse=True)
            if not steps:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
            if not fallback:
                steps = steps[:1]
        if do_reshard:
            # validate BEFORE any restore attempt: a refused geometry must
            # raise its own error, never be mistaken for corruption
            from tpudist.resilience import elastic

            reason = elastic.refusal_reason(saved_meta, run_meta)
            if reason is not None:
                raise elastic.ElasticRefusal(
                    f"checkpoint at {self.directory} cannot be elastically "
                    f"resumed: {reason} — resume with the original settings "
                    "or start a fresh checkpoint_dir"
                )
        last_exc: Exception | None = None
        for i, s in enumerate(steps):
            try:
                if do_reshard:
                    from tpudist.resilience import elastic

                    state = elastic.reshard_restore(
                        self, like, s, mesh=mesh, saved_meta=saved_meta,
                        run_meta=run_meta, on_event=on_event,
                    )
                else:
                    abstract = jax.tree_util.tree_map(
                        lambda x: jax.ShapeDtypeStruct(
                            x.shape, x.dtype, sharding=x.sharding
                        )
                        if isinstance(x, jax.Array) else x,
                        like,
                    )
                    state = self._mgr.restore(
                        s, args=ocp.args.StandardRestore(abstract)
                    )
                return state
            except Exception as exc:  # truncated/partial step dir
                from tpudist.resilience.elastic import ElasticRefusal

                if isinstance(exc, ElasticRefusal):
                    # geometry/structure refusals are decisions, not damage
                    # — an older checkpoint would refuse identically
                    raise
                last_exc = exc
                if on_event is not None and len(steps) > 1:
                    on_event({
                        "tag": "checkpoint_fallback",
                        "failed_step": int(s),
                        "error": f"{type(exc).__name__}: {exc}"[:400],
                        "next_step": (
                            int(steps[i + 1]) if i + 1 < len(steps) else None
                        ),
                    })
        raise last_exc

    # -- elastic reshard commit -------------------------------------------
    # An elastic resume rewrites history: the restored state's step counter
    # is in NEW-world units, so the old-geometry step dirs become
    # uninterpretable (and orbax refuses out-of-order saves anyway when the
    # remapped counter shrank). The commit protocol keeps a restorable —
    # and correctly DESCRIBED — checkpoint on disk at every instant:
    #   1. quarantine_steps(commit_meta=new): atomically rename every old
    #      step dir into QUARANTINE_DIR (still a valid old-world
    #      checkpoint), drop the commit marker (the NEW meta, written
    #      atomically inside the quarantine dir) and reopen the manager
    #      on the now-empty step namespace;
    #   2. save(state, wait=True) at the remapped step (durable);
    #   3. write_meta(new) — the atomic flip;
    #   4. purge_quarantined() — garbage (marker included) only now.
    # recover_interrupted_reshard() makes every crash window safe:
    #   - any live step + the marker ⇒ the save landed but the flip may
    #     not have: ADOPT the marker as the meta and purge (idempotent
    #     past step 3 — without this, a crash between 2 and 3 would make
    #     the next bring-up re-reshard the already-new-world checkpoint:
    #     a double-remapped cursor, and a quarantine rename onto the
    #     occupied source step number);
    #   - no marker ⇒ the renames may be partial: roll every quarantined
    #     dir back (the old meta still describes them);
    #   - marker but no live step ⇒ the save never landed: roll back and
    #     drop the marker.

    COMMIT_MARKER = "commit_meta.json"

    def _reopen(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
        self._mgr = self._make_manager()

    @staticmethod
    def _sync(tag: str) -> None:
        # multi-process fence around rank-0 directory surgery: every
        # process must see the renames complete before rebuilding its
        # manager (whose constructor scans the step namespace) or calling
        # the next coordinated save. No-op single-process.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"tpudist-ckpt-{tag}")

    def _quarantined(self) -> list[Path]:
        q = self.directory / QUARANTINE_DIR
        if not q.is_dir():
            return []
        return sorted(
            (p for p in q.iterdir() if p.is_dir() and p.name.isdigit()),
            key=lambda p: int(p.name),
        )

    def quarantine_steps(self, commit_meta: dict | None = None) -> list[int]:
        """Move every live step dir aside (atomic renames), drop the
        commit marker describing the NEW geometry, and reopen the manager
        on the emptied namespace. Returns the quarantined step numbers."""
        import json

        self._mgr.wait_until_finished()
        steps = self.all_steps()
        if jax.process_index() == 0:
            q = self.directory / QUARANTINE_DIR
            q.mkdir(exist_ok=True)
            for s in steps:
                src = self.directory / str(s)
                if src.is_dir():
                    os.replace(src, q / str(s))
            if commit_meta is not None:
                # written only AFTER every rename: its presence certifies
                # the quarantine completed, so recovery can tell a
                # mid-commit crash from a mid-quarantine one
                fd, tmp = tempfile.mkstemp(dir=q, suffix=".tmp")
                with os.fdopen(fd, "w") as f:
                    f.write(json.dumps(commit_meta))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, q / self.COMMIT_MARKER)
        self._sync("quarantine")
        self._reopen()
        return steps

    def recover_interrupted_reshard(self) -> str | None:
        """Finish or roll back a reshard commit a crash interrupted (see
        the protocol above). Returns ``"completed"`` (a saved new-world
        step existed: its marker meta was adopted and the quarantine
        purged), ``"rolled_back"`` (quarantined dirs renamed back under
        the still-valid old meta), or ``None`` (no interrupted commit)."""
        import json

        # the decision and the marker's content are read from
        # PRE-mutation state, which is stable: every rank calls this at
        # the same bring-up point, and no rank mutates anything until
        # the entry barrier below has collected them all — so every rank
        # takes the same branch and runs the same collective sequence
        # (the TOCTOU alternative — rank 0 finishing its surgery before
        # a slower rank's existence check — would strand that rank
        # outside the barrier and hang the relaunch).
        q_dir = self.directory / QUARANTINE_DIR
        if not q_dir.is_dir():
            return None
        marker = q_dir / self.COMMIT_MARKER
        adopt_meta = None
        if self.all_steps() and marker.exists():
            adopt_meta = json.loads(marker.read_text())
        self._sync("recover-enter")
        if adopt_meta is not None:
            # the barrier-save landed: the live steps are NEW-world and
            # the marker is their authoritative description — flip the
            # meta (idempotent if the crash came after the flip) and purge
            self.write_meta(adopt_meta)
            self._sync("adopt-commit")
            self.purge_quarantined()
            self._sync("adopt-purge")
            return "completed"
        if jax.process_index() == 0:
            # marker FIRST: a rollback that crashes mid-way must leave a
            # marker-less quarantine (retried as another rollback), never
            # marker + rolled-back old steps (which the next bring-up
            # would mis-read as a committed save and stamp with NEW meta)
            if marker.exists():
                os.unlink(marker)
            for p in self._quarantined():
                os.replace(p, self.directory / p.name)
            try:
                q_dir.rmdir()
            except OSError:
                pass
        self._sync("unquarantine")
        self._reopen()
        return "rolled_back"

    def purge_quarantined(self) -> None:
        """Delete quarantined old-geometry dirs — only called once a
        new-world step AND its meta are durable (they are garbage from
        then on). Step dirs go first and the commit marker LAST: a crash
        mid-purge must leave either marker+dirs (re-adopt, idempotent) or
        marker-with-no-dirs — never orphaned old-world dirs without the
        marker, which the recovery path would roll back into a live
        directory already described by the NEW meta."""
        import shutil

        if jax.process_index() == 0:
            q = self.directory / QUARANTINE_DIR
            for p in self._quarantined():
                shutil.rmtree(p, ignore_errors=True)
            shutil.rmtree(q, ignore_errors=True)

    # -- run metadata -----------------------------------------------------
    # guards resume against a changed run geometry (batch size / world size
    # shift the meaning of state.step, silently corrupting the data order)
    def write_meta(self, meta: dict) -> None:
        if jax.process_index() == 0:
            atomic_write_json(self.directory, "tpudist_meta.json", meta)

    def read_meta(self) -> dict | None:
        import json

        p = self.directory / "tpudist_meta.json"
        return json.loads(p.read_text()) if p.exists() else None

    # -- last-known-good anchor (tpudist.resilience.repair) ----------------
    def write_anchor(self, step: int) -> None:
        """Promote ``step`` to the last-known-good rollback target. The
        PROMOTION rule (K clean health steps after the save) lives in
        the repair controller — this is only the durable marker, shared
        by ``_prune``'s exemption and the next generation's bring-up."""
        if jax.process_index() == 0:
            atomic_write_json(self.directory, ANCHOR_FILE,
                              {"step": int(step)})

    def read_anchor(self) -> int | None:
        import json

        p = self.directory / ANCHOR_FILE
        if not p.exists():
            return None
        try:
            return int(json.loads(p.read_text())["step"])
        except (ValueError, KeyError, TypeError, OSError):
            return None

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def latest_step(directory: str | Path) -> int | None:
    """Newest saved step under ``directory`` — a pure directory scan, no
    CheckpointManager lifecycle (Orbax step dirs are bare integers; in-flight
    tmp dirs carry a suffix and are skipped)."""
    p = Path(directory)
    if not p.exists():
        return None
    steps = [int(d.name) for d in p.iterdir() if d.is_dir() and d.name.isdigit()]
    return max(steps, default=None)
