"""Checkpoint / resume — sharding-aware training-state persistence.

The reference has NO checkpointing (verified in SURVEY.md §5: no
``state_dict``/``torch.save`` anywhere; training always starts from random
init, /root/reference/main.py:40, and the process exits without persisting).
tpudist adds it as a capability extension because on TPU pods it is the
failure-recovery story (SURVEY.md §5 notes fail-fast is the reference's only
answer): the launcher restarts a dead world and training resumes from the
last saved step.

Built on Orbax, the TPU-native checkpoint layer: saves are async (the step
loop keeps running while the previous checkpoint flushes), every process
writes only its own shards of sharded arrays (TP/FSDP states don't gather),
and restore places leaves directly onto the mesh according to a target
sharding tree.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tpudist.train import TrainState


@dataclasses.dataclass
class Checkpointer:
    """Manages a directory of step-numbered TrainState checkpoints.

    >>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(state)                        # async; step from state.step
    >>> state = ckpt.restore(like=state)        # latest, onto state's shardings
    >>> ckpt.latest_step()
    """

    directory: str | Path
    max_to_keep: int = 3

    def __post_init__(self):
        self.directory = Path(self.directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    # -- write ------------------------------------------------------------
    def save(self, state: TrainState, step: int | None = None,
             wait: bool = False) -> bool:
        """Persist ``state`` (async by default). Returns False if this step
        is already saved.

        ``wait=True`` is the EMERGENCY-SAVE contract
        (``tpudist.resilience``): it blocks until the checkpoint — and any
        earlier in-flight async save — is durable on disk, which is what
        fit()'s graceful-preemption path calls before exiting 75. The
        supervisor may relaunch the moment this process dies; only a
        synchronous save guarantees the next generation finds the step it
        was promised."""
        if step is None:
            step = int(state.step)
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()
        return saved

    def wait(self) -> None:
        """Block until in-flight async saves are durable."""
        self._mgr.wait_until_finished()

    # -- read -------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, like: TrainState, step: int | None = None) -> TrainState:
        """Restore a checkpoint onto the placement of ``like``.

        ``like`` supplies the tree structure, dtypes, and shardings (it can
        be a freshly-initialized state); leaves are created directly on the
        devices that own them — no host-side gather.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            like,
        )
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    # -- run metadata -----------------------------------------------------
    # guards resume against a changed run geometry (batch size / world size
    # shift the meaning of state.step, silently corrupting the data order)
    def write_meta(self, meta: dict) -> None:
        import json

        if jax.process_index() == 0:
            (self.directory / "tpudist_meta.json").write_text(json.dumps(meta))

    def read_meta(self) -> dict | None:
        import json

        p = self.directory / "tpudist_meta.json"
        return json.loads(p.read_text()) if p.exists() else None

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def latest_step(directory: str | Path) -> int | None:
    """Newest saved step under ``directory`` — a pure directory scan, no
    CheckpointManager lifecycle (Orbax step dirs are bare integers; in-flight
    tmp dirs carry a suffix and are skipped)."""
    p = Path(directory)
    if not p.exists():
        return None
    steps = [int(d.name) for d in p.iterdir() if d.is_dir() and d.name.isdigit()]
    return max(steps, default=None)
