"""Host-side batch transforms: augmentation + normalization.

The reference applies only ``ToTensor`` (/root/reference/main.py:46 —
SURVEY.md §2a notes "no augmentation, no normalization"); ``to_tensor``
in :mod:`tpudist.data.cifar` reproduces that default. This module adds the
standard CIFAR training recipe as an opt-in extension: pad-reflect random
crop + horizontal flip on uint8 (cheap on host, before the float conversion)
then per-channel normalization after it.

Transforms are ``dict -> dict`` callables over the batch (NHWC arrays) and
compose left-to-right with :func:`compose`, matching the DataLoader's
``transform=`` contract. Augmentation randomness is a seeded per-loader
stream: sampler order stays the reference's deterministic permutation, and
(like torch's DataLoader) augmentation noise is NOT replayed exactly across
a mid-epoch checkpoint resume.
"""

from __future__ import annotations

import numpy as np

# torchvision's canonical per-dataset statistics
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_STATS = {
    "cifar10": (CIFAR10_MEAN, CIFAR10_STD),
    "cifar100": (CIFAR100_MEAN, CIFAR100_STD),
    # synthetic mimics the 100-class set (main.py --dataset synthetic)
    "synthetic": (CIFAR100_MEAN, CIFAR100_STD),
    "imagenet": (IMAGENET_MEAN, IMAGENET_STD),
    # sklearn digits (tpudist/data/digits.py), stats of the 0.8 train split
    "digits": (
        np.array([0.3053, 0.3053, 0.3053], np.float32),
        np.array([0.3763, 0.3763, 0.3763], np.float32),
    ),
}


def compose(*fns):
    def run(batch):
        for f in fns:
            batch = f(batch)
        return batch

    return run


def normalize(mean=CIFAR10_MEAN, std=CIFAR10_STD, key: str = "image"):
    """Per-channel (x − mean)/std on float NHWC images."""
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)

    def run(batch):
        out = dict(batch)
        out[key] = (np.asarray(batch[key], np.float32) - mean) / std
        return out

    return run


def random_crop_flip(
    pad: int = 4, flip: bool = True, seed: int = 0, key: str = "image"
):
    """Pad-reflect + random crop back to size, then random horizontal flip.

    Operates on uint8 NHWC before ``to_tensor`` (integer moves are cheaper
    than float). Vectorized: one gather per batch, no per-image python loop.
    """
    rng = np.random.Generator(np.random.PCG64(seed))

    def run(batch):
        img = np.asarray(batch[key])
        n, h, w, c = img.shape
        padded = np.pad(
            img, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
        )
        ys = rng.integers(0, 2 * pad + 1, n)
        xs = rng.integers(0, 2 * pad + 1, n)
        rows = ys[:, None] + np.arange(h)[None, :]          # [n, h]
        cols = xs[:, None] + np.arange(w)[None, :]          # [n, w]
        cropped = padded[
            np.arange(n)[:, None, None], rows[:, :, None], cols[:, None, :]
        ]
        if flip:
            do = rng.random(n) < 0.5
            cropped[do] = cropped[do, :, ::-1]
        out = dict(batch)
        out[key] = cropped
        return out

    return run


def to_tensor_normalize(mean, std, key: str = "image"):
    """ToTensor + per-channel normalize fused into ONE affine on uint8:
    ``(x/255 − mean)/std  ≡  x · 1/(255·std) − mean/std``.

    Advertises a per-channel ``native_spec`` so the C++ core
    (``tpd_gather_u8_to_f32_ch``) can fuse the sampler gather, float
    conversion, and normalization into a single pass with no uint8 or
    unnormalized-float intermediates.
    """
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    scale = (1.0 / 255.0) / std
    shift = -mean / std

    def run(batch):
        out = dict(batch)
        out[key] = np.asarray(batch[key], np.float32) * scale + shift
        return out

    run.native_spec = {key: (scale, shift)}
    return run


def device_normalize(mean, std, dtype=None):
    """The ToTensor+normalize affine of :func:`to_tensor_normalize`, but as
    an IN-GRAPH function for ``make_train_step(input_transform=...)``.

    The loader then ships raw uint8 (``transform=None`` — 4× less
    host→device traffic than float32 and no host float conversion) and the
    affine runs on device, where XLA fuses it into the first conv's input
    read. ``dtype`` casts the result (e.g. ``jnp.bfloat16`` to match a bf16
    model and halve the HBM write); default float32 matches the host path
    bit-for-bit on the affine's f32 arithmetic.
    """
    import jax.numpy as jnp

    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    scale = jnp.asarray((1.0 / 255.0) / std)
    shift = jnp.asarray(-mean / std)

    def run(x):
        out = x.astype(jnp.float32) * scale + shift
        return out.astype(dtype) if dtype is not None else out

    return run


def device_random_crop_flip(pad: int = 4, flip: bool = True, *, seed: int = 0):
    """IN-GRAPH train augmentation — the device twin of
    :func:`random_crop_flip` for batches that never touch the host
    (DeviceCachedLoader gathers, packed memmap batches staged raw).

    Declares ``wants_step``: randomness is keyed by ``(seed, step)`` via
    ``fold_in`` — deterministic, identical across replicas/processes (the
    compiled program is SPMD over the global batch), fresh every step and
    every grad-accumulation microbatch. Reflect-pad + per-sample random
    crop + random horizontal flip, all fused by XLA into the surrounding
    gather/normalize.
    """
    import jax
    import jax.numpy as jnp

    def run(x, step):
        b, h, w, _ = x.shape
        key = jax.random.fold_in(jax.random.key(seed), step)
        ky, kx, kf = jax.random.split(key, 3)
        padded = jnp.pad(
            x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
        )
        ys = jax.random.randint(ky, (b,), 0, 2 * pad + 1)
        xs = jax.random.randint(kx, (b,), 0, 2 * pad + 1)
        rows = ys[:, None] + jnp.arange(h)[None, :]
        cols = xs[:, None] + jnp.arange(w)[None, :]
        out = padded[
            jnp.arange(b)[:, None, None], rows[:, :, None], cols[:, None, :]
        ]
        if flip:
            do = jax.random.bernoulli(kf, 0.5, (b,))
            out = jnp.where(do[:, None, None, None], out[:, :, ::-1, :], out)
        return out

    run.wants_step = True
    return run


def device_compose(*fns):
    """Compose in-graph transforms (for ``make_train_step``'s
    ``input_transform`` / ``DeviceCachedLoader.input_transform``'s
    ``post``); the composite declares ``wants_step`` iff any part does,
    and the step reaches exactly the parts that asked for it."""

    def run(x, step=None):
        for f in fns:
            x = f(x, step) if getattr(f, "wants_step", False) else f(x)
        return x

    run.wants_step = any(getattr(f, "wants_step", False) for f in fns)
    return run


def standard_cifar_augment(seed: int = 0, dataset: str = "cifar10"):
    """crop(pad 4) + flip → fused ToTensor+normalize — the standard CIFAR
    training pipeline (the reference's is ToTensor only), with the named
    dataset's normalization statistics."""
    mean, std = _STATS[dataset]
    return compose(random_crop_flip(seed=seed), to_tensor_normalize(mean, std))


def standard_cifar_eval(dataset: str = "cifar10"):
    """The SAME statistics as :func:`standard_cifar_augment` (no crop/flip)
    — the matching eval-time transform; keep the pair together so
    train/eval can't diverge. Rides the fused native gather."""
    mean, std = _STATS[dataset]
    return to_tensor_normalize(mean, std)
