"""CIFAR-10/100 dataset — the reference's workload
(``torchvision.datasets.CIFAR100(download=True)``, /root/reference/main.py:43-51).

Self-contained loader: downloads the official tarball, parses the python
pickle batches into numpy NHWC uint8 (TPU-native layout; torchvision is
CHW), and caches under ``root``. Two deliberate deviations, both recorded in
SURVEY.md:

- **download race fixed** (§5): the reference lets every rank call
  ``download=True`` concurrently on a shared filesystem; here only process 0
  downloads and the rest wait on a barrier.
- transform parity: the reference applies only ``ToTensor`` (float32 in
  [0,1], no normalization/augmentation — §2a); :func:`to_tensor` reproduces
  exactly that.

For hermetic/egress-free runs, :func:`synthetic_cifar` generates a
deterministic class-separable dataset with the same shapes/dtypes, used by
the test suite and ``--synthetic`` mode.
"""

from __future__ import annotations

import os
import pickle
import tarfile
import urllib.request
from pathlib import Path

import numpy as np

_SPECS = {
    "cifar10": dict(
        url="https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
        dirname="cifar-10-batches-py",
        train_files=[f"data_batch_{i}" for i in range(1, 6)],
        test_files=["test_batch"],
        label_key=b"labels",
        num_classes=10,
    ),
    "cifar100": dict(
        url="https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
        dirname="cifar-100-python",
        train_files=["train"],
        test_files=["test"],
        label_key=b"fine_labels",
        num_classes=100,
    ),
}


def _download(root: Path, spec: dict) -> None:
    """Rank-0-guarded download + extract (fixes the reference's race)."""
    import jax

    from tpudist.distributed import barrier

    target = root / spec["dirname"]
    if not target.exists() and jax.process_index() == 0:
        root.mkdir(parents=True, exist_ok=True)
        tar_path = root / Path(spec["url"]).name
        if not tar_path.exists():
            # download to a temp name then rename, so an interrupted fetch
            # can't leave a truncated tarball that poisons every later run
            tmp_path = tar_path.with_suffix(".tmp")
            try:
                urllib.request.urlretrieve(spec["url"], tmp_path)
                tmp_path.rename(tar_path)
            except OSError as e:
                tmp_path.unlink(missing_ok=True)
                raise RuntimeError(
                    f"could not download {spec['url']} ({e}). Either place "
                    f"the extracted dataset at {root / spec['dirname']}, or "
                    "run with --dataset synthetic for an egress-free stand-in."
                ) from e
        with tarfile.open(tar_path, "r:gz") as tf:
            tf.extractall(root)
    # every process joins the barrier unconditionally — a late-arriving
    # process that already sees the extracted dataset must not strand rank 0
    barrier("cifar-download")


def load_cifar(
    root: str | os.PathLike = "dataset",
    dataset: str = "cifar100",
    train: bool = True,
    download: bool = True,
) -> dict[str, np.ndarray]:
    """Returns ``{"image": (N,32,32,3) uint8, "label": (N,) int32}``."""
    spec = _SPECS[dataset]
    root = Path(root)
    if download:
        _download(root, spec)
    files = spec["train_files"] if train else spec["test_files"]
    images, labels = [], []
    for fname in files:
        with open(root / spec["dirname"] / fname, "rb") as f:
            entry = pickle.load(f, encoding="bytes")
        data = entry[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(data)
        labels.extend(entry[spec["label_key"]])
    return {
        "image": np.concatenate(images).astype(np.uint8),
        "label": np.asarray(labels, np.int32),
    }


def synthetic_cifar(
    n: int = 2048,
    num_classes: int = 100,
    image_size: int = 32,
    seed: int = 0,
) -> dict[str, np.ndarray]:
    """Deterministic class-separable stand-in with CIFAR shapes/dtypes.

    Each class has a fixed random template; samples are template + noise, so
    a real model can drive the loss down (needed by the loss-decrease smoke
    test, SURVEY.md §4).
    """
    rng = np.random.Generator(np.random.PCG64(seed))
    templates = rng.integers(0, 256, (num_classes, image_size, image_size, 3))
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    noise = rng.normal(0, 24, (n, image_size, image_size, 3))
    images = np.clip(templates[labels] * 0.7 + 64 + noise, 0, 255).astype(np.uint8)
    return {"image": images, "label": labels}


def to_tensor(batch: dict) -> dict:
    """The reference's ``ToTensor`` transform (/root/reference/main.py:46):
    uint8 [0,255] → float32 [0,1]; layout stays NHWC (TPU-native)."""
    out = dict(batch)
    out["image"] = np.asarray(batch["image"], np.float32) / 255.0
    return out


# lets the native DataLoader path (tpudist/data/native.py) fuse this
# transform into the C++ batch gather: image = u8 * (1/255) + 0
to_tensor.native_spec = {"image": (1.0 / 255.0, 0.0)}
