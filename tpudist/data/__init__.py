from tpudist.data.sampler import DistributedSampler
from tpudist.data.loader import DataLoader

__all__ = ["DistributedSampler", "DataLoader"]
