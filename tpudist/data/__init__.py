from tpudist.data.sampler import DistributedSampler
from tpudist.data.loader import DataLoader
from tpudist.data.imagenet import ImageFolderLoader
from tpudist.data.lm import TokenWindowLoader

__all__ = [
    "DistributedSampler",
    "DataLoader",
    "ImageFolderLoader",
    "TokenWindowLoader",
]
