"""Deterministic per-rank data sharding.

Re-implements the *semantics* of ``torch.utils.data.DistributedSampler`` as
driven by the reference (/root/reference/main.py:53,93 — all-default
construction, so ``shuffle=True``, ``seed=0``, ``drop_last=False``):

1. permutation of ``len(dataset)`` indices keyed by ``seed + epoch``
   (``set_epoch`` re-keys the shuffle each epoch, /root/reference/main.py:89-93);
2. pad to a multiple of ``num_replicas`` by wrapping indices from the head
   (``drop_last=False`` default) — or truncate when ``drop_last=True``;
3. strided subsample ``indices[rank::num_replicas]``.

The permutation itself comes from numpy's PCG64 rather than torch's MT19937 —
bit-identical torch order is not a capability, determinism and
disjoint-coverage are (SURVEY.md §2.6).

On TPU the "rank" that consumes a shard is a *process* (host), and the
process's shard is further split across its local devices by
``mesh.shard_batch``; using ``rank=process_index, num_replicas=process_count``
reproduces the reference's per-worker disjointness at host granularity.
"""

from __future__ import annotations

import math
from typing import Iterator, Sequence

import numpy as np


class DistributedSampler:
    """Index sampler yielding this rank's shard of the dataset each epoch."""

    def __init__(
        self,
        dataset_size: int | Sequence,
        num_replicas: int | None = None,
        rank: int | None = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not isinstance(dataset_size, int):
            dataset_size = len(dataset_size)
        if num_replicas is None or rank is None:
            import jax

            num_replicas = jax.process_count() if num_replicas is None else num_replicas
            rank = jax.process_index() if rank is None else rank
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_size % num_replicas != 0:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = math.ceil(dataset_size / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Re-key the shuffle for a new epoch — without this every epoch
        replays the same order (the exact pitfall the reference's comment
        warns about, /root/reference/main.py:89-92)."""
        self.epoch = epoch

    def _indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.Generator(np.random.PCG64(self.seed + self.epoch))
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if self.drop_last:
            indices = indices[: self.total_size]
        else:
            pad = self.total_size - len(indices)
            if pad > 0:
                # wrap from the head, repeating the whole sequence if the pad
                # exceeds the dataset (torch semantics)
                reps = math.ceil(pad / len(indices))
                indices = np.concatenate([indices, np.tile(indices, reps)[:pad]])
        assert len(indices) == self.total_size
        return indices[self.rank :: self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self._indices().tolist())

    def epoch_indices(self) -> np.ndarray:
        """This rank's full index shard for the current epoch (vectorized
        form of ``__iter__`` for array-at-once loaders)."""
        return self._indices()

    def __len__(self) -> int:
        return self.num_samples
