"""Pre-decoded packed image datasets — the streaming-ImageNet throughput fix.

The reference's input pipeline decodes JPEGs on the host every epoch
(/root/reference/main.py:54-63 drives torchvision's loader; an ImageFolder
re-decodes every sample every pass). At BASELINE configs 2/3 scale a TPU
chip consumes ~2,570 images/sec, but PIL JPEG decode tops out at O(100)
images/sec per host core — on a small-host TPU attach the streaming path is
decode-bound no matter how deep the prefetch queue in front of it
(docs/PERF.md §3c has the measured math). The TPU-native fix is the MLPerf
one: **decode once, train from pixels**.

:func:`pack_image_folder` runs the one-time pass: scan the class tree
(torchvision ``ImageFolder`` semantics, same scan as
``tpudist.data.imagenet``), decode every image through the deterministic
eval transform (resize-short-side + center crop — bit-identical to
``ImageFolderLoader(train=False)`` pixels), and write a fixed-shape uint8
memmap:

- ``<prefix>_images.npy`` — ``[N, size, size, 3]`` uint8, written through a
  memmap so the pack never holds the dataset in RAM;
- ``<prefix>_labels.npy`` — ``[N]`` int32;
- ``<prefix>_meta.json`` — class names + image size + provenance.

:func:`load_packed` memory-maps the pack back as the ordinary
``{"image", "label"}`` array dataset, so the WHOLE existing array pipeline
applies unchanged: ``DataLoader`` (C++ fused gather) streams batches at
memcpy speed (~GB/s, 30×+ the decode rate), and ``DeviceCachedLoader``
stages the pack to HBM once and ships only indices per step — the two
framework answers to a decode-bound and a link-bound attach respectively.

Trade-off, stated plainly: packed pixels are the EVAL transform, so the
per-epoch RandomResizedCrop augmentation of the streaming loader does not
apply — use :class:`tpudist.data.imagenet.ImageFolderLoader` when the
recipe needs fresh crops and the host has the cores to decode them; pack
when input throughput is the binding constraint (the SURVEY.md §7 hard-part
#1 regime).

CLI::

    python -m tpudist.data.packed --root /data/imagenet/train --out inpack \
        --image_size 224
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np

from tpudist.data.imagenet import _resize_center_crop, scan_image_folder


def pack_image_folder(
    root: str | os.PathLike,
    out_prefix: str | os.PathLike,
    *,
    image_size: int = 224,
    workers: int | None = None,
    classes: list[str] | None = None,
) -> dict:
    """One-time decode pass: image-folder tree → packed uint8 memmap.

    Returns a summary dict (``n``, ``seconds``, ``images_per_sec``,
    ``bytes``) — the pack rate IS the host's sustained JPEG decode rate,
    which docs/PERF.md §3c compares against the chip's consumption rate.
    Pass the train split's ``classes`` when packing a val split (same
    label-stability contract as ``scan_image_folder``).
    """
    paths, labels, classes = scan_image_folder(root, classes)
    n = len(paths)
    out_prefix = str(out_prefix)
    workers = (
        max(1, workers) if workers is not None
        else min(os.cpu_count() or 8, 16)
    )

    from PIL import Image

    def decode(i: int) -> None:
        with Image.open(paths[i]) as img:
            img = _resize_center_crop(img.convert("RGB"), image_size)
            images[i] = np.asarray(img, np.uint8)

    t0 = time.perf_counter()
    # write-through memmap: the pack never materializes the dataset in RAM
    images = np.lib.format.open_memmap(
        out_prefix + "_images.npy", mode="w+", dtype=np.uint8,
        shape=(n, image_size, image_size, 3),
    )
    with ThreadPoolExecutor(max_workers=workers) as pool:
        # consume the iterator to surface decode errors
        for _ in pool.map(decode, range(n)):
            pass
    images.flush()
    dt = time.perf_counter() - t0
    np.save(out_prefix + "_labels.npy", np.asarray(labels, np.int32))
    meta = {
        "classes": classes,
        "image_size": image_size,
        "n": n,
        "source_root": str(Path(root).resolve()),
        "transform": "resize_short_side_256/224 + center_crop (eval)",
    }
    with open(out_prefix + "_meta.json", "w") as f:
        json.dump(meta, f)
    return {
        "n": n,
        "seconds": dt,
        "images_per_sec": n / dt if dt > 0 else float("inf"),
        "bytes": int(images.nbytes),
    }


def load_packed(prefix: str | os.PathLike, *, mmap: bool = True) -> dict:
    """Packed dataset → ``{"image": [N,s,s,3] uint8, "label": [N] int32,
    "classes": [...]}``.

    ``mmap=True`` (default) memory-maps the pixels: batch gathers fault in
    only the pages they touch, so a pack larger than RAM still streams.
    The returned dict drops straight into ``DataLoader`` /
    ``DeviceCachedLoader`` / ``evaluate``.
    """
    prefix = str(prefix)
    with open(prefix + "_meta.json") as f:
        meta = json.load(f)
    images = np.load(
        prefix + "_images.npy", mmap_mode="r" if mmap else None
    )
    labels = np.load(prefix + "_labels.npy")
    if images.shape[0] != meta["n"] or images.shape[0] != labels.shape[0]:
        raise ValueError(
            f"pack {prefix} is inconsistent: images {images.shape[0]} rows, "
            f"labels {labels.shape[0]}, meta n={meta['n']} — repack"
        )
    return {"image": images, "label": labels, "classes": meta["classes"]}


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--root", required=True,
                    help="image-folder tree (root/<class>/*.jpg)")
    ap.add_argument("--out", required=True, help="output file prefix")
    ap.add_argument("--image_size", type=int, default=224)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--classes_from", default=None,
                    help="train-split pack prefix whose class list keys the "
                    "labels (pass when packing a val split)")
    args = ap.parse_args(argv)
    classes = None
    if args.classes_from:
        with open(args.classes_from + "_meta.json") as f:
            classes = json.load(f)["classes"]
    out = pack_image_folder(
        args.root, args.out, image_size=args.image_size,
        workers=args.workers, classes=classes,
    )
    print(
        f"packed {out['n']} images ({out['bytes'] / 1e6:.0f} MB) in "
        f"{out['seconds']:.1f}s = {out['images_per_sec']:.0f} images/sec "
        f"sustained decode"
    )


if __name__ == "__main__":
    main()
