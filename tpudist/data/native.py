"""ctypes wrapper over the C++ batch-assembly core (tpudist/csrc/batcher.cpp).

This is the native half of the DataLoader — the TPU-native counterpart of
torch's C++ DataLoader machinery (worker pool + pinned staging,
/root/reference/main.py:54-63, SURVEY.md §2.7). The hot operation is
gathering the sampler's index shard into one contiguous batch, fused with
the ToTensor uint8→float32 scale (/root/reference/main.py:46); both run on
a persistent C++ thread pool. Falls back to numpy transparently when the
native library is unavailable (see :mod:`tpudist.csrc`).
"""

from __future__ import annotations

import atexit
import threading

import numpy as np

from tpudist import csrc


def _require_contiguous(src: np.ndarray) -> None:
    if not src.flags["C_CONTIGUOUS"]:
        raise ValueError(
            "native gather requires a C-contiguous source array; "
            "np.ascontiguousarray it once up front"
        )


def _checked_indices(idx: np.ndarray, n: int) -> np.ndarray:
    """Validate + normalize indices to int64 with numpy semantics (negative
    indices wrap; out-of-range raises) — the C side trusts its pointers."""
    idx = np.ascontiguousarray(idx, np.int64)
    lo, hi = (int(idx.min()), int(idx.max())) if len(idx) else (0, -1)
    if lo < -n or hi >= n:
        raise IndexError(f"index out of range for axis of size {n} "
                         f"(min {lo}, max {hi})")
    if lo < 0:
        idx = np.where(idx < 0, idx + n, idx)
    return idx


class NativeBatcher:
    """A persistent C++ thread pool with parallel gather kernels."""

    def __init__(self, num_threads: int = 0):
        lib = csrc.lib()
        if lib is None:
            raise RuntimeError("tpudist native core unavailable")
        self._lib = lib
        self._pool = lib.tpd_pool_create(num_threads)
        if not self._pool:
            raise RuntimeError("tpd_pool_create failed")

    @property
    def num_threads(self) -> int:
        return self._lib.tpd_pool_size(self._pool)

    def close(self) -> None:
        if self._pool:
            self._lib.tpd_pool_destroy(self._pool)
            self._pool = None

    def gather(self, src: np.ndarray, idx: np.ndarray) -> np.ndarray:
        """out[i] = src[idx[i]] — dtype-preserving parallel row gather.

        ``src`` must be C-contiguous (the caller owns that invariant; a
        silent per-batch full copy here would defeat the fast path).
        """
        _require_contiguous(src)
        idx = _checked_indices(idx, len(src))
        out = np.empty((len(idx),) + src.shape[1:], src.dtype)
        item_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
        self._lib.tpd_gather_rows(
            self._pool,
            src.ctypes.data, item_bytes,
            idx.ctypes.data, len(idx),
            out.ctypes.data,
        )
        return out

    def gather_u8_to_f32(
        self, src: np.ndarray, idx: np.ndarray,
        scale: float = 1.0 / 255.0, shift: float = 0.0,
    ) -> np.ndarray:
        """out[i] = float32(src[idx[i]]) * scale + shift, in one fused pass —
        the sampler gather + ToTensor conversion with no uint8 intermediate."""
        if src.dtype != np.uint8:
            raise TypeError(f"expected uint8 source, got {src.dtype}")
        _require_contiguous(src)
        idx = _checked_indices(idx, len(src))
        out = np.empty((len(idx),) + src.shape[1:], np.float32)
        item_elems = int(np.prod(src.shape[1:], dtype=np.int64))
        self._lib.tpd_gather_u8_to_f32(
            self._pool,
            src.ctypes.data, item_elems,
            idx.ctypes.data, len(idx),
            out.ctypes.data,
            scale, shift,
        )
        return out


    def gather_u8_to_f32_channels(
        self, src: np.ndarray, idx: np.ndarray,
        scale: np.ndarray, shift: np.ndarray,
    ) -> np.ndarray:
        """out[i][..., c] = f32(src[idx[i]][..., c]) * scale[c] + shift[c] —
        the gather fused with ToTensor + per-channel normalization
        ((x/255 − mean)/std folds to one affine per channel)."""
        if src.dtype != np.uint8:
            raise TypeError(f"expected uint8 source, got {src.dtype}")
        _require_contiguous(src)
        if src.ndim < 2:
            # a 1-D source would make channels == len(src) and the kernel
            # would read scale/shift far out of bounds
            raise ValueError(
                "per-channel gather needs src.ndim >= 2 ([N, ..., C]); got "
                f"shape {src.shape}"
            )
        channels = src.shape[-1]
        scale = np.ascontiguousarray(scale, np.float32)
        shift = np.ascontiguousarray(shift, np.float32)
        if scale.shape != (channels,) or shift.shape != (channels,):
            raise ValueError(
                f"scale/shift must be shape ({channels},) to match the "
                f"innermost source dim; got {scale.shape}/{shift.shape}"
            )
        idx = _checked_indices(idx, len(src))
        out = np.empty((len(idx),) + src.shape[1:], np.float32)
        item_elems = int(np.prod(src.shape[1:], dtype=np.int64))
        self._lib.tpd_gather_u8_to_f32_ch(
            self._pool,
            src.ctypes.data, item_elems, channels,
            idx.ctypes.data, len(idx),
            out.ctypes.data,
            scale.ctypes.data, shift.ctypes.data,
        )
        return out


_default: NativeBatcher | None = None
_default_lock = threading.Lock()
_default_failed = False


def default_batcher() -> NativeBatcher | None:
    """Process-wide shared batcher (or None when native is unavailable)."""
    global _default, _default_failed
    if _default is not None or _default_failed:
        return _default
    with _default_lock:
        if _default is not None or _default_failed:
            return _default
        try:
            _default = NativeBatcher()
            atexit.register(_default.close)
        except Exception:
            _default_failed = True
    return _default


def native_batch(dataset, idx: np.ndarray, transform) -> dict | None:
    """Assemble a batch through the native core, or None if it can't.

    ``transform`` participates when it advertises a ``native_spec``
    (mapping key → (scale, shift) for fused uint8→f32 conversion —
    scalars, e.g. :func:`tpudist.data.cifar.to_tensor`, or per-channel
    arrays, e.g. :func:`tpudist.data.transforms.to_tensor_normalize`);
    transforms without a spec force the Python path so arbitrary
    augmentation keeps working.
    """
    b = default_batcher()
    if b is None:
        return None
    spec = getattr(transform, "native_spec", None) if transform is not None else {}
    if spec is None:
        return None
    # the fused path only covers uint8 sources and contiguous arrays; any
    # mismatch falls back to the Python path (which applies the transform)
    # rather than silently skipping the conversion
    for k, v in dataset.items():
        if (k in spec and v.dtype != np.uint8) or not v.flags["C_CONTIGUOUS"]:
            return None
        if k in spec and np.ndim(spec[k][0]) > 0 and (
            v.ndim < 2 or v.shape[-1] != np.shape(spec[k][0])[0]
        ):
            return None  # per-channel spec must match the innermost dim
    out = {}
    for k, v in dataset.items():
        if k in spec:
            scale, shift = spec[k]
            if np.ndim(scale) > 0:
                out[k] = b.gather_u8_to_f32_channels(v, idx, scale, shift)
            else:
                out[k] = b.gather_u8_to_f32(v, idx, scale, shift)
        else:
            out[k] = b.gather(v, idx)
    return out
