"""Host-side batch assembly and device staging.

TPU-native replacement for ``DataLoader(pin_memory=True)`` + in-loop
``.cuda()`` copies (/root/reference/main.py:54-63,98-99). The reference's
synchronous per-step H2D copy sits on the critical path (SURVEY.md §7 "hard
parts" #1); here batches are assembled from an in-memory numpy dataset
(vectorized gather — optionally via the C++ batcher in tpudist/csrc) and
staged onto the mesh with ``shard_batch``, with an N-deep prefetch queue so
the copy for step k+1 overlaps the compute of step k.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Iterator, Mapping

import numpy as np

from tpudist.data.sampler import DistributedSampler


class SampledLoader:
    """The shared iterator contract of every tpudist loader.

    Subclasses set ``sampler``, ``batch_size``, ``drop_remainder`` and
    implement ``_gather_batch(indices, start)`` (``start`` = the batch's
    position in the epoch's index stream, for position-keyed augmentation).
    This base provides ``__len__`` / ``__iter__`` / ``iter_from`` — one
    implementation of the drop-remainder and mid-epoch-resume math shared by
    the array-backed, image-folder, and token-window loaders.
    """

    sampler: DistributedSampler
    batch_size: int
    drop_remainder: bool

    def __len__(self) -> int:
        n = self.sampler.num_samples
        return (
            n // self.batch_size
            if self.drop_remainder
            else -(-n // self.batch_size)
        )

    def _gather_batch(self, indices: np.ndarray, start: int) -> dict:
        raise NotImplementedError

    def probe(self) -> dict:
        """A one-SAMPLE batch for shape/dtype inspection — lets ``fit`` learn
        the element spec without gathering (for the image loader: decoding)
        a full per-process batch that the epoch loop will re-gather anyway."""
        return self._gather_batch(self.sampler.epoch_indices()[:1], 0)

    def __iter__(self) -> Iterator[dict]:
        return self.iter_from(0)

    def iter_from(self, start_batch: int) -> Iterator[dict]:
        """Iterate this epoch starting at batch ``start_batch`` — index-level
        skip for mid-epoch resume (no gather/transform work for the skipped
        batches, unlike islice over __iter__)."""
        indices = self.sampler.epoch_indices()
        limit = len(self) * self.batch_size if self.drop_remainder else len(indices)
        for start in range(start_batch * self.batch_size, limit, self.batch_size):
            yield self._gather_batch(indices[start : start + self.batch_size], start)


class DataLoader(SampledLoader):
    """Iterates minibatches of an array-backed dataset for one epoch.

    ``dataset`` is a mapping of name → numpy array, all with equal leading
    dimension (e.g. ``{"image": (N,32,32,3) uint8, "label": (N,) int32}``).
    A ``DistributedSampler`` supplies this rank's index shard; batches are
    gathered host-side and handed to ``transform`` (e.g. uint8→float32
    normalization, augmentation) before staging.

    Matches the reference loader's contract: ``shuffle=False`` at the loader
    (the sampler owns shuffling, /root/reference/main.py:56-58) and
    ``drop_last=False`` → final short batch is dropped only if
    ``drop_remainder`` (pjit needs static shapes, so the default drops the
    ragged tail — with the sampler's padding this loses < one batch/epoch).
    """

    def __init__(
        self,
        dataset: Mapping[str, np.ndarray],
        batch_size: int,
        sampler: DistributedSampler | None = None,
        transform: Callable[[dict], dict] | None = None,
        drop_remainder: bool = True,
        native: bool = True,
    ):
        sizes = {k: len(v) for k, v in dataset.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"ragged dataset arrays: {sizes}")
        self.dataset = dict(dataset)
        self.size = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.sampler = sampler or DistributedSampler(
            self.size, num_replicas=1, rank=0, shuffle=False
        )
        self.transform = transform
        self.drop_remainder = drop_remainder
        # native=True routes batch assembly through the C++ core (parallel
        # gather fused with the ToTensor conversion, tpudist/csrc/batcher.cpp)
        # when the library is available and the transform supports it; the
        # numpy path below is the always-available fallback
        self.native = native

    def _gather_batch(self, idx: np.ndarray, start: int) -> dict:
        if self.native:
            from tpudist.data.native import native_batch

            batch = native_batch(self.dataset, idx, self.transform)
            if batch is not None:
                return batch
        batch = {k: v[idx] for k, v in self.dataset.items()}
        if self.transform is not None:
            batch = self.transform(batch)
        return batch


def prefetch_to_mesh(iterator, mesh, *, depth: int = 2, stage_fn=None,
                     stop_check=None, stop_poll_s: float = 0.5):
    """Stage host batches onto the device mesh ``depth`` steps ahead.

    The replacement for pinned-memory + synchronous ``.cuda()``: device_put
    is async in JAX, so keeping ``depth`` batches in flight overlaps host
    gather + H2D DMA with on-device compute. A background thread runs the
    host-side gather/transform so it too leaves the critical path.

    ``stage_fn`` overrides the default flat-batch sharding (used e.g. by the
    grad-accumulation path, which folds a microbatch dim in first).

    ``stop_check`` (polled every ``stop_poll_s`` while the consumer waits
    on the producer): returning True ends the stream EARLY — already
    staged batches still drain, then the generator finishes as if the
    epoch ended. fit() passes its preemption flag here: a SIGTERM landing
    while the input pipeline is STALLED (a wedged data source, realistic
    at exactly preemption time) must still reach the graceful
    emergency-checkpoint path instead of blocking in a timeout-less wait
    until the scheduler's SIGKILL.
    """
    from tpudist.mesh import shard_batch

    queue: collections.deque = collections.deque()
    host_q: collections.deque = collections.deque()
    lock = threading.Condition()
    DONE = object()
    abandoned = False  # set when the consumer drops the generator early

    def _producer():
        try:
            for item in iterator:
                with lock:
                    while len(host_q) >= depth + 1 and not abandoned:
                        lock.wait()
                    if abandoned:
                        return
                    host_q.append(item)
                    lock.notify_all()
        except BaseException as e:  # surface loader errors to the consumer
            with lock:
                host_q.append(e)
                lock.notify_all()
        finally:
            with lock:
                host_q.append(DONE)
                lock.notify_all()

    thread = threading.Thread(target=_producer, daemon=True)
    thread.start()

    def _next_host():
        """Next host item, the producer's error object, or DONE. Producer
        errors are RETURNED (so the consumer can defer them behind staged
        batches); exceptions raised here — e.g. a KeyboardInterrupt during
        the wait — propagate immediately. With ``stop_check``, a stalled
        wait polls the flag and reports DONE on a stop — the producer
        thread is retired by the generator's finally."""
        with lock:
            while not host_q:
                if stop_check is not None and stop_check():
                    return DONE
                lock.wait(None if stop_check is None else stop_poll_s)
            item = host_q.popleft()
            lock.notify_all()
        return item

    if stage_fn is None:
        stage_fn = lambda b: shard_batch(b, mesh)

    try:
        finished = False
        pending_err: BaseException | None = None
        while True:
            while not finished and pending_err is None and len(queue) < depth:
                item = _next_host()
                if item is DONE:
                    finished = True
                elif isinstance(item, BaseException):
                    # deliver every batch staged BEFORE the loader died, then
                    # the error — the already-good work (e.g. a step that
                    # crosses a checkpoint boundary) isn't discarded with it.
                    # Only producer-delivered errors defer; a KeyboardInterrupt
                    # in THIS thread propagates from _next_host immediately.
                    pending_err = item
                else:
                    queue.append(stage_fn(item))
            if queue:
                yield queue.popleft()
            elif pending_err is not None:
                raise pending_err
            else:
                return
    finally:
        # unblock and retire the producer if the consumer bailed mid-epoch
        with lock:
            abandoned = True
            host_q.clear()
            lock.notify_all()
