"""Language-model token datasets — the input path for BASELINE.json config 5
(GPT-2 124M on OpenWebText-scale corpora).

The reference's data layer holds a decoded array in memory
(/root/reference/main.py:42-63); a web-scale token stream (OpenWebText is
~9B tokens) cannot be materialized per host, so this module reads windows
lazily from a memory-mapped flat token file and gathers only the rows a
batch needs (one fancy-index on the memmap touches only those pages).

Two on-disk formats, both zero-copy:

- ``.npy`` — any integer dtype, read with ``np.load(mmap_mode="r")``;
- ``.bin`` — raw little-endian tokens (the nanoGPT/OpenWebText convention),
  read with ``np.memmap``; dtype defaults to uint16 (GPT-2's 50257-entry
  vocab fits).

:class:`TokenWindowLoader` exposes the same iterator contract as
:class:`tpudist.data.loader.DataLoader` (``sampler``/``__len__``/
``iter_from``), so ``fit``/``prefetch_to_mesh``/checkpoint-resume compose
unchanged, and the DistributedSampler gives each host a disjoint shard of
windows (SURVEY.md §2.6 semantics over windows instead of images).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from tpudist.data.loader import SampledLoader
from tpudist.data.sampler import DistributedSampler


def load_token_stream(path: str | os.PathLike, dtype=None) -> np.ndarray:
    """Open a flat token file as a read-only memmap (no materialization)."""
    path = Path(path)
    if path.suffix == ".npy":
        flat = np.load(path, mmap_mode="r")
        if flat.ndim != 1:
            raise ValueError(f"{path}: expected a 1-D token array, got {flat.shape}")
        return flat
    if path.suffix == ".bin":
        return np.memmap(path, dtype=dtype or np.uint16, mode="r")
    raise ValueError(f"{path}: unknown token-file suffix (want .npy or .bin)")


def encode_bytes(text: str | bytes) -> np.ndarray:
    """Byte-level tokenization (vocab 256) — an egress-free stand-in for a
    trained tokenizer, enough to train a real LM on any local text file."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.frombuffer(text, np.uint8).astype(np.int32)


class TokenWindowLoader(SampledLoader):
    """Batches of ``seq_len`` token windows from a flat stream.

    ``source`` is a path (``.npy``/``.bin``) or a 1-D array. Windows start
    every ``stride`` tokens (default ``seq_len``: non-overlapping, each
    token trained on once per epoch). Each window carries one extra token
    when ``targets_in_window`` so the model's shift-by-one loss
    (``tpudist.train.lm_loss``: predict ``tokens[1:]`` from ``tokens[:-1]``)
    loses no positions at window boundaries.

    ``vocab_size`` guards every gathered batch: an out-of-range id (wrong
    ``--token_dtype``, tokenizer/vocab mismatch) raises instead of letting
    XLA's embedding gather clamp it and train silently on wrong vectors —
    the whole stream is never scanned (it's a memmap).

    Yields ``{"tokens": int32 [batch, seq_len(+1 if targets_in_window)]}``.
    """

    def __init__(
        self,
        source,
        batch_size: int,
        seq_len: int,
        *,
        stride: int | None = None,
        dtype=None,
        vocab_size: int | None = None,
        sampler: DistributedSampler | None = None,
        num_replicas: int = 1,
        rank: int = 0,
        seed: int = 0,
        shuffle: bool = True,
        targets_in_window: bool = False,
        drop_remainder: bool = True,
        transform=None,
    ):
        if isinstance(source, (str, os.PathLike)):
            source = load_token_stream(source, dtype=dtype)
        # dict -> dict over the gathered batch, applied after the vocab
        # check — e.g. the BERT MLM corruption
        # (tpudist.models.bert.mlm_transform), same contract as the
        # DataLoader's transform
        self.transform = transform
        self.flat = source
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.stride = stride or seq_len
        self.window = seq_len + (1 if targets_in_window else 0)
        self.drop_remainder = drop_remainder
        if len(self.flat) < self.window:
            raise ValueError(
                f"stream of {len(self.flat)} tokens is shorter than one "
                f"window ({self.window})"
            )
        n_windows = (len(self.flat) - self.window) // self.stride + 1
        self.num_windows = n_windows
        self.vocab_size = vocab_size
        self.sampler = sampler or DistributedSampler(
            n_windows, num_replicas=num_replicas, rank=rank,
            shuffle=shuffle, seed=seed,
        )

    def gather(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        offsets = (
            np.asarray(indices, np.int64)[:, None] * self.stride
            + np.arange(self.window)[None, :]
        )
        tokens = np.asarray(self.flat[offsets], np.int32)
        if self.vocab_size is not None and tokens.size:
            peak = int(tokens.max())
            if peak >= self.vocab_size or int(tokens.min()) < 0:
                raise ValueError(
                    f"token id {peak if peak >= self.vocab_size else int(tokens.min())} "
                    f"outside [0, {self.vocab_size}) — wrong --token_dtype or "
                    "tokenizer/vocab mismatch"
                )
        return {"tokens": tokens}

    def _gather_batch(self, idx: np.ndarray, start: int) -> dict:
        batch = self.gather(idx)
        if self.transform is None:
            return batch
        if getattr(self.transform, "wants_position", False):
            # position-keyed objective transforms (T5 span corruption):
            # (epoch, start) key the randomness, so every epoch draws
            # fresh corruptions AND a mid-epoch resume (iter_from passes
            # the true start) replays the original run's stream exactly
            return self.transform(batch, self.sampler.epoch, start)
        return self.transform(batch)
