"""Device-resident dataset cache: the input pipeline for link-bound attaches.

The reference stages every batch host→device inside the timed step
(/root/reference/main.py:98-99). That is fine when the staging link keeps up
(a TPU VM's DMA path: ≥8 GB/s against a ~385 MB/s requirement), but on a
remote/tunnel attach the post-compile H2D link collapses to ~25 MB/s
(measured, docs/PERF.md §3) and the *pipeline* becomes the benchmark.

The TPU-native fix (MLPerf-style) is to stop shipping pixels per step:

1. stage the WHOLE uint8 dataset to HBM **once, before the first compiled
   program runs** (the pre-compile link runs at 1.4–1.6 GB/s on the same
   attach — 60× the degraded rate; on any attach it removes per-step pixel
   traffic entirely). CIFAR-100 is 150 MB; the bench's synthetic ImageNet
   set is 385 MB — both noise against 16 GB HBM;
2. per step, ship only the sampler's **indices** (a few KB) and gather the
   batch in-graph (``jnp.take``), fused by XLA straight into the normalize
   + first-conv read.

The loader yields ``{input_key: indices, label_key: labels}`` and exposes
:meth:`input_transform` — the in-graph ``indices → normalized images``
function to pass to ``make_train_step(input_transform=...)`` /
``evaluate(input_transform=...)``. The per-epoch shuffle is the SAME
``DistributedSampler`` order as the host loaders (seed+epoch permutation),
so switching loaders does not change the data order.

Multi-process: every process stages the full replicated cache (one
pre-compile H2D each) and ships its own rank's index shard per step; the
gather stays collective-free because the cache is replicated.
"""

from __future__ import annotations

import functools
import logging
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from tpudist import mesh as mesh_lib
from tpudist.data.sampler import DistributedSampler

logger = logging.getLogger(__name__)

# the transport-hang guard's slice budget: a single hundreds-of-MB
# device_put has been observed to hang a remote-attach transport outright
# (docs/PERF.md §3b), so every staging path bounds its transfers to this
# many bytes. Module-level so the regression tests can tighten it and
# prove the multi-process rotation path (ADVICE r5) really chunks.
_CHUNK_BYTES = 64 * 1024 * 1024


def _chunked_device_put(
    images: np.ndarray, sharding, *, in_place: bool = False
) -> jax.Array:
    """One H2D of a large array in ~64 MB slices — a single
    hundreds-of-MB ``device_put`` has been observed to hang a
    remote-attach transport outright, and chunking costs nothing on a
    local DMA path. Two assembly modes, each matched to WHEN it runs:

    - default (``in_place=False``): all slices transfer FIRST, then one
      ``concatenate`` compiles/executes. Transient device footprint is 2×
      the array, but every byte rides the fast PRE-compile link — the
      DeviceCachedLoader constructor's contract (docs/PERF.md §3b: the
      degraded attach drops 60× after the first compiled program, and
      measured: interleaving jitted writes with the transfer collapses
      staging from ~1.5 GB/s to ~20 MB/s on that attach).
    - ``in_place=True``: each slice is written into a DONATED device
      buffer (``dynamic_update_slice``), high-water mark ONE buffer plus
      one slice. For mid-training staging (RotatingDeviceCache), where
      compiled programs have already run — the link is whatever it is —
      and shard-sized HBM headroom is the scarce resource."""
    row_bytes = max(images[:1].nbytes, 1)
    rows_per_chunk = max(_CHUNK_BYTES // row_bytes, 1)
    n = images.shape[0]
    if n <= rows_per_chunk:
        return jax.device_put(images, sharding)
    if not in_place:
        pieces = [
            jax.device_put(images[lo: lo + rows_per_chunk], sharding)
            for lo in range(0, n, rows_per_chunk)
        ]
        # enforce the documented order: device_put is async, so without
        # this the concatenate (the process's first compiled program)
        # would dispatch while slices are still streaming on the
        # pre-compile link
        jax.block_until_ready(pieces)
        return jnp.concatenate(pieces, axis=0)
    init, write = _assembly_fns(images.shape, images.dtype.str, sharding)
    buf = init()
    for lo in range(0, n, rows_per_chunk):
        piece = jax.device_put(images[lo: lo + rows_per_chunk], sharding)
        buf = write(buf, piece, lo)
    return buf


def _chunked_replicated_put(x: np.ndarray, sharding) -> jax.Array:
    """Multi-process-safe chunked staging of a REPLICATED value.

    ``put_sharded``'s multi-process path
    (``make_array_from_process_local_data``) issues ONE full-shard
    ``device_put`` per device — for a GB-scale rotation shard that is
    exactly the single hundreds-of-MB transfer the ~64 MB
    ``_chunked_device_put`` guard exists to prevent (observed to hang a
    remote-attach transport outright). This constructor keeps BOTH
    disciplines at once:

    - **chunked**: per addressable device, the full value is assembled in
      ~64 MB slices into a donated single-device buffer
      (``_chunked_device_put(..., in_place=True)`` under a
      ``SingleDeviceSharding``);
    - **local-only** (the 2-process-deadlock fix, see ``_stage``): every
      operation here is either a transfer or a single-device,
      collective-free compiled program — nothing lockstep, so per-process
      issue orders may diverge freely while the main thread runs
      collective train steps. The final
      ``make_array_from_single_device_arrays`` is metadata-only.
    """
    from jax.sharding import SingleDeviceSharding

    bufs = [
        _chunked_device_put(x, SingleDeviceSharding(d), in_place=True)
        for d in sorted(sharding.addressable_devices, key=lambda d: d.id)
    ]
    return jax.make_array_from_single_device_arrays(x.shape, sharding, bufs)


@functools.lru_cache(maxsize=64)  # 8 local devices x a few shard shapes
def _assembly_fns(shape: tuple, dtype_str: str, sharding):
    """Jitted (zeros-init, donated-write) pair for in-place assembly,
    cached per (shape, dtype, sharding): jit's executable cache keys on
    the function object, so fresh lambdas per shard would re-compile the
    same two programs on every rotation (measured: 2 compiles per call)."""
    dtype = np.dtype(dtype_str)
    init = jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)
    write = jax.jit(
        lambda b, piece, lo: jax.lax.dynamic_update_slice(
            b, piece, (lo,) + (0,) * (b.ndim - 1)
        ),
        donate_argnums=0,
        out_shardings=sharding,
    )
    return init, write


class DeviceCachedLoader:
    """Iterable of index batches over an HBM-cached dataset.

    Parameters
    ----------
    dataset: mapping with the image array (any dtype; uint8 recommended —
        4× smaller to stage) and per-row labels.
    batch_size: per-process batch (rows this process contributes per step).
    mesh: the device mesh the cache is replicated over.
    sampler: optional pre-built DistributedSampler (defaults to a
        shuffle-on sampler over this process's rank).
    drop_remainder: drop the ragged tail (training default True).
    stage_in_place: assemble the cache with the 1×-transient donated-buffer
        mode instead of the default transfer-all-then-concatenate (which
        transiently holds 2× the array). Turn on for datasets near HBM
        capacity; costs the fast pre-compile link on degraded remote
        attaches (see ``_chunked_device_put``).
    """

    def __init__(
        self,
        dataset: Mapping[str, np.ndarray],
        batch_size: int,
        *,
        mesh=None,
        sampler: DistributedSampler | None = None,
        input_key: str = "image",
        label_key: str = "label",
        drop_remainder: bool = True,
        seed: int = 0,
        stage_in_place: bool = False,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh()
        self.batch_size = batch_size
        self.input_key = input_key
        self.label_key = label_key
        self.drop_remainder = drop_remainder
        images = np.ascontiguousarray(dataset[input_key])
        n = images.shape[0]
        self.sampler = sampler or DistributedSampler(
            n,
            num_replicas=jax.process_count(),
            rank=jax.process_index(),
            seed=seed,
        )
        # labels stay host-side: they ride each index batch (a few KB) so the
        # loss path needs no second gather
        self._labels = np.ascontiguousarray(dataset[label_key])
        # ONE H2D of the full set, replicated over the mesh. Done eagerly at
        # construction — build the loader BEFORE the first compiled program
        # (e.g. before create_train_state) to get the fast pre-compile link
        # on remote attaches. Chunked via _chunked_device_put (transport-
        # hang guard).
        self._cache = _chunked_device_put(
            images, mesh_lib.replicated_sharding(self.mesh),
            in_place=stage_in_place,
        )
        self._img_shape = images.shape[1:]

    def __len__(self) -> int:
        n = self.sampler.num_samples
        if self.drop_remainder:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def probe(self) -> dict:
        """Shape/dtype probe for fit()'s init. Returns an IMAGE-shaped f32
        row (not an index row): fit derives the model's init input from the
        probe, and the model sees post-gather images — float32 so init never
        feeds raw integer pixels to a float conv."""
        return {
            self.input_key: np.zeros((1, *self._img_shape), np.float32),
            self.label_key: self._labels[:1],
        }

    def input_transform(self, post=None):
        """The in-graph ``indices → images`` gather to pass as
        ``make_train_step(input_transform=...)``; ``post`` (e.g.
        :func:`tpudist.data.transforms.device_normalize`, or a
        ``device_compose`` chain with in-graph augmentation) is applied to
        the gathered batch inside the same program. A ``post`` declaring
        ``wants_step`` propagates: the composite receives the step counter
        and hands it through (the augmentation-randomness contract).

        The cache array reaches the compiled program as a REAL argument —
        every batch this loader yields carries it under ``"_cache"`` and the
        transform declares ``wants_batch`` (the make_train_step/evaluate
        contract). Capturing it in the closure instead would lower the
        whole dataset as an HLO literal: measured as a multi-minute compile
        stall on a remote-compile attach (the literal ships with the HLO
        over the degraded tunnel) and a duplicated copy in device memory."""
        post_wants_step = getattr(post, "wants_step", False)

        def run(indices, batch, step=None):
            gathered = jnp.take(batch["_cache"], indices, axis=0)
            if post is None:
                return gathered
            return post(gathered, step) if post_wants_step else post(gathered)

        run.wants_batch = True
        run.wants_step = post_wants_step
        return run

    def _index_batches(self):
        order = self.sampler.epoch_indices()
        n = len(order)
        end = n - n % self.batch_size if self.drop_remainder else n
        for lo in range(0, end, self.batch_size):
            yield order[lo : lo + self.batch_size]

    def iter_from(self, start_batch: int):
        for i, idx in enumerate(self._index_batches()):
            if i < start_batch:
                continue
            yield self._make_batch(idx)

    def _make_batch(self, idx: np.ndarray) -> dict:
        return {
            self.input_key: np.ascontiguousarray(idx.astype(np.int32)),
            self.label_key: np.ascontiguousarray(self._labels[idx]),
            # the HBM cache rides along as a device array (stage() and
            # _padded_batches pass jax.Arrays through) so the in-graph
            # gather sees it as a jit argument, not a baked-in literal
            "_cache": self._cache,
        }

    def __iter__(self):
        for idx in self._index_batches():
            yield self._make_batch(idx)


class RotatingDeviceCache:
    """Device cache for datasets LARGER than HBM: the set is split into
    row-shards, and while the step consumes shard ``k`` from HBM, shard
    ``k+1`` stages in the background (host memmap read + chunked H2D on a
    staging thread), so the per-step path stays index-only. HBM residency:
    two shards held by the loader, and the consumer's in-flight batch can
    transiently pin a third around a shard transition — size
    ``shard_rows`` for at most THREE shard buffers against free HBM.

    This is the streaming complement to :class:`DeviceCachedLoader`
    (docs/PERF.md §3c): a packed ImageNet-1k at 224² is ~193 GB against
    16 GB HBM, but a 2–4 GB shard stages in well under the time the chip
    spends training through the previous one (shard of R rows buys
    ``R/rate`` seconds of compute against ``R·row_bytes/bandwidth``
    seconds of transfer — at 2,570 img/s and 150 KB/row, any link above
    ~385 MB/s keeps the rotation ahead, the same §3 requirement as direct
    streaming, but paid OFF the critical path and with in-graph
    gather/augment/normalize like the resident cache).

    Shuffle semantics, stated plainly: rotation trades the sampler's
    GLOBAL per-epoch permutation for the standard windowed approximation —
    shard ORDER is permuted per epoch and rows shuffle WITHIN the resident
    shard (window = shard_rows, vastly larger than typical shuffle-buffer
    windows). Coverage: when ``shard_rows`` divides the dataset, every row
    is visited exactly once per epoch; otherwise the ragged TAIL shard is
    dropped (static shapes — the compiled program sees one
    ``[shard_rows, ...]`` cache operand), so up to ``shard_rows - 1``
    rows sit out each epoch. The dropped rows are a fresh random subset
    per epoch (the (seed, epoch)-keyed permutation runs before sharding),
    so over a run every row still trains — the same expectation-level
    coverage as shuffle-buffer pipelines; a warning is logged at
    construction when the tail exists. The (seed, epoch) keying keeps the
    plan deterministic and resumable. Recipes that need the exact global
    permutation use the host loaders or the fully-resident cache.

    Works straight off a :func:`tpudist.data.packed.load_packed` memmap:
    each shard's rows are materialized host-side only transiently for the
    H2D copy.

    Multi-process: the (seed, epoch) plan is global and identical on every
    process, each process stages the SAME shard pixels (the cache operand
    is replicated, like :class:`DeviceCachedLoader`'s), and per batch each
    process contributes its rank's stride of the global within-shard
    order — the DistributedSampler disjointness contract at the batch
    level. Staging OVERLAP is single-process only: multi-process runs
    stage inline at shard boundaries (no extra device-work-issuing
    thread — the measured deadlock and the threading-shape reasoning are
    in ``_iter_impl``).
    """

    def __init__(
        self,
        dataset: Mapping[str, np.ndarray],
        batch_size: int,
        *,
        shard_rows: int,
        mesh=None,
        input_key: str = "image",
        label_key: str = "label",
        seed: int = 0,
        rank: int | None = None,
        num_replicas: int | None = None,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh()
        self.batch_size = batch_size  # per-process rows per step
        self.input_key = input_key
        self.label_key = label_key
        self.seed = seed
        self._images = dataset[input_key]  # memmap-friendly: sliced per shard
        self._labels = np.ascontiguousarray(dataset[label_key])
        self._n = self._images.shape[0]
        self._rank = rank if rank is not None else jax.process_index()
        self._world = (
            num_replicas if num_replicas is not None else jax.process_count()
        )
        self._global_batch = batch_size * self._world
        shard_rows = min(shard_rows, self._n)
        if shard_rows % self._global_batch:
            raise ValueError(
                f"shard_rows {shard_rows} must divide by the global batch "
                f"{self._global_batch} (a batch never spans two resident "
                "shards)"
            )
        self.shard_rows = shard_rows
        if self._n % shard_rows:
            logger.warning(
                "RotatingDeviceCache: dataset rows (%d) are not a multiple "
                "of shard_rows (%d); the ragged tail shard is dropped, so "
                "%d randomly-chosen rows (a fresh subset per epoch) sit "
                "out each epoch", self._n, shard_rows, self._n % shard_rows,
            )
        self.epoch = 0
        # fit() drives per-epoch reshuffle via loader.sampler.set_epoch();
        # the rotation owns its epoch keying, so it is its own "sampler"
        self.sampler = self
        self._sharding = mesh_lib.replicated_sharding(self.mesh)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        # whole shards only, ragged tail shard dropped (static shapes: the
        # compiled program sees ONE [shard_rows, ...] cache operand)
        return (self._n // self.shard_rows) * (
            self.shard_rows // self._global_batch
        )

    def probe(self) -> dict:
        return {
            self.input_key: np.zeros(
                (1, *self._images.shape[1:]), np.float32
            ),
            self.label_key: self._labels[:1],
        }

    # same in-graph contract as DeviceCachedLoader (the "_cache" operand)
    input_transform = DeviceCachedLoader.input_transform

    def _epoch_plan(self):
        """(shards, orders): global row ids per shard (sorted — sequential
        memmap reads) and the within-shard shuffle, identical on every
        process by (seed, epoch) construction."""
        rng = np.random.Generator(np.random.PCG64(
            np.random.SeedSequence([self.seed, self.epoch])
        ))
        order = rng.permutation(self._n)
        n_shards = self._n // self.shard_rows
        shards = [
            np.sort(order[s * self.shard_rows:(s + 1) * self.shard_rows])
            for s in range(n_shards)
        ]
        orders = [rng.permutation(self.shard_rows) for _ in range(n_shards)]
        return shards, orders

    def _stage(self, shard_global_rows: np.ndarray):
        """Gather one shard's pixels from the (mem-mapped) source and put
        them on device (single-process: chunked, in-place-assembled —
        transport-hang guard + HBM high-water discipline; runs on the
        dedicated staging thread, both the host read and the H2D off the
        critical path).

        Multi-process: LOCAL-ONLY construction, now ALSO chunked —
        ``_chunked_replicated_put`` assembles the replicated shard
        per-device in ~64 MB slices (every process holds the identical
        full value, so assembly needs no cross-process transfer, and the
        slicing keeps the documented transport-hang guard that a single
        full-shard ``device_put`` per device — the old ``put_sharded``
        route — bypassed). A raw cross-process ``device_put`` of the
        replicated shard is a lockstep operation, and one issued off the
        main thread raced the step loop's collectives into a reproducible
        2-process deadlock (both ranks asleep; the host loaders never
        deadlock precisely because their staging is this same local-only
        constructor)."""
        pixels = np.ascontiguousarray(self._images[shard_global_rows])
        if jax.process_count() > 1:
            cache = _chunked_replicated_put(pixels, self._sharding)
        else:
            cache = _chunked_device_put(pixels, self._sharding, in_place=True)
        return cache, self._labels[shard_global_rows]

    def iter_from(self, start_batch: int):
        """Mid-epoch resume at the batch level (shards before the target
        batch are skipped without staging)."""
        per_shard = self.shard_rows // self._global_batch
        first_shard = start_batch // per_shard
        skip = start_batch - first_shard * per_shard
        for i, batch in enumerate(self._iter_impl(first_shard)):
            if i >= skip:
                yield batch

    def __iter__(self):
        return self._iter_impl(0)

    def _stage_async(self, shard_global_rows: np.ndarray):
        """Run :meth:`_stage` on a DAEMON thread (a ThreadPoolExecutor's
        non-daemon worker would be joined at interpreter exit — a stage
        in flight over a wedged attach would then hang process shutdown
        instead of letting the original error kill the run); returns a
        one-slot queue carrying (ok, value_or_exception)."""
        import queue
        import threading

        out: queue.Queue = queue.Queue(1)

        def work():
            try:
                out.put((True, self._stage(shard_global_rows)))
            except BaseException as e:  # surfaced at .get() in the iterator
                out.put((False, e))

        threading.Thread(target=work, daemon=True).start()
        return out

    @staticmethod
    def _resolve(pending):
        ok, value = pending.get()
        if not ok:
            raise value
        return value

    def _iter_impl(self, start_shard: int):
        shards, orders = self._epoch_plan()
        shards, orders = shards[start_shard:], orders[start_shard:]
        if not shards:
            return
        # Single-process: dedicated staging thread — the next shard's
        # memmap gather AND its H2D both run there, overlapping the whole
        # current shard's stepping. Multi-process: stage INLINE in this
        # iterator (no extra thread). Measured hazard, not theory: with
        # the staging thread, a 2-process XLA:CPU world deadlocked
        # reproducibly (both ranks asleep after compile) — three
        # concurrent device-work issuers per process (staging thread's
        # puts, the prefetch producer thread that drives this iterator
        # under fit(), and the main thread's compiled steps whose
        # collectives run in lockstep) let per-process orders diverge.
        # Staging inline collapses rotation to the exact threading shape
        # of the host-loader path — ONE producer thread issuing transfers
        # plus the main thread issuing programs — which multi-process
        # worlds demonstrably sustain (tests/test_multiproc_fit.py, and
        # tests/test_multiproc_rotation.py drives THIS path through
        # fit()+prefetch end-to-end). The cost is a staging stall per
        # shard boundary; the per-step path stays index-only either way.
        overlap = jax.process_count() == 1
        pending = self._stage_async(shards[0]) if overlap else None
        for s in range(len(shards)):
            if overlap:
                cache, labels = self._resolve(pending)
                if s + 1 < len(shards):
                    pending = self._stage_async(shards[s + 1])
            else:
                cache, labels = self._stage(shards[s])
            order = orders[s]
            for lo in range(0, self.shard_rows, self._global_batch):
                window = order[lo:lo + self._global_batch]
                # this process's stride of the global batch (disjoint
                # across ranks, union = the window)
                idx = window[self._rank::self._world]
                yield {
                    self.input_key: np.ascontiguousarray(
                        idx.astype(np.int32)
                    ),
                    self.label_key: np.ascontiguousarray(labels[idx]),
                    "_cache": cache,
                }
