"""sklearn's bundled handwritten-digits set as a tpudist dataset.

The reference trains on auto-downloaded CIFAR-100
(/root/reference/main.py:43-51). In a zero-egress environment that download
is impossible, so the recorded convergence evidence (CONVERGENCE.json) uses
the one REAL image dataset shipped inside the image: scikit-learn's
``load_digits`` — 1,797 real 8×8 grayscale handwritten digits (a UCI/NIST
subset), 10 classes. Images are nearest-neighbor upscaled to 32×32 RGB
uint8 so the CIFAR model geometry (``small_inputs`` ResNets, 4-pixel-patch
ViT) and the ``to_tensor`` transform apply unchanged.

The train/val split is a deterministic seeded permutation so every process
computes the identical split with no coordination — the same
shared-seed-instead-of-broadcast idiom as ``create_train_state``.
"""

from __future__ import annotations

import numpy as np

_SPLIT_SEED = 0
_TRAIN_FRACTION = 0.8


def load_digits_dataset(
    train: bool = True, *, upscale: int = 4, rgb: bool = True
) -> dict[str, np.ndarray]:
    """The digits images as ``{"image": uint8 NHWC, "label": int32}``.

    ``upscale`` repeats each pixel into an ``upscale×upscale`` block
    (8×8 → 32×32 at the default); ``rgb`` replicates the gray channel to 3
    channels. Pixel intensities (0..16 in the source) are rescaled to the
    full 0..255 range the CIFAR transforms expect.
    """
    from sklearn.datasets import load_digits

    bunch = load_digits()
    images = bunch.images  # [1797, 8, 8] float64, values 0..16
    labels = bunch.target.astype(np.int32)

    rng = np.random.Generator(np.random.PCG64(_SPLIT_SEED))
    order = rng.permutation(len(labels))
    n_train = int(len(labels) * _TRAIN_FRACTION)
    keep = order[:n_train] if train else order[n_train:]

    img = np.clip(images[keep] * (255.0 / 16.0), 0, 255).astype(np.uint8)
    if upscale > 1:
        img = img.repeat(upscale, axis=1).repeat(upscale, axis=2)
    img = img[..., None]
    if rgb:
        img = np.repeat(img, 3, axis=-1)
    return {
        "image": np.ascontiguousarray(img),
        "label": np.ascontiguousarray(labels[keep]),
    }
