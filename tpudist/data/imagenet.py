"""ImageNet-style image-folder pipeline — the input path for BASELINE.json
configs 2/3 (ResNet-50 / ImageNet on a TPU mesh).

The reference's data layer is an array-backed CIFAR loader
(/root/reference/main.py:42-63); ImageNet does not fit in memory as decoded
arrays, so this module adds the streaming equivalent: a torchvision
``ImageFolder``-style directory scan (``root/class_x/*.jpg``, classes sorted
by name) feeding a decode-on-demand loader with a thread pool (PIL's JPEG
decode releases the GIL, so threads give real parallelism without worker
processes). The loader keeps the exact contract of
:class:`tpudist.data.loader.DataLoader` — ``sampler`` (per-host
DistributedSampler shard), ``__len__``, ``iter_from`` for mid-epoch resume —
so ``tpudist.train.fit`` and ``prefetch_to_mesh`` compose unchanged: decode
runs in the prefetch producer thread, off the device critical path
(SURVEY.md §7 "hard parts" #1).

Transforms are the standard ImageNet recipe (the capability the reference's
``ToTensor``-only CIFAR path scales up to): train = RandomResizedCrop +
horizontal flip; eval = resize-short-side(256/224·size) + center crop; both
then per-channel normalize with the canonical statistics. Augmentation
randomness is derived per (seed, epoch, sample-position) so a resumed epoch
re-draws the same crops it would have drawn uninterrupted.
"""

from __future__ import annotations

import math
import os
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np

from tpudist.data.loader import SampledLoader
from tpudist.data.sampler import DistributedSampler
from tpudist.data.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    to_tensor_normalize,
)

_EXTENSIONS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


def scan_image_folder(root: str | os.PathLike, classes: list[str] | None = None):
    """``root/<class>/<image>`` → (paths, labels, class_names).

    Classes are the sorted subdirectory names, label = class position —
    torchvision ``ImageFolder`` semantics, so an existing ImageNet tree
    works unchanged. Files within a class are sorted for a deterministic
    index space (the DistributedSampler permutes *indices*, so every process
    must agree on the index → file mapping).

    Pass the TRAIN split's ``classes`` when scanning a val split: labels are
    then positions in that list, so a val tree missing a class directory
    (partial download) cannot silently shift every later label — an unknown
    class raises instead.
    """
    root = Path(root)
    if not root.is_dir():
        raise FileNotFoundError(f"image folder root {root} does not exist")
    found = sorted(d.name for d in root.iterdir() if d.is_dir())
    if not found:
        raise ValueError(f"{root} has no class subdirectories")
    if classes is None:
        classes = found
    else:
        unknown = set(found) - set(classes)
        if unknown:
            raise ValueError(
                f"{root} has class dirs not in the reference class list "
                f"(train split): {sorted(unknown)[:5]}"
            )
    index = {cls: i for i, cls in enumerate(classes)}
    paths: list[str] = []
    labels: list[int] = []
    for cls in found:
        files = sorted(
            p for p in (root / cls).iterdir()
            if p.suffix.lower() in _EXTENSIONS
        )
        paths.extend(str(p) for p in files)
        labels.extend([index[cls]] * len(files))
    if not paths:
        raise ValueError(f"{root} has no images under its class directories")
    return paths, np.asarray(labels, np.int32), list(classes)


def _random_resized_crop(img, size: int, rng: np.random.Generator,
                         scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """Sample a crop of random area/aspect, resize to ``size``² (bilinear).

    The standard Inception-style train crop. PIL's ``resize(box=...)`` does
    crop + resample in one pass over the source pixels.
    """
    from PIL import Image

    w, h = img.size
    area = w * h
    log_lo, log_hi = math.log(ratio[0]), math.log(ratio[1])
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        ar = math.exp(rng.uniform(log_lo, log_hi))
        cw = int(round(math.sqrt(target_area * ar)))
        ch = int(round(math.sqrt(target_area / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x = int(rng.integers(0, w - cw + 1))
            y = int(rng.integers(0, h - ch + 1))
            return img.resize((size, size), Image.BILINEAR,
                              box=(x, y, x + cw, y + ch))
    # degenerate aspect ratios: fall back to the central square
    edge = min(w, h)
    x, y = (w - edge) // 2, (h - edge) // 2
    return img.resize((size, size), Image.BILINEAR,
                      box=(x, y, x + edge, y + edge))


def _resize_center_crop(img, size: int):
    """Resize short side to ``round(256/224·size)`` then center-crop
    ``size``² — the standard ImageNet eval transform."""
    from PIL import Image

    resize_to = max(int(round(size * 256 / 224)), size)
    w, h = img.size
    if w <= h:
        new_w, new_h = resize_to, int(round(h * resize_to / w))
    else:
        new_w, new_h = int(round(w * resize_to / h)), resize_to
    img = img.resize((new_w, new_h), Image.BILINEAR)
    x, y = (new_w - size) // 2, (new_h - size) // 2
    return img.crop((x, y, x + size, y + size))




class ImageFolderLoader(SampledLoader):
    """Streaming decode-on-demand loader over an image-folder tree.

    Same iterator contract as :class:`tpudist.data.loader.DataLoader`
    (``__len__``, ``__iter__``, ``iter_from``, ``sampler``, ``batch_size``)
    so it drops into ``fit``/``evaluate``/``prefetch_to_mesh`` unchanged.
    Yields ``{"image": float32 [B, size, size, 3] (normalized),
    "label": int32 [B]}``.

    The decode pool spins up on first iteration; ``close()`` (or use as a
    context manager) releases the threads — long-lived processes that build
    many loaders should close each when done.
    """

    def __init__(
        self,
        root: str | os.PathLike,
        batch_size: int,
        *,
        train: bool = True,
        image_size: int = 224,
        sampler: DistributedSampler | None = None,
        num_replicas: int = 1,
        rank: int = 0,
        workers: int | None = None,
        seed: int = 0,
        drop_remainder: bool = True,
        normalize: bool = True,
        classes: list[str] | None = None,
    ):
        # val loaders pass the train loader's .classes so the two splits
        # can never disagree on the label ↔ class-name mapping
        self.paths, self.labels, self.classes = scan_image_folder(root, classes)
        self.batch_size = batch_size
        self.train = train
        self.image_size = image_size
        self.seed = seed
        self.drop_remainder = drop_remainder
        # the standard stack from tpudist.data.transforms (one home for the
        # normalization math + statistics): uint8 → (x/255 − mean)/std
        self._transform = (
            to_tensor_normalize(IMAGENET_MEAN, IMAGENET_STD)
            if normalize
            else None
        )
        # the sampler needs the scanned dataset size, so the loader builds
        # its own per-host shard from (num_replicas, rank) unless given one
        self.sampler = sampler or DistributedSampler(
            len(self.paths), num_replicas=num_replicas, rank=rank,
            shuffle=train, seed=seed,
        )
        # workers=0 means serial decode (a 1-thread pool), not "default"
        self.workers = (
            max(1, workers) if workers is not None
            else min(os.cpu_count() or 8, 16)
        )
        self._pool: ThreadPoolExecutor | None = None

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _decode(self, index: int, position: int) -> np.ndarray:
        from PIL import Image

        with Image.open(self.paths[index]) as img:
            img = img.convert("RGB")
            if self.train:
                # keyed by (seed, epoch, sample position): deterministic,
                # process-independent, and replayed exactly across a
                # mid-epoch resume (iter_from keeps positions aligned)
                rng = np.random.Generator(np.random.PCG64(
                    np.random.SeedSequence(
                        [self.seed, self.sampler.epoch, position]
                    )
                ))
                img = _random_resized_crop(img, self.image_size, rng)
                if rng.random() < 0.5:
                    img = img.transpose(Image.Transpose.FLIP_LEFT_RIGHT)
            else:
                img = _resize_center_crop(img, self.image_size)
            return np.asarray(img, np.uint8)

    def _gather_batch(self, idx: np.ndarray, start: int) -> dict:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=self.workers)
        images = list(
            self._pool.map(
                self._decode, idx.tolist(), range(start, start + len(idx))
            )
        )
        batch = {"image": np.stack(images), "label": self.labels[idx]}
        return self._transform(batch) if self._transform else batch


def synthetic_imagenet(
    n: int = 512, num_classes: int = 1000, image_size: int = 224, seed: int = 0
) -> dict[str, np.ndarray]:
    """Class-separable in-memory stand-in with ImageNet shapes (egress-free
    smoke/bench path; same template+noise recipe as ``synthetic_cifar``)."""
    from tpudist.data.cifar import synthetic_cifar

    return synthetic_cifar(
        n, num_classes=num_classes, image_size=image_size, seed=seed
    )
