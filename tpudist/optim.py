"""Optimizer construction: schedules, clipping, decay masks.

The reference's optimization surface is exactly ``Adam(lr=1e-3)``
(/root/reference/main.py:80) with no schedule, clipping, or weight decay.
:func:`make_optimizer` reproduces that as its default and adds the standard
knobs the BASELINE ladder's transformer configs want (warmup+cosine, global
-norm clipping, AdamW with norm/bias exclusion), all as one ``optax.chain``
that runs in-graph inside the compiled train step.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import DATA_AXIS, largest_divisible_spec


def warmup_cosine(
    peak_lr: float,
    *,
    warmup_steps: int,
    total_steps: int,
    end_lr_ratio: float = 0.0,
) -> optax.Schedule:
    """Linear warmup from 0 to ``peak_lr`` then cosine decay to
    ``peak_lr·end_lr_ratio`` at ``total_steps``."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=peak_lr * end_lr_ratio,
    )


def run_schedule(
    peak_lr: float, *, total_steps: int, warmup_steps: int = 0
) -> optax.Schedule:
    """:func:`warmup_cosine` sized to a training run: one optimizer step
    per loader batch (grad accumulation does not reduce the count), warmup
    clamped to half the horizon so short runs still decay. The one home
    for this recipe — both CLI entry points use it."""
    total = max(total_steps, 1)
    return warmup_cosine(
        peak_lr, warmup_steps=min(warmup_steps, total // 2), total_steps=total
    )


def decay_mask(params) -> Any:
    """True for leaves that SHOULD receive weight decay: everything except
    1-D params (biases, LayerNorm/BatchNorm scales and offsets)."""
    return jax.tree_util.tree_map(lambda p: p.ndim > 1, params)


def make_optimizer(
    lr: float | optax.Schedule = 1e-3,
    *,
    optimizer: str = "adam",
    b1: float = 0.9,
    b2: float | None = None,  # None → 0.999 (adam/lamb), 0.99 (lion)
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    clip_norm: float | None = None,
    skip_nonfinite_updates: bool = False,
    fused: bool = False,
    compute_dtype: Any = None,
) -> optax.GradientTransformation:
    """One-stop optimizer factory.

    Defaults reproduce the reference exactly: ``make_optimizer()`` ≡
    ``Adam(lr=1e-3)`` (/root/reference/main.py:80). ``weight_decay > 0``
    switches to decoupled decay (AdamW) masked off 1-D params;
    ``clip_norm`` prepends global-norm clipping;
    ``skip_nonfinite_updates`` wraps the chain in
    :func:`tpudist.amp.skip_nonfinite`.

    ``fused=True`` builds :func:`fused_adamw` instead — the one-pass
    Pallas update kernel with bit-compatible math (``optimizer="adam"``
    only; clipping/decay/mask/skip all compose). ``compute_dtype`` (with
    ``fused``) keeps the in-state compute-precision param copy the fused
    train step's forward reads (``make_train_step(fused=...)``).
    """
    if b2 is None:
        b2 = 0.99 if optimizer == "lion" else 0.999
    if fused:
        if optimizer != "adam":
            raise ValueError(
                f"fused=True implements the adam/adamw update only, got "
                f"optimizer={optimizer!r}"
            )
        tx = fused_adamw(
            lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
            mask=decay_mask if weight_decay > 0.0 else None,
            clip_norm=clip_norm, compute_dtype=compute_dtype,
        )
        if skip_nonfinite_updates:
            from tpudist.amp import skip_nonfinite

            tx = skip_nonfinite(tx)
        return tx
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    if optimizer == "adam":
        if weight_decay > 0.0:
            parts.append(
                optax.adamw(
                    lr, b1=b1, b2=b2, eps=eps,
                    weight_decay=weight_decay, mask=decay_mask,
                )
            )
        else:
            parts.append(optax.adam(lr, b1=b1, b2=b2, eps=eps))
    elif optimizer == "sgd":
        parts.append(optax.sgd(lr, momentum=b1))
        if weight_decay > 0.0:
            parts.insert(-1, optax.add_decayed_weights(weight_decay, decay_mask))
    elif optimizer == "lamb":
        # layerwise-adaptive Adam — the large-batch (32k+) training optimizer
        parts.append(
            optax.lamb(lr, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay, mask=decay_mask)
        )
    elif optimizer == "muon":
        # Newton-Schulz-orthogonalized momentum on hidden weight matrices
        # (the modded-nanogpt optimizer), Adam on everything else — all
        # in-graph, so the 5 NS iterations fuse into the compiled step.
        # Following the speedrun recipe, embeddings and classifier/LM heads
        # stay on Adam even when 2-D (orthogonalizing their updates hurts);
        # biases/norm scales (1-D) ride Adam too. Weight decay applies to
        # the Muon-routed matrices (decay_mask is all-true there); the
        # Adam-routed remainder is exactly the set the recipe leaves
        # undecayed. Multi-axis kernels are orthogonalized through their
        # matrix view via MuonDimensionNumbers — qkv [D,3,H,dh] as
        # D×(3·H·dh), out/o_proj [H,dh,D] as (H·dh)×D, convs [kh,kw,I,O] as
        # (kh·kw·I)×O — so attention and conv weights get real Muon, not a
        # silent Adam fallback.
        from optax.contrib import MuonDimensionNumbers

        # top-level param names that are embeddings or heads (wte/wpe/embed/
        # lm_head/embedding: GPT-2+Llama+ViT embeddings; head: ViT head;
        # Dense_0: ResNet's anonymous final classifier)
        _ADAM_TOP = ("wte", "wpe", "embed", "lm_head", "embedding",
                     "head", "Dense_0")

        def muon_dims(params):
            def label(path, p):
                # train-state bring-up runs tx.init on flax-BOXED params
                # (nn.Partitioned); updates run on raw arrays — unbox so the
                # routing (and optax's partition structure) agree between
                # the two, or the moment trees mismatch at the first step
                if hasattr(p, "unbox"):
                    p = p.unbox()
                top = getattr(path[0], "key", str(path[0]))
                leaf = getattr(path[-1], "key", str(path[-1]))
                # only weight kernels orthogonalize: a reshaped multi-dim
                # BIAS (e.g. qkv's [3,H,dh]) is still a vector per output
                if p.ndim < 2 or top in _ADAM_TOP or leaf != "kernel":
                    return None  # Adam
                names = {getattr(k, "key", str(k)) for k in path}
                if names & {"out", "o_proj"}:
                    # DenseGeneral contracting all leading axes → last
                    return MuonDimensionNumbers(
                        tuple(range(p.ndim - 1)), (p.ndim - 1,)
                    )
                if any("conv" in n.lower() for n in names):
                    # HWIO conv kernel: spatial+input reduce into output
                    return MuonDimensionNumbers(
                        tuple(range(p.ndim - 1)), (p.ndim - 1,)
                    )
                # Dense/DenseGeneral splitting the output (qkv [D,3,H,dh],
                # llama qkv [D,H,dh], plain 2-D): input first, rest output
                return MuonDimensionNumbers((0,), tuple(range(1, p.ndim)))

            return jax.tree_util.tree_map_with_path(
                label, params, is_leaf=lambda x: hasattr(x, "unbox")
            )

        parts.append(
            optax.contrib.muon(
                lr, eps=eps, weight_decay=weight_decay,
                weight_decay_mask=decay_mask,
                adam_b1=b1, adam_b2=b2,
                muon_weight_dimension_numbers=muon_dims,
            )
        )
    elif optimizer == "lion":
        # sign-momentum; half the optimizer HBM of Adam (one moment, and it
        # tolerates bf16) — useful when the Adam mirrors dominate memory
        parts.append(
            optax.lion(lr, b1=b1, b2=b2,
                       weight_decay=weight_decay, mask=decay_mask)
        )
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")
    tx = optax.chain(*parts) if len(parts) > 1 else parts[0]
    if skip_nonfinite_updates:
        from tpudist.amp import skip_nonfinite

        tx = skip_nonfinite(tx)
    return tx


# --------------------------------------------------------------------------
# ZeRO-1 / cross-replica weight-update sharding (arXiv:2004.13336)
# --------------------------------------------------------------------------
#
# Replicated Adam keeps TWO fp32 params-shaped mirrors (mu, nu) on every
# chip: at ~1B params that is ~8 GB of a 16 GB HBM before a single
# activation exists. But the update is elementwise — nothing about it needs
# the whole tree on one chip. shard_state() places each moment leaf sharded
# over the ``data`` axis; because the compiled train step's out_shardings
# then pin the moments sharded while the loss is still a global-batch mean,
# XLA lowers the gradient all-reduce into reduce-scatter → per-shard update
# → params all-gather (the automatic weight-update sharding of
# arXiv:2004.13336) inside the SAME single jit-compiled step, donated
# buffers and all. Per-chip optimizer state drops ~world_size×; step cost is
# the same collective bytes re-ordered (rs+ag ≡ all-reduce).


def _zero1_layout(shape, world: int, min_size: int):
    """How one state leaf is stored under ZeRO-1.

    Returns ``("replicate", None)`` (scalars / below ``min_size``),
    ``("shard", dim)`` (largest ``world``-divisible dim — the leaf keeps
    its natural shape and a ``PartitionSpec`` does the work), or
    ``("pad", cols)`` (no divisible dim: the leaf is stored flattened,
    zero-padded to ``world·cols`` and reshaped ``[world, cols]`` so the
    ``data`` axis shards its leading dim evenly — the paper's pad-and-
    reshape fallback, required because uneven shardings are rejected)."""
    if world <= 1 or len(shape) == 0 or math.prod(shape) < min_size:
        return ("replicate", None)
    spec = largest_divisible_spec(shape, DATA_AXIS, world, min_size=min_size)
    if any(s is not None for s in spec):
        return ("shard", next(i for i, s in enumerate(spec) if s is not None))
    return ("pad", -(-math.prod(shape) // world))


@dataclasses.dataclass(frozen=True)
class ShardedStateOptimizer:
    """ZeRO-1 wrapper around a ``GradientTransformation``.

    Duck-types the ``init``/``update`` surface every consumer in this repo
    uses (``create_train_state``, ``make_train_step``), and additionally
    exposes :meth:`state_shardings` so the state can be *born* sharded —
    ``create_train_state`` consults it instead of the (replicated)
    partitioning-metadata path, and the moments never materialize
    replicated even transiently.
    """

    init: Callable
    update: Callable
    state_shardings: Callable
    inner: optax.GradientTransformation
    mesh: Mesh
    axis: str


def shard_state(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis: str = DATA_AXIS,
    min_size: int = 1024,
    skip_spec: "Callable[[tuple], bool] | None" = None,
) -> ShardedStateOptimizer:
    """Shard ``tx``'s state across the ``axis`` (default ``data``) replicas.

    The wrapped transformation stores every state leaf per
    :func:`_zero1_layout`; ``update`` restores the natural layout in-graph
    (a reshape/slice XLA folds away), runs the inner update, and re-stores
    — so the inner optimizer's math is untouched and the wrapped step is
    numerically the replicated step (``tests/test_sharded_optim.py`` holds
    it to that on an emulated mesh, non-divisible shapes included).

    Composition notes: apply OUTERMOST (around ``make_optimizer``'s whole
    chain, including ``skip_nonfinite``) so every params-shaped mirror in
    the chain shards. Params themselves stay wherever their own shardings
    put them (replicated for DP, ``fsdp``-sharded under ZeRO-3, Megatron
    specs under TP) — this wrapper touches optimizer STATE only, which is
    what makes it ZeRO-1. Leaves below ``min_size`` elements stay
    replicated (same threshold rule as ``fsdp_spec``).

    Explicit gradient reduction (``make_train_step(reduce=...)``,
    ``tpudist.parallel.dp``) composes from the OUTSIDE: the reducer hands
    this wrapper replicated, already-dequantized mean gradients, so XLA's
    weight-update-sharding decomposition inserts no second gradient
    collective — the update math runs on the sharded moments (the grads
    slice for free) and only the params-shaped update all-gather that
    ZeRO-1 always pays remains. Net wire bytes: ~0.5× fp32-AR for the int8
    grad reduction + 1× for the update all-gather, vs 2× for the implicit
    fp32 rs+ag — docs/PERF.md §11 carries the full budget table.

    Checkpoints hold the stored (sharded/padded) layout; resuming needs the
    same world size, which the geometry guard in ``fit()`` already
    enforces.

    ``skip_spec(shape) -> bool`` exempts leaves from the ZeRO layout
    entirely (stored natural, classified ``replicate`` here) — the
    composition hook ``tpudist.parallel.plan.ParallelPlan.wrap_zero1``
    uses so leaves the plan scatters over ``fsdp`` are never flattened
    into the pad-and-reshape layout out from under their fsdp spec
    (sharded state either way, no double-sharding).
    """
    world = int(mesh.shape[axis])

    def _layout(shape):
        if skip_spec is not None and skip_spec(tuple(shape)):
            return ("replicate", None)
        return _zero1_layout(shape, world, min_size)

    def _unbox(tree):
        # create_train_state runs init on flax-BOXED params; the ZeRO
        # layout is pure shape math, so strip the metadata boxes (the
        # moments' placement comes from state_shardings, not nn.Partitioned)
        return jax.tree_util.tree_map(
            lambda p: p.unbox() if hasattr(p, "unbox") else p,
            tree,
            is_leaf=lambda x: hasattr(x, "unbox"),
        )

    def _inner_shapes(params):
        # the natural (unpadded) state layout, recomputed per call from
        # params — trace-time only under jit, so it costs nothing at run
        # time and needs no mutable closure state to survive restore
        return jax.eval_shape(tx.init, _unbox(params))

    def _store(leaf, ref):
        mode, cols = _layout(ref.shape)
        if mode != "pad":
            return leaf
        flat = jnp.ravel(leaf)
        return jnp.pad(flat, (0, world * cols - flat.size)).reshape(world, cols)

    def _restore(leaf, ref):
        mode, _ = _layout(ref.shape)
        if mode != "pad":
            return leaf
        return jnp.ravel(leaf)[: math.prod(ref.shape)].reshape(ref.shape)

    def init(params):
        params = _unbox(params)
        state = tx.init(params)
        return jax.tree_util.tree_map(
            _store, state, jax.eval_shape(tx.init, params)
        )

    def update(updates, state, params=None):
        if params is None:
            raise ValueError(
                "shard_state requires params at update time (the natural "
                "state layout is derived from them); tpudist's train step "
                "always passes them"
            )
        refs = _inner_shapes(params)
        natural = jax.tree_util.tree_map(_restore, state, refs)
        out, new_state = tx.update(updates, natural, params)
        return out, jax.tree_util.tree_map(_store, new_state, refs)

    def state_shardings(params):
        """Opt-state-shaped tree of NamedShardings for the STORED layout —
        feed to ``create_train_state``/``make_train_step`` (the former does
        so automatically when it sees this attribute)."""

        def sharding(ref):
            mode, _ = _layout(ref.shape)
            if mode == "replicate":
                return NamedSharding(mesh, P())
            if mode == "pad":
                return NamedSharding(mesh, P(axis, None))
            spec = largest_divisible_spec(
                ref.shape, axis, world, min_size=min_size
            )
            return NamedSharding(mesh, spec)

        return jax.tree_util.tree_map(sharding, _inner_shapes(params))

    return ShardedStateOptimizer(
        init=init, update=update, state_shardings=state_shardings,
        inner=tx, mesh=mesh, axis=axis,
    )


# --------------------------------------------------------------------------
# Fused one-pass AdamW (tpudist.ops.fused_update) — the non-GEMM-tail lever
# --------------------------------------------------------------------------
#
# docs/PERF.md §4b measured the 124M step's residual as the serial
# elementwise tail BETWEEN the matmuls; the optax Adam chain (moment pass,
# bias correction, decayed weights, lr scale) plus the per-step fp32→bf16
# param casts are the optimizer's share of it. fused_adamw runs the whole
# update as ONE Pallas sweep per leaf — read (g, m, v, p), write (m', v',
# update, bf16 compute copy) — behind the standard optax (init, update)
# surface, so everything that composes with an optimizer here (ZeRO-1
# shard_state, amp.skip_nonfinite, make_train_step's guard_nonfinite,
# telemetry's norms) composes with it unchanged.


class FusedAdamWState(NamedTuple):
    """State of :func:`fused_adamw`. ``compute`` is the params-shaped
    compute-dtype copy (written by the kernel in the same sweep as the
    moments) or the EMPTY tuple when ``compute_dtype`` is off — zero
    leaves, so checkpoints/shardings of copy-less states carry nothing
    extra (the ``TrainState.comm_residual`` convention)."""

    count: Any
    mu: Any
    nu: Any
    compute: Any


@dataclasses.dataclass(frozen=True)
class FusedAdamW:
    """Duck-typed ``(init, update)`` optimizer running the one-pass fused
    AdamW kernel (:mod:`tpudist.ops.fused_update`). Built by
    :func:`fused_adamw`; detected through wrappers (``shard_state``,
    ``amp.skip_nonfinite`` — both expose ``inner``) by
    :func:`find_fused`."""

    init: Callable
    update: Callable
    compute_dtype: Any
    learning_rate: Any
    weight_decay: float


def fused_adamw(
    learning_rate: float | optax.Schedule = 1e-3,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    mask: Callable | None = None,
    clip_norm: float | None = None,
    compute_dtype: Any = None,
    min_kernel_elems: int | None = None,
) -> FusedAdamW:
    """One-pass fused AdamW with an optax-compatible surface.

    Matches ``optax.adamw(lr, b1, b2, eps, weight_decay, mask=mask)``
    (and plain ``optax.adam`` at ``weight_decay=0``) BIT-FOR-BIT in
    interpret mode — same division-form bias correction, same
    ``√v̂ + eps`` denominator, same decay-then-scale order
    (tests/test_fused_update.py pins it) — while collapsing the chain's
    per-transform tree passes into one HBM sweep per leaf.

    ``mask``: callable ``params → tree of static bools`` selecting decayed
    leaves (:func:`decay_mask`); ``None`` decays everything (optax's
    convention). ``clip_norm`` prepends ``clip_by_global_norm`` with
    optax's exact arithmetic (the global norm is one tree reduction XLA
    fuses with the backward; the scale rides into the kernel's read of
    ``g``). ``learning_rate`` may be a schedule (called on the
    pre-increment step count, optax's convention).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) adds a params-shaped compute
    copy to the state, refreshed by the kernel in the same sweep as the
    moments: ``compute = compute_dtype(p + update)``, bit-identical to
    casting the post-update master. ``make_train_step(fused=...)`` routes
    the next step's forward through it, which deletes the per-step
    fp32→bf16 cast of every parameter AND halves the forward's param-read
    bytes. Float leaves cast; non-float leaves ride along unchanged.

    ZeRO-1: apply ``tpudist.optim.shard_state`` AROUND this (the usual
    order) — the update math runs on the restored layout; on the CPU
    interpret path the kernel decomposes into partitionable ops and runs
    on the 1/W shard, on real TPUs measure before combining (pallas_call
    has no GSPMD rule — see tpudist.ops.fused_update's module docstring).
    """
    from tpudist.ops.fused_update import fused_leaf_update

    def _cast_copy(p):
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
            return jnp.asarray(p, compute_dtype)
        return p

    def init(params):
        # zeros_like/astype map over the INNER arrays of nn.Partitioned
        # boxes (they are pytree nodes), so a boxed init — what
        # create_train_state runs — yields moments/copy carrying the same
        # partitioning metadata as the params, like optax.adam's would
        zeros = lambda tree: jax.tree_util.tree_map(jnp.zeros_like, tree)
        compute = (
            jax.tree_util.tree_map(_cast_copy, params)
            if compute_dtype is not None else ()
        )
        return FusedAdamWState(
            count=jnp.zeros((), jnp.int32),
            mu=zeros(params), nu=zeros(params), compute=compute,
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError(
                "fused_adamw requires params at update time (weight decay "
                "and the compute copy read them); tpudist's train step "
                "always passes them"
            )
        if clip_norm is not None:
            # optax.clip_by_global_norm's exact arithmetic (divide by the
            # norm, then scale by the max) so the fused chain stays
            # bit-compatible with the unfused one
            g_norm = optax.global_norm(grads)
            grads = jax.tree_util.tree_map(
                lambda t: jnp.where(
                    g_norm < clip_norm, t,
                    (t / g_norm.astype(t.dtype)) * clip_norm,
                ),
                grads,
            )
        count_inc = optax.safe_int32_increment(state.count)
        b1c = 1.0 - b1 ** count_inc.astype(jnp.float32)
        b2c = 1.0 - b2 ** count_inc.astype(jnp.float32)
        lr_t = (
            learning_rate(state.count) if callable(learning_rate)
            else learning_rate
        )
        lr_t = jnp.asarray(lr_t, jnp.float32)

        mask_tree = mask(params) if mask is not None else None
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)
        wd_leaves = (
            treedef.flatten_up_to(mask_tree) if mask_tree is not None
            else [True] * len(p_leaves)
        )
        results = [
            fused_leaf_update(
                g, m, v, p, lr_t, b1c, b2c, b1=b1, b2=b2, eps=eps,
                wd=weight_decay if decayed else 0.0,
                compute_dtype=(
                    compute_dtype if compute_dtype is not None
                    and jnp.issubdtype(p.dtype, jnp.floating) else None
                ),
                **({} if min_kernel_elems is None
                   else {"min_kernel_elems": min_kernel_elems}),
            )
            for g, m, v, p, decayed in zip(
                g_leaves, m_leaves, v_leaves, p_leaves, wd_leaves
            )
        ]
        updates = treedef.unflatten([r[0] for r in results])
        new_state = FusedAdamWState(
            count=count_inc,
            mu=treedef.unflatten([r[1] for r in results]),
            nu=treedef.unflatten([r[2] for r in results]),
            compute=(
                treedef.unflatten([
                    r[3] if r[3] is not None else p
                    for r, p in zip(results, p_leaves)
                ])
                if compute_dtype is not None else ()
            ),
        )
        return updates, new_state

    return FusedAdamW(
        init=init, update=update, compute_dtype=compute_dtype,
        learning_rate=learning_rate, weight_decay=weight_decay,
    )


def find_fused(tx) -> FusedAdamW | None:
    """The :class:`FusedAdamW` inside ``tx``, walking the wrappers that
    expose ``inner`` (:class:`ShardedStateOptimizer`,
    ``amp.SkipNonfinite``) — or ``None``. An ``optax.chain`` hides its
    members, so a chained fused optimizer keeps the kernel update but is
    invisible to the compute-copy wiring; build clipping into
    :func:`fused_adamw` (``clip_norm=``) instead of chaining."""
    seen = 0
    while tx is not None and seen < 8:
        if isinstance(tx, FusedAdamW):
            return tx
        tx = getattr(tx, "inner", None)
        seen += 1
    return None


def _fused_state_in(opt_state):
    from tpudist.amp import is_skip_state

    if isinstance(opt_state, FusedAdamWState):
        return opt_state
    if is_skip_state(opt_state):
        return _fused_state_in(opt_state[0])
    if isinstance(opt_state, (tuple, list)) and not hasattr(
        opt_state, "_fields"
    ):
        for el in opt_state:
            found = _fused_state_in(el)
            if found is not None:
                return found
    return None


def _copy_matches(compute, params) -> bool:
    c_leaves = jax.tree_util.tree_leaves(compute)
    p_leaves = jax.tree_util.tree_leaves(params)
    if not c_leaves or len(c_leaves) != len(p_leaves):
        return False
    if jax.tree_util.tree_structure(compute) != jax.tree_util.tree_structure(
        params
    ):
        return False
    return all(
        getattr(c, "shape", None) == getattr(p, "shape", None)
        for c, p in zip(c_leaves, p_leaves)
    )


def fused_compute_params(opt_state, params):
    """The compute-dtype param copy carried by a :func:`fused_adamw` state,
    or ``None`` when absent/unusable. Usable means: reachable through the
    known wrappers AND params-shaped leaf-for-leaf — under ZeRO-1 a
    pad-and-reshape-stored leaf breaks the shape match and the whole copy
    is declined (the forward then reads the masters; a stale or re-laid-out
    copy can never be silently used). Static structure/shape checks only —
    free at trace time."""
    st = _fused_state_in(opt_state)
    if st is None:
        return None
    if not _copy_matches(st.compute, params):
        return None
    return st.compute


def refresh_fused_compute(opt_state, params):
    """Re-cast the fused compute copy from ``params`` wherever it is
    reachable and params-shaped — fit()'s warm-start hook (``init_params``
    replaces the masters AFTER ``tx.init`` built the copy; without the
    refresh the copy would describe the discarded random init). States
    without a usable copy pass through unchanged, which is safe: the same
    shape predicate gates :func:`fused_compute_params`, so an unrefreshed
    copy is also an unused one."""
    if isinstance(opt_state, FusedAdamWState):
        if not _copy_matches(opt_state.compute, params):
            return opt_state
        fresh = jax.tree_util.tree_map(
            lambda p, c: jnp.asarray(p, c.dtype), params, opt_state.compute
        )
        return opt_state._replace(compute=fresh)
    from tpudist.amp import is_skip_state

    if is_skip_state(opt_state):
        inner = refresh_fused_compute(opt_state[0], params)
        return opt_state if inner is opt_state[0] else (inner, opt_state[1])
    if isinstance(opt_state, tuple) and not hasattr(opt_state, "_fields"):
        refreshed = tuple(refresh_fused_compute(el, params) for el in opt_state)
        if all(a is b for a, b in zip(refreshed, opt_state)):
            return opt_state  # nothing fused inside: identity, not a rebuild
        return refreshed
    return opt_state
