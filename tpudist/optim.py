"""Optimizer construction: schedules, clipping, decay masks.

The reference's optimization surface is exactly ``Adam(lr=1e-3)``
(/root/reference/main.py:80) with no schedule, clipping, or weight decay.
:func:`make_optimizer` reproduces that as its default and adds the standard
knobs the BASELINE ladder's transformer configs want (warmup+cosine, global
-norm clipping, AdamW with norm/bias exclusion), all as one ``optax.chain``
that runs in-graph inside the compiled train step.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.mesh import DATA_AXIS, largest_divisible_spec


def warmup_cosine(
    peak_lr: float,
    *,
    warmup_steps: int,
    total_steps: int,
    end_lr_ratio: float = 0.0,
) -> optax.Schedule:
    """Linear warmup from 0 to ``peak_lr`` then cosine decay to
    ``peak_lr·end_lr_ratio`` at ``total_steps``."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=peak_lr * end_lr_ratio,
    )


def run_schedule(
    peak_lr: float, *, total_steps: int, warmup_steps: int = 0
) -> optax.Schedule:
    """:func:`warmup_cosine` sized to a training run: one optimizer step
    per loader batch (grad accumulation does not reduce the count), warmup
    clamped to half the horizon so short runs still decay. The one home
    for this recipe — both CLI entry points use it."""
    total = max(total_steps, 1)
    return warmup_cosine(
        peak_lr, warmup_steps=min(warmup_steps, total // 2), total_steps=total
    )


def decay_mask(params) -> Any:
    """True for leaves that SHOULD receive weight decay: everything except
    1-D params (biases, LayerNorm/BatchNorm scales and offsets)."""
    return jax.tree_util.tree_map(lambda p: p.ndim > 1, params)


def make_optimizer(
    lr: float | optax.Schedule = 1e-3,
    *,
    optimizer: str = "adam",
    b1: float = 0.9,
    b2: float | None = None,  # None → 0.999 (adam/lamb), 0.99 (lion)
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    clip_norm: float | None = None,
    skip_nonfinite_updates: bool = False,
) -> optax.GradientTransformation:
    """One-stop optimizer factory.

    Defaults reproduce the reference exactly: ``make_optimizer()`` ≡
    ``Adam(lr=1e-3)`` (/root/reference/main.py:80). ``weight_decay > 0``
    switches to decoupled decay (AdamW) masked off 1-D params;
    ``clip_norm`` prepends global-norm clipping;
    ``skip_nonfinite_updates`` wraps the chain in
    :func:`tpudist.amp.skip_nonfinite`.
    """
    if b2 is None:
        b2 = 0.99 if optimizer == "lion" else 0.999
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    if optimizer == "adam":
        if weight_decay > 0.0:
            parts.append(
                optax.adamw(
                    lr, b1=b1, b2=b2, eps=eps,
                    weight_decay=weight_decay, mask=decay_mask,
                )
            )
        else:
            parts.append(optax.adam(lr, b1=b1, b2=b2, eps=eps))
    elif optimizer == "sgd":
        parts.append(optax.sgd(lr, momentum=b1))
        if weight_decay > 0.0:
            parts.insert(-1, optax.add_decayed_weights(weight_decay, decay_mask))
    elif optimizer == "lamb":
        # layerwise-adaptive Adam — the large-batch (32k+) training optimizer
        parts.append(
            optax.lamb(lr, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay, mask=decay_mask)
        )
    elif optimizer == "muon":
        # Newton-Schulz-orthogonalized momentum on hidden weight matrices
        # (the modded-nanogpt optimizer), Adam on everything else — all
        # in-graph, so the 5 NS iterations fuse into the compiled step.
        # Following the speedrun recipe, embeddings and classifier/LM heads
        # stay on Adam even when 2-D (orthogonalizing their updates hurts);
        # biases/norm scales (1-D) ride Adam too. Weight decay applies to
        # the Muon-routed matrices (decay_mask is all-true there); the
        # Adam-routed remainder is exactly the set the recipe leaves
        # undecayed. Multi-axis kernels are orthogonalized through their
        # matrix view via MuonDimensionNumbers — qkv [D,3,H,dh] as
        # D×(3·H·dh), out/o_proj [H,dh,D] as (H·dh)×D, convs [kh,kw,I,O] as
        # (kh·kw·I)×O — so attention and conv weights get real Muon, not a
        # silent Adam fallback.
        from optax.contrib import MuonDimensionNumbers

        # top-level param names that are embeddings or heads (wte/wpe/embed/
        # lm_head/embedding: GPT-2+Llama+ViT embeddings; head: ViT head;
        # Dense_0: ResNet's anonymous final classifier)
        _ADAM_TOP = ("wte", "wpe", "embed", "lm_head", "embedding",
                     "head", "Dense_0")

        def muon_dims(params):
            def label(path, p):
                # train-state bring-up runs tx.init on flax-BOXED params
                # (nn.Partitioned); updates run on raw arrays — unbox so the
                # routing (and optax's partition structure) agree between
                # the two, or the moment trees mismatch at the first step
                if hasattr(p, "unbox"):
                    p = p.unbox()
                top = getattr(path[0], "key", str(path[0]))
                leaf = getattr(path[-1], "key", str(path[-1]))
                # only weight kernels orthogonalize: a reshaped multi-dim
                # BIAS (e.g. qkv's [3,H,dh]) is still a vector per output
                if p.ndim < 2 or top in _ADAM_TOP or leaf != "kernel":
                    return None  # Adam
                names = {getattr(k, "key", str(k)) for k in path}
                if names & {"out", "o_proj"}:
                    # DenseGeneral contracting all leading axes → last
                    return MuonDimensionNumbers(
                        tuple(range(p.ndim - 1)), (p.ndim - 1,)
                    )
                if any("conv" in n.lower() for n in names):
                    # HWIO conv kernel: spatial+input reduce into output
                    return MuonDimensionNumbers(
                        tuple(range(p.ndim - 1)), (p.ndim - 1,)
                    )
                # Dense/DenseGeneral splitting the output (qkv [D,3,H,dh],
                # llama qkv [D,H,dh], plain 2-D): input first, rest output
                return MuonDimensionNumbers((0,), tuple(range(1, p.ndim)))

            return jax.tree_util.tree_map_with_path(
                label, params, is_leaf=lambda x: hasattr(x, "unbox")
            )

        parts.append(
            optax.contrib.muon(
                lr, eps=eps, weight_decay=weight_decay,
                weight_decay_mask=decay_mask,
                adam_b1=b1, adam_b2=b2,
                muon_weight_dimension_numbers=muon_dims,
            )
        )
    elif optimizer == "lion":
        # sign-momentum; half the optimizer HBM of Adam (one moment, and it
        # tolerates bf16) — useful when the Adam mirrors dominate memory
        parts.append(
            optax.lion(lr, b1=b1, b2=b2,
                       weight_decay=weight_decay, mask=decay_mask)
        )
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")
    tx = optax.chain(*parts) if len(parts) > 1 else parts[0]
    if skip_nonfinite_updates:
        from tpudist.amp import skip_nonfinite

        tx = skip_nonfinite(tx)
    return tx


# --------------------------------------------------------------------------
# ZeRO-1 / cross-replica weight-update sharding (arXiv:2004.13336)
# --------------------------------------------------------------------------
#
# Replicated Adam keeps TWO fp32 params-shaped mirrors (mu, nu) on every
# chip: at ~1B params that is ~8 GB of a 16 GB HBM before a single
# activation exists. But the update is elementwise — nothing about it needs
# the whole tree on one chip. shard_state() places each moment leaf sharded
# over the ``data`` axis; because the compiled train step's out_shardings
# then pin the moments sharded while the loss is still a global-batch mean,
# XLA lowers the gradient all-reduce into reduce-scatter → per-shard update
# → params all-gather (the automatic weight-update sharding of
# arXiv:2004.13336) inside the SAME single jit-compiled step, donated
# buffers and all. Per-chip optimizer state drops ~world_size×; step cost is
# the same collective bytes re-ordered (rs+ag ≡ all-reduce).


def _zero1_layout(shape, world: int, min_size: int):
    """How one state leaf is stored under ZeRO-1.

    Returns ``("replicate", None)`` (scalars / below ``min_size``),
    ``("shard", dim)`` (largest ``world``-divisible dim — the leaf keeps
    its natural shape and a ``PartitionSpec`` does the work), or
    ``("pad", cols)`` (no divisible dim: the leaf is stored flattened,
    zero-padded to ``world·cols`` and reshaped ``[world, cols]`` so the
    ``data`` axis shards its leading dim evenly — the paper's pad-and-
    reshape fallback, required because uneven shardings are rejected)."""
    if world <= 1 or len(shape) == 0 or math.prod(shape) < min_size:
        return ("replicate", None)
    spec = largest_divisible_spec(shape, DATA_AXIS, world, min_size=min_size)
    if any(s is not None for s in spec):
        return ("shard", next(i for i, s in enumerate(spec) if s is not None))
    return ("pad", -(-math.prod(shape) // world))


@dataclasses.dataclass(frozen=True)
class ShardedStateOptimizer:
    """ZeRO-1 wrapper around a ``GradientTransformation``.

    Duck-types the ``init``/``update`` surface every consumer in this repo
    uses (``create_train_state``, ``make_train_step``), and additionally
    exposes :meth:`state_shardings` so the state can be *born* sharded —
    ``create_train_state`` consults it instead of the (replicated)
    partitioning-metadata path, and the moments never materialize
    replicated even transiently.
    """

    init: Callable
    update: Callable
    state_shardings: Callable
    inner: optax.GradientTransformation
    mesh: Mesh
    axis: str


def shard_state(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis: str = DATA_AXIS,
    min_size: int = 1024,
) -> ShardedStateOptimizer:
    """Shard ``tx``'s state across the ``axis`` (default ``data``) replicas.

    The wrapped transformation stores every state leaf per
    :func:`_zero1_layout`; ``update`` restores the natural layout in-graph
    (a reshape/slice XLA folds away), runs the inner update, and re-stores
    — so the inner optimizer's math is untouched and the wrapped step is
    numerically the replicated step (``tests/test_sharded_optim.py`` holds
    it to that on an emulated mesh, non-divisible shapes included).

    Composition notes: apply OUTERMOST (around ``make_optimizer``'s whole
    chain, including ``skip_nonfinite``) so every params-shaped mirror in
    the chain shards. Params themselves stay wherever their own shardings
    put them (replicated for DP, ``fsdp``-sharded under ZeRO-3, Megatron
    specs under TP) — this wrapper touches optimizer STATE only, which is
    what makes it ZeRO-1. Leaves below ``min_size`` elements stay
    replicated (same threshold rule as ``fsdp_spec``).

    Explicit gradient reduction (``make_train_step(reduce=...)``,
    ``tpudist.parallel.dp``) composes from the OUTSIDE: the reducer hands
    this wrapper replicated, already-dequantized mean gradients, so XLA's
    weight-update-sharding decomposition inserts no second gradient
    collective — the update math runs on the sharded moments (the grads
    slice for free) and only the params-shaped update all-gather that
    ZeRO-1 always pays remains. Net wire bytes: ~0.5× fp32-AR for the int8
    grad reduction + 1× for the update all-gather, vs 2× for the implicit
    fp32 rs+ag — docs/PERF.md §11 carries the full budget table.

    Checkpoints hold the stored (sharded/padded) layout; resuming needs the
    same world size, which the geometry guard in ``fit()`` already
    enforces.
    """
    world = int(mesh.shape[axis])

    def _unbox(tree):
        # create_train_state runs init on flax-BOXED params; the ZeRO
        # layout is pure shape math, so strip the metadata boxes (the
        # moments' placement comes from state_shardings, not nn.Partitioned)
        return jax.tree_util.tree_map(
            lambda p: p.unbox() if hasattr(p, "unbox") else p,
            tree,
            is_leaf=lambda x: hasattr(x, "unbox"),
        )

    def _inner_shapes(params):
        # the natural (unpadded) state layout, recomputed per call from
        # params — trace-time only under jit, so it costs nothing at run
        # time and needs no mutable closure state to survive restore
        return jax.eval_shape(tx.init, _unbox(params))

    def _store(leaf, ref):
        mode, cols = _zero1_layout(ref.shape, world, min_size)
        if mode != "pad":
            return leaf
        flat = jnp.ravel(leaf)
        return jnp.pad(flat, (0, world * cols - flat.size)).reshape(world, cols)

    def _restore(leaf, ref):
        mode, _ = _zero1_layout(ref.shape, world, min_size)
        if mode != "pad":
            return leaf
        return jnp.ravel(leaf)[: math.prod(ref.shape)].reshape(ref.shape)

    def init(params):
        params = _unbox(params)
        state = tx.init(params)
        return jax.tree_util.tree_map(
            _store, state, jax.eval_shape(tx.init, params)
        )

    def update(updates, state, params=None):
        if params is None:
            raise ValueError(
                "shard_state requires params at update time (the natural "
                "state layout is derived from them); tpudist's train step "
                "always passes them"
            )
        refs = _inner_shapes(params)
        natural = jax.tree_util.tree_map(_restore, state, refs)
        out, new_state = tx.update(updates, natural, params)
        return out, jax.tree_util.tree_map(_store, new_state, refs)

    def state_shardings(params):
        """Opt-state-shaped tree of NamedShardings for the STORED layout —
        feed to ``create_train_state``/``make_train_step`` (the former does
        so automatically when it sees this attribute)."""

        def sharding(ref):
            mode, _ = _zero1_layout(ref.shape, world, min_size)
            if mode == "replicate":
                return NamedSharding(mesh, P())
            if mode == "pad":
                return NamedSharding(mesh, P(axis, None))
            spec = largest_divisible_spec(
                ref.shape, axis, world, min_size=min_size
            )
            return NamedSharding(mesh, spec)

        return jax.tree_util.tree_map(sharding, _inner_shapes(params))

    return ShardedStateOptimizer(
        init=init, update=update, state_shardings=state_shardings,
        inner=tx, mesh=mesh, axis=axis,
    )
