"""Version compatibility for the narrow band of jax APIs that moved.

The repo targets current jax (where ``shard_map`` is a top-level export
with ``check_vma``/``axis_names`` kwargs); the graft container pins an
older jax (0.4.x) where the same callable lives at
``jax.experimental.shard_map.shard_map`` with the pre-rename kwargs
(``check_rep``, ``auto``). One import site per concept lives here so the
call sites stay written against the CURRENT api and the translation is a
single, deletable function.
"""

from __future__ import annotations

import jax

try:  # current jax: top-level export, check_vma/axis_names kwargs
    from jax import shard_map as _new_shard_map  # type: ignore

    shard_map = _new_shard_map
except ImportError:  # jax 0.4.x: experimental module, check_rep/auto kwargs
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
                  axis_names=None):
        # old-name translation: check_vma was check_rep; manual-over-a-
        # subset (axis_names) was expressed as its complement (auto)
        auto = frozenset()
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _old_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, auto=auto,
        )


try:  # public since jax 0.4.x-late; the underscore path covers 0.4.37
    from jax.ad_checkpoint import saved_residuals  # type: ignore  # noqa: F401
except ImportError:
    from jax._src.ad_checkpoint import saved_residuals  # noqa: F401


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Size of a manual-mesh axis inside shard_map — old-jax spelling
        (a psum of 1 lowers to a constant, same as the new primitive)."""
        return jax.lax.psum(1, axis_name)


def profile_options(python_tracer_level: int, host_tracer_level: int):
    """``jax.profiler.ProfileOptions`` configured, or None where the class
    doesn't exist yet (old jax: ``start_trace`` takes no options — the
    caller must then also omit the kwarg, see :func:`start_trace`)."""
    if not hasattr(jax.profiler, "ProfileOptions"):
        return None
    options = jax.profiler.ProfileOptions()
    options.python_tracer_level = python_tracer_level
    options.host_tracer_level = host_tracer_level
    return options


def start_trace(log_dir: str, options=None) -> None:
    """``jax.profiler.start_trace`` across the profiler_options rename/
    introduction boundary."""
    if options is None:
        jax.profiler.start_trace(log_dir)
    else:
        jax.profiler.start_trace(log_dir, profiler_options=options)
