"""Host-keyed persistent-compile-cache location.

XLA:CPU serializes AOT-compiled executables with the *compile* machine's
feature set; loading them on a host with different CPU features only logs a
warning ("could lead to execution errors such as SIGILL") and then can
SIGABRT mid-run — observed in this environment when the VM migrated to a
host with a different AVX feature mix while ``/tmp``'s cache survived.
Keying the cache directory by the host's CPU flags turns that crash into a
cold compile on the new host.
"""

from __future__ import annotations

import hashlib
import os


def host_cpu_fingerprint() -> str:
    """Short stable hash of this host's CPU feature flags."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        import platform

        flags = platform.machine() + platform.processor()
    return hashlib.sha1(flags.encode()).hexdigest()[:10]


def host_keyed_cache_dir(base: str = "/tmp/tpudist_jax_cache") -> str:
    return os.environ.get(
        "TPUDIST_JAX_CACHE_DIR", f"{base}_{host_cpu_fingerprint()}"
    )
