from tpudist.utils.tree import tree_size, tree_bytes

__all__ = ["tree_size", "tree_bytes"]
