"""Windowed profiler tracing.

Replaces ``torch.profiler.profile(schedule=schedule(wait=2, warmup=2,
active=6, repeat=1), tensorboard_trace_handler('./log_{jobId}'))``
(/root/reference/main.py:70-78,115) with :mod:`jax.profiler`: after
``wait + warmup`` steps are skipped, a single ``active``-step window is
captured via ``start_trace``/``stop_trace`` into ``./log_{jobId}`` — the
same per-job directory convention — producing TensorBoard/XProf-viewable
traces with the TPU device timeline and HLO ops (Kineto's CUPTI role is
played by the XLA runtime's own instrumentation; SURVEY.md §2.10).

Usage mirrors the reference: wrap training in the context manager and call
``p.step()`` once per iteration.

``with_stack=True`` (the default, matching the reference's
``with_stack=True`` at /root/reference/main.py:77) turns on the profiler's
python tracer, so captured windows carry host-side python call stacks
alongside the device timeline — the Kineto python-stack capability,
natively. :meth:`annotate` additionally brackets each traced step in a
``StepTraceAnnotation`` so XProf's step-time view can attribute device work
to training steps.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from pathlib import Path

import jax

logger = logging.getLogger(__name__)


class WindowedProfiler:
    def __init__(
        self,
        job_id: str,
        *,
        wait: int = 2,
        warmup: int = 2,
        active: int = 6,
        repeat: int = 1,
        log_dir: str | Path | None = None,
        enabled: bool = True,
        with_stack: bool = True,
    ):
        # torch semantics: skip `wait`, then `warmup` (instrument, discard),
        # then record `active` steps; `repeat` cycles. jax.profiler has no
        # warmup/active distinction, so the capture window is `active` steps
        # beginning after wait+warmup.
        self.skip = wait + warmup
        self.active = active
        self.repeat = repeat
        self.log_dir = str(log_dir if log_dir is not None else f"./log_{job_id}")
        self.enabled = enabled
        self.with_stack = with_stack
        self._step = 0
        self._cycle = 0
        self._tracing = False
        self._armed = 0  # remaining steps of an on-demand (arm()) window
        # serializes the state machine against flush_armed(), which the
        # hang watchdog calls from ITS thread: without it, a stall that
        # resolves mid-flush lets the resumed main thread's step() race
        # the teardown into a second stop_trace (which raises)
        self._mutex = threading.Lock()

    def __enter__(self):
        # wait+warmup == 0 means "capture from the first step" — the window
        # must open before any step() call
        if self.enabled and self.repeat > 0 and self.skip == 0:
            self._start()
        return self

    def _start(self) -> None:
        from tpudist.utils import compat

        Path(self.log_dir).mkdir(parents=True, exist_ok=True)
        options = None
        if self.with_stack:
            # None on old jax (no ProfileOptions): the trace still runs,
            # just without the python-stack tracer levels
            options = compat.profile_options(
                python_tracer_level=1, host_tracer_level=2
            )
        compat.start_trace(self.log_dir, options)
        self._tracing = True

    def annotate(self, step_num: int):
        """Context manager bracketing one training step: a
        ``StepTraceAnnotation`` while a window is recording (XProf's
        step-time attribution), a no-op otherwise."""
        if self._tracing:
            return jax.profiler.StepTraceAnnotation(
                "tpudist_train", step_num=step_num
            )
        return contextlib.nullcontext()

    def arm(self, active_steps: int) -> bool:
        """Open an on-demand capture window NOW for the next
        ``active_steps`` iterations — the telemetry flight recorder's
        anomaly capture (tpudist.telemetry), independent of the
        wait/warmup/active schedule and usable even after every scheduled
        ``repeat`` cycle has run. While a window (scheduled or armed) is
        already recording, the anomaly is already in a trace: the call
        extends nothing and reports True. Returns False when disabled —
        the caller logs ``profiler_armed: false`` rather than losing the
        anomaly event itself."""
        if not self.enabled or active_steps <= 0:
            return False
        with self._mutex:
            # same mutex as step()/flush_armed(): an arm racing the
            # watchdog thread's flush must either land before the close
            # (and be flushed with it) or open a fresh window after it —
            # never overlap a start with an in-flight stop, and never
            # report "already tracing" about a window being torn down
            if self._tracing:
                return True
            self._armed = active_steps
            self._start()
            return True

    def step(self) -> None:
        """Advance the schedule; call once per training iteration
        (the ``p.step()`` of /root/reference/main.py:115)."""
        with self._mutex:
            if self._armed:
                # an armed window counts its own steps and leaves the
                # scheduled state machine (cycle/step counters) exactly
                # where it froze
                self._armed -= 1
                if self._armed <= 0 and self._tracing:
                    self._close_armed()
                return
            if not self.enabled or self._cycle >= self.repeat:
                return
            self._step += 1
            if self._tracing and self._step >= self.skip + self.active:
                self._stop()
                if self._cycle < self.repeat and self.skip == 0:
                    self._start()
            elif not self._tracing and self._step == self.skip:
                self._start()

    def flush_armed(self) -> None:
        """Close a currently-armed on-demand window NOW, flushing its
        trace to disk — the hang watchdog's crash path
        (tpudist.telemetry.health): a hung job's armed anomaly window
        would otherwise die unwritten with the process. Scheduled windows
        are left alone (their cycle accounting belongs to the main
        thread); no-op when nothing is armed. Safe from the watchdog
        thread: the mutex makes the close atomic against a resumed main
        thread's step()."""
        with self._mutex:
            if self._tracing and self._armed:
                self._close_armed()

    def _close_armed(self) -> None:
        # the armed-window teardown, shared by step()'s countdown and
        # __exit__'s flush: closes the trace WITHOUT touching the scheduled
        # cycle/step counters (contrast _stop)
        self._armed = 0
        jax.profiler.stop_trace()
        self._tracing = False
        logger.info("anomaly-armed trace written to %s", self.log_dir)

    def _stop(self) -> None:
        # block_until_ready is implicit: stop_trace flushes what the runtime
        # has; callers log loss each step so device work is already synced.
        jax.profiler.stop_trace()
        self._tracing = False
        self._cycle += 1
        self._step = 0
        logger.info("profiler trace written to %s", self.log_dir)

    def __exit__(self, *exc):
        with self._mutex:
            if self._tracing:
                if self._armed:
                    # a run ending mid-anomaly-capture must not consume a
                    # scheduled repeat that never ran
                    self._close_armed()
                else:
                    self._stop()
