"""Weight interop: Hugging Face checkpoints → tpudist model params.

The reference trains from random init only (SURVEY.md §5: no persistence,
/root/reference/main.py:40), but a framework its users switch to needs to
ingest the ecosystem's pretrained weights. These converters map a GPT-2 /
Llama / BERT / T5 ``state_dict`` (any mapping of name → array; torch
tensors work via ``numpy()``) onto the exact parameter trees of the
corresponding :mod:`tpudist.models` classes — every model family carries
the same from/to-HF contract.

They double as an external correctness oracle: the test suite builds tiny
randomly-initialized HF models (no network), converts their weights, and
checks our logits against transformers' — validating attention scaling,
GELU flavor, LayerNorm/RMSNorm placement, RoPE convention, and GQA head
layout against an independent implementation.

Layout notes (the whole conversion is layout bookkeeping):

- HF GPT-2 uses ``Conv1D`` (weights stored ``[in, out]`` — same as flax
  Dense kernels, no transpose); qkv is packed ``[D, 3D]`` column-wise.
- HF Llama uses ``nn.Linear`` (weights ``[out, in]`` — transpose), heads
  flattened head-major, which matches ``W.T.reshape(D, H, dh)``.
- HF Llama's rotary (q·cos + rotate_half(q)·sin over concatenated halves)
  is exactly :func:`tpudist.models.llama.apply_rope`'s rotate-half form.
"""

from __future__ import annotations

import numpy as np


def _np(x) -> np.ndarray:
    """Accept numpy arrays, jax arrays, or torch tensors (incl. bf16 —
    numpy has no bfloat16, so torch tensors upcast before .numpy())."""
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, np.float32)


def load_hf_state_dict(path) -> dict:
    """A local HF checkpoint (dir or single file) → {name: tensor}.

    Reads ``*.safetensors`` (preferred; sharded checkpoints concatenate) or
    ``pytorch_model*.bin``. No network access — point it at a directory
    downloaded elsewhere (``from_pretrained``'s cache layout works).
    """
    from pathlib import Path

    p = Path(path)
    if p.is_dir():
        files = sorted(p.glob("*.safetensors")) or sorted(p.glob("pytorch_model*.bin"))
        if not files:
            raise FileNotFoundError(
                f"{p} holds no *.safetensors or pytorch_model*.bin"
            )
    elif p.exists():
        files = [p]
    else:
        raise FileNotFoundError(str(p))
    sd = {}
    for f in files:
        if f.suffix == ".safetensors":
            # the torch loader handles bf16 (numpy has no bfloat16)
            from safetensors.torch import load_file

            sd.update(load_file(str(f)))
        else:
            import torch

            sd.update(torch.load(f, map_location="cpu", weights_only=True))
    return sd


def gpt2_params_from_hf(state_dict, *, depth: int, num_heads: int) -> dict:
    """HF ``GPT2LMHeadModel``/``GPT2Model`` state dict → ``GPT2`` params.

    The LM head is weight-tied in both implementations, so only ``wte``
    transfers. Keys may carry the ``transformer.`` prefix or not.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    wte = _np(sd["wte.weight"])
    d = wte.shape[1]
    h = num_heads
    dh = d // h

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    params = {
        "wte": wte,
        "wpe": _np(sd["wpe.weight"]),
        "ln_f": ln("ln_f"),
    }
    for i in range(depth):
        p = f"h.{i}"
        params[f"h_{i}"] = {
            "ln_1": ln(f"{p}.ln_1"),
            "ln_2": ln(f"{p}.ln_2"),
            # Conv1D packs q|k|v along the output dim: [D, 3D] → [D, 3, H, dh]
            "qkv": {
                "kernel": _np(sd[f"{p}.attn.c_attn.weight"]).reshape(d, 3, h, dh),
                "bias": _np(sd[f"{p}.attn.c_attn.bias"]).reshape(3, h, dh),
            },
            # out projection contracts (H, dh) → [H, dh, D]
            "out": {
                "kernel": _np(sd[f"{p}.attn.c_proj.weight"]).reshape(h, dh, d),
                "bias": _np(sd[f"{p}.attn.c_proj.bias"]),
            },
            "mlp_fc": {
                "kernel": _np(sd[f"{p}.mlp.c_fc.weight"]),
                "bias": _np(sd[f"{p}.mlp.c_fc.bias"]),
            },
            "mlp_proj": {
                "kernel": _np(sd[f"{p}.mlp.c_proj.weight"]),
                "bias": _np(sd[f"{p}.mlp.c_proj.bias"]),
            },
        }
    return params


def llama_params_from_hf(
    state_dict, *, depth: int, num_heads: int, num_kv_heads: int | None = None,
) -> dict:
    """HF ``LlamaForCausalLM``/``LlamaModel`` state dict → ``Llama`` params.

    Handles GQA (``num_kv_heads < num_heads``) and both tied and untied
    heads (``lm_head`` is emitted only when present and untied — pass the
    result to a ``Llama(tie_embeddings=...)`` that matches).
    """
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    embed = _np(sd["embed_tokens.weight"])
    d = embed.shape[1]
    h = num_heads
    kv = num_kv_heads or h
    dh = d // h

    def lin(key, out_shape):
        # torch Linear stores [out, in]; flax kernels are [in, out...]
        return {"kernel": _np(sd[key]).T.reshape(out_shape)}

    params = {
        "embed": embed,
        "norm": {"scale": _np(sd["norm.weight"])},
    }
    for i in range(depth):
        p = f"layers.{i}"
        params[f"layer_{i}"] = {
            "attn_norm": {"scale": _np(sd[f"{p}.input_layernorm.weight"])},
            "mlp_norm": {"scale": _np(sd[f"{p}.post_attention_layernorm.weight"])},
            "q_proj": lin(f"{p}.self_attn.q_proj.weight", (d, h, dh)),
            "k_proj": lin(f"{p}.self_attn.k_proj.weight", (d, kv, dh)),
            "v_proj": lin(f"{p}.self_attn.v_proj.weight", (d, kv, dh)),
            "o_proj": {
                "kernel": _np(sd[f"{p}.self_attn.o_proj.weight"]).T.reshape(h, dh, d)
            },
            "gate_proj": {"kernel": _np(sd[f"{p}.mlp.gate_proj.weight"]).T},
            "up_proj": {"kernel": _np(sd[f"{p}.mlp.up_proj.weight"]).T},
            "down_proj": {"kernel": _np(sd[f"{p}.mlp.down_proj.weight"]).T},
        }
    if "lm_head.weight" in state_dict:
        head = _np(state_dict["lm_head.weight"])
        if not np.shares_memory(head, embed) and not np.array_equal(head, embed):
            params["lm_head"] = head
    return params


def bert_params_from_hf(state_dict, *, depth: int, num_heads: int) -> dict:
    """HF ``BertForMaskedLM``/``BertModel`` state dict →
    :class:`tpudist.models.bert.Bert` params.

    Linears are ``nn.Linear`` ([out, in] — transpose); q/k/v are separate
    and stack into our packed ``qkv`` kernel; the MLM head maps
    ``cls.predictions.transform``/``.bias`` onto ``mlm_head`` (the decoder
    matrix is tied to ``wte`` in both). The pooler (absent from the MLM
    loss) is ignored.
    """
    sd = {k.removeprefix("bert."): v for k, v in state_dict.items()}
    wte = _np(sd["embeddings.word_embeddings.weight"])
    d = wte.shape[1]
    h = num_heads
    dh = d // h

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    params = {
        "wte": wte,
        "wpe": _np(sd["embeddings.position_embeddings.weight"]),
        "wty": _np(sd["embeddings.token_type_embeddings.weight"]),
        "ln_embed": ln("embeddings.LayerNorm"),
    }
    for i in range(depth):
        p = f"encoder.layer.{i}"
        qkv_k = np.stack(
            [
                _np(sd[f"{p}.attention.self.{n}.weight"]).T.reshape(d, h, dh)
                for n in ("query", "key", "value")
            ],
            axis=1,
        )  # [D, 3, H, dh]
        qkv_b = np.stack(
            [
                _np(sd[f"{p}.attention.self.{n}.bias"]).reshape(h, dh)
                for n in ("query", "key", "value")
            ],
            axis=0,
        )  # [3, H, dh]
        params[f"h_{i}"] = {
            "qkv": {"kernel": qkv_k, "bias": qkv_b},
            "out": {
                "kernel": _np(
                    sd[f"{p}.attention.output.dense.weight"]
                ).T.reshape(h, dh, d),
                "bias": _np(sd[f"{p}.attention.output.dense.bias"]),
            },
            "ln_attn": ln(f"{p}.attention.output.LayerNorm"),
            "mlp_fc": {
                "kernel": _np(sd[f"{p}.intermediate.dense.weight"]).T,
                "bias": _np(sd[f"{p}.intermediate.dense.bias"]),
            },
            "mlp_proj": {
                "kernel": _np(sd[f"{p}.output.dense.weight"]).T,
                "bias": _np(sd[f"{p}.output.dense.bias"]),
            },
            "ln_mlp": ln(f"{p}.output.LayerNorm"),
        }
    if "cls.predictions.transform.dense.weight" in state_dict:
        params["mlm_head"] = {
            "transform": {
                "kernel": _np(
                    state_dict["cls.predictions.transform.dense.weight"]
                ).T,
                "bias": _np(state_dict["cls.predictions.transform.dense.bias"]),
            },
            "ln": {
                "scale": _np(
                    state_dict["cls.predictions.transform.LayerNorm.weight"]
                ),
                "bias": _np(
                    state_dict["cls.predictions.transform.LayerNorm.bias"]
                ),
            },
            "bias": _np(state_dict["cls.predictions.bias"]),
        }
    return params


def bert_params_to_hf(params, *, depth: int) -> dict:
    """Inverse of :func:`bert_params_from_hf`: ``Bert`` params → a state
    dict loadable by HF ``BertForMaskedLM.load_state_dict(strict=False)``
    (strict=False for HF's position_ids buffer and the pooler, which the
    MLM model doesn't train)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    wte = _np(p["wte"])
    d = wte.shape[1]
    sd = {
        "bert.embeddings.word_embeddings.weight": wte,
        "bert.embeddings.position_embeddings.weight": _np(p["wpe"]),
        "bert.embeddings.token_type_embeddings.weight": _np(p["wty"]),
        "bert.embeddings.LayerNorm.weight": _np(p["ln_embed"]["scale"]),
        "bert.embeddings.LayerNorm.bias": _np(p["ln_embed"]["bias"]),
        "cls.predictions.decoder.weight": wte,  # tied
    }
    for i in range(depth):
        blk = p[f"h_{i}"]
        o = f"bert.encoder.layer.{i}"
        qkv_k = _np(blk["qkv"]["kernel"])  # [D, 3, H, dh]
        qkv_b = _np(blk["qkv"]["bias"])    # [3, H, dh]
        for j, n in enumerate(("query", "key", "value")):
            sd[f"{o}.attention.self.{n}.weight"] = qkv_k[:, j].reshape(d, d).T
            sd[f"{o}.attention.self.{n}.bias"] = qkv_b[j].reshape(d)
        sd[f"{o}.attention.output.dense.weight"] = (
            _np(blk["out"]["kernel"]).reshape(d, d).T
        )
        sd[f"{o}.attention.output.dense.bias"] = _np(blk["out"]["bias"])
        sd[f"{o}.attention.output.LayerNorm.weight"] = _np(blk["ln_attn"]["scale"])
        sd[f"{o}.attention.output.LayerNorm.bias"] = _np(blk["ln_attn"]["bias"])
        sd[f"{o}.intermediate.dense.weight"] = _np(blk["mlp_fc"]["kernel"]).T
        sd[f"{o}.intermediate.dense.bias"] = _np(blk["mlp_fc"]["bias"])
        sd[f"{o}.output.dense.weight"] = _np(blk["mlp_proj"]["kernel"]).T
        sd[f"{o}.output.dense.bias"] = _np(blk["mlp_proj"]["bias"])
        sd[f"{o}.output.LayerNorm.weight"] = _np(blk["ln_mlp"]["scale"])
        sd[f"{o}.output.LayerNorm.bias"] = _np(blk["ln_mlp"]["bias"])
    if "mlm_head" in p:
        head = p["mlm_head"]
        sd["cls.predictions.transform.dense.weight"] = (
            _np(head["transform"]["kernel"]).T
        )
        sd["cls.predictions.transform.dense.bias"] = _np(head["transform"]["bias"])
        sd["cls.predictions.transform.LayerNorm.weight"] = _np(head["ln"]["scale"])
        sd["cls.predictions.transform.LayerNorm.bias"] = _np(head["ln"]["bias"])
        sd["cls.predictions.bias"] = _np(head["bias"])
        sd["cls.predictions.decoder.bias"] = _np(head["bias"])
    return sd


def t5_params_from_hf(
    state_dict, *, enc_depth: int, dec_depth: int, num_heads: int,
) -> dict:
    """HF ``T5ForConditionalGeneration`` (v1.1 conventions: gated-gelu,
    untied lm_head) state dict → :class:`tpudist.models.t5.T5` params.

    Linears are ``nn.Linear`` ([out, in] — transpose); the shared relative
    position bias lives on block 0 in HF and as the stack-level
    ``enc_rel_bias``/``dec_rel_bias`` params here (the same sharing, two
    spellings). Encoder/decoder embeddings are the tied ``shared.weight``.
    """
    sd = state_dict
    wte = _np(sd["shared.weight"])
    d = wte.shape[1]
    h = num_heads
    inner = _np(sd["encoder.block.0.layer.0.SelfAttention.q.weight"]).shape[0]
    dh = inner // h

    def lin(key, out_shape):
        return {"kernel": _np(sd[key]).T.reshape(out_shape)}

    def attn(prefix):
        return {
            "q": lin(f"{prefix}.q.weight", (d, h, dh)),
            "k": lin(f"{prefix}.k.weight", (d, h, dh)),
            "v": lin(f"{prefix}.v.weight", (d, h, dh)),
            "out": {
                "kernel": _np(sd[f"{prefix}.o.weight"]).T.reshape(h, dh, d)
            },
        }

    def mlp(prefix):
        return {
            "wi_0": {"kernel": _np(sd[f"{prefix}.wi_0.weight"]).T},
            "wi_1": {"kernel": _np(sd[f"{prefix}.wi_1.weight"]).T},
            "wo": {"kernel": _np(sd[f"{prefix}.wo.weight"]).T},
        }

    def scale(key):
        return {"scale": _np(sd[key])}

    params = {
        "wte": wte,
        "enc_rel_bias": _np(
            sd["encoder.block.0.layer.0.SelfAttention"
               ".relative_attention_bias.weight"]
        ),
        "dec_rel_bias": _np(
            sd["decoder.block.0.layer.0.SelfAttention"
               ".relative_attention_bias.weight"]
        ),
        "ln_enc": scale("encoder.final_layer_norm.weight"),
        "ln_dec": scale("decoder.final_layer_norm.weight"),
        "lm_head": {"kernel": _np(sd["lm_head.weight"]).T},
    }
    for i in range(enc_depth):
        p = f"encoder.block.{i}"
        params[f"enc_{i}"] = {
            "ln_attn": scale(f"{p}.layer.0.layer_norm.weight"),
            "attn": attn(f"{p}.layer.0.SelfAttention"),
            "ln_mlp": scale(f"{p}.layer.1.layer_norm.weight"),
            "mlp": mlp(f"{p}.layer.1.DenseReluDense"),
        }
    for i in range(dec_depth):
        p = f"decoder.block.{i}"
        params[f"dec_{i}"] = {
            "ln_self": scale(f"{p}.layer.0.layer_norm.weight"),
            "self_attn": attn(f"{p}.layer.0.SelfAttention"),
            "ln_cross": scale(f"{p}.layer.1.layer_norm.weight"),
            "cross_attn": attn(f"{p}.layer.1.EncDecAttention"),
            "ln_mlp": scale(f"{p}.layer.2.layer_norm.weight"),
            "mlp": mlp(f"{p}.layer.2.DenseReluDense"),
        }
    return params


def t5_params_to_hf(params, *, enc_depth: int, dec_depth: int) -> dict:
    """Inverse of :func:`t5_params_from_hf`: ``T5`` params → a state dict
    loadable by HF ``T5ForConditionalGeneration.load_state_dict`` on a
    matching v1.1 config (``feed_forward_proj="gated-gelu"``,
    ``tie_word_embeddings=False``)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    wte = _np(p["wte"])
    d = wte.shape[1]

    sd = {
        "shared.weight": wte,
        "encoder.embed_tokens.weight": wte,
        "decoder.embed_tokens.weight": wte,
        "encoder.block.0.layer.0.SelfAttention"
        ".relative_attention_bias.weight": _np(p["enc_rel_bias"]),
        "decoder.block.0.layer.0.SelfAttention"
        ".relative_attention_bias.weight": _np(p["dec_rel_bias"]),
        "encoder.final_layer_norm.weight": _np(p["ln_enc"]["scale"]),
        "decoder.final_layer_norm.weight": _np(p["ln_dec"]["scale"]),
        "lm_head.weight": _np(p["lm_head"]["kernel"]).T,
    }

    def put_attn(prefix, blk):
        sd[f"{prefix}.q.weight"] = _np(blk["q"]["kernel"]).reshape(d, -1).T
        sd[f"{prefix}.k.weight"] = _np(blk["k"]["kernel"]).reshape(d, -1).T
        sd[f"{prefix}.v.weight"] = _np(blk["v"]["kernel"]).reshape(d, -1).T
        sd[f"{prefix}.o.weight"] = _np(blk["out"]["kernel"]).reshape(-1, d).T

    def put_mlp(prefix, blk):
        sd[f"{prefix}.wi_0.weight"] = _np(blk["wi_0"]["kernel"]).T
        sd[f"{prefix}.wi_1.weight"] = _np(blk["wi_1"]["kernel"]).T
        sd[f"{prefix}.wo.weight"] = _np(blk["wo"]["kernel"]).T

    for i in range(enc_depth):
        blk = p[f"enc_{i}"]
        o = f"encoder.block.{i}"
        sd[f"{o}.layer.0.layer_norm.weight"] = _np(blk["ln_attn"]["scale"])
        put_attn(f"{o}.layer.0.SelfAttention", blk["attn"])
        sd[f"{o}.layer.1.layer_norm.weight"] = _np(blk["ln_mlp"]["scale"])
        put_mlp(f"{o}.layer.1.DenseReluDense", blk["mlp"])
    for i in range(dec_depth):
        blk = p[f"dec_{i}"]
        o = f"decoder.block.{i}"
        sd[f"{o}.layer.0.layer_norm.weight"] = _np(blk["ln_self"]["scale"])
        put_attn(f"{o}.layer.0.SelfAttention", blk["self_attn"])
        sd[f"{o}.layer.1.layer_norm.weight"] = _np(blk["ln_cross"]["scale"])
        put_attn(f"{o}.layer.1.EncDecAttention", blk["cross_attn"])
        sd[f"{o}.layer.2.layer_norm.weight"] = _np(blk["ln_mlp"]["scale"])
        put_mlp(f"{o}.layer.2.DenseReluDense", blk["mlp"])
    return sd


def load_hf_params(
    path, *, arch: str, depth: int, num_heads: int,
    num_kv_heads: int | None = None,
) -> dict:
    """One-call warm-start: local HF checkpoint → tpudist params for the
    named architecture (the import-side twin of :func:`save_hf_checkpoint`)."""
    sd = load_hf_state_dict(path)
    if arch == "gpt2":
        return gpt2_params_from_hf(sd, depth=depth, num_heads=num_heads)
    if arch == "llama":
        return llama_params_from_hf(
            sd, depth=depth, num_heads=num_heads, num_kv_heads=num_kv_heads
        )
    if arch == "bert":
        return bert_params_from_hf(sd, depth=depth, num_heads=num_heads)
    if arch == "t5":
        # symmetric stacks (the published t5/v1.1 geometries); call
        # t5_params_from_hf directly for asymmetric enc/dec depths
        return t5_params_from_hf(
            sd, enc_depth=depth, dec_depth=depth, num_heads=num_heads
        )
    raise ValueError(f"unknown arch {arch!r} (want gpt2, llama, bert, or t5)")


def save_hf_checkpoint(params, path, *, arch: str, depth: int) -> None:
    """Write tpudist params as an HF-layout ``model.safetensors`` under
    ``path`` — the hand-off back to the torch/transformers ecosystem
    (loadable with ``load_state_dict`` on the matching config; pair with
    the architecture's config.json as needed)."""
    import os

    from safetensors.numpy import save_file

    if arch == "gpt2":
        sd = gpt2_params_to_hf(params, depth=depth)
    elif arch == "llama":
        sd = llama_params_to_hf(params, depth=depth)
    elif arch == "bert":
        sd = bert_params_to_hf(params, depth=depth)
    elif arch == "t5":
        sd = t5_params_to_hf(params, enc_depth=depth, dec_depth=depth)
    else:
        raise ValueError(f"unknown arch {arch!r} (want gpt2, llama, bert, or t5)")
    os.makedirs(path, exist_ok=True)
    save_file(
        {k: np.ascontiguousarray(v) for k, v in sd.items()},
        os.path.join(path, "model.safetensors"),
        # transformers' from_pretrained refuses metadata-less safetensors
        metadata={"format": "pt"},
    )


def gpt2_params_to_hf(params, *, depth: int) -> dict:
    """Inverse of :func:`gpt2_params_from_hf`: ``GPT2`` params → a state
    dict loadable by HF ``GPT2LMHeadModel.load_state_dict(strict=False)``
    (strict=False only because HF registers non-weight buffers like the
    causal-mask ``attn.bias``)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    wte = _np(p["wte"])
    d = wte.shape[1]
    sd = {
        "transformer.wte.weight": wte,
        "transformer.wpe.weight": _np(p["wpe"]),
        "transformer.ln_f.weight": _np(p["ln_f"]["scale"]),
        "transformer.ln_f.bias": _np(p["ln_f"]["bias"]),
        "lm_head.weight": wte,  # tied
    }
    for i in range(depth):
        blk = p[f"h_{i}"]
        o = f"transformer.h.{i}"
        sd[f"{o}.ln_1.weight"] = _np(blk["ln_1"]["scale"])
        sd[f"{o}.ln_1.bias"] = _np(blk["ln_1"]["bias"])
        sd[f"{o}.ln_2.weight"] = _np(blk["ln_2"]["scale"])
        sd[f"{o}.ln_2.bias"] = _np(blk["ln_2"]["bias"])
        sd[f"{o}.attn.c_attn.weight"] = _np(blk["qkv"]["kernel"]).reshape(d, 3 * d)
        sd[f"{o}.attn.c_attn.bias"] = _np(blk["qkv"]["bias"]).reshape(3 * d)
        sd[f"{o}.attn.c_proj.weight"] = _np(blk["out"]["kernel"]).reshape(d, d)
        sd[f"{o}.attn.c_proj.bias"] = _np(blk["out"]["bias"])
        sd[f"{o}.mlp.c_fc.weight"] = _np(blk["mlp_fc"]["kernel"])
        sd[f"{o}.mlp.c_fc.bias"] = _np(blk["mlp_fc"]["bias"])
        sd[f"{o}.mlp.c_proj.weight"] = _np(blk["mlp_proj"]["kernel"])
        sd[f"{o}.mlp.c_proj.bias"] = _np(blk["mlp_proj"]["bias"])
    return sd


def llama_params_to_hf(params, *, depth: int) -> dict:
    """Inverse of :func:`llama_params_from_hf`: ``Llama`` params → a state
    dict loadable by HF ``LlamaForCausalLM.load_state_dict`` (tied models
    emit ``lm_head.weight`` = embedding, matching
    ``tie_word_embeddings=True``)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    embed = _np(p["embed"])
    d = embed.shape[1]
    sd = {
        "model.embed_tokens.weight": embed,
        "model.norm.weight": _np(p["norm"]["scale"]),
        "lm_head.weight": _np(p.get("lm_head", p["embed"])),
    }
    for i in range(depth):
        blk = p[f"layer_{i}"]
        o = f"model.layers.{i}"
        sd[f"{o}.input_layernorm.weight"] = _np(blk["attn_norm"]["scale"])
        sd[f"{o}.post_attention_layernorm.weight"] = _np(blk["mlp_norm"]["scale"])
        for ours, theirs in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                             ("v_proj", "v_proj")):
            k = _np(blk[ours]["kernel"])           # [D, H, dh]
            sd[f"{o}.self_attn.{theirs}.weight"] = k.reshape(d, -1).T
        sd[f"{o}.self_attn.o_proj.weight"] = (
            _np(blk["o_proj"]["kernel"]).reshape(-1, d).T
        )
        sd[f"{o}.mlp.gate_proj.weight"] = _np(blk["gate_proj"]["kernel"]).T
        sd[f"{o}.mlp.up_proj.weight"] = _np(blk["up_proj"]["kernel"]).T
        sd[f"{o}.mlp.down_proj.weight"] = _np(blk["down_proj"]["kernel"]).T
    return sd
