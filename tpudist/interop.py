"""Weight interop: Hugging Face checkpoints → tpudist model params.

The reference trains from random init only (SURVEY.md §5: no persistence,
/root/reference/main.py:40), but a framework its users switch to needs to
ingest the ecosystem's pretrained weights. These converters map a GPT-2 /
Llama ``state_dict`` (any mapping of name → array; torch tensors work via
``numpy()``) onto the exact parameter trees of
:class:`tpudist.models.gpt2.GPT2` and :class:`tpudist.models.llama.Llama`.

They double as an external correctness oracle: the test suite builds tiny
randomly-initialized HF models (no network), converts their weights, and
checks our logits against transformers' — validating attention scaling,
GELU flavor, LayerNorm/RMSNorm placement, RoPE convention, and GQA head
layout against an independent implementation.

Layout notes (the whole conversion is layout bookkeeping):

- HF GPT-2 uses ``Conv1D`` (weights stored ``[in, out]`` — same as flax
  Dense kernels, no transpose); qkv is packed ``[D, 3D]`` column-wise.
- HF Llama uses ``nn.Linear`` (weights ``[out, in]`` — transpose), heads
  flattened head-major, which matches ``W.T.reshape(D, H, dh)``.
- HF Llama's rotary (q·cos + rotate_half(q)·sin over concatenated halves)
  is exactly :func:`tpudist.models.llama.apply_rope`'s rotate-half form.
"""

from __future__ import annotations

import numpy as np


def _np(x) -> np.ndarray:
    """Accept numpy arrays, jax arrays, or torch tensors (incl. bf16 —
    numpy has no bfloat16, so torch tensors upcast before .numpy())."""
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, np.float32)


def load_hf_state_dict(path) -> dict:
    """A local HF checkpoint (dir or single file) → {name: tensor}.

    Reads ``*.safetensors`` (preferred; sharded checkpoints concatenate) or
    ``pytorch_model*.bin``. No network access — point it at a directory
    downloaded elsewhere (``from_pretrained``'s cache layout works).
    """
    from pathlib import Path

    p = Path(path)
    if p.is_dir():
        files = sorted(p.glob("*.safetensors")) or sorted(p.glob("pytorch_model*.bin"))
        if not files:
            raise FileNotFoundError(
                f"{p} holds no *.safetensors or pytorch_model*.bin"
            )
    elif p.exists():
        files = [p]
    else:
        raise FileNotFoundError(str(p))
    sd = {}
    for f in files:
        if f.suffix == ".safetensors":
            # the torch loader handles bf16 (numpy has no bfloat16)
            from safetensors.torch import load_file

            sd.update(load_file(str(f)))
        else:
            import torch

            sd.update(torch.load(f, map_location="cpu", weights_only=True))
    return sd


def gpt2_params_from_hf(state_dict, *, depth: int, num_heads: int) -> dict:
    """HF ``GPT2LMHeadModel``/``GPT2Model`` state dict → ``GPT2`` params.

    The LM head is weight-tied in both implementations, so only ``wte``
    transfers. Keys may carry the ``transformer.`` prefix or not.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    wte = _np(sd["wte.weight"])
    d = wte.shape[1]
    h = num_heads
    dh = d // h

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    params = {
        "wte": wte,
        "wpe": _np(sd["wpe.weight"]),
        "ln_f": ln("ln_f"),
    }
    for i in range(depth):
        p = f"h.{i}"
        params[f"h_{i}"] = {
            "ln_1": ln(f"{p}.ln_1"),
            "ln_2": ln(f"{p}.ln_2"),
            # Conv1D packs q|k|v along the output dim: [D, 3D] → [D, 3, H, dh]
            "qkv": {
                "kernel": _np(sd[f"{p}.attn.c_attn.weight"]).reshape(d, 3, h, dh),
                "bias": _np(sd[f"{p}.attn.c_attn.bias"]).reshape(3, h, dh),
            },
            # out projection contracts (H, dh) → [H, dh, D]
            "out": {
                "kernel": _np(sd[f"{p}.attn.c_proj.weight"]).reshape(h, dh, d),
                "bias": _np(sd[f"{p}.attn.c_proj.bias"]),
            },
            "mlp_fc": {
                "kernel": _np(sd[f"{p}.mlp.c_fc.weight"]),
                "bias": _np(sd[f"{p}.mlp.c_fc.bias"]),
            },
            "mlp_proj": {
                "kernel": _np(sd[f"{p}.mlp.c_proj.weight"]),
                "bias": _np(sd[f"{p}.mlp.c_proj.bias"]),
            },
        }
    return params


def llama_params_from_hf(
    state_dict, *, depth: int, num_heads: int, num_kv_heads: int | None = None,
) -> dict:
    """HF ``LlamaForCausalLM``/``LlamaModel`` state dict → ``Llama`` params.

    Handles GQA (``num_kv_heads < num_heads``) and both tied and untied
    heads (``lm_head`` is emitted only when present and untied — pass the
    result to a ``Llama(tie_embeddings=...)`` that matches).
    """
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    embed = _np(sd["embed_tokens.weight"])
    d = embed.shape[1]
    h = num_heads
    kv = num_kv_heads or h
    dh = d // h

    def lin(key, out_shape):
        # torch Linear stores [out, in]; flax kernels are [in, out...]
        return {"kernel": _np(sd[key]).T.reshape(out_shape)}

    params = {
        "embed": embed,
        "norm": {"scale": _np(sd["norm.weight"])},
    }
    for i in range(depth):
        p = f"layers.{i}"
        params[f"layer_{i}"] = {
            "attn_norm": {"scale": _np(sd[f"{p}.input_layernorm.weight"])},
            "mlp_norm": {"scale": _np(sd[f"{p}.post_attention_layernorm.weight"])},
            "q_proj": lin(f"{p}.self_attn.q_proj.weight", (d, h, dh)),
            "k_proj": lin(f"{p}.self_attn.k_proj.weight", (d, kv, dh)),
            "v_proj": lin(f"{p}.self_attn.v_proj.weight", (d, kv, dh)),
            "o_proj": {
                "kernel": _np(sd[f"{p}.self_attn.o_proj.weight"]).T.reshape(h, dh, d)
            },
            "gate_proj": {"kernel": _np(sd[f"{p}.mlp.gate_proj.weight"]).T},
            "up_proj": {"kernel": _np(sd[f"{p}.mlp.up_proj.weight"]).T},
            "down_proj": {"kernel": _np(sd[f"{p}.mlp.down_proj.weight"]).T},
        }
    if "lm_head.weight" in state_dict:
        head = _np(state_dict["lm_head.weight"])
        if not np.shares_memory(head, embed) and not np.array_equal(head, embed):
            params["lm_head"] = head
    return params


def load_hf_params(
    path, *, arch: str, depth: int, num_heads: int,
    num_kv_heads: int | None = None,
) -> dict:
    """One-call warm-start: local HF checkpoint → tpudist params for the
    named architecture (the import-side twin of :func:`save_hf_checkpoint`)."""
    sd = load_hf_state_dict(path)
    if arch == "gpt2":
        return gpt2_params_from_hf(sd, depth=depth, num_heads=num_heads)
    if arch == "llama":
        return llama_params_from_hf(
            sd, depth=depth, num_heads=num_heads, num_kv_heads=num_kv_heads
        )
    raise ValueError(f"unknown arch {arch!r} (want gpt2 or llama)")


def save_hf_checkpoint(params, path, *, arch: str, depth: int) -> None:
    """Write tpudist params as an HF-layout ``model.safetensors`` under
    ``path`` — the hand-off back to the torch/transformers ecosystem
    (loadable with ``load_state_dict`` on the matching config; pair with
    the architecture's config.json as needed)."""
    import os

    from safetensors.numpy import save_file

    if arch == "gpt2":
        sd = gpt2_params_to_hf(params, depth=depth)
    elif arch == "llama":
        sd = llama_params_to_hf(params, depth=depth)
    else:
        raise ValueError(f"unknown arch {arch!r} (want gpt2 or llama)")
    os.makedirs(path, exist_ok=True)
    save_file(
        {k: np.ascontiguousarray(v) for k, v in sd.items()},
        os.path.join(path, "model.safetensors"),
        # transformers' from_pretrained refuses metadata-less safetensors
        metadata={"format": "pt"},
    )


def gpt2_params_to_hf(params, *, depth: int) -> dict:
    """Inverse of :func:`gpt2_params_from_hf`: ``GPT2`` params → a state
    dict loadable by HF ``GPT2LMHeadModel.load_state_dict(strict=False)``
    (strict=False only because HF registers non-weight buffers like the
    causal-mask ``attn.bias``)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    wte = _np(p["wte"])
    d = wte.shape[1]
    sd = {
        "transformer.wte.weight": wte,
        "transformer.wpe.weight": _np(p["wpe"]),
        "transformer.ln_f.weight": _np(p["ln_f"]["scale"]),
        "transformer.ln_f.bias": _np(p["ln_f"]["bias"]),
        "lm_head.weight": wte,  # tied
    }
    for i in range(depth):
        blk = p[f"h_{i}"]
        o = f"transformer.h.{i}"
        sd[f"{o}.ln_1.weight"] = _np(blk["ln_1"]["scale"])
        sd[f"{o}.ln_1.bias"] = _np(blk["ln_1"]["bias"])
        sd[f"{o}.ln_2.weight"] = _np(blk["ln_2"]["scale"])
        sd[f"{o}.ln_2.bias"] = _np(blk["ln_2"]["bias"])
        sd[f"{o}.attn.c_attn.weight"] = _np(blk["qkv"]["kernel"]).reshape(d, 3 * d)
        sd[f"{o}.attn.c_attn.bias"] = _np(blk["qkv"]["bias"]).reshape(3 * d)
        sd[f"{o}.attn.c_proj.weight"] = _np(blk["out"]["kernel"]).reshape(d, d)
        sd[f"{o}.attn.c_proj.bias"] = _np(blk["out"]["bias"])
        sd[f"{o}.mlp.c_fc.weight"] = _np(blk["mlp_fc"]["kernel"])
        sd[f"{o}.mlp.c_fc.bias"] = _np(blk["mlp_fc"]["bias"])
        sd[f"{o}.mlp.c_proj.weight"] = _np(blk["mlp_proj"]["kernel"])
        sd[f"{o}.mlp.c_proj.bias"] = _np(blk["mlp_proj"]["bias"])
    return sd


def llama_params_to_hf(params, *, depth: int) -> dict:
    """Inverse of :func:`llama_params_from_hf`: ``Llama`` params → a state
    dict loadable by HF ``LlamaForCausalLM.load_state_dict`` (tied models
    emit ``lm_head.weight`` = embedding, matching
    ``tie_word_embeddings=True``)."""
    from flax import linen as nn

    p = nn.meta.unbox(params)
    embed = _np(p["embed"])
    d = embed.shape[1]
    sd = {
        "model.embed_tokens.weight": embed,
        "model.norm.weight": _np(p["norm"]["scale"]),
        "lm_head.weight": _np(p.get("lm_head", p["embed"])),
    }
    for i in range(depth):
        blk = p[f"layer_{i}"]
        o = f"model.layers.{i}"
        sd[f"{o}.input_layernorm.weight"] = _np(blk["attn_norm"]["scale"])
        sd[f"{o}.post_attention_layernorm.weight"] = _np(blk["mlp_norm"]["scale"])
        for ours, theirs in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                             ("v_proj", "v_proj")):
            k = _np(blk[ours]["kernel"])           # [D, H, dh]
            sd[f"{o}.self_attn.{theirs}.weight"] = k.reshape(d, -1).T
        sd[f"{o}.self_attn.o_proj.weight"] = (
            _np(blk["o_proj"]["kernel"]).reshape(-1, d).T
        )
        sd[f"{o}.mlp.gate_proj.weight"] = _np(blk["gate_proj"]["kernel"]).T
        sd[f"{o}.mlp.up_proj.weight"] = _np(blk["up_proj"]["kernel"]).T
        sd[f"{o}.mlp.down_proj.weight"] = _np(blk["down_proj"]["kernel"]).T
    return sd
