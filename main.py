"""Parity entrypoint — the reference's ``main.py`` re-expressed TPU-native.

Same CLI contract as /root/reference/main.py:23-28 (``--local_rank``,
``--batch_size`` default 128, ``--JobID`` default "Job0"), same defaults
(``epochs=2``, ``lr=0.001``, main.py:31-32 — promoted to flags), same
training program (2 epochs of Adam on a ResNet over CIFAR-100 with
global-batch loss/BN, rank-0 TSV logging every 5 steps, console prints
every 10 batches, windowed profiler traces in ``./log_{JobID}``, terminal
``TrainTime`` row) — but the whole per-step pipeline is one pjit-compiled
SPMD program on the TPU mesh instead of eager CUDA ops + NCCL callbacks.

Launch exactly like the reference (README.md:12-35), with
``python -m tpudist.launch`` standing in for ``torch.distributed.launch``:

    # single host (all local TPU chips)
    python main.py --batch_size 128 --JobID Job0

    # multi-host (per host; master = node A)
    python -m tpudist.launch --nnode=2 --node_rank=0 --master_addr=A main.py ...
    python -m tpudist.launch --nnode=2 --node_rank=1 --master_addr=A main.py ...
"""

from __future__ import annotations

import argparse
import os


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    # flag names/defaults match /root/reference/main.py:23-28
    parser.add_argument("--local_rank", type=int, default=int(os.environ.get("LOCAL_RANK", 0)),
                        help="local process id on this host (launcher-injected)")
    parser.add_argument("--batch_size", default=128, type=int,
                        help="per-replica batch size (reference semantics: per-GPU)")
    parser.add_argument("--JobID", default="Job0", type=str, help="JOB ID")
    # hardcoded in the reference (main.py:31-32); promoted to flags with the
    # same defaults
    parser.add_argument("--epochs", default=2, type=int)
    parser.add_argument("--lr", default=0.001, type=float)
    parser.add_argument("--schedule", default="constant",
                        choices=["constant", "cosine"],
                        help="constant = reference parity (fixed lr, "
                        "main.py:32); cosine adds linear warmup + cosine "
                        "decay over the full run")
    parser.add_argument("--warmup_steps", default=0, type=int,
                        help="warmup steps for --schedule cosine")
    # capability knobs beyond the reference CLI
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet18", "resnet34", "resnet50", "resnet101", "resnet152", "vit_b16", "gpt2"])
    parser.add_argument("--dataset", default="cifar100",
                        choices=["cifar10", "cifar100", "synthetic", "imagenet",
                                 "digits"],
                        help="digits = sklearn's bundled real handwritten-"
                        "digit images (for egress-free convergence runs, "
                        "tpudist/data/digits.py)")
    parser.add_argument("--data_root", default="dataset", type=str,
                        help="CIFAR cache dir, or for --dataset imagenet an "
                        "image-folder tree with train/ and val/ class subdirs")
    parser.add_argument("--synthetic_size", default=2048, type=int)
    parser.add_argument("--image_size", default=224, type=int,
                        help="crop size for --dataset imagenet")
    parser.add_argument("--workers", default=None, type=int,
                        help="decode threads for --dataset imagenet")
    parser.add_argument("--packed", default=None, type=str,
                        help="pre-decoded pack prefix for --dataset imagenet "
                        "(tpudist.data.packed; build once with `python -m "
                        "tpudist.data.packed --root .../train --out X`) — "
                        "streams pixels from a uint8 memmap at memcpy speed "
                        "instead of re-decoding JPEGs every epoch; composes "
                        "with --device_cache (pack staged to HBM, index-only "
                        "steps)")
    parser.add_argument("--packed_val", default=None, type=str,
                        help="pack prefix for the val split (with --eval); "
                        "defaults to the image-folder val/ tree")
    parser.add_argument("--cache_shard_rows", default=0, type=int,
                        help="with --packed --device_cache: rotate the HBM "
                        "cache in shards of this many rows (for packs "
                        "larger than HBM; shard k+1 stages while shard k "
                        "trains — tpudist.data.device_cache."
                        "RotatingDeviceCache). 0 = fully resident")
    parser.add_argument("--bf16", action="store_true", help="bfloat16 compute")
    parser.add_argument("--amp", action="store_true",
                        help="mixed precision END-TO-END (tpudist.amp): the "
                        "bf16 compute policy (implies --bf16) plus the "
                        "non-finite update guard — a gradient spike skips "
                        "one optimizer step (counted) instead of poisoning "
                        "params and Adam moments")
    parser.add_argument("--stem", default="conv7",
                        choices=["conv7", "space_to_depth"],
                        help="ResNet stem; space_to_depth is the MLPerf TPU "
                        "stem (same function class, ~2.5%% faster on v5e)")
    parser.add_argument("--optimizer", default="adam",
                        choices=["adam", "sgd", "lamb", "lion", "muon"],
                        help="reference default: Adam(lr=1e-3), main.py:80")
    parser.add_argument("--weight_decay", default=0.0, type=float,
                        help="decoupled (AdamW) weight decay, 1-D params excluded")
    parser.add_argument("--clip_norm", default=None, type=float,
                        help="global gradient-norm clip")
    def _smoothing_eps(v):
        v = float(v)
        if not 0.0 <= v < 1.0:
            raise argparse.ArgumentTypeError(
                f"label smoothing must be in [0, 1), got {v}"
            )
        return v

    parser.add_argument("--label_smoothing", default=0.0, type=_smoothing_eps,
                        help="smoothed-CE epsilon in [0,1) (ImageNet recipe: "
                        "0.1); 0 = the reference's plain CE (main.py:79)")
    parser.add_argument("--grad_accum", default=1, type=int)
    parser.add_argument("--fused", default="none",
                        choices=["none", "auto", "ln", "optimizer", "all"],
                        help="step-fusion layer (docs/PERF.md §4c): 'ln' = "
                        "Pallas fused residual-add+LayerNorm in the "
                        "transformer blocks (vit_b16), 'optimizer' = the "
                        "one-pass fused-AdamW kernel (requires --optimizer "
                        "adam; under --bf16 the forward reads its bf16 "
                        "compute copy), "
                        "'all' both, 'auto' whatever model/optimizer "
                        "support")
    parser.add_argument("--reduce", default="none",
                        choices=("none", "bucketed", "quantized", "auto"),
                        help="gradient-reduction path (tpudist.parallel.dp)"
                        ": none = implicit XLA psum (optimal on ICI); "
                        "bucketed = explicit fp32 bucketed all-reduce; "
                        "quantized = int8-on-the-wire with per-bucket "
                        "scales + error feedback (the DCN-bound lever, "
                        "docs/PERF.md §11); auto = quantized on a "
                        "multi-slice attach, none otherwise")
    parser.add_argument("--fsdp", default=1, type=int,
                        help="'fsdp' mesh axis size (tpudist.parallel.plan)"
                        ": params + Adam mirrors scattered over it, batch "
                        "split over data x fsdp (ZeRO semantics — sharded "
                        "state, DP gradients); >1 runs the whole loop "
                        "under a ParallelPlan")
    parser.add_argument("--augment", action="store_true",
                        help="train augmentation (crop+flip+normalize); "
                        "reference default is ToTensor only. Host-side for "
                        "host loaders; IN-GRAPH (step-keyed crop+flip) with "
                        "--device_cache or --packed")
    parser.add_argument("--device_cache", action="store_true",
                        help="stage the uint8 dataset to HBM once before "
                        "compile and ship only sampler indices per step "
                        "(tpudist/data/device_cache.py) — removes pixels "
                        "from the step's H2D path; incompatible with "
                        "--augment (host-side) and --dataset imagenet "
                        "(streaming)")
    parser.add_argument("--telemetry", action="store_true",
                        help="observability subsystem (tpudist.telemetry): "
                        "in-step health metrics + non-finite update guard, "
                        "NaN/divergence sentry with profiler flight "
                        "recorder, step-time breakdown, MFU rows — a "
                        "per-process JSONL stream next to the TSV "
                        "(docs/OBSERVABILITY.md)")
    parser.add_argument("--health", action="store_true",
                        help="run-health layer on top of --telemetry "
                        "(implied): cross-process straggler aggregation, "
                        "in-graph replica-divergence probe, hang watchdog "
                        "with crash forensics, and a {JobID}_report.json "
                        "end-of-run report (docs/OBSERVABILITY.md §7, "
                        "docs/MULTIHOST.md)")
    parser.add_argument("--trace", action="store_true",
                        help="structured span rows on the telemetry stream "
                        "(tpudist.telemetry.trace; implies --telemetry): "
                        "per-step spans with data-wait/dispatch/device "
                        "breakdown, checkpoint saves, probe/repair/reshard "
                        "markers — and per-request lifecycle spans under "
                        "--serve. Stitch into a Perfetto timeline with "
                        "tools/tracelens.py (docs/OBSERVABILITY.md §8)")
    parser.add_argument("--metrics_port", default=None, type=int,
                        help="live Prometheus text endpoint on "
                        "http://0.0.0.0:<port>/metrics (0 = ephemeral "
                        "port): host-side counters only, no extra device "
                        "syncs (docs/OBSERVABILITY.md §8)")
    parser.add_argument("--hang_timeout", default=300.0, type=float,
                        help="with --health: seconds without a completed "
                        "step before the watchdog dumps thread stacks and "
                        "writes the crash report (keep it above the "
                        "attach's compile time; 0 disables the watchdog)")
    parser.add_argument("--divergence_every", default=200, type=int,
                        help="with --health: steps between replica-"
                        "checksum divergence probes (0 disables the probe)")
    parser.add_argument("--hang_action", default="report",
                        choices=["report", "exit"],
                        help="with --health: what the hang watchdog does "
                        "after writing its crash forensics — 'report' "
                        "(non-fatal, the pre-resilience behavior) or "
                        "'exit' (terminate with the restartable code 76 "
                        "so tpudist.launch relaunches from the last "
                        "checkpoint; docs/MULTIHOST.md)")
    parser.add_argument("--chaos", default=None, type=str,
                        help="fault injection for recovery drills "
                        "(tpudist.resilience.chaos): '<kind>[:<n>]"
                        "@<step>[@<generation>|@*]' with kind in crash/"
                        "hang/sigterm/corrupt/bitflip/nanburst, comma-"
                        "separable — e.g. 'sigterm@50' rehearses a "
                        "preemption, 'bitflip@50' an SDC, "
                        "'bitflip@10,nanburst:3@30' composes an SDC with "
                        "a later spike in one drill")
    parser.add_argument("--repair", action="store_true",
                        help="self-healing loop (tpudist.resilience."
                        "repair, docs/MULTIHOST.md): detector verdicts "
                        "(replica divergence, non-finite skip streaks, "
                        "sustained loss spikes) roll state back to the "
                        "last-known-good ANCHORED checkpoint, skip "
                        "--skip_window batches past the trigger, and "
                        "continue in-process; repeat triggers exit 77 "
                        "for a supervised relaunch, a rolling budget "
                        "circuit-breaks deterministic poison. Needs "
                        "--checkpoint_dir + a save cadence; implies "
                        "--telemetry (combine with --health for the "
                        "SDC/divergence trigger)")
    parser.add_argument("--skip_window", default=8, type=int,
                        help="with --repair: batches skipped past a "
                        "trigger on rollback (the presumed-offending "
                        "data window)")
    parser.add_argument("--keep_last", default=0, type=int,
                        help="checkpoint retention: keep only the newest "
                        "N step dirs (health-anchored steps exempt — "
                        "they are the repair rollback target); 0 keeps "
                        "the legacy orbax max_to_keep=3 behavior")
    parser.add_argument("--serve", action="store_true",
                        help="continuous-batching serving demo "
                        "(tpudist.serve, docs/SERVING.md): a byte-vocab "
                        "GPT-2 with random params streams mixed-length "
                        "synthetic requests through the slot-pooled "
                        "engine, writing serve telemetry rows to "
                        "{log_dir}/{JobID}_serve_0.jsonl and printing the "
                        "TTFT/TPOT/throughput summary")
    parser.add_argument("--serve_requests", default=8, type=int,
                        help="with --serve: number of demo requests")
    parser.add_argument("--serve_slots", default=4, type=int,
                        help="with --serve: KV slot-pool size (the decode "
                        "batch)")
    parser.add_argument("--spec_draft", default=0, type=int,
                        help="with --serve: speculative decoding via an "
                        "early-exit draft of this DEPTH (the target's "
                        "first N blocks sharing its weights, "
                        "tpudist.serve.spec.early_exit_draft; 0 = off). "
                        "Each tick the draft proposes --spec_k tokens per "
                        "slot and the target verifies the window in one "
                        "bulk pass; greedy output is token-identical to "
                        "the non-speculative engine (docs/SERVING.md §6)")
    parser.add_argument("--serve_experts", default=0, type=int,
                        help="with --serve: make every other demo-model "
                        "block a routed top-2 MoE of this many experts "
                        "(tpudist.parallel.ep; 0 = dense). Decode routes "
                        "per generated token; greedy output is identical "
                        "across dispatch impls")
    parser.add_argument("--serve_moe_dispatch", default="einsum",
                        choices=["einsum", "index"],
                        help="with --serve_experts: expert dispatch impl "
                        "(docs/PERF.md §13)")
    parser.add_argument("--spec_k", default=4, type=int,
                        help="with --spec_draft: draft tokens proposed per "
                        "slot per tick (a slot emits up to spec_k+1 "
                        "tokens per verified sweep)")
    parser.add_argument("--tensor", default=1, type=int,
                        help="with --serve: tensor-parallel world — the "
                        "engine runs sharded over the mesh's 'tensor' "
                        "axis (weights by their Megatron metadata, KV "
                        "pools on the KV-head dim; docs/SERVING.md §7). "
                        "num_heads must divide it; 1 = single chip")
    parser.add_argument("--no_profiler", action="store_true")
    parser.add_argument("--log_dir", default=".", type=str)
    parser.add_argument("--checkpoint_dir", default=None, type=str,
                        help="enable async checkpoint/resume (extension; the "
                        "reference has no persistence, SURVEY.md §5)")
    parser.add_argument("--checkpoint_every", default=0, type=int,
                        help="steps between checkpoints (0 = end of run only)")
    parser.add_argument("--checkpoint_every_s", default=0.0, type=float,
                        help="WALL-CLOCK seconds between checkpoints, "
                        "alongside --checkpoint_every (a save triggers "
                        "when either is due; any save resets this clock, "
                        "the step knob stays step-aligned) — the knob "
                        "that bounds preemption loss to 'at most M "
                        "minutes of work' on runs with variable step "
                        "times (0 = off)")
    parser.add_argument("--no_resume", action="store_true")
    parser.add_argument("--elastic", action="store_true",
                        help="allow a resume whose checkpoint was written "
                        "at a DIFFERENT world size: ZeRO-1 optimizer "
                        "shards reshard onto the live mesh, the "
                        "error-feedback residual restarts zeroed, and the "
                        "step counter/sampler cursor remap to the same "
                        "data position (tpudist.resilience.elastic, "
                        "docs/MULTIHOST.md 'Resuming on a different "
                        "world size')")
    parser.add_argument("--compile_cache", default=None, type=str,
                        help="directory of serialized AOT step "
                        "executables (tpudist.compile_cache): a "
                        "relaunched generation deserializes its compiled "
                        "step — overlapped with the checkpoint restore — "
                        "instead of re-tracing; misses compile at "
                        "bring-up and store for the next life")
    parser.add_argument("--eval", action="store_true",
                        help="run the top-1 eval pass after training — the "
                        "reference's dormant eval loop "
                        "(/root/reference/main.py:119-130), alive")
    return parser.parse_args(argv)


def _serve_demo(args):
    """The --serve demo: the continuous-batching engine end to end on a
    small randomly-initialized byte-vocab GPT-2 — admission, slot reuse,
    per-request sampling params, streaming delivery, and the serve
    telemetry rows, all observable in seconds on CPU (the real-model
    entrypoint is examples/serve_gpt2.py)."""
    import numpy as np

    import jax

    from tpudist.models.gpt2 import GPT2
    from tpudist.serve import ServeEngine
    from tpudist.telemetry import TelemetrySink

    moe_kw = {}
    if args.serve_experts:
        # sparse demo model: every other block routed top-2 MoE; the
        # decode step routes each generated token (capacity auto-sizes
        # to the one-token step, so nothing drops at decode)
        moe_kw = dict(num_experts=args.serve_experts, moe_every=2,
                      moe_dispatch=args.serve_moe_dispatch)
    model = GPT2(vocab_size=256, max_seq_len=256, hidden_dim=128, depth=2,
                 num_heads=4, **moe_kw)
    params = model.init(
        jax.random.key(0), np.zeros((1, 8), np.int32), train=False
    )["params"]
    sink = TelemetrySink(
        os.path.join(args.log_dir, f"{args.JobID}_serve_0.jsonl")
    )
    streamed: dict[int, int] = {}

    def on_token(ev):
        streamed[ev.request_id] = streamed.get(ev.request_id, 0) + 1
        if ev.done:
            print(f"request {ev.request_id}: {streamed[ev.request_id]} "
                  "tokens (done)")

    spec_kw = {}
    if args.spec_draft:
        from tpudist.serve import early_exit_draft

        draft_model, draft_params = early_exit_draft(
            model, params, args.spec_draft
        )
        spec_kw = dict(draft_model=draft_model, draft_params=draft_params,
                       spec_k=args.spec_k)
    mesh_kw = {}
    if args.tensor > 1:
        from tpudist import mesh as mesh_lib

        # head-divisibility is validated by the engine with a loud
        # ValueError before any weights move
        mesh_kw = {"mesh": mesh_lib.create_mesh(
            mesh_lib.MeshConfig(tensor=args.tensor)
        )}
    engine = ServeEngine(model, params, max_slots=args.serve_slots,
                         sink=sink, stats_every=10, on_token=on_token,
                         trace=args.trace, metrics_port=args.metrics_port,
                         **spec_kw, **mesh_kw)
    if engine.metrics_port is not None:
        print(f"metrics: http://0.0.0.0:{engine.metrics_port}/metrics")
    rng = np.random.Generator(np.random.PCG64(0))
    for i in range(args.serve_requests):
        engine.submit(
            rng.integers(0, 256, (int(rng.integers(4, 48)),)),
            int(rng.integers(8, 48)),
            # alternate greedy and sampled requests: per-slot params share
            # one compiled decode step
            temperature=0.0 if i % 2 == 0 else 0.8,
            top_k=0 if i % 2 == 0 else 50,
        )
    engine.run()
    engine.close()
    sink.close()
    snap = engine.stats.snapshot()
    from tpudist.serve.stats import fmt_s

    print(
        f"served {snap['completed']} requests, {snap['tokens']} tokens in "
        f"{snap['wall_s']:.2f}s ({snap['tokens_per_sec']:.1f} tok/s); "
        f"TTFT p50/p95 {fmt_s(snap['ttft_p50'])}/{fmt_s(snap['ttft_p95'])}s, "
        f"TPOT p50 {fmt_s(snap['tpot_p50'], 1e3, 1)}ms, slot utilization "
        f"{fmt_s(snap['slot_utilization'], digits=2)}"
    )
    if args.spec_draft:
        print(
            f"speculative: {snap['spec_accepted']}/{snap['spec_drafted']} "
            "drafts accepted (rate "
            f"{fmt_s(snap['spec_acceptance_rate'], digits=2)})"
        )
    print(f"serve telemetry: {sink.path}")
    return snap


def main(argv=None):
    args = parse_args(argv)
    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.serve:
        return _serve_demo(args)

    import jax
    import jax.numpy as jnp

    from tpudist import init_from_env, create_mesh
    from tpudist.data.cifar import load_cifar, synthetic_cifar, to_tensor
    from tpudist.data.loader import DataLoader
    from tpudist.data.sampler import DistributedSampler
    from tpudist.models import (
        resnet18, resnet34, resnet50, resnet101, resnet152, vit_b16,
    )
    from tpudist.train import fit

    ctx = init_from_env()
    plan = None
    if args.fsdp > 1:
        from tpudist.parallel.plan import ParallelPlan

        plan = ParallelPlan.build(data=-1, fsdp=args.fsdp)
        mesh = plan.mesh
    else:
        mesh = create_mesh()

    # --amp = the named policy (fp32 master params, bf16 compute) + the
    # overflow guard on the optimizer below; --bf16 alone = dtype only
    from tpudist.amp import policy_for

    dtype = policy_for(args.bf16 or args.amp).compute_dtype
    # reference keeps the stock 1000-way head even on CIFAR (main.py:40)
    resnets = {"resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50,
               "resnet101": resnet101, "resnet152": resnet152}
    small = args.dataset != "imagenet"  # 32x32 CIFAR vs 224x224 folder images
    if args.model in resnets:
        model = resnets[args.model](dtype=dtype, stem=args.stem)
    elif args.model == "vit_b16":
        # 4-pixel patches keep 32x32 inputs at 64 tokens; ImageNet crops use
        # the standard 16-pixel patches
        model = vit_b16(dtype=dtype, patch_size=4 if small else 16)
    else:
        raise SystemExit("gpt2 training uses examples/train_gpt2.py (token data)")

    # reference semantics: --batch_size is per-replica (per-GPU, main.py:25);
    # this process's loader yields batch_size × local replicas, and the mesh
    # assembles the global batch of batch_size × world_size
    per_process_batch = args.batch_size * jax.local_device_count()
    input_transform = None  # set by the --device_cache path
    if args.cache_shard_rows and not (
        args.dataset == "imagenet" and args.packed and args.device_cache
    ):
        # guard EVERY dataset path: the rotation only backs the packed HBM
        # cache, and silently ignoring the flag would run a path with a
        # completely different memory/throughput profile
        raise SystemExit(
            "--cache_shard_rows rotates the packed HBM cache and needs "
            "--dataset imagenet --packed <prefix> --device_cache"
        )

    if args.dataset == "imagenet" and args.packed:
        # pre-decoded pack (tpudist.data.packed): pixels stream from a uint8
        # memmap at memcpy speed — the fix for decode-bound hosts (PERF §3c);
        # normalization runs in-graph either way (uint8 H2D, 4x less traffic)
        from tpudist.data.packed import load_packed
        from tpudist.data.transforms import (
            IMAGENET_MEAN, IMAGENET_STD, device_normalize,
        )

        packed = load_packed(args.packed)
        train_classes = packed["classes"]
        pdata = {"image": packed["image"], "label": packed["label"]}
        norm = device_normalize(IMAGENET_MEAN, IMAGENET_STD, dtype=dtype)
        if args.augment:
            # packed pixels are the deterministic eval decode; --augment
            # restores train-time variety IN-GRAPH (reflect-pad crop +
            # flip, step-keyed) — weaker than streaming RandomResizedCrop
            # but fresh every epoch at zero host cost
            from tpudist.data.transforms import (
                device_compose, device_random_crop_flip,
            )

            norm = device_compose(
                device_random_crop_flip(pad=max(args.image_size // 28, 4)),
                norm,
            )
        if args.device_cache and args.cache_shard_rows:
            from tpudist.data.device_cache import RotatingDeviceCache

            # pack larger than HBM: double-buffered shard rotation with a
            # windowed shuffle. The rotation is its OWN sampler (its
            # (seed, epoch) plan replaces the DistributedSampler's global
            # permutation), so no sampler is built here.
            loader = RotatingDeviceCache(
                pdata, per_process_batch, mesh=mesh,
                shard_rows=args.cache_shard_rows,
            )
            input_transform = loader.input_transform(norm)
        elif args.device_cache:
            from tpudist.data.device_cache import DeviceCachedLoader

            # staged pre-compile (same contract as the CIFAR path below)
            loader = DeviceCachedLoader(
                pdata, per_process_batch, mesh=mesh,
                sampler=DistributedSampler(
                    len(pdata["label"]), num_replicas=ctx.process_count,
                    rank=ctx.process_index,
                ),
            )
            input_transform = loader.input_transform(norm)
        else:
            loader = DataLoader(
                pdata, per_process_batch,
                sampler=DistributedSampler(
                    len(pdata["label"]), num_replicas=ctx.process_count,
                    rank=ctx.process_index,
                ),
                transform=None,
            )
            input_transform = norm
    elif args.dataset == "imagenet":
        # streaming image-folder pipeline (BASELINE configs 2/3): decode-on-
        # demand with the standard train augmentation; --augment is implied
        from tpudist.data.imagenet import ImageFolderLoader

        loader = ImageFolderLoader(
            os.path.join(args.data_root, "train"), per_process_batch,
            train=True, image_size=args.image_size,
            num_replicas=ctx.process_count, rank=ctx.process_index,
            workers=args.workers,
        )
        train_classes = loader.classes
    else:
        # --- dataset (reference: CIFAR-100 + ToTensor only, main.py:42-51);
        # the model head deliberately stays 1000-way regardless of the
        # dataset's class count — the reference does not adapt it (main.py:40)
        if args.dataset == "synthetic":
            data = synthetic_cifar(args.synthetic_size, num_classes=100)
        elif args.dataset == "digits":
            from tpudist.data.digits import load_digits_dataset

            data = load_digits_dataset(train=True)
        else:
            data = load_cifar(args.data_root, dataset=args.dataset, train=True)
        sampler = DistributedSampler(
            len(data["label"]), num_replicas=ctx.process_count,
            rank=ctx.process_index,
        )
        if args.device_cache:
            from tpudist.data.device_cache import DeviceCachedLoader

            # staged HERE — before create_train_state compiles anything —
            # so the one-time H2D rides the fast pre-compile link on
            # remote attaches (docs/PERF.md §3b)
            loader = DeviceCachedLoader(
                data, per_process_batch, mesh=mesh, sampler=sampler
            )
            if args.augment:
                # the host augmentation's in-graph twin (crop+flip then
                # the dataset-stats normalize), applied after the HBM
                # gather — augmented device-cached training
                from tpudist.data.transforms import (
                    _STATS, device_compose, device_normalize,
                    device_random_crop_flip,
                )

                mean, std = _STATS[args.dataset]
                input_transform = loader.input_transform(
                    device_compose(
                        device_random_crop_flip(),
                        device_normalize(mean, std, dtype=dtype),
                    )
                )
            else:
                # in-graph ToTensor (uint8 → [0,1] float), the reference's
                # transform (main.py:46) moved into the compiled step
                input_transform = loader.input_transform(
                    lambda x: x.astype(dtype) / 255.0
                )
        elif args.augment:
            from tpudist.data.transforms import standard_cifar_augment

            transform = standard_cifar_augment(
                seed=ctx.process_index, dataset=args.dataset
            )
            loader = DataLoader(
                data, per_process_batch, sampler=sampler, transform=transform
            )
        else:
            # reference parity (main.py:46: ToTensor only)
            loader = DataLoader(
                data, per_process_batch, sampler=sampler, transform=to_tensor
            )

    from tpudist.optim import make_optimizer

    # defaults reproduce the reference's Adam(lr=1e-3) (main.py:80) exactly
    if args.schedule == "cosine":
        from tpudist.optim import run_schedule

        lr = run_schedule(
            args.lr, total_steps=args.epochs * len(loader),
            warmup_steps=args.warmup_steps,
        )
    else:
        lr = args.lr
    fuse_opt = args.fused in ("optimizer", "all") or (
        args.fused == "auto" and args.optimizer == "adam"
    )
    tx = make_optimizer(
        lr, optimizer=args.optimizer,
        weight_decay=args.weight_decay, clip_norm=args.clip_norm,
        skip_nonfinite_updates=args.amp,
        fused=fuse_opt,
        # the compute copy only pays when the model computes in a narrower
        # dtype than the fp32 masters
        compute_dtype=dtype if dtype != jnp.float32 else None,
    )
    if args.label_smoothing:
        from tpudist.train import smoothed_cross_entropy

        loss_fn = smoothed_cross_entropy(args.label_smoothing)
    else:
        from tpudist.train import cross_entropy_loss as loss_fn
    telemetry = args.telemetry
    if args.health:
        from tpudist.telemetry.health import health_config

        telemetry = health_config(
            divergence_every=args.divergence_every,
            hang_timeout_s=args.hang_timeout or None,
            hang_action=args.hang_action,
        )
    if args.trace:
        import dataclasses

        from tpudist.telemetry import TelemetryConfig

        # --trace implies --telemetry: spans ride the JSONL sink
        telemetry = dataclasses.replace(
            telemetry if not isinstance(telemetry, bool)
            else TelemetryConfig(),
            trace=True,
        )
    state, losses = fit(
        model, tx, loader,
        epochs=args.epochs, mesh=mesh, plan=plan,
        loss_fn=loss_fn,
        job_id=args.JobID,
        batch_size=args.batch_size,
        world_size=ctx.world_size,
        global_rank=ctx.process_index,
        grad_accum=args.grad_accum,
        reduce=args.reduce,
        fused=None if args.fused == "none" else args.fused,
        input_transform=input_transform,
        profile=not args.no_profiler,
        log_dir=args.log_dir,
        telemetry=telemetry,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        checkpoint_every_s=args.checkpoint_every_s or None,
        keep_last=args.keep_last or None,
        resume=not args.no_resume,
        elastic=args.elastic,
        compile_cache=args.compile_cache,
        repair=(
            {"skip_window": args.skip_window} if args.repair else None
        ),
        chaos=args.chaos,
        metrics_port=args.metrics_port,
    )

    if args.amp and ctx.process_index == 0:
        from tpudist.amp import skipped_steps

        skipped = skipped_steps(state.opt_state)
        if skipped:
            print(f"amp: skipped {skipped} non-finite update step(s)")

    if args.eval:
        from tpudist.train import evaluate

        # the reference's val loader is unsharded (every rank sees the full
        # set, /root/reference/main.py:56-63); same here, and only rank 0
        # reports — matching the commented-out accuracy print (main.py:129)
        eval_input_transform = None
        if args.dataset == "imagenet" and args.packed_val:
            from tpudist.data.packed import load_packed
            from tpudist.data.transforms import (
                IMAGENET_MEAN, IMAGENET_STD, device_normalize,
            )

            vdata = load_packed(args.packed_val)
            if vdata["classes"] != train_classes:
                # same label-stability contract as the streaming val path
                # below: a val pack built without --classes_from (or from a
                # tree missing a class dir) would silently shift labels
                raise SystemExit(
                    "--packed_val class list does not match the training "
                    "classes — rebuild it with `python -m "
                    "tpudist.data.packed --classes_from <train pack>`"
                )
            val_loader = DataLoader(
                {"image": vdata["image"], "label": vdata["label"]},
                per_process_batch, transform=None, drop_remainder=False,
            )
            # same in-graph normalize the training step used
            eval_input_transform = device_normalize(
                IMAGENET_MEAN, IMAGENET_STD, dtype=dtype
            )
        elif args.dataset == "imagenet":
            from tpudist.data.imagenet import ImageFolderLoader

            val_loader = ImageFolderLoader(
                os.path.join(args.data_root, "val"), per_process_batch,
                train=False, image_size=args.image_size,
                workers=args.workers, drop_remainder=False,
                # train's class list keys the labels: a val tree missing a
                # class dir can't silently shift every later label
                classes=train_classes,
            )
        else:
            if args.dataset == "synthetic":
                val = synthetic_cifar(args.synthetic_size // 4 or 1, num_classes=100)
            elif args.dataset == "digits":
                from tpudist.data.digits import load_digits_dataset

                val = load_digits_dataset(train=False)
            else:
                val = load_cifar(args.data_root, dataset=args.dataset, train=False)
            # drop_remainder=False + evaluate's pad-and-mask scores the FULL
            # val set (the reference's loop covers every sample too)
            eval_batch = min(per_process_batch, len(val["label"]))
            if args.augment:
                # eval must see the training distribution: normalized (same
                # stats as the train transform), but no crop/flip
                from tpudist.data.transforms import standard_cifar_eval

                eval_transform = standard_cifar_eval(dataset=args.dataset)
            else:
                eval_transform = to_tensor
            val_loader = DataLoader(
                val, eval_batch, transform=eval_transform, drop_remainder=False
            )
        acc = evaluate(
            model, state, val_loader, mesh,
            input_transform=eval_input_transform,
        )
        if ctx.process_index == 0:
            print(f"Accuracy: {acc:.4f}")
    return state, losses


if __name__ == "__main__":
    main()
