"""Continuous-batching GPT-2 serving (tpudist.serve, docs/SERVING.md).

Streams mixed-length requests through the slot-pooled engine: FIFO
admission, bucketed chunked prefill, one compiled masked decode step over
the slot batch, per-request sampling params, per-token streaming, and
``serve`` telemetry rows (TTFT/TPOT percentiles, queue depth, slot
utilization) next to the run.

    # random-weight smoke run (any machine, seconds on CPU)
    python examples/serve_gpt2.py --requests 8 --slots 4

    # real GPT-2 124M weights from a local HF checkpoint
    python examples/serve_gpt2.py --init_hf /path/to/gpt2 \
        --prompt "464,3290,373" --prompt "15496,995" --max_new 64 \
        --temperature 0.8 --top_k 50

``--prompt`` takes comma-separated token ids (the repo ships no
tokenizer); without any, mixed-length random prompts exercise the
scheduler the way the bench leg does.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--init_hf", default=None, type=str,
                   help="LOCAL HF GPT-2 checkpoint dir/file to serve "
                   "(tpudist.interop conversion); default: random params")
    p.add_argument("--vocab_size", default=None, type=int,
                   help="default: 50257 with --init_hf, else 256")
    p.add_argument("--seq_len", default=1024, type=int)
    p.add_argument("--hidden_dim", default=768, type=int)
    p.add_argument("--depth", default=12, type=int)
    p.add_argument("--num_heads", default=12, type=int)
    p.add_argument("--small", action="store_true",
                   help="tiny random geometry (128 wide, 2 deep) for a "
                   "seconds-scale smoke run; implied without --init_hf")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--prompt", action="append", default=None,
                   help="comma-separated token ids; repeatable (one per "
                   "request)")
    p.add_argument("--requests", default=8, type=int,
                   help="synthetic request count when no --prompt is given")
    p.add_argument("--max_new", default=32, type=int)
    p.add_argument("--slots", default=4, type=int,
                   help="KV slot-pool size = the decode batch")
    p.add_argument("--max_queue", default=256, type=int)
    p.add_argument("--temperature", default=0.0, type=float)
    p.add_argument("--top_k", default=0, type=int)
    p.add_argument("--top_p", default=1.0, type=float)
    p.add_argument("--eos_id", default=None, type=int)
    p.add_argument("--spec_draft", default=0, type=int,
                   help="speculative decoding: early-exit draft DEPTH "
                   "(the target's first N blocks, zero extra weight HBM; "
                   "0 = off). The draft proposes --spec_k tokens per slot "
                   "per tick and the target verifies the window in one "
                   "bulk pass — greedy output stays token-identical "
                   "(docs/SERVING.md §6)")
    p.add_argument("--spec_k", default=4, type=int,
                   help="with --spec_draft: proposals per slot per tick")
    p.add_argument("--tensor", default=1, type=int,
                   help="tensor-parallel world: shard the engine (weights "
                   "by their Megatron metadata, KV pools on the KV-head "
                   "dim) over the mesh's 'tensor' axis; num_heads must "
                   "divide it (docs/SERVING.md §7). 1 = single chip")
    p.add_argument("--trace", action="store_true",
                   help="per-request lifecycle span rows on the serve "
                   "telemetry stream (queued/prefill/decode/preempted "
                   "phases per request); stitch into a Perfetto timeline "
                   "with tools/tracelens.py (docs/OBSERVABILITY.md §8)")
    p.add_argument("--metrics_port", default=None, type=int,
                   help="live Prometheus text endpoint on "
                   "http://0.0.0.0:<port>/metrics (0 = ephemeral port)")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--log_dir", default=".", type=str)
    p.add_argument("--JobID", default="Serve", type=str)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpudist.models.gpt2 import GPT2
    from tpudist.serve import ServeEngine
    from tpudist.telemetry import TelemetrySink

    small = args.small or not args.init_hf
    vocab = args.vocab_size or (50257 if args.init_hf else 256)
    model = GPT2(
        vocab_size=vocab, max_seq_len=args.seq_len,
        hidden_dim=128 if small else args.hidden_dim,
        depth=2 if small else args.depth,
        num_heads=4 if small else args.num_heads,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
    )
    if args.init_hf:
        from tpudist.interop import load_hf_params

        params = load_hf_params(
            args.init_hf, arch="gpt2", depth=model.depth,
            num_heads=model.num_heads,
        )
    else:
        params = model.init(
            jax.random.key(args.seed), np.zeros((1, 8), np.int32),
            train=False,
        )["params"]
    if args.bf16:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params,
        )

    rng = np.random.Generator(np.random.PCG64(args.seed))
    if args.prompt:
        prompts = [
            np.asarray([int(t) for t in s.split(",")], np.int32)
            for s in args.prompt
        ]
    else:
        prompts = [
            rng.integers(0, vocab, (int(rng.integers(4, 64)),)).astype(np.int32)
            for _ in range(args.requests)
        ]

    sink = TelemetrySink(
        os.path.join(args.log_dir, f"{args.JobID}_serve_0.jsonl")
    )
    spec_kw = {}
    if args.spec_draft:
        from tpudist.serve import early_exit_draft

        draft_model, draft_params = early_exit_draft(
            model, params, args.spec_draft
        )
        spec_kw = dict(draft_model=draft_model, draft_params=draft_params,
                       spec_k=args.spec_k)
    mesh_kw = {}
    if args.tensor > 1:
        from tpudist import mesh as mesh_lib

        # the engine refuses loudly when num_heads (or a GQA model's KV
        # heads) doesn't divide the tensor world — surface that before
        # any weights move
        mesh_kw = {"mesh": mesh_lib.create_mesh(
            mesh_lib.MeshConfig(tensor=args.tensor)
        )}
    engine = ServeEngine(
        model, params, max_slots=args.slots, max_queue=args.max_queue,
        seed=args.seed, sink=sink, stats_every=10, trace=args.trace,
        metrics_port=args.metrics_port, **spec_kw, **mesh_kw,
    )
    if engine.metrics_port is not None:
        print(f"metrics: http://0.0.0.0:{engine.metrics_port}/metrics")
    rids = [
        engine.submit(
            pr, args.max_new, temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p, eos_id=args.eos_id,
        )
        for pr in prompts
    ]
    # streaming consumption: tokens print as slots produce them,
    # interleaved across requests — the continuous-batching shape
    for ev in engine.events():
        print(f"  r{ev.request_id} +{ev.token}" + (" [done]" if ev.done else ""))
    for r in rids:
        print(f"request {r}: {len(engine.result(r))} tokens -> "
              f"{engine.result(r)}")
    snap = engine.stats.snapshot()
    engine.close()
    sink.close()
    from tpudist.serve.stats import fmt_s

    print(
        f"\nserved {snap['completed']} requests, {snap['tokens']} tokens in "
        f"{snap['wall_s']:.2f}s ({snap['tokens_per_sec']:.1f} tok/s)\n"
        f"TTFT p50/p95 {fmt_s(snap['ttft_p50'])}/{fmt_s(snap['ttft_p95'])}s, "
        f"TPOT p50/p95 {fmt_s(snap['tpot_p50'], 1e3, 1)}/"
        f"{fmt_s(snap['tpot_p95'], 1e3, 1)}ms, "
        f"slot utilization {fmt_s(snap['slot_utilization'], digits=2)}\n"
        + (
            f"speculative: {snap['spec_accepted']}/{snap['spec_drafted']} "
            "drafts accepted (rate "
            f"{fmt_s(snap['spec_acceptance_rate'], digits=2)})\n"
            if args.spec_draft else ""
        )
        + f"serve telemetry: {sink.path}"
    )
    return snap


if __name__ == "__main__":
    main()
