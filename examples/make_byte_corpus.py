"""Build a byte-level token corpus from text that ships inside the image.

The reference trains on an auto-downloaded dataset
(/root/reference/main.py:43-51); this environment has zero egress, so the
convergence-evidence runs (CONVERGENCE.json) use real local text instead:
the Python standard library's sources, the installed numpy/jax package
sources, and this repository's docs. That is real, structured,
natural-ish data — exactly what a byte-level LM can learn from — and it
is reproducible from a fresh image with this one script.

The train/val split hashes each file's CONTENT, so byte-identical files
(vendored copies, repeated licenses) always land in the same split — the
"no validation text appears in training" guarantee holds even across
duplicated files.

Output: ``<out>_train.bin`` / ``<out>_val.bin`` — flat little-endian
uint16 token files in the nanoGPT convention that
``tpudist.data.lm.load_token_stream`` reads (byte ids 0..255; uint16 so
the same file drives models with any vocab_size >= 256, e.g. GPT-2's
50257). The split is by whole file (a deterministic hash), not by byte
offset, so no validation window overlaps training text.

Usage::

    python examples/make_byte_corpus.py --out pytext --max_mb 24
"""

from __future__ import annotations

import argparse
import hashlib
import sysconfig
from pathlib import Path

import numpy as np


def source_roots() -> list[Path]:
    roots = [Path(sysconfig.get_paths()["stdlib"])]
    for pkg in ("numpy", "jax", "flax", "optax"):
        try:
            mod = __import__(pkg)
            roots.append(Path(mod.__file__).parent)
        except Exception:
            pass
    repo = Path(__file__).resolve().parent.parent
    roots += [repo / "docs", repo / "tpudist"]
    return roots


def gather_files(roots: list[Path]) -> list[Path]:
    files: list[Path] = []
    for root in roots:
        if not root.exists():
            continue
        for pattern in ("*.py", "*.md", "*.rst", "*.txt"):
            for p in root.rglob(pattern):
                # filter on the path BELOW the root: the roots themselves
                # live under site-packages, which must not exclude them
                rel = p.relative_to(root)
                if p.name.startswith("test_"):
                    continue
                if {"test", "tests", "site-packages"} & set(rel.parts[:-1]):
                    continue
                files.append(p)
    # deterministic order independent of filesystem enumeration
    return sorted(set(files))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="pytext", help="output file prefix")
    ap.add_argument("--max_mb", type=float, default=24.0,
                    help="stop collecting after this many MB of text")
    ap.add_argument("--val_frac", type=int, default=16,
                    help="1/N of files (by hash) go to validation")
    args = ap.parse_args()
    if args.val_frac < 2:
        ap.error(f"--val_frac must be >= 2 (got {args.val_frac}): 1/N of "
                 "files go to validation, so N=1 would put EVERY file in "
                 "val and N<=0 is undefined")

    budget = int(args.max_mb * 1e6)
    train_parts: list[bytes] = []
    val_parts: list[bytes] = []
    total = 0
    for path in gather_files(source_roots()):
        try:
            data = path.read_bytes()
        except OSError:
            continue
        if not data or len(data) > 2_000_000 or b"\x00" in data:
            continue  # NUL-free text only, so NUL can serve as the doc separator
        try:
            data.decode("utf-8")
        except UnicodeDecodeError:
            continue
        h = int.from_bytes(hashlib.sha1(data).digest()[:4], "big")
        (val_parts if h % args.val_frac == 0 else train_parts).append(data)
        total += len(data)
        if total >= budget:
            break

    # validate BOTH splits before writing EITHER file: a tiny --max_mb
    # budget can fill one split before the hash ever routes a file to the
    # other, and writing the good split first would leave a fresh train
    # .bin silently pairing with a stale val .bin from an earlier run
    empty = [n for n, p in (("train", train_parts), ("val", val_parts)) if not p]
    if empty:
        raise SystemExit(
            f"make_byte_corpus: the {'/'.join(empty)} split is EMPTY "
            f"(budget {args.max_mb} MB consumed before any file hashed "
            "into it) — raise --max_mb or adjust --val_frac; nothing "
            "was written"
        )
    for name, parts in (("train", train_parts), ("val", val_parts)):
        blob = b"\x00".join(parts)  # NUL = doc separator (NUL-bearing files were filtered)
        tokens = np.frombuffer(blob, np.uint8).astype(np.uint16)
        out = f"{args.out}_{name}.bin"
        tokens.tofile(out)
        print(f"{out}: {tokens.size:,} tokens from {len(parts)} files")


if __name__ == "__main__":
    main()
