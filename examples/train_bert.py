"""BERT masked-LM pretraining — the encoder counterpart of train_gpt2.py.

Same data format (flat token stream, ``.bin``/``.npy`` memmap), same
observability contract (TSV metrics, windowed profiler, TrainTime), same
multi-host launch (``python -m tpudist.launch ... examples/train_bert.py``).
The model vocabulary is the corpus vocabulary plus one reserved [MASK] id
appended at the top (``--mask_id`` overrides when the tokenizer already has
one), and each gathered window gets BERT's 80/10/10 corruption on the host
(tpudist.models.bert.mlm_transform).

No reference counterpart (SURVEY.md §2.12 — the reference has one model);
this is capability surface beyond the baseline ladder.

    # byte-level corpus, bert-base geometry, bf16:
    python examples/train_bert.py --tokens corpus.bin --vocab_size 256 \
        --bf16 --batch_size 32 --JobID MLM --eval
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as a plain script from anywhere: put the repo root (one level up)
# on sys.path when tpudist isn't pip-installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--local_rank", type=int,
                   default=int(os.environ.get("LOCAL_RANK", 0)))
    p.add_argument("--tokens", required=True,
                   help=".bin (raw little-endian) or .npy flat token stream")
    p.add_argument("--val_tokens", default=None)
    p.add_argument("--token_dtype", default="uint16")
    p.add_argument("--vocab_size", default=30522, type=int,
                   help="CORPUS vocabulary; the model reserves one extra "
                   "[MASK] id above it unless --mask_id is given")
    p.add_argument("--mask_id", default=None, type=int)
    p.add_argument("--seq_len", default=512, type=int)
    p.add_argument("--batch_size", default=32, type=int,
                   help="per data-parallel replica (reference semantics)")
    p.add_argument("--hidden_dim", default=768, type=int)
    p.add_argument("--depth", default=12, type=int)
    p.add_argument("--num_heads", default=12, type=int)
    p.add_argument("--mask_rate", default=0.15, type=float)
    p.add_argument("--epochs", default=1, type=int)
    p.add_argument("--total_steps", default=0, type=int)
    p.add_argument("--lr", default=1e-4, type=float)
    p.add_argument("--warmup_steps", default=0, type=int)
    p.add_argument("--optimizer", default="adam")
    p.add_argument("--weight_decay", default=0.0, type=float)
    p.add_argument("--clip_norm", default=None, type=float)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--grad_accum", default=1, type=int)
    p.add_argument("--chunked_ce", default=0, type=int,
                   help="scan the MLM head over sequence chunks of this "
                   "size (bounds the [B,S,V] logits)")
    p.add_argument("--tensor", default=1, type=int,
                   help="Megatron TP degree over the 'tensor' mesh axis")
    p.add_argument("--cp", default=1, type=int,
                   help="context-parallel degree over the 'seq' mesh axis "
                   "(pair with --attn ring/ulysses/ulysses_flash)")
    p.add_argument("--attn", default="xla",
                   choices=["xla", "flash", "ring", "ulysses", "ulysses_flash"])
    p.add_argument("--scan_layers", action="store_true",
                   help="nn.scan the depth (one traced layer; params stack "
                   "[depth, ...])")
    p.add_argument("--remat_layers", action="store_true",
                   help="checkpoint each scanned layer (requires "
                   "--scan_layers)")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--JobID", default="Bert0", type=str)
    p.add_argument("--log_dir", default=".", type=str)
    p.add_argument("--no_profiler", action="store_true")
    p.add_argument("--checkpoint_dir", default=None, type=str)
    p.add_argument("--checkpoint_every", default=0, type=int)
    p.add_argument("--no_resume", action="store_true")
    p.add_argument("--eval", action="store_true",
                   help="masked-prediction loss + accuracy on the held-out "
                   "stream (or the train stream in order)")
    p.add_argument("--init_hf", default=None, type=str,
                   help="warm-start from a LOCAL HF BertForMaskedLM "
                   "checkpoint dir (tpudist.interop); sizes must match the "
                   "model flags, and --mask_id should name the tokenizer's "
                   "[MASK] id (BERT-base: 103)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist import init_from_env
    from tpudist import mesh as mesh_lib
    from tpudist.data.lm import TokenWindowLoader, load_token_stream
    from tpudist.models.bert import Bert, mlm_forward, mlm_transform
    from tpudist.optim import make_optimizer, run_schedule
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=-1, tensor=args.tensor, seq=args.cp)
    )
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    if args.cp > 1 and args.attn not in ("ring", "ulysses", "ulysses_flash"):
        raise SystemExit(
            "--cp needs a sequence-parallel attention: "
            "--attn ring|ulysses|ulysses_flash"
        )

    if args.mask_id is None:
        mask_id, model_vocab = args.vocab_size, args.vocab_size + 1
    else:
        if not 0 <= args.mask_id < args.vocab_size:
            raise SystemExit(
                f"--mask_id {args.mask_id} outside [0, {args.vocab_size})"
            )
        mask_id, model_vocab = args.mask_id, args.vocab_size

    if args.remat_layers and not args.scan_layers:
        raise SystemExit("--remat_layers requires --scan_layers")
    if args.scan_layers and args.init_hf:
        raise SystemExit(
            "--init_hf uses the unrolled layout; convert with "
            "tpudist.models.lm_utils.stack_layers or drop --scan_layers"
        )
    model = Bert(
        vocab_size=model_vocab, max_seq_len=args.seq_len,
        hidden_dim=args.hidden_dim, depth=args.depth,
        num_heads=args.num_heads, dtype=dtype,
        attn_impl=args.attn, mesh=mesh,
        scan_layers=args.scan_layers, remat_layers=args.remat_layers,
    )

    local_replicas = max(
        mesh_lib.data_parallel_size(mesh) // ctx.process_count, 1
    )
    per_process_batch = args.batch_size * local_replicas * args.grad_accum
    corruption = mlm_transform(
        model_vocab, mask_id, mask_rate=args.mask_rate,
        seed=args.seed + ctx.process_index,
    )
    loader = TokenWindowLoader(
        args.tokens, per_process_batch, args.seq_len,
        dtype=np.dtype(args.token_dtype), vocab_size=args.vocab_size,
        num_replicas=ctx.process_count, rank=ctx.process_index,
        transform=corruption,
    )

    steps_per_epoch = len(loader)
    total = args.total_steps or args.epochs * steps_per_epoch
    tx = make_optimizer(
        run_schedule(args.lr, total_steps=total,
                     warmup_steps=args.warmup_steps),
        optimizer=args.optimizer,
        weight_decay=args.weight_decay, clip_norm=args.clip_norm,
    )

    init_params = None
    if args.init_hf:
        from tpudist.interop import load_hf_params

        if args.mask_id is None:
            raise SystemExit(
                "--init_hf needs --mask_id (the pretrained tokenizer's "
                "[MASK] id; the +1 reserved-id vocab wouldn't match the "
                "checkpoint)"
            )
        init_params = load_hf_params(
            args.init_hf, arch="bert", depth=args.depth,
            num_heads=args.num_heads,
        )

    batch_spec = None
    if args.cp > 1:
        from jax.sharding import PartitionSpec as P

        bd = (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
        # every [B, S] key rides sequence-sharded; grad accumulation adds
        # the leading (replicated) microbatch dim
        spec = (
            P(bd, mesh_lib.SEQUENCE_AXIS)
            if args.grad_accum == 1
            else P(None, bd, mesh_lib.SEQUENCE_AXIS)
        )
        batch_spec = {"tokens": spec, "targets": spec, "mlm_mask": spec}

    dp_size = mesh_lib.data_parallel_size(mesh)
    t0 = time.time()
    state, losses = fit(
        model, tx, loader,
        epochs=args.epochs, mesh=mesh, seed=args.seed,
        job_id=args.JobID, batch_size=args.batch_size,
        world_size=dp_size, global_rank=ctx.process_index,
        input_key="tokens", label_key="targets",
        forward_loss=mlm_forward(model, chunk=args.chunked_ce or None),
        grad_accum=args.grad_accum, batch_spec=batch_spec,
        profile=not args.no_profiler, log_dir=args.log_dir,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=not args.no_resume,
        init_params=init_params,
    )
    wall = time.time() - t0
    if losses and ctx.process_index == 0:
        seqs = len(losses) * args.batch_size * dp_size * args.grad_accum
        print(
            f"tokens/sec: {seqs * args.seq_len / wall:.1f} "
            f"(global, incl. compile) steps={len(losses)} "
            f"final_loss={losses[-1]:.4f}"
        )

    if args.eval:
        source = (
            load_token_stream(
                args.val_tokens, dtype=np.dtype(args.token_dtype)
            )
            if args.val_tokens
            else load_token_stream(args.tokens, dtype=np.dtype(args.token_dtype))
        )
        metrics = evaluate_mlm(
            model, state, source, args, mesh, corruption=mlm_transform(
                model_vocab, mask_id, mask_rate=args.mask_rate,
                seed=args.seed + 10_000,
            ),
        )
        if ctx.process_index == 0:
            print(
                f"mlm_loss: {metrics['loss']:.4f} "
                f"masked_accuracy: {metrics['accuracy']:.4f}"
            )
    return state, losses


def evaluate_mlm(model, state, source, args, mesh, *, corruption):
    """Masked-prediction CE + top-1 accuracy over a token stream, every
    process scoring its own shard (the shard-safe global-mask accounting of
    tpudist.train.evaluate). Rides the same chunked head as training
    (``--chunked_ce``), so eval never re-creates the [B,S,V] logits peak
    the training path avoided."""
    import jax
    import jax.numpy as jnp

    from tpudist.data.lm import TokenWindowLoader
    from tpudist.models.bert import MlmHead, mlm_head_logits_fn
    from tpudist.models.lm_utils import chunked_head_reduce
    from tpudist.train import _padded_batches

    loader = TokenWindowLoader(
        source, args.batch_size, args.seq_len,
        vocab_size=args.vocab_size, shuffle=False, drop_remainder=False,
        num_replicas=jax.process_count(), rank=jax.process_index(),
        transform=corruption,
    )
    head = MlmHead(dtype=model.dtype)
    chunk = args.chunked_ce or args.seq_len  # one chunk == the full head

    @jax.jit
    def score(params, batch, row_mask):
        hidden = model.apply(
            {"params": params}, batch["tokens"], train=False,
            return_hidden=True,
        )
        pos = (batch["mlm_mask"] & row_mask[:, None]).astype(jnp.float32)
        ce_sum, hit_sum = chunked_head_reduce(
            mlm_head_logits_fn(head, params), hidden, batch["targets"],
            pos, chunk, hits=True,
        )
        return ce_sum, hit_sum, jnp.sum(pos)

    total_ce, total_hit, total_pos = 0.0, 0, 0.0
    for batch, row_mask, _ in _padded_batches(loader, mesh, "tokens"):
        ce, hit, pos = score(state.params, batch, row_mask)
        total_ce += float(ce)
        total_hit += int(hit)
        total_pos += float(pos)
    denom = max(total_pos, 1.0)
    return {"loss": total_ce / denom, "accuracy": total_hit / denom}


if __name__ == "__main__":
    main()
