"""Achieved-HBM-bandwidth probe for the step-fusion kernels — the
measurement behind docs/PERF.md §4c.

The fused LN and fused-AdamW kernels (tpudist/ops/layernorm.py,
tpudist/ops/fused_update.py) attack the bandwidth-bound non-GEMM tail
§4b measured, so their figure of merit is GB/s against the chip's HBM
roofline (v5e: 819 GB/s), not FLOP/s. This probe times each kernel in
isolation with the same differential method as examples/mfu_probe.py
(tpudist.telemetry.microbench: adaptive iters, ``(t(4n)−t(n))/3n``,
anti-hoisting operands, plausibility retries) and reports
bytes-moved / second.

Byte accounting (the numerator) is the kernel's mandatory HBM traffic:

- LN forward, residual variant: read x + y, write out + r → 4·N·D·dsize
  (+ the [D] vectors, negligible);
- LN backward: read r + g (+ gr), write dr → 3–4 passes;
- fused AdamW: read g/m/v/p (4×4 B), write m'/v'/u (3×4 B) + the bf16
  copy (2 B) → 30 B/element.

Run on the bench chip::

    python examples/kernel_probe.py                 # default shapes
    python examples/kernel_probe.py --rows 32768 --hidden 1024 --bw 819e9

On CPU it still runs (the kernels interpret) — the GB/s are then host
numbers, useful only as a smoke test.
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudist.telemetry import microbench  # noqa: E402

V5E_HBM_BW = 819e9  # bytes/s — the roofline every PERF.md section quotes


def _measure(body, operand, nbytes, *, bw, reps):
    timed = microbench.anti_hoist_scan(body, operand, reps=reps)
    est = nbytes / (0.3 * bw)  # optimistic: 30% of the roofline
    dt = microbench.measure_iter_seconds(
        timed, est, floor_s=nbytes / (1.05 * bw)
    )
    return nbytes / dt if dt > 0 else float("nan")


def probe_ln(rows: int, hidden: int, dtype, *, bw: float, reps: int):
    """Fused residual-add+LN forward and forward+backward GB/s."""
    from tpudist.ops.layernorm import fused_layernorm

    rng = np.random.Generator(np.random.PCG64(0))
    x = jnp.asarray(rng.standard_normal((rows, hidden)), dtype)
    y = jnp.asarray(rng.standard_normal((rows, hidden)), dtype)
    scale = jnp.asarray(rng.standard_normal(hidden), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(hidden), jnp.float32)
    dsize = jnp.dtype(dtype).itemsize
    fwd_bytes = 4 * rows * hidden * dsize  # read x,y; write out,r

    def fwd(xs):
        n, r = fused_layernorm(xs, scale, bias, residual=y, eps=1e-5)
        return n + r  # keep both outputs live

    fwd_gbps = _measure(fwd, x, fwd_bytes, bw=bw, reps=reps)

    # fwd+bwd: fwd traffic + read r,g,gr + write dr (cotangents for both
    # outputs are the same buffer)
    full_bytes = fwd_bytes + 4 * rows * hidden * dsize

    def fwdbwd(xs):
        def loss(xs):
            n, r = fused_layernorm(xs, scale, bias, residual=y, eps=1e-5)
            return jnp.sum(n.astype(jnp.float32)) + jnp.sum(
                r.astype(jnp.float32)
            )

        return jax.grad(loss)(xs)

    full_gbps = _measure(fwdbwd, x, full_bytes, bw=bw, reps=reps)
    return fwd_gbps, full_gbps


def probe_fused_update(n_elems: int, *, bw: float, reps: int,
                       compute_dtype=jnp.bfloat16):
    """Fused AdamW sweep GB/s over one ``n_elems`` fp32 leaf."""
    from tpudist.ops.fused_update import fused_leaf_update

    rng = np.random.Generator(np.random.PCG64(1))
    leaf = lambda: jnp.asarray(rng.standard_normal(n_elems), jnp.float32)
    g, m, v, p = leaf(), leaf(), leaf(), leaf()
    copy_b = jnp.dtype(compute_dtype).itemsize if compute_dtype else 0
    nbytes = n_elems * (4 * 4 + 3 * 4 + copy_b)  # r g/m/v/p, w m'/v'/u, copy

    def body(gs):
        u, m2, v2, c = fused_leaf_update(
            gs, m, v, p, jnp.float32(1e-3), jnp.float32(0.1),
            jnp.float32(0.001), b1=0.9, b2=0.999, eps=1e-8, wd=0.01,
            compute_dtype=compute_dtype,
        )
        out = u + m2 + v2
        if c is not None:
            out = out + c.astype(jnp.float32)
        return out

    return _measure(body, g, nbytes, bw=bw, reps=reps)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--rows", type=int, default=8192,
                    help="LN rows = tokens of one microbatch (8 x 1024)")
    ap.add_argument("--hidden", type=int, default=768)
    ap.add_argument("--elems", type=int, default=8_000_000,
                    help="fused-update leaf size (~a GPT-2 124M block pair)")
    ap.add_argument("--bw", type=float, default=V5E_HBM_BW,
                    help="HBM roofline bytes/s (default v5e 819e9)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--bf16", action="store_true",
                    help="probe the LN kernel at bf16 activations")
    args = ap.parse_args()

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    print(f"# step-fusion kernel HBM bandwidth vs the "
          f"{args.bw / 1e9:.0f} GB/s roofline (backend: "
          f"{jax.default_backend()})")
    print(f"{'kernel':34s} {'GB/s':>9s} {'%roofline':>10s}")

    fwd, full = probe_ln(args.rows, args.hidden, dtype,
                         bw=args.bw, reps=args.reps)
    for name, g in [
        (f"ln fwd (res+LN, {args.rows}x{args.hidden})", fwd),
        ("ln fwd+bwd", full),
    ]:
        print(f"{name:34s} {g / 1e9:9.1f} {100 * g / args.bw:9.1f}%")

    upd = probe_fused_update(args.elems, bw=args.bw, reps=args.reps)
    name = f"fused adamw ({args.elems / 1e6:.0f}M elems)"
    print(f"{name:34s} {upd / 1e9:9.1f} {100 * upd / args.bw:9.1f}%")


if __name__ == "__main__":
    main()
