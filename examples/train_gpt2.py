"""GPT-2 language-model training — BASELINE.json config 5 (GPT-2 124M,
DP + gradient accumulation, tokens/sec) and the showcase for the framework's
parallelism axes beyond the reference's DP (SURVEY.md §2.12).

The data/metrics contract matches the reference's trainer
(/root/reference/main.py:86-117) with sequences standing in for images: the
per-rank TSV log keeps the exact header/fields (examples_per_sec counts
sequences), and a final tokens/sec summary is printed for the baseline table.

Launch (single host):

    python examples/train_gpt2.py --batch_size 8 --grad_accum 4 --JobID LM

Parallelism knobs compose on the named mesh:

    --fsdp 4               params+Adam scattered over 'fsdp' (ZeRO-3-style;
                           composes with --tensor/--pipe under a
                           ParallelPlan — tpudist.parallel.plan)
    --tensor 4             Megatron TP over 'tensor'
    --pipe 4 --num_micro 8 microbatch pipelining over 'pipe' (stacked
                           blocks; --pipe_schedule gpipe|1f1b)
    --cp 4 --attn ring     ring-attention context parallelism over 'seq'
    --experts 8            MoE blocks (every other for gpt2, every for
                           llama/Mixtral-style), experts over 'expert'

Multi-host works exactly like main.py: ``python -m tpudist.launch ...``.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as a plain script from anywhere: put the repo root (one level up)
# on sys.path when tpudist isn't pip-installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--local_rank", type=int, default=int(os.environ.get("LOCAL_RANK", 0)))
    p.add_argument("--batch_size", default=8, type=int,
                   help="per-replica sequences per step (reference semantics)")
    p.add_argument("--JobID", default="GPT2", type=str)
    p.add_argument("--epochs", default=1, type=int)
    p.add_argument("--lr", default=3e-4, type=float)
    p.add_argument("--warmup_steps", default=100, type=int)
    p.add_argument("--total_steps", default=0, type=int,
                   help="schedule horizon; 0 = epochs x steps_per_epoch")
    p.add_argument("--optimizer", default="adam",
                   choices=["adam", "sgd", "lamb", "lion", "muon"])
    p.add_argument("--weight_decay", default=0.1, type=float)
    p.add_argument("--clip_norm", default=1.0, type=float)
    p.add_argument("--grad_accum", default=1, type=int)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--amp", action="store_true",
                   help="mixed precision end-to-end (tpudist.amp): bf16 "
                   "compute policy (implies --bf16) + non-finite update "
                   "guard on the optimizer")
    p.add_argument("--dropout", default=0.0, type=float,
                   help="embedding+residual dropout rate (GPT-2 paper: 0.1)")
    p.add_argument("--remat", default=None, nargs="?", const="full",
                   choices=["none", "full", "dots_saveable", "save_nothing"],
                   help="whole-forward jax.checkpoint under a named policy "
                   "(tpudist.remat; bare --remat = full, the legacy "
                   "behavior)")
    p.add_argument("--remat_policy", default=None,
                   choices=["none", "full", "dots_saveable", "save_nothing"],
                   help="per-BLOCK checkpoint policy on the transformer "
                   "blocks (the deep-model memory lever; works unrolled "
                   "and with --scan_layers)")
    p.add_argument("--shard_opt_state", action="store_true",
                   help="ZeRO-1 cross-replica optimizer-state sharding "
                   "(tpudist.optim.shard_state): Adam mirrors live "
                   "~1/world_size per chip; with --remat_policy this is "
                   "the ~1B-on-16GB recipe (docs/PERF.md §10)")
    p.add_argument("--fused", default="none",
                   choices=["none", "auto", "ln", "optimizer", "all"],
                   help="step-fusion layer (docs/PERF.md §4c): 'ln' = the "
                   "Pallas fused residual-add+LayerNorm kernel in every "
                   "block, 'optimizer' = the one-pass fused-AdamW kernel "
                   "(+ bf16 compute-copy forward under --bf16; requires "
                   "--optimizer adam), 'all' both, 'auto' whatever the "
                   "model/optimizer support")
    p.add_argument("--chunked_ce", default=0, type=int,
                   help="sequence-chunked weight-tied CE (chunk size); the "
                   "[B,S,V] logits never materialize — raises the max batch/"
                   "seq_len per chip (dense models only)")
    # model family + size
    p.add_argument("--arch", default="gpt2", choices=["gpt2", "llama"],
                   help="decoder family: GPT-2 (learned positions, GELU MLP, "
                   "tied head) or Llama (RoPE, RMSNorm, SwiGLU, GQA)")
    p.add_argument("--hidden_dim", default=768, type=int)
    p.add_argument("--depth", default=12, type=int)
    p.add_argument("--num_heads", default=12, type=int)
    p.add_argument("--num_kv_heads", default=0, type=int,
                   help="llama GQA K/V heads (0 = MHA)")
    p.add_argument("--ffn_dim", default=0, type=int,
                   help="llama SwiGLU width (0 = 8/3*hidden rounded to 256)")
    p.add_argument("--rope_theta", default=10000.0, type=float)
    p.add_argument("--tie_embeddings", action="store_true",
                   help="llama: tie the LM head to the embedding")
    p.add_argument("--scan_layers", action="store_true",
                   help="nn.scan the depth (one traced layer, params stacked "
                   "[depth,...]) — compile time O(1) in depth; dense "
                   "training only")
    p.add_argument("--remat_layers", action="store_true",
                   help="with --scan_layers: checkpoint each layer (store "
                   "boundaries, recompute inside) — the deep-model memory "
                   "lever")
    p.add_argument("--vocab_size", default=50257, type=int)
    p.add_argument("--seq_len", default=1024, type=int)
    # data: a flat token file (.npy, or nanoGPT-style raw .bin) or synthetic
    p.add_argument("--tokens", default=None, type=str,
                   help="flat token file (.npy, or raw .bin read as "
                   "--token_dtype); memory-mapped, never materialized")
    p.add_argument("--token_dtype", default="uint16", type=str,
                   help="dtype of a raw .bin token file (uint16 fits GPT-2's "
                   "50257-entry vocab)")
    p.add_argument("--synthetic_tokens", default=2_000_000, type=int)
    # parallelism (sizes of the mesh axes; data gets the rest)
    p.add_argument("--fsdp", default=1, type=int,
                   help="'fsdp' mesh axis size: every leaf the Megatron/"
                   "pipe metadata leaves replicated (Adam mirrors "
                   "included) is scattered over it and the batch splits "
                   "over data x fsdp — the composed run goes through a "
                   "ParallelPlan (tpudist.parallel.plan)")
    p.add_argument("--tensor", default=1, type=int)
    p.add_argument("--pipe", default=1, type=int)
    p.add_argument("--num_micro", default=8, type=int)
    p.add_argument("--pipe_schedule", default="gpipe",
                   choices=["gpipe", "1f1b"],
                   help="microbatch schedule for --pipe (tpudist.parallel"
                   ".pp): gpipe = reverse-mode through the forward scan; "
                   "1f1b = explicit one-forward-one-backward backward "
                   "ring — same math, stage internals recomputed instead "
                   "of stored (the deep-pipeline activation lever)")
    p.add_argument("--cp", default=1, type=int, help="'seq' (context) axis size")
    p.add_argument("--experts", default=0, type=int, help="MoE experts (0=dense)")
    p.add_argument("--expert_axis", default=0, type=int,
                   help="'expert' mesh axis size (0 → min(experts, devices))")
    p.add_argument("--moe_every", default=0, type=int,
                   help="MoE block cadence: every Nth block is sparse "
                   "(0 = family default: 2 for gpt2, 1/Mixtral for llama)")
    p.add_argument("--moe_top_k", default=2, type=int,
                   help="experts each token is routed to")
    p.add_argument("--capacity_factor", default=1.25, type=float,
                   help="per-expert slot headroom over the balanced load "
                   "(tokens over capacity are dropped to the residual)")
    p.add_argument("--moe_dispatch", default="einsum",
                   choices=["einsum", "index"],
                   help="expert dispatch impl (tpudist.parallel.ep): "
                   "'einsum' = the one-hot oracle, 'index' = slot-index "
                   "gather/scatter + the explicit expert-axis all-to-all "
                   "on a real --expert_axis mesh (docs/PERF.md §13)")
    p.add_argument("--router_z_loss", default=0.0, type=float,
                   help="router z-loss weight (fp32 logit-norm regularizer; "
                   "0 = off, byte-identical trajectory)")
    p.add_argument("--router_jitter", default=0.0, type=float,
                   help="multiplicative router input noise, train only "
                   "(0 = off)")
    p.add_argument("--attn", default="auto",
                   choices=["auto", "xla", "vmem", "flash", "ring", "ulysses",
                            "ulysses_flash"],
                   help="auto picks by context length: the whole-sequence "
                   "VMEM kernel wins up to 1k (measured 126k vs 80k tok/s "
                   "at 1024 on v5e), the blockwise flash kernel wins beyond "
                   "(~14x over XLA at 8k), XLA is the dense-mask oracle")
    p.add_argument("--init_hf", default=None, type=str,
                   help="warm-start from a LOCAL HF checkpoint dir/file "
                   "(*.safetensors or pytorch_model*.bin) converted via "
                   "tpudist.interop; sizes must match the model flags")
    p.add_argument("--generate", default=0, type=int,
                   help="after training, KV-cache-generate this many tokens "
                   "from the start of the stream (greedy unless --temperature)")
    p.add_argument("--temperature", default=0.0, type=float)
    p.add_argument("--top_k", default=None, type=int)
    p.add_argument("--top_p", default=None, type=float,
                   help="nucleus sampling: keep the smallest token set "
                   "with cumulative probability >= p")
    p.add_argument("--eval", action="store_true",
                   help="after training, report next-token loss + perplexity "
                   "over --val_tokens (or the training stream if unset)")
    p.add_argument("--val_tokens", default=None, type=str,
                   help="held-out token file (.npy/.bin) for --eval")
    p.add_argument("--no_profiler", action="store_true")
    p.add_argument("--telemetry", action="store_true",
                   help="observability subsystem (docs/OBSERVABILITY.md): "
                   "in-step grad/param/update norms + non-finite update "
                   "guard, NaN/divergence sentry with on-demand trace "
                   "capture, step-time breakdown, MFU rows — JSONL stream "
                   "next to the reference TSV")
    p.add_argument("--log_dir", default=".", type=str)
    p.add_argument("--checkpoint_dir", default=None, type=str)
    p.add_argument("--checkpoint_every", default=0, type=int)
    p.add_argument("--no_resume", action="store_true")
    p.add_argument("--elastic", action="store_true",
                   help="resume a checkpoint written at a different world "
                   "size: ZeRO-1 shards reshard onto the live mesh "
                   "(docs/MULTIHOST.md 'Resuming on a different world "
                   "size')")
    p.add_argument("--compile_cache", default=None, type=str,
                   help="AOT executable cache dir (tpudist.compile_cache) "
                   "— a relaunched run deserializes its compiled step "
                   "instead of re-tracing")
    return p.parse_args(argv)


def token_source(args):
    """The flat token stream: a read-only memmap of ``--tokens`` (web-scale
    corpora never materialize) or a synthetic in-memory stand-in."""
    import numpy as np

    from tpudist.data.lm import load_token_stream

    if args.tokens:
        # vocab-range checking happens per gathered batch inside
        # TokenWindowLoader (scanning max() over a multi-billion-token
        # memmap up front would read the whole file)
        return load_token_stream(args.tokens, dtype=np.dtype(args.token_dtype))
    rng = np.random.Generator(np.random.PCG64(0))
    return rng.integers(0, args.vocab_size, args.synthetic_tokens).astype(np.int32)


def main(argv=None):
    args = parse_args(argv)
    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    attn_requested = args.attn  # the user's words, pre-resolution
    if args.attn == "auto":
        # multi_head_attention(impl="auto") would route per-call; resolving
        # here keeps the choice visible in the run's config echo. Matches
        # attention.py's measured crossover (vmem ≤ 1024, dense XLA in the
        # 1025–2047 window, flash from 2048). Off-TPU the Pallas kernels
        # only run in interpret emulation, so CPU runs stay on XLA; inside
        # --pipe the kernels don't compose with the GPipe shard_map
        # (build_model's guard), so auto resolves to XLA there too.
        if args.pipe > 1 or jax.default_backend() != "tpu":
            args.attn = "xla"
        elif args.seq_len <= 1024:
            args.attn = "vmem"
        elif args.seq_len < 2048:
            args.attn = "xla"
        else:
            args.attn = "flash"
    import jax.numpy as jnp

    from tpudist import init_from_env
    from tpudist import mesh as mesh_lib
    from tpudist.models.gpt2 import GPT2, PipelinedGPT2
    from tpudist.optim import make_optimizer, run_schedule
    from tpudist.train import fit, lm_loss

    cp_attn = args.attn in ("ring", "ulysses", "ulysses_flash")
    if args.generate and cp_attn:
        raise SystemExit(
            f"--attn {args.attn} has no decode path; --generate needs the "
            "xla/flash model"
        )
    if (args.eval or args.generate) and (args.cp > 1 or args.pipe > 1):
        # fail fast, BEFORE the (possibly hours-long) training run: cp
        # eval/decode would need the plain forward, pipe eval batches padded
        # to num_micro — neither is what evaluate_lm/generate does
        raise SystemExit(
            "--eval/--generate support the non-cp, non-pipe paths; rerun "
            "them separately without --cp/--pipe"
        )
    if args.experts and args.init_hf:
        # HF checkpoints are dense; an MoE model's per-block moe/router
        # subtrees have no source weights — fail fast, not mid-warm-start
        raise SystemExit("--init_hf converts dense checkpoints only")
    if args.generate and args.generate >= args.seq_len:
        raise SystemExit(
            f"--generate {args.generate} must be < --seq_len {args.seq_len} "
            "(the KV cache is seq_len slots)"
        )

    ctx = init_from_env()
    n_dev = jax.device_count()
    if args.expert_axis:
        expert_axis = args.expert_axis
    elif args.experts:
        # largest axis that divides both the expert count (weights shard
        # evenly) and the devices left over from the other model axes
        avail = max(n_dev // (args.tensor * args.pipe * args.cp), 1)
        expert_axis = max(
            d for d in range(1, min(args.experts, avail) + 1)
            if args.experts % d == 0 and avail % d == 0
        )
    else:
        expert_axis = 1
    if args.fsdp > 1 and args.cp > 1:
        raise SystemExit(
            "--fsdp does not compose with --cp yet (the context-parallel "
            "batch_spec owns the batch layout); drop one"
        )
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(
            data=-1, fsdp=args.fsdp, tensor=args.tensor, pipe=args.pipe,
            seq=args.cp, expert=max(expert_axis, 1),
        )
    )
    # the composed-parallelism resolver (tpudist.parallel.plan): engaged
    # when the fsdp axis is real — tensor/pipe-only runs keep the
    # metadata path they always used (identical placements)
    plan = None
    if args.fsdp > 1:
        from tpudist.parallel.plan import ParallelPlan

        plan = ParallelPlan(mesh)
    dtype = jnp.bfloat16 if (args.bf16 or args.amp) else jnp.float32

    def build_model(scan_layers: bool, remat_layers: bool):
        """Model per the CLI flags; the scan/remat layout is a parameter so
        the remote-compile fallback below can rebuild unrolled."""
        if args.pipe > 1:
            # --pipe composes with data AND tensor parallelism (the pipeline
            # shard_map is manual over 'pipe' only; Megatron tensor shardings
            # ride the stacked params under GSPMD — tpudist.parallel.pp);
            # MoE/context-parallel/kernel attention are not pipelined. An
            # EXPLICIT kernel request errors; --attn auto quietly resolves
            # to the supported XLA path inside the pipeline.
            if args.experts or attn_requested not in ("xla", "auto"):
                raise SystemExit(
                    "--pipe composes with --tensor and data parallelism and "
                    "runs XLA attention; MoE/context-parallel/kernel "
                    "attention are not pipelined"
                )
            if args.dropout:
                raise SystemExit("--dropout is not supported with --pipe")
            if args.arch != "gpt2":
                raise SystemExit("--pipe supports the gpt2 arch only")
            if args.scan_layers or args.remat_layers:
                raise SystemExit(
                    "--scan_layers/--remat_layers are not supported with --pipe "
                    "(the pipeline already stacks blocks over the 'pipe' axis)"
                )
            if args.remat_policy:
                raise SystemExit(
                    "--remat_policy is not supported with --pipe (checkpoint "
                    "the whole forward with --remat instead)"
                )
            return PipelinedGPT2(
                mesh, num_micro=args.num_micro, vocab_size=args.vocab_size,
                max_seq_len=args.seq_len, hidden_dim=args.hidden_dim,
                depth=args.depth, num_heads=args.num_heads, dtype=dtype,
                attn_impl=args.attn, schedule=args.pipe_schedule,
            )
        if args.arch == "llama":
            from tpudist.models.llama import Llama

            if args.dropout:
                raise SystemExit("llama has no dropout (matching the family)")
            if args.scan_layers and (args.generate or args.init_hf or args.experts):
                raise SystemExit(
                    "--scan_layers uses the stacked dense layout; --generate/"
                    "--init_hf/--experts need the unrolled model"
                )
            return Llama(
                vocab_size=args.vocab_size, max_seq_len=args.seq_len,
                hidden_dim=args.hidden_dim, depth=args.depth,
                num_heads=args.num_heads,
                num_kv_heads=args.num_kv_heads or None,
                ffn_dim=args.ffn_dim or None, rope_theta=args.rope_theta,
                tie_embeddings=args.tie_embeddings, scan_layers=scan_layers,
                remat_layers=remat_layers, remat_policy=args.remat_policy,
                num_experts=args.experts,  # Mixtral-style SwiGLU experts
                moe_every=args.moe_every or 1, moe_top_k=args.moe_top_k,
                capacity_factor=args.capacity_factor,
                moe_dispatch=args.moe_dispatch,
                router_z_loss=args.router_z_loss,
                router_jitter=args.router_jitter,
                dtype=dtype, attn_impl=args.attn, mesh=mesh,
            )
        if args.scan_layers and (args.experts or args.generate or args.init_hf):
            raise SystemExit(
                "--scan_layers supports dense training only (no --experts/"
                "--generate/--init_hf: those need the unrolled layout)"
            )
        return GPT2(
            vocab_size=args.vocab_size, max_seq_len=args.seq_len,
            hidden_dim=args.hidden_dim, depth=args.depth,
            num_heads=args.num_heads, dtype=dtype, attn_impl=args.attn,
            num_experts=args.experts, moe_every=args.moe_every or 2,
            moe_top_k=args.moe_top_k, capacity_factor=args.capacity_factor,
            moe_dispatch=args.moe_dispatch,
            router_z_loss=args.router_z_loss,
            router_jitter=args.router_jitter,
            mesh=mesh, dropout=args.dropout,
            scan_layers=scan_layers, remat_layers=remat_layers,
            remat_policy=args.remat_policy,
        )

    model = build_model(args.scan_layers, args.remat_layers)

    from tpudist.data.lm import TokenWindowLoader

    # --batch_size is per data-parallel replica (reference semantics); model-
    # parallel axes (tensor/pipe/seq/expert) don't multiply the batch
    local_replicas = max(
        mesh_lib.data_parallel_size(mesh) // ctx.process_count, 1
    )
    per_process_batch = args.batch_size * local_replicas * args.grad_accum
    loader = TokenWindowLoader(
        token_source(args), per_process_batch, args.seq_len,
        vocab_size=args.vocab_size,
        num_replicas=ctx.process_count, rank=ctx.process_index,
    )

    steps_per_epoch = len(loader)
    total = args.total_steps or args.epochs * steps_per_epoch
    # --fused optimizer/all/auto builds the one-pass fused-AdamW kernel
    # (auto only when the optimizer is adam — the kernel implements the
    # adam/adamw update); under --bf16 it also keeps the bf16 compute
    # copy the fused step's forward reads
    fuse_opt = args.fused in ("optimizer", "all") or (
        args.fused == "auto" and args.optimizer == "adam"
    )
    tx = make_optimizer(
        run_schedule(args.lr, total_steps=total,
                     warmup_steps=args.warmup_steps),
        optimizer=args.optimizer,
        weight_decay=args.weight_decay, clip_norm=args.clip_norm,
        skip_nonfinite_updates=args.amp,
        fused=fuse_opt,
        compute_dtype=dtype if dtype != jnp.float32 else None,
    )

    def build_forward_loss(mdl):
        if not args.chunked_ce:
            return None
        from tpudist.models.gpt2 import chunked_lm_forward

        if args.pipe > 1:
            raise SystemExit("--chunked_ce does not compose with --pipe")
        # MoE composes: the chunked scan carries the sowed aux loss
        # (lm_utils applies with 'losses' mutable); router jitter is the
        # one knob it can't serve (no rng stream on the fused path)
        return chunked_lm_forward(mdl, chunk=args.chunked_ce)

    forward_loss = build_forward_loss(model)

    batch_spec = None
    if args.cp > 1:
        from jax.sharding import PartitionSpec as P

        shape = (
            P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS), mesh_lib.SEQUENCE_AXIS)
            if args.grad_accum == 1
            else P(None, (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
                   mesh_lib.SEQUENCE_AXIS)
        )
        batch_spec = {"tokens": shape}

    init_params = None
    if args.init_hf:
        from tpudist.interop import load_hf_params

        init_params = load_hf_params(
            args.init_hf, arch=args.arch, depth=args.depth,
            num_heads=args.num_heads, num_kv_heads=args.num_kv_heads or None,
        )
        if args.pipe > 1:
            # re-layout the unrolled HF params into the pipelined stacked
            # form (pure re-indexing — same function, now layer-over-stage)
            from flax import linen as nn

            from tpudist.models.gpt2 import stack_gpt2_params

            init_params = nn.meta.unbox(
                stack_gpt2_params(init_params, args.depth)["params"]
            )

    import time

    # throughput accounting counts data-parallel replicas (the reference's
    # world = one replica per GPU); model-parallel axes don't multiply it
    dp_size = mesh_lib.data_parallel_size(mesh)

    def run_fit(mdl, fwd_loss, remat):
        if os.environ.get("TPUDIST_TEST_FAIL_SCAN_COMPILE") and getattr(
            mdl, "scan_layers", False
        ):
            # test hook: simulate the tunnel's compile failure so the
            # fallback path below is exercisable without a remote TPU
            raise RuntimeError(
                "remote_compile: HTTP 500 (injected by "
                "TPUDIST_TEST_FAIL_SCAN_COMPILE)"
            )
        return fit(
            mdl, tx, loader,
            epochs=args.epochs, mesh=mesh, plan=plan,
            job_id=args.JobID, batch_size=args.batch_size,
            world_size=dp_size, global_rank=ctx.process_index,
            loss_fn=lm_loss, input_key="tokens", label_key="tokens",
            grad_accum=args.grad_accum, remat=remat,
            shard_opt_state=args.shard_opt_state,
            fused=None if args.fused == "none" else args.fused,
            batch_spec=batch_spec, forward_loss=fwd_loss,
            profile=not args.no_profiler, log_dir=args.log_dir,
            telemetry=args.telemetry,
            checkpoint_dir=args.checkpoint_dir,
            elastic=args.elastic,
            compile_cache=args.compile_cache,
            checkpoint_every=args.checkpoint_every,
            resume=not args.no_resume,
            init_params=init_params,
        )

    t0 = time.time()
    try:
        state, losses = run_fit(model, forward_loss, args.remat)
    except Exception as e:
        # known environment limit: a REMOTE-compile TPU attach (axon-style
        # tunnel) can 500 compiling the nn.scan'd step at larger shapes
        # (docs/LM_TRAINING.md §3.6). Infra-shaped failures on a scanned
        # model retry with the unrolled layout (remat_layers degrades to
        # whole-forward remat to keep the memory intent); anything else
        # re-raises.
        compile_infra = any(
            s in str(e)
            for s in ("remote_compile", "tpu_compile_helper", "HTTP 5")
        )
        if not (args.scan_layers and compile_infra):
            raise
        if args.checkpoint_dir:
            from tpudist.checkpoint import latest_step

            if latest_step(args.checkpoint_dir) is not None:
                # saved checkpoints hold the scan layout's stacked 'layers'
                # tree; silently resuming them into an unrolled rebuild
                # would crash (or mix runs). Convert explicitly instead.
                raise RuntimeError(
                    "remote compile of the scanned step failed after "
                    f"checkpoints were written to {args.checkpoint_dir}; "
                    "not auto-falling-back across layouts. Convert with "
                    "tpudist.models.lm_utils.unstack_layers and rerun "
                    "without --scan_layers (docs/LM_TRAINING.md §3.6)."
                ) from e
        print(
            "warning: remote compile of the nn.scan'd train step failed "
            f"({e}); retrying with the unrolled layer layout "
            "(docs/LM_TRAINING.md §3.6). Checkpoints from a previous "
            "scan-layout run need tpudist.models.lm_utils.unstack_layers.",
            file=sys.stderr,
        )
        model = build_model(False, False)
        forward_loss = build_forward_loss(model)
        t0 = time.time()
        state, losses = run_fit(
            model, forward_loss, args.remat or args.remat_layers
        )
    wall = time.time() - t0
    n_steps = len(losses)
    if n_steps and ctx.process_index == 0:
        seqs = n_steps * args.batch_size * dp_size * args.grad_accum
        print(
            f"tokens/sec: {seqs * args.seq_len / wall:.1f} "
            f"(global, incl. compile) steps={n_steps} final_loss={losses[-1]:.4f}"
        )
    if args.amp and ctx.process_index == 0:
        from tpudist.amp import skipped_steps

        skipped = skipped_steps(state.opt_state)
        if skipped:
            print(f"amp: skipped {skipped} non-finite update step(s)")

    if args.generate:
        # EVERY process runs the (collective) jitted decode — params are
        # global arrays; the prompt is identical everywhere (same stream),
        # so outputs agree and only rank 0 prints
        import numpy as np

        from tpudist.generate import generate

        prompt_len = max(1, min(32, args.seq_len - args.generate))
        prompt = np.asarray(token_source(args)[:prompt_len], np.int32)[None]
        out = generate(
            model, state.params, prompt, args.generate,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p,
        )[0]
        if ctx.process_index == 0:
            print(f"generated tokens: {out.tolist()}")
            if args.vocab_size <= 256:
                # byte-level vocab decodes straight back to text
                text = bytes(int(t) % 256 for t in out).decode("utf-8", "replace")
                print(f"generated text: {text!r}")

    if args.eval:
        from tpudist.train import evaluate_lm
        # held-out stream if provided; otherwise the training stream in
        # order (smoke-level perplexity, like the reference's val loader
        # being the train-distribution set, /root/reference/main.py:56-63)
        if args.val_tokens:
            import numpy as np

            from tpudist.data.lm import load_token_stream

            source = load_token_stream(
                args.val_tokens, dtype=np.dtype(args.token_dtype)
            )
        else:
            source = token_source(args)

        # sharded like the train loader so N hosts split the eval work
        # instead of each scoring the full set (the sampler's pad-to-
        # divisible may re-count at most process_count-1 head windows)
        val_loader = TokenWindowLoader(
            source, args.batch_size * local_replicas, args.seq_len,
            vocab_size=args.vocab_size, shuffle=False, drop_remainder=False,
            num_replicas=ctx.process_count, rank=ctx.process_index,
        )
        # same chunked head as training: without it, --eval would re-create
        # the [B,S,V] logits peak that --chunked_ce exists to avoid
        metrics = evaluate_lm(
            model, state, val_loader, mesh, chunk=args.chunked_ce or None
        )
        if ctx.process_index == 0:
            print(
                f"val_loss: {metrics['loss']:.4f} "
                f"perplexity: {metrics['perplexity']:.2f}"
            )
    return state, losses


if __name__ == "__main__":
    main()
