"""Per-GEMM MXU-utilization probe — the measurement behind docs/PERF.md §4b.

The GPT-2 124M training step is kernel-efficiency-limited at hidden=768
(PERF §4): this probe quantifies WHERE by timing each GEMM shape of the
step in isolation on the attached chip, plus the same block mix at wider
hidden sizes (the "would a bigger model hit higher MFU" experiment).

Method: each shape runs inside ONE jitted ``lax.scan`` of ``iters``
matmuls whose left operand is scaled per-iteration (defeats loop-invariant
hoisting) and accumulated (defeats dead-code elimination); timing is
sync'd by fetching a scalar of the result (the remote-attach
block_until_ready hazard — see bench.py). The per-iteration time is
DIFFERENTIAL — ``(t(4n) − t(n)) / 3n`` — so the remote attach's ~100 ms
per-call RTT cancels instead of polluting sub-millisecond GEMMs (a
non-differential first version under-read small shapes 30×). Per-shape
report: achieved TFLOP/s and fraction of the chip's bf16 peak.

Run on the bench chip::

    python examples/mfu_probe.py            # per-GEMM table + hidden sweep
    python examples/mfu_probe.py --peak 197e12
"""

from __future__ import annotations

import argparse
import os
import sys

import jax.numpy as jnp
import numpy as np

# runnable as a plain script from anywhere: put the repo root (one level up)
# on sys.path when tpudist isn't pip-installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# single source of truth for the analytic counters, the GEMM-shape table,
# and the peak default (tpudist.telemetry.flops): this file keeps only the
# CLI — the math it times lives with the MFU accounting that fit()'s
# telemetry and bench.py's legs share, and the differential-timing
# skeleton (adaptive iters, (t(4n)−t(n))/3n, anti-hoisting operands,
# plausibility retries) lives in tpudist.telemetry.microbench so this
# probe and examples/kernel_probe.py measure the same way
from tpudist.telemetry import microbench  # noqa: E402
from tpudist.telemetry.flops import DEFAULT_PEAK_FLOPS, gpt2_step_shapes  # noqa: E402


def time_gemm(m: int, k: int, n: int, *, reps: int = 5,
              peak: float = DEFAULT_PEAK_FLOPS) -> float:
    """Median achieved FLOP/s for a bf16 [m,k]x[k,n] matmul.

    Differential timing (tpudist.telemetry.microbench) cancels per-call
    fixed costs (dispatch, the remote tunnel's ~100 ms ±100 ms RTT);
    iteration counts are ADAPTIVE so the differential spans ~1.5 s of
    device time, far above the tunnel's jitter (a fixed small count read
    impossible >100%-peak values through the noise)."""
    rng = np.random.Generator(np.random.PCG64(0))
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((k, n)), jnp.bfloat16)

    timed = microbench.anti_hoist_scan(lambda xs: xs @ w, x, reps=reps)
    flops = 2.0 * m * k * n
    # optimistic per-iter estimate (50% of peak, bandwidth floor included)
    est = max(flops / (0.5 * peak),
              2.0 * (m * k + k * n + m * n) / 819e9)
    dt = microbench.measure_iter_seconds(
        timed, est, floor_s=flops / (1.05 * peak)
    )
    return flops / dt if dt > 0 else float("nan")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--peak", type=float, default=DEFAULT_PEAK_FLOPS,
                    help="chip bf16 peak FLOP/s (default v5e 197e12)")
    ap.add_argument("--tokens", type=int, default=8192,
                    help="GEMM rows = microbatch tokens of the bench step "
                    "(8 seqs x 1024)")
    ap.add_argument("--sweep", default="768,1024,1536,2048",
                    help="hidden sizes for the wider-GEMM block-mix sweep")
    args = ap.parse_args()

    print(f"# per-GEMM MXU utilization at tokens={args.tokens} "
          f"(bf16, peak {args.peak / 1e12:.0f} TFLOP/s)")
    print(f"{'shape':24s} {'M':>7s} {'K':>6s} {'N':>6s} "
          f"{'TFLOP/s':>8s} {'%peak':>6s}")
    for name, m, k, n in gpt2_step_shapes(args.tokens, 768):
        fl = time_gemm(m, k, n, peak=args.peak)
        print(f"{name:24s} {m:7d} {k:6d} {n:6d} "
              f"{fl / 1e12:8.1f} {100 * fl / args.peak:5.1f}%")

    print("\n# block GEMM mix vs hidden width (fwd shapes, wider d)")
    print(f"{'hidden':>6s} {'weighted TFLOP/s':>16s} {'%peak':>6s}")
    for d in [int(s) for s in args.sweep.split(",")]:
        total_flops, total_time = 0.0, 0.0
        for name, m, k, n in gpt2_step_shapes(args.tokens, d)[:-3:3]:
            # fwd block GEMMs only (dgrad/wgrad track them; head excluded:
            # its width is vocab-fixed)
            fl = time_gemm(m, k, n, reps=3, peak=args.peak)
            if not np.isfinite(fl):
                continue  # persistently-noisy shape: excluded, not faked
            f = 2.0 * m * k * n
            total_flops += f
            total_time += f / fl
        eff = total_flops / total_time if total_time else float("nan")
        print(f"{d:6d} {eff / 1e12:16.1f} {100 * eff / args.peak:5.1f}%")


if __name__ == "__main__":
    main()
