"""T5 span-corruption pretraining — the encoder-decoder counterpart of
train_gpt2.py / train_bert.py.

Same data format (flat token stream, ``.bin``/``.npy`` memmap), same
observability contract (TSV metrics, windowed profiler, TrainTime), same
multi-host launch (``python -m tpudist.launch ... examples/train_t5.py``).
The model vocabulary is the corpus vocabulary plus a reserved block at the
top for the span sentinels and EOS (tpudist.models.t5's fixed-count
corruption), and each gathered window is corrupted on the host
(span_corrupt_transform) into static-shape (encoder, decoder, targets)
triples — no padding, no masks.

No reference counterpart (SURVEY.md §2.12 — the reference has one model);
this is capability surface beyond the baseline ladder.

    # byte-level corpus, t5-small-ish geometry, bf16:
    python examples/train_t5.py --tokens corpus.bin --vocab_size 256 \
        --bf16 --batch_size 16 --JobID T5 --eval
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable as a plain script from anywhere: put the repo root (one level up)
# on sys.path when tpudist isn't pip-installed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--local_rank", type=int,
                   default=int(os.environ.get("LOCAL_RANK", 0)))
    p.add_argument("--tokens", required=True,
                   help=".bin (raw little-endian) or .npy flat token stream")
    p.add_argument("--val_tokens", default=None)
    p.add_argument("--token_dtype", default="uint16")
    p.add_argument("--vocab_size", default=256, type=int,
                   help="CORPUS vocabulary; the model reserves sentinel/EOS "
                   "ids in a block ABOVE it")
    p.add_argument("--seq_len", default=512, type=int,
                   help="window length BEFORE corruption")
    p.add_argument("--density", default=0.15, type=float,
                   help="fraction of each window corrupted")
    p.add_argument("--mean_span", default=3.0, type=float)
    p.add_argument("--batch_size", default=16, type=int,
                   help="per data-parallel replica (reference semantics)")
    p.add_argument("--hidden_dim", default=512, type=int)
    p.add_argument("--ffn_dim", default=1024, type=int)
    p.add_argument("--enc_depth", default=8, type=int)
    p.add_argument("--dec_depth", default=8, type=int)
    p.add_argument("--num_heads", default=6, type=int)
    p.add_argument("--epochs", default=1, type=int)
    p.add_argument("--total_steps", default=0, type=int)
    p.add_argument("--lr", default=1e-3, type=float)
    p.add_argument("--warmup_steps", default=0, type=int)
    p.add_argument("--optimizer", default="adam")
    p.add_argument("--weight_decay", default=0.0, type=float)
    p.add_argument("--clip_norm", default=None, type=float)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--amp", action="store_true",
                   help="bf16 policy + non-finite update guard (tpudist.amp)")
    p.add_argument("--grad_accum", default=1, type=int)
    p.add_argument("--tensor", default=1, type=int,
                   help="Megatron TP degree over the 'tensor' mesh axis")
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--JobID", default="T5_0", type=str)
    p.add_argument("--log_dir", default=".", type=str)
    p.add_argument("--no_profiler", action="store_true")
    p.add_argument("--checkpoint_dir", default=None, type=str)
    p.add_argument("--checkpoint_every", default=0, type=int)
    p.add_argument("--no_resume", action="store_true")
    p.add_argument("--eval", action="store_true",
                   help="span-denoising loss + in-span token accuracy on "
                   "the held-out stream (or the train stream in order)")
    p.add_argument("--generate", action="store_true",
                   help="after training, greedily DENOISE one held-out "
                   "window with the KV-cache decoder "
                   "(tpudist.generate.generate_seq2seq) and report the "
                   "generated vs true span targets")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import time

    import jax.numpy as jnp
    import numpy as np

    from tpudist import init_from_env
    from tpudist import mesh as mesh_lib
    from tpudist.data.lm import TokenWindowLoader, load_token_stream
    from tpudist.models.t5 import (
        T5, seq2seq_forward, span_corrupt_transform, span_corruption_plan,
    )
    from tpudist.optim import make_optimizer, run_schedule
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=-1, tensor=args.tensor)
    )
    dtype = jnp.bfloat16 if (args.bf16 or args.amp) else jnp.float32

    # the sentinel/EOS block sits above the corpus vocab: spans sentinels
    # plus one EOS id (span_corruption_plan fixes `spans` per seq_len)
    _, spans, enc_len, dec_len = span_corruption_plan(
        args.seq_len, density=args.density, mean_span=args.mean_span
    )
    model_vocab = args.vocab_size + spans + 1
    model = T5(
        vocab_size=model_vocab, hidden_dim=args.hidden_dim,
        ffn_dim=args.ffn_dim, enc_depth=args.enc_depth,
        dec_depth=args.dec_depth, num_heads=args.num_heads, dtype=dtype,
        # generation (--generate) decodes the span targets: start token +
        # dec_len slots in the decoder KV cache
        max_decode_len=dec_len + 1,
    )

    local_replicas = max(
        mesh_lib.data_parallel_size(mesh) // ctx.process_count, 1
    )
    per_process_batch = args.batch_size * local_replicas * args.grad_accum
    corruption = span_corrupt_transform(
        model_vocab, density=args.density, mean_span=args.mean_span,
        seed=args.seed + ctx.process_index,
    )
    loader = TokenWindowLoader(
        args.tokens, per_process_batch, args.seq_len,
        dtype=np.dtype(args.token_dtype), vocab_size=args.vocab_size,
        num_replicas=ctx.process_count, rank=ctx.process_index,
        transform=corruption,
    )

    steps_per_epoch = len(loader)
    total = args.total_steps or args.epochs * steps_per_epoch
    tx = make_optimizer(
        run_schedule(args.lr, total_steps=total,
                     warmup_steps=args.warmup_steps),
        optimizer=args.optimizer,
        weight_decay=args.weight_decay, clip_norm=args.clip_norm,
        skip_nonfinite_updates=args.amp,
    )

    dp = mesh_lib.data_parallel_size(mesh)
    t0 = time.time()
    state, losses = fit(
        model, tx, loader,
        epochs=args.epochs, mesh=mesh, seed=args.seed,
        job_id=args.JobID, batch_size=args.batch_size,
        world_size=dp, global_rank=ctx.process_index,
        input_key="enc_tokens", label_key="targets",
        forward_loss=seq2seq_forward(model),
        grad_accum=args.grad_accum,
        profile=not args.no_profiler, log_dir=args.log_dir,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=not args.no_resume,
        # two-stream init: fit's probe only covers batch[input_key]
        init_input=(
            jnp.zeros((dp, enc_len), jnp.int32),
            jnp.zeros((dp, dec_len), jnp.int32),
        ),
    )
    wall = time.time() - t0
    if losses and ctx.process_index == 0:
        seqs = len(losses) * args.batch_size * dp * args.grad_accum
        print(
            f"tokens/sec: {seqs * args.seq_len / wall:.1f} "
            f"(global, incl. compile) steps={len(losses)} "
            f"final_loss={losses[-1]:.4f}"
        )

    if args.eval:
        import jax

        source = load_token_stream(
            args.val_tokens or args.tokens, dtype=np.dtype(args.token_dtype)
        )
        val_corruption = span_corrupt_transform(
            model_vocab, density=args.density, mean_span=args.mean_span,
            seed=args.seed + 10_000,
        )
        val_loader = TokenWindowLoader(
            source, args.batch_size, args.seq_len,
            vocab_size=args.vocab_size, shuffle=False, drop_remainder=True,
            num_replicas=ctx.process_count, rank=ctx.process_index,
            transform=val_corruption,
        )

        @jax.jit
        def score(params, enc, dec, tgt, row_mask):
            import optax

            logits = model.apply({"params": params}, enc, dec, train=False)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            hit = jnp.argmax(logits, axis=-1) == tgt
            rows = row_mask[:, None]
            return (
                jnp.sum(jnp.where(rows, ce, 0.0)),
                jnp.sum(jnp.where(rows, hit, False)),
                jnp.sum(row_mask) * tgt.shape[1],
            )

        # globally-accounted, like tpudist.train.evaluate/evaluate_lm: each
        # process's (disjoint, rank-sharded) rows are staged as ONE global
        # batch-sharded array padded to the mesh's replica multiple (the
        # pad rows masked out of every sum), so the in-graph sums are
        # global sums and every process sees the same totals — a rank-0
        # print of its local sums would report 1/world of the set on a
        # real multi-host run, and jitting mesh-global params with
        # process-local host arrays can fail outright there. Lockstep
        # holds: drop_remainder=True plus the sampler's stride gives every
        # process the same batch count.
        dp = mesh_lib.data_parallel_size(mesh)
        total_ce, total_hit, total_n = 0.0, 0, 0
        for batch in val_loader:
            arrs = {k: np.asarray(batch[k])
                    for k in ("enc_tokens", "dec_tokens", "targets")}
            n = arrs["targets"].shape[0]
            pad = -n % (dp // ctx.process_count or 1)
            if pad:
                arrs = {
                    k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                    for k, v in arrs.items()
                }
            row_mask = np.arange(n + pad) < n
            dev = mesh_lib.shard_batch(arrs, mesh)
            mask_dev = mesh_lib.put_sharded(
                row_mask, mesh_lib.batch_sharding(mesh, extra_dims=0)
            )
            ce, hit, cnt = score(
                state.params, dev["enc_tokens"], dev["dec_tokens"],
                dev["targets"], mask_dev,
            )
            total_ce += float(ce)
            total_hit += int(hit)
            total_n += int(cnt)
        if ctx.process_index == 0 and total_n:
            print(
                f"span_loss: {total_ce / total_n:.4f} "
                f"span_accuracy: {total_hit / total_n:.4f}"
            )

    if args.generate:
        from tpudist.generate import generate_seq2seq

        # greedily denoise one held-out window with the KV-cache decoder:
        # the generated sequence should reproduce the span targets
        source = load_token_stream(
            args.val_tokens or args.tokens, dtype=np.dtype(args.token_dtype)
        )
        if len(source) < args.seq_len:
            # a short val stream would corrupt to a different dec_len than
            # the model's cache was sized for — refuse with the reason
            # instead of a downstream shape error
            raise SystemExit(
                f"--generate needs a stream of >= --seq_len "
                f"({args.seq_len}) tokens to build one window; "
                f"{args.val_tokens or args.tokens} holds {len(source)}"
            )
        gen_corruption = span_corrupt_transform(
            model_vocab, density=args.density, mean_span=args.mean_span,
            seed=args.seed + 20_000,
        )
        window = np.asarray(source[: args.seq_len], np.int32)[None]
        demo = gen_corruption({"tokens": window})
        out = generate_seq2seq(
            model, state.params, demo["enc_tokens"], dec_len,
            temperature=0.0,
        )
        tgt = demo["targets"][0]
        match = float((out[0] == tgt).mean())
        if ctx.process_index == 0:
            print(f"generated span tokens: {out[0].tolist()}")
            print(f"true span targets:     {tgt.tolist()}")
            print(f"generation_span_match: {match:.4f}")
    return state, losses


if __name__ == "__main__":
    main()
