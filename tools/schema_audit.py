"""Telemetry schema audit: every JSONL row kind the code can emit must be
documented in docs/OBSERVABILITY.md §1.

The JSONL stream's schema table (docs/OBSERVABILITY.md §1) is the contract
offline consumers — dashboards, tools/tracelens.py, post-mortem scripts —
program against. Nothing enforced that the table keeps up with the code: a
new ``sink.write("<kind>", ...)`` call site ships a new row kind silently,
and the first consumer to meet it learns about the schema drift from a
KeyError in production.

This module statically scans ``tpudist/**/*.py`` for sink-write call sites
whose first argument is a string literal (the row kind), parses the
backticked first-column cells out of the §1 schema table, and FAILS (exit
status 3, same convention as tools/marker_audit.py) listing any emitted
kind the table is missing. Literal-first-arg extraction is deliberate: the
``TelemetrySink.write`` convention is a literal kind at every call site,
so the scan has no false negatives to chase through dataflow.

Two ways to run it:

- ``python tools/schema_audit.py`` — audits the repo this file lives in;
  exit 0 clean, 3 with undocumented kinds listed.
- ``tests/test_schema_audit.py`` — the tier-1 test wrapper: unit-tests
  the pure logic on synthetic inputs AND runs the real audit, so an
  undocumented kind fails the suite the same commit it appears.

Pure logic lives in :func:`emitted_kinds` / :func:`documented_kinds` /
:func:`offenders` so it is unit-testable without touching the real tree.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

EXIT_OFFENDERS = 3

# a sink-write call site with a literal row kind: `.write("kind", ...)` /
# `.write(\n    "kind", ...)`. The attribute spelling (`.write(`) rather
# than a bare name keeps file-handle writes like `f.write(line)` out —
# those pass variables, not kind literals, and the literal requirement
# filters the rest.
_WRITE_RE = re.compile(r"""\.write\(\s*["']([A-Za-z_][A-Za-z0-9_]*)["']""")

# a §1 schema-table row: `| `kind` | fields | when |`
_ROW_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*\|")


def emitted_kinds(source: str) -> set[str]:
    """Row kinds a module can emit: string-literal first arguments of
    ``.write(...)`` call sites (newline-tolerant — the wrapped calls the
    line length limit produces)."""
    return set(_WRITE_RE.findall(source))


def documented_kinds(md_text: str) -> set[str]:
    """Backticked first-column cells of every markdown table row in the
    §1 section (from the first ``## 1.`` heading to the next ``## ``).
    Falls back to the whole document when the section heading is missing
    — a renumbered doc should not make the audit vacuously fail."""
    lines = md_text.splitlines()
    start = next(
        (i for i, ln in enumerate(lines) if ln.startswith("## 1.")), None
    )
    if start is not None:
        end = next(
            (
                i for i in range(start + 1, len(lines))
                if lines[i].startswith("## ")
            ),
            len(lines),
        )
        lines = lines[start:end]
    out = set()
    for ln in lines:
        m = _ROW_RE.match(ln)
        # skip the header separator and the header row itself ("kind")
        if m and m.group(1) not in ("kind", "field"):
            out.add(m.group(1))
    return out


def scan_tree(pkg_dir: Path) -> dict[str, set[str]]:
    """``{kind: {relative paths emitting it}}`` over every ``.py`` under
    ``pkg_dir``."""
    by_kind: dict[str, set[str]] = {}
    for path in sorted(pkg_dir.rglob("*.py")):
        try:
            source = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            continue
        for kind in emitted_kinds(source):
            by_kind.setdefault(kind, set()).add(
                str(path.relative_to(pkg_dir.parent))
            )
    return by_kind


def offenders(emitted: dict[str, set[str]],
              documented: set[str]) -> list[tuple[str, list[str]]]:
    """``(kind, sorted emitting files)`` for every emitted kind absent
    from the schema table, sorted by kind. Documented-but-never-emitted
    kinds are NOT offenders — the table may legitimately describe rows a
    feature branch removed behind a flag."""
    return [
        (kind, sorted(paths))
        for kind, paths in sorted(emitted.items())
        if kind not in documented
    ]


def audit(repo: Path) -> list[tuple[str, list[str]]]:
    md = (repo / "docs" / "OBSERVABILITY.md").read_text(encoding="utf-8")
    return offenders(scan_tree(repo / "tpudist"), documented_kinds(md))


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo = Path(args[0]) if args else Path(__file__).resolve().parent.parent
    bad = audit(repo)
    if not bad:
        print("schema audit: every emitted row kind is documented in "
              "docs/OBSERVABILITY.md §1")
        return 0
    print(f"schema audit FAILED: {len(bad)} emitted row kind(s) missing "
          "from the docs/OBSERVABILITY.md §1 schema table:")
    for kind, paths in bad:
        print(f"  {kind}  (emitted by {', '.join(paths)})")
    return EXIT_OFFENDERS


if __name__ == "__main__":
    raise SystemExit(main())
