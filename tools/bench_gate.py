"""Perf-regression gate over bench.py records: fresh leg values vs a
rolling per-leg baseline store, with a noise band.

bench.py measures; nothing *judged*. A throughput leg could quietly lose
8% per quarter and every run would still print green, because the
``vs_baseline`` column in BENCH_SUMMARY.json compares against a single
hand-pinned number that nobody updates. This tool closes the loop:

- a **baseline store** (JSON file, default ``BENCH_BASELINES.json`` next
  to the record) keeps a capped rolling history of values per leg;
- ``check`` compares a fresh record against ``median(history)`` with a
  noise band of ``max(--band, 3 * MAD / median)`` — legs whose run-to-run
  scatter is naturally wide earn a proportionally wide band, quiet legs
  get the floor — and exits **3** (the tools/marker_audit.py /
  tools/schema_audit.py offender convention) when any leg regresses;
- ``seed`` builds the store from recorded history (BENCH_SUMMARY.json
  files, JSONL metric streams, and archived BENCH_r*.json round files —
  whose ``tail`` field is truncated to the last ~2000 characters, so the
  compact-summary line on its last line is usually *torn at the front*;
  leg entries interior to the tail are recovered by regex salvage).

Direction is inferred from the metric name: legs that measure a cost
(``*_overhead_pct``, ``*_recovery_s``, latency/ttft, bytes-per-step)
regress *upward*; everything else (throughput) regresses *downward*.
Legs with fewer than ``--min-history`` recorded values pass with a note
— a gate that fails on its first run trains people to delete it.

Exit codes: 0 all legs pass, 3 regression(s), 2 usage / unreadable
record. stdlib-only, same as tools/tracelens.py, so it runs anywhere the
record files land.

Usage::

    python tools/bench_gate.py seed  --store BENCH_BASELINES.json \
        BENCH_SUMMARY.json bench_archive/BENCH_r*.json
    python tools/bench_gate.py check --store BENCH_BASELINES.json \
        BENCH_SUMMARY.json [--band 0.05] [--update]

Pure logic (``extract_legs`` / ``baseline_of`` / ``lower_is_better`` /
``judge``) is import-testable without touching the filesystem; see
tests/test_bench_gate.py.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

EXIT_REGRESSION = 3  # marker_audit / schema_audit offender convention
EXIT_USAGE = 2

DEFAULT_STORE = "BENCH_BASELINES.json"
DEFAULT_BAND = 0.05   # noise-band floor (fraction of baseline)
DEFAULT_KEEP = 20     # rolling history cap per leg
DEFAULT_MIN_HISTORY = 3

# Leg entry inside a compact summary line:  "name": {"value": 12.3,
# Works on *torn* BENCH_r*.json tails too — entries interior to the tail
# survive truncation even when the line's head is gone.
_LEG_RE = re.compile(
    r'"([A-Za-z_][A-Za-z0-9_]*)":\s*\{\s*"value":\s*'
    r'(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)'
)

# Metric-name tokens that mean "smaller is better". Checked as whole
# underscore-delimited tokens (plus the _s unit suffix) so that e.g.
# "tokens_per_sec" never matches "_s".
_COST_TOKENS = frozenset({
    "overhead", "latency", "ttft", "recovery", "bytes", "stall",
    "p50", "p99", "ms", "s",
})
_COST_HINTS = ("overhead", "recovery_s", "bytes_per_step", "latency",
               "ttft")


def lower_is_better(name: str) -> bool:
    """True when the metric measures a cost (time, bytes, overhead) so a
    regression is an *increase*. Throughput-style names default False."""
    if any(h in name for h in _COST_HINTS):
        return True
    tokens = name.split("_")
    # unit suffix: *_s / *_ms / *_pct read as durations or ratios only
    # when the name isn't a rate ("per_sec" etc. never reach here).
    return bool(tokens) and tokens[-1] in _COST_TOKENS and \
        "per" not in tokens


def extract_legs(text: str) -> dict[str, float]:
    """``{leg: value}`` from any recorded bench artifact, newest wins.

    Accepts, in one pass over the lines:
    - a whole-file JSON summary with a ``legs`` dict (BENCH_SUMMARY.json)
      or a BENCH_r*.json round file (legs salvaged from its ``tail``);
    - JSONL metric lines ``{"metric": ..., "value": ...}`` (the
      $TPUDIST_BENCH_RECORD stream);
    - compact-summary lines with a ``legs`` dict, even torn ones —
      falls back to regex salvage when json.loads refuses the line.
    """
    legs: dict[str, float] = {}
    try:
        whole = json.loads(text)
    except ValueError:
        whole = None
    if isinstance(whole, dict):
        if isinstance(whole.get("legs"), dict):
            for name, ent in whole["legs"].items():
                val = ent.get("value") if isinstance(ent, dict) else ent
                if isinstance(val, (int, float)):
                    legs[str(name)] = float(val)
            return legs
        if isinstance(whole.get("tail"), str):
            # archived round file; the tail is truncated, salvage it
            text = whole["tail"]
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        row = None
        if line.startswith("{"):
            try:
                row = json.loads(line)
            except ValueError:
                row = None
        if isinstance(row, dict):
            if isinstance(row.get("legs"), dict):
                for name, ent in row["legs"].items():
                    val = ent.get("value") if isinstance(ent, dict) \
                        else ent
                    if isinstance(val, (int, float)):
                        legs[str(name)] = float(val)
                continue
            metric, val = row.get("metric"), row.get("value")
            if isinstance(metric, str) and isinstance(val, (int, float)):
                legs[metric] = float(val)
                continue
        if '"value"' in line:  # torn summary line: regex salvage
            for name, num in _LEG_RE.findall(line):
                legs[name] = float(num)
    return legs


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def baseline_of(history: list[float],
                band_floor: float = DEFAULT_BAND) -> tuple[float, float]:
    """``(median, band)`` for a leg's history. The band is the larger of
    the floor and ``3 * MAD / median`` — a robust scale estimate, so one
    historical outlier widens the band far less than a stdev would."""
    med = _median(history)
    if med == 0:
        return med, band_floor
    mad = _median([abs(v - med) for v in history])
    return med, max(band_floor, 3.0 * mad / abs(med))


def judge(name: str, value: float, history: list[float],
          band_floor: float = DEFAULT_BAND,
          min_history: int = DEFAULT_MIN_HISTORY) -> dict:
    """One leg's verdict: ``{leg, value, status, ...}`` where status is
    ``pass`` / ``regression`` / ``no-history``."""
    if len(history) < min_history:
        return {"leg": name, "value": value, "status": "no-history",
                "history": len(history)}
    med, band = baseline_of(history, band_floor)
    lower = lower_is_better(name)
    if lower:
        limit = med * (1.0 + band)
        bad = value > limit
    else:
        limit = med * (1.0 - band)
        bad = value < limit
    delta = 0.0 if med == 0 else (value - med) / abs(med)
    return {
        "leg": name, "value": value, "baseline": med,
        "band_pct": round(band * 100.0, 2),
        "delta_pct": round(delta * 100.0, 2),
        "direction": "lower-is-better" if lower else "higher-is-better",
        "status": "regression" if bad else "pass",
    }


def load_store(path: Path) -> dict[str, list[float]]:
    if not path.exists():
        return {}
    raw = json.loads(path.read_text(encoding="utf-8"))
    out: dict[str, list[float]] = {}
    for name, vals in raw.items():
        if isinstance(vals, list):
            out[name] = [float(v) for v in vals
                         if isinstance(v, (int, float))]
    return out


def save_store(path: Path, store: dict[str, list[float]],
               keep: int = DEFAULT_KEEP) -> None:
    trimmed = {k: v[-keep:] for k, v in sorted(store.items())}
    path.write_text(json.dumps(trimmed, indent=1, sort_keys=True) + "\n",
                    encoding="utf-8")


def _read_records(paths: list[str]) -> list[tuple[str, dict[str, float]]]:
    out = []
    for p in paths:
        path = Path(p)
        try:
            text = path.read_text(encoding="utf-8", errors="replace")
        except OSError as exc:
            print(f"bench_gate: cannot read {p}: {exc}", file=sys.stderr)
            return []
        out.append((p, extract_legs(text)))
    return out


def cmd_seed(args) -> int:
    records = _read_records(args.records)
    if not records:
        return EXIT_USAGE
    store = load_store(Path(args.store))
    added = 0
    for name, legs in records:
        if not legs:
            print(f"bench_gate: no legs recovered from {name}")
            continue
        for leg, val in legs.items():
            store.setdefault(leg, []).append(val)
            added += 1
        print(f"bench_gate: seeded {len(legs)} leg value(s) from {name}")
    save_store(Path(args.store), store, args.keep)
    print(f"bench_gate: store {args.store} now tracks "
          f"{len(store)} leg(s) ({added} value(s) added)")
    return 0


def cmd_check(args) -> int:
    records = _read_records(args.records)
    if not records:
        return EXIT_USAGE
    fresh: dict[str, float] = {}
    for _, legs in records:
        fresh.update(legs)
    if not fresh:
        print("bench_gate: no leg values found in the given record(s)",
              file=sys.stderr)
        return EXIT_USAGE
    store = load_store(Path(args.store))
    verdicts = [judge(leg, val, store.get(leg, []), args.band,
                      args.min_history)
                for leg, val in sorted(fresh.items())]
    bad = [v for v in verdicts if v["status"] == "regression"]
    for v in verdicts:
        if v["status"] == "no-history":
            print(f"  {v['leg']}: {v['value']:g}  (no baseline yet, "
                  f"{v['history']} recorded — passes)")
        else:
            sign = "+" if v["delta_pct"] >= 0 else ""
            mark = "REGRESSION" if v["status"] == "regression" else "ok"
            print(f"  {v['leg']}: {v['value']:g} vs baseline "
                  f"{v['baseline']:g} ({sign}{v['delta_pct']}%, band "
                  f"±{v['band_pct']}%, {v['direction']}) {mark}")
    if args.update and not bad:
        for leg, val in fresh.items():
            store.setdefault(leg, []).append(val)
        save_store(Path(args.store), store, args.keep)
        print(f"bench_gate: store updated ({args.store})")
    if bad:
        print(f"bench gate FAILED: {len(bad)} leg(s) regressed beyond "
              "the noise band")
        return EXIT_REGRESSION
    print(f"bench gate: {len(verdicts)} leg(s) within the noise band")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_gate.py",
        description="rolling-baseline perf gate over bench.py records",
    )
    sub = ap.add_subparsers(dest="cmd")
    common = dict(store=DEFAULT_STORE, keep=DEFAULT_KEEP)

    def _shared(p):
        p.add_argument("records", nargs="+",
                       help="record file(s): BENCH_SUMMARY.json, JSONL "
                            "metric stream, or archived BENCH_r*.json")
        p.add_argument("--store", default=common["store"],
                       help="baseline store JSON path "
                            f"(default {DEFAULT_STORE})")
        p.add_argument("--keep", type=int, default=common["keep"],
                       help="rolling history cap per leg "
                            f"(default {DEFAULT_KEEP})")

    chk = sub.add_parser("check", help="gate a fresh record (exit 3 on "
                                       "regression)")
    _shared(chk)
    chk.add_argument("--band", type=float, default=DEFAULT_BAND,
                     help="noise-band floor as a fraction "
                          f"(default {DEFAULT_BAND})")
    chk.add_argument("--min-history", type=int,
                     default=DEFAULT_MIN_HISTORY,
                     help="baseline needs this many recorded values "
                          f"(default {DEFAULT_MIN_HISTORY})")
    chk.add_argument("--update", action="store_true",
                     help="on pass, append the fresh values to the store")
    seed = sub.add_parser("seed", help="build the baseline store from "
                                       "recorded history")
    _shared(seed)

    args = ap.parse_args(argv)
    if args.cmd == "seed":
        return cmd_seed(args)
    if args.cmd == "check":
        return cmd_check(args)
    ap.print_help(sys.stderr)
    return EXIT_USAGE


if __name__ == "__main__":
    raise SystemExit(main())
