"""Tier-1 marker audit: keep the `not slow` suite inside its time window.

The tier-1 gate runs ``pytest -m 'not slow'`` under a hard 870 s budget
(ROADMAP.md). That window only holds if every test that got expensive —
usually by growing a subprocess world or a fat compile — carries the
``slow`` marker. Nothing enforced that until now: a test could creep past
a minute and silently eat the whole suite's headroom until the next
timeout-driven archaeology session.

This module is a pytest plugin (plus a CLI wrapper) that records every
executed test's call duration and, at session end, FAILS the run (exit
status 3) listing any test that exceeded the per-test budget without the
``slow`` marker. Budget: ``TPUDIST_MARKER_BUDGET_S`` (seconds, default
30 — generous against the measured suite, where the slowest properly
tier-1 tests sit in the low-20s cold).

Second rule, enforced at COLLECTION (no need to pay the runtime to catch
the offender): a test whose module spawns a subprocess *world* — launches
``tpudist.launch`` or ``--emulate-devices`` children, each of which
cold-compiles its own jax programs with no shared persistent-cache
warmth guarantee — must carry the ``slow`` marker. Every such test
measured to date sits far past the per-test budget, and the duration
rule only catches it after burning the budget once; the source rule
catches it before it ever runs.

Three ways to run it:

- ``python tools/marker_audit.py`` — runs the tier-1 selection
  (``tests/ -m 'not slow'``) with the audit armed; extra args pass
  through to pytest.
- ``TPUDIST_MARKER_AUDIT=1 python -m pytest tests/ -m 'not slow'`` —
  tests/conftest.py registers the plugin when the env var is set, so the
  audit can ride any existing invocation.
- ``python -m pytest <dir> -p marker_audit`` with this directory on
  ``PYTHONPATH`` — what the audit's own integration test does.

Pure logic lives in :func:`offenders` so it is unit-testable without a
pytest session.
"""

from __future__ import annotations

import os
import sys

DEFAULT_BUDGET_S = 30.0
EXIT_OFFENDERS = 3

_records: list[tuple[str, float, bool]] = []


def budget_s() -> float:
    return float(os.environ.get("TPUDIST_MARKER_BUDGET_S", DEFAULT_BUDGET_S))


def offenders(records, budget: float) -> list[tuple[str, float]]:
    """``(nodeid, seconds)`` for every recorded test over ``budget`` that
    is NOT marked ``slow``, slowest first. Marked tests may take as long
    as they like — they are deselected from tier-1 by construction."""
    bad = [
        (nodeid, duration)
        for nodeid, duration, is_slow in records
        if duration > budget and not is_slow
    ]
    return sorted(bad, key=lambda r: -r[1])


# source substrings that mean "this module launches a subprocess world":
# the launcher module itself (python -m tpudist.launch), the emulated
# per-process device split only the launcher consumes, or a direct
# child-interpreter spawn that builds its own emulated device world via
# the raw XLA flag (the elastic drills relaunch children at a DIFFERENT
# device count this way, bypassing the launcher). Checked against the
# test FILE's source — a world is spawned from module-level harness
# strings as often as from the test body. TPUDIST_EMULATE_WORLD is the
# composition drills' env-indirect spelling
# (tests/test_parallel_plan_world.py hands the child its device count
# through the env and the child expands it to the XLA flag): the parent
# file may then never contain the raw flag string, and an unmarked
# multi-world drill would slip the audit.
WORLD_PATTERNS = (
    "tpudist.launch",
    "--emulate-devices",
    "xla_force_host_platform_device_count",
    "TPUDIST_EMULATE_WORLD",
)


def spawns_world(source: str) -> bool:
    return any(p in source for p in WORLD_PATTERNS)


def world_offenders(records) -> list[str]:
    """``nodeid`` for every collected test whose module spawns a
    subprocess world but which is NOT marked ``slow`` — flagged at
    collection, before the cost is ever paid. ``records`` rows are
    ``(nodeid, spawns_world, is_slow)``."""
    return [
        nodeid
        for nodeid, spawns, is_slow in records
        if spawns and not is_slow
    ]


# -- pytest plugin hooks ----------------------------------------------------

_world_records: list[tuple[str, bool, bool]] = []


def pytest_collection_modifyitems(config, items):
    # the world rule runs at collection: read each collected test FILE's
    # source once (cached per path) and flag unmarked tests in
    # world-spawning modules before they execute
    sources: dict[str, bool] = {}
    for item in items:
        path = str(item.fspath)
        if path not in sources:
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    sources[path] = spawns_world(f.read())
            except OSError:
                sources[path] = False
        _world_records.append((
            item.nodeid,
            sources[path],
            "slow" in item.keywords,
        ))


def pytest_runtest_logreport(report):
    if report.when != "call":
        return
    _records.append((
        report.nodeid,
        float(getattr(report, "duration", 0.0)),
        "slow" in getattr(report, "keywords", {}),
    ))


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    bad = offenders(_records, budget_s())
    worlds = world_offenders(_world_records)
    if not bad and not worlds:
        terminalreporter.write_line(
            f"marker audit: all {len(_records)} tests within the "
            f"{budget_s():.0f}s per-test budget or marked slow"
        )
        return
    if bad:
        terminalreporter.write_line(
            f"marker audit FAILED: {len(bad)} test(s) exceeded the "
            f"{budget_s():.0f}s per-test budget without the 'slow' marker "
            "(tier-1 window erosion — mark them slow or make them cheap):",
        )
        for nodeid, duration in bad:
            terminalreporter.write_line(f"  {duration:8.1f}s  {nodeid}")
    if worlds:
        terminalreporter.write_line(
            f"marker audit FAILED: {len(worlds)} test(s) spawn a "
            "subprocess world (tpudist.launch / --emulate-devices "
            "children cold-compile their own jax programs) without the "
            "'slow' marker:",
        )
        for nodeid in worlds:
            terminalreporter.write_line(f"  {nodeid}")


def pytest_sessionfinish(session, exitstatus):
    if offenders(_records, budget_s()) or world_offenders(_world_records):
        session.exitstatus = EXIT_OFFENDERS


# -- CLI --------------------------------------------------------------------

DEFAULT_ARGS = ["tests/", "-q", "-m", "not slow", "-p", "no:cacheprovider"]


def main(argv=None) -> int:
    import pytest

    # extra args APPEND to the tier-1 selection (they are pass-through
    # flags like -x or -k) — replacing it would silently audit a
    # different suite than the one the budget protects; a later -m from
    # the user still wins per pytest's last-one-wins flag handling
    args = DEFAULT_ARGS + list(sys.argv[1:] if argv is None else argv)
    return pytest.main(args, plugins=[sys.modules[__name__]])


if __name__ == "__main__":
    raise SystemExit(main())
