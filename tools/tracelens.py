"""tracelens: stitch per-rank telemetry JSONL streams into one Perfetto
timeline plus a latency report (docs/OBSERVABILITY.md §8).

The telemetry subsystem writes one JSONL stream per process, rotated into
numbered segments, each on its own clocks: span rows carry ``t0``/``dur_s``
on the emitter's *span clock* (``time.monotonic`` for train spans — the
heartbeat ``mono`` domain — and the ServeStats ``time.perf_counter`` clock
for serve spans), with the row's own wall ``t`` stamped at write time.
Wall clocks skew across hosts and span clocks have arbitrary epochs, so no
single stream is a timeline by itself. This tool is the offline other half
of the contract:

1. **discover** — expand the given files/directories into rotation chains
   (``X.jsonl.1``, ``.2``, …, base last — the sink's sealing order) and
   read each chain oldest→newest.
2. **align** — per (rank, generation), place the train span clock on the
   wall timeline via the heartbeat pairs (offset = median of ``t − mono``);
   serve spans self-anchor the same way (each row's ``t`` is written at
   span close, so offset = median of ``t − (t0 + dur_s)``). Medians, not
   means: a row written during a filesystem stall is late by seconds and
   must not drag the whole track.
3. **emit** — a Chrome trace-event file Perfetto/``chrome://tracing``
   loads directly: one process per rank, a ``steps`` thread for the train
   timeline, a ``scheduler`` thread for serve ticks/queue phases, and one
   thread per serve slot for request phase spans; instants (preempt,
   repair, probe, anomaly, reshard) ride their track as instant events.
4. **report** — top-K slowest requests with their exact phase
   decomposition (the terminal ``request`` span's telescoping fields),
   per-rank step-time stragglers, and the goodput partition when a
   ``{job}_report.json`` is present.

Usage::

    python tools/tracelens.py LOGDIR [more files/dirs ...]
        [--job JOB]          only streams of this job id
        [--run_id ID]        only rows of this run (multi-run directories)
        [--out trace.json]   Perfetto output path (default: trace.json)
        [--top K]            rows in the slowest-request table (default 10)
        [--no-report]        skip the text report

Stdlib only — the tool must run on a laptop holding nothing but the
downloaded log directory.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

# a rotated segment: `<base>.jsonl.<N>`; the base file is the live tail
_SEG_RE = re.compile(r"^(?P<base>.+\.jsonl)\.(?P<n>\d+)$")


# -- discovery ---------------------------------------------------------------

def discover(paths, job: str | None = None) -> dict[str, list[Path]]:
    """``{base stream name: ordered segment chain}`` — numbered segments
    ascending (rotation seals oldest-first), base file last. Directories
    expand to every ``*.jsonl*`` inside; ``job`` filters to streams whose
    filename starts with ``{job}_``."""
    files: list[Path] = []
    for p in map(Path, paths):
        if p.is_dir():
            files.extend(sorted(p.glob("*.jsonl*")))
        elif p.exists():
            files.append(p)
    chains: dict[str, list[tuple[int, Path]]] = {}
    for f in files:
        m = _SEG_RE.match(f.name)
        base, order = (m.group("base"), int(m.group("n"))) if m \
            else (f.name, sys.maxsize)  # the live tail sorts last
        if job and not base.startswith(f"{job}_"):
            continue
        chains.setdefault(str(f.parent / base), []).append((order, f))
    return {
        base: [f for _, f in sorted(segs)]
        for base, segs in sorted(chains.items())
    }


def read_chain(segments) -> list[dict]:
    rows = []
    for seg in segments:
        with open(seg, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # a torn tail line from a crashed writer
    return rows


# -- clock alignment ---------------------------------------------------------

def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2


def train_offsets(rows) -> dict[tuple[int, int], float]:
    """Wall offset of the monotonic clock per (rank, generation):
    heartbeats carry both stamps of the same instant (``t`` wall, ``mono``
    span clock), so ``t − mono`` is the offset; median over the stream
    rejects stall-skewed rows. Falls back to span-close anchors
    (``t − (t0 + dur_s)``) for a stream traced without heartbeats."""
    pairs: dict[tuple[int, int], list[float]] = {}
    fallback: dict[tuple[int, int], list[float]] = {}
    for r in rows:
        key = (int(r.get("rank", 0)), int(r.get("generation", 0)))
        if r.get("kind") == "heartbeat" and "mono" in r:
            pairs.setdefault(key, []).append(r["t"] - r["mono"])
        elif r.get("kind") == "span" and r.get("cat") == "train":
            fallback.setdefault(key, []).append(
                r["t"] - (r["t0"] + r["dur_s"])
            )
    out = {k: _median(v) for k, v in pairs.items()}
    for k, v in fallback.items():
        out.setdefault(k, _median(v))
    return out


def serve_offsets(rows) -> dict[int, float]:
    """Wall offset of the serve span clock per rank: every serve span row
    is written at span close, so ``t − (t0 + dur_s)`` is the offset plus
    only the write latency — the median strips the stalls."""
    anchors: dict[int, list[float]] = {}
    for r in rows:
        if r.get("kind") == "span" and r.get("cat") == "serve":
            anchors.setdefault(int(r.get("rank", 0)), []).append(
                r["t"] - (r["t0"] + r["dur_s"])
            )
    return {k: _median(v) for k, v in anchors.items()}


# -- Perfetto emission -------------------------------------------------------

# serve thread layout inside a rank's process: the scheduler track, then
# one track per slot (slot-less phases — queued, preempted, a preempt
# instant after its slot was surrendered — ride the scheduler track)
TID_TRAIN = 0
TID_SCHED = 1
TID_SLOT0 = 100


def _tid(row) -> int:
    if row.get("cat") != "serve":
        return TID_TRAIN
    slot = row.get("slot")
    if row.get("name") in ("tick", "queued", "preempted") or slot is None:
        return TID_SCHED
    return TID_SLOT0 + int(slot)


_ENVELOPE = ("v", "t", "kind", "rank", "step", "name", "cat", "ph",
             "t0", "dur_s")


def to_trace_events(rows) -> list[dict]:
    """Chrome trace-event list: ``X``/``i`` events in wall microseconds
    (rebased to the earliest span so timestamps start near zero), plus the
    process/thread naming metadata."""
    t_off = train_offsets(rows)
    s_off = serve_offsets(rows)
    spans = [r for r in rows if r.get("kind") == "span"]
    placed = []
    for r in spans:
        rank = int(r.get("rank", 0))
        if r.get("cat") == "serve":
            off = s_off.get(rank, 0.0)
        else:
            off = t_off.get((rank, int(r.get("generation", 0))), 0.0)
        placed.append((r["t0"] + off, r))
    if not placed:
        return []
    epoch = min(ts for ts, _ in placed)
    events = []
    seen_tracks: set[tuple[int, int]] = set()
    for ts, r in placed:
        rank = int(r.get("rank", 0))
        tid = _tid(r)
        seen_tracks.add((rank, tid))
        ev = {
            "name": r.get("name", "?"),
            "cat": r.get("cat", "train"),
            "ph": r.get("ph", "X"),
            "ts": round((ts - epoch) * 1e6, 3),
            "pid": rank,
            "tid": tid,
            "args": {
                k: v for k, v in r.items()
                if k not in _ENVELOPE and v is not None
            },
        }
        if ev["ph"] == "X":
            ev["dur"] = round(r.get("dur_s", 0.0) * 1e6, 3)
        else:
            ev["s"] = "t"  # thread-scoped instant
        if r.get("step") is not None:
            ev["args"]["step"] = r["step"]
        events.append(ev)
    for rank, tid in sorted(seen_tracks):
        if tid == TID_TRAIN:
            tname = "steps"
        elif tid == TID_SCHED:
            tname = "serve scheduler"
        else:
            tname = f"serve slot {tid - TID_SLOT0}"
        events.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
            "args": {"name": tname},
        })
    for rank in sorted({pid for pid, _ in seen_tracks}):
        events.append({
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"},
        })
    return events


# -- text report -------------------------------------------------------------

def request_table(rows, top: int = 10) -> list[dict]:
    """The terminal ``request`` spans, slowest first — each carries the
    exact telescoping decomposition the tracer computed at retire."""
    reqs = [
        r for r in rows
        if r.get("kind") == "span" and r.get("name") == "request"
    ]
    return sorted(reqs, key=lambda r: -r["dur_s"])[:top]


def straggler_table(rows) -> list[tuple]:
    """Per-(rank, generation) mean step-span duration against the fleet
    median — the offline twin of the live straggler rule."""
    per: dict[tuple[int, int], list[float]] = {}
    for r in rows:
        if r.get("kind") == "span" and r.get("name") == "step":
            per.setdefault(
                (int(r.get("rank", 0)), int(r.get("generation", 0))), []
            ).append(r["dur_s"])
    if not per:
        return []
    means = {k: sum(v) / len(v) for k, v in per.items()}
    med = _median(list(means.values()))
    return sorted(
        (
            (rank, gen, m, len(per[(rank, gen)]),
             m / med if med > 0 else 1.0)
            for (rank, gen), m in means.items()
        ),
        key=lambda t: -t[4],
    )


def goodput_section(paths, job: str | None) -> dict | None:
    """The goodput partition from a run report sitting next to the
    streams, when one exists (fit() writes ``{job}_report.json``)."""
    for p in map(Path, paths):
        d = p if p.is_dir() else p.parent
        pattern = f"{job}_report.json" if job else "*_report.json"
        for rp in sorted(d.glob(pattern)):
            try:
                report = json.loads(rp.read_text(encoding="utf-8"))
            except (OSError, json.JSONDecodeError):
                continue
            gp = report.get("goodput")
            if gp:
                return {"path": str(rp), **gp}
    return None


def _fmt_ms(x) -> str:
    return "n/a" if x is None else f"{x * 1e3:8.1f}"


def render_report(rows, paths, job, top=10, out=None) -> None:
    # resolve sys.stdout at call time, not def time — callers (and test
    # harnesses) that swap sys.stdout must see the report
    w = (sys.stdout if out is None else out).write
    spans = [r for r in rows if r.get("kind") == "span"]
    run_ids = sorted({r["run_id"] for r in rows if "run_id" in r})
    w(f"tracelens: {len(rows)} rows, {len(spans)} spans"
      + (f", run_id {', '.join(run_ids)}" if run_ids else "") + "\n")

    reqs = request_table(rows, top)
    if reqs:
        w(f"\nslowest {len(reqs)} request(s) (ms; total == queued + "
          "prefill + decode + preempted):\n")
        w("  rid      total   queued  prefill   decode  preempt  "
          "tok  pre  lane\n")
        for r in reqs:
            w(f"  {r.get('rid', '?'):>3}{_fmt_ms(r['dur_s'])}"
              f"{_fmt_ms(r.get('queued_s'))}{_fmt_ms(r.get('prefill_s'))}"
              f"{_fmt_ms(r.get('decode_s'))}{_fmt_ms(r.get('preempt_s'))}"
              f"  {r.get('tokens', 0):>3}  {r.get('preempts', 0):>3}"
              f"  {r.get('lane', 0):>4}\n")

    stragglers = straggler_table(rows)
    if stragglers:
        w("\nper-rank step time (vs fleet median):\n")
        for rank, gen, mean_s, n, frac in stragglers:
            flag = "  <-- straggler" if frac > 1.5 else ""
            w(f"  rank {rank} gen {gen}: mean {mean_s * 1e3:.1f} ms over "
              f"{n} step span(s), {frac:.2f}x median{flag}\n")

    gp = goodput_section(paths, job)
    if gp:
        path = gp.pop("path")
        w(f"\ngoodput partition ({path}):\n")
        for k, v in gp.items():
            w(f"  {k}: {v}\n")


# -- CLI ---------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch tpudist telemetry JSONL into a Perfetto "
        "trace.json + latency report (docs/OBSERVABILITY.md §8)"
    )
    ap.add_argument("paths", nargs="+",
                    help="JSONL files and/or log directories")
    ap.add_argument("--job", default=None,
                    help="only streams of this job id ({job}_*.jsonl)")
    ap.add_argument("--run_id", default=None,
                    help="only rows of this run_id — a log dir holding "
                    "several runs' rotated segments (append-mode relaunch, "
                    "shared dir) stitches ONE run instead of interleaving "
                    "them; run ids are listed in the report header")
    ap.add_argument("--out", default="trace.json",
                    help="Perfetto trace output path")
    ap.add_argument("--top", default=10, type=int,
                    help="rows in the slowest-request table")
    ap.add_argument("--no-report", action="store_true")
    args = ap.parse_args(argv)

    chains = discover(args.paths, args.job)
    if not chains:
        print("tracelens: no .jsonl streams found", file=sys.stderr)
        return 2
    rows = []
    for base, segments in chains.items():
        rows.extend(read_chain(segments))
    if args.run_id:
        # row-level, not file-level: rotation interleaves runs within one
        # segment chain when a job id is reused, so filenames can't split
        # them — the per-row run_id stamp can
        rows = [r for r in rows if r.get("run_id") == args.run_id]
        if not rows:
            print(f"tracelens: no rows with run_id {args.run_id}",
                  file=sys.stderr)
            return 2
    events = to_trace_events(rows)
    Path(args.out).write_text(
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms"}),
        encoding="utf-8",
    )
    print(f"tracelens: wrote {len(events)} events from "
          f"{len(chains)} stream(s) to {args.out}")
    if not args.no_report:
        render_report(rows, args.paths, args.job, args.top)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
