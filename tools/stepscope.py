"""stepscope: measured per-op attribution of device time in a profiler
capture (docs/PERF.md §4c, docs/OBSERVABILITY.md §9).

``WindowedProfiler`` (and any ``jax.profiler`` trace) writes a Chrome
trace-event file — ``<host>.trace.json.gz`` under
``{log_dir}/plugins/profile/<timestamp>/`` — next to the xplane protobuf.
The JSON side is parseable with nothing but the stdlib, and its XLA op
events (``ph == "X"`` with an ``hlo_op`` arg, or events on a device-named
process) carry exactly what the roofline arguments in docs/PERF.md reason
about by hand: which HLO ops the step's time actually went to. This tool
is the measured other half of ``tpudist/telemetry/anatomy.py``'s static
counts:

1. **bucket** — device-op time into GEMM / collective-comm /
   attention-custom-call / elementwise-other (HLO name + metadata
   heuristics; the last bucket is the explicit catch-all, so attribution
   is total by construction and the report prints the named share).
2. **bound** — classify each bucket compute- vs HBM-bound: GEMM/attention
   from the program's arithmetic intensity (an ``anatomy`` telemetry row's
   ``flops_scaled / bytes_accessed``, or ``--ai``) against the chip's
   ridge point (``--peak-flops / --hbm-gbps``); collectives are
   interconnect-bound and elementwise HBM-bound by construction.
3. **top-K** — the heaviest individual ops with bucket, time share, and
   call count.
4. **diff** — A/B mode (``--diff A B``): per-bucket and per-op deltas
   between two captures, largest regressions first — the measured form of
   "what got slower".

Usage::

    python tools/stepscope.py TRACE_DIR [--top K]
        [--anatomy FILE.jsonl]   arithmetic intensity from an anatomy row
        [--ai FLOPS_PER_BYTE]    ... or given directly
        [--peak-flops F] [--hbm-gbps G]   ridge point (default v5e bf16)
    python tools/stepscope.py --diff BEFORE_DIR AFTER_DIR [--top K]

Stdlib only — like tracelens, this must run on a laptop holding nothing
but the downloaded log directory.
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from pathlib import Path

# chip defaults for the ridge point: TPU v5e bf16 peak over HBM bandwidth
# (197 TFLOP/s / 819 GB/s ≈ 240 FLOPs/byte). Overridable per chip; the
# tool cannot import tpudist (stdlib-only), so the constant is restated
# here with its source.
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_HBM_GBPS = 819.0

BUCKETS = ("gemm", "collective-comm", "attention-custom-call",
           "elementwise-other")

_GEMM_PREFIXES = ("dot", "convolution", "cublas", "gemm")
_COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "send", "recv",
    "partition-id", "replica-id",
)
_ATTENTION_HINTS = ("attention", "flash", "mha", "pallas", "splash",
                    "paged_attention")
# host/infra lanes that appear on device-named processes in some backends
# but are runtime plumbing, not HLO work
_INFRA_NAMES = ("ThreadpoolListener", "ThunkExecutor", "TaskDispatcher",
                "ExecuteThunks", "Barrier")


# -- trace loading -----------------------------------------------------------

def find_trace_files(path) -> list[Path]:
    """Every Chrome-trace JSON under ``path`` (a file, a profile dir, or a
    log dir holding ``plugins/profile/<ts>/``), sorted for determinism."""
    p = Path(path)
    if p.is_file():
        return [p]
    found = set()
    for pat in ("*.trace.json.gz", "*.trace.json"):
        found.update(p.rglob(pat))
    return sorted(found)


def load_events(path) -> list[dict]:
    p = Path(path)
    opener = gzip.open if p.suffix == ".gz" else open
    with opener(p, "rt", encoding="utf-8") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in events if isinstance(e, dict)]


def _process_names(events) -> dict[int, str]:
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e.get("pid")] = str((e.get("args") or {}).get("name", ""))
    return names


def device_op_events(events) -> list[dict]:
    """The HLO-op execution events: complete (``X``) events carrying an
    ``hlo_op``/``hlo_module`` arg (XLA's own annotation — present on CPU
    and GPU device lanes), plus, for backends that drop the args, named
    events on a device-named process that aren't known runtime plumbing.
    Python-tracer and host-infra events never qualify."""
    pnames = _process_names(events)
    ops = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        args = e.get("args") or {}
        if "hlo_op" in args or "hlo_module" in args:
            ops.append(e)
            continue
        pname = pnames.get(e.get("pid"), "").lower()
        if ("device" in pname or "tpu" in pname or "gpu" in pname):
            name = str(e.get("name", ""))
            if name and not any(i in name for i in _INFRA_NAMES):
                ops.append(e)
    return ops


# -- bucketing ---------------------------------------------------------------

def op_base(name: str) -> str:
    """``dot.3`` → ``dot``; ``fusion.12.clone`` → ``fusion`` — the HLO
    opcode-ish base the bucket rules match on."""
    out = name.split(".")[0] if name else name
    return out.strip("%")


def classify(name: str, args: dict | None = None) -> str:
    """One of :data:`BUCKETS` for an op event. ``elementwise-other`` is
    the explicit catch-all (fusions, reduces, copies, converts) — every
    device op lands in a named bucket, by construction."""
    base = op_base(str(name)).lower()
    hlo = op_base(str((args or {}).get("hlo_op", ""))).lower()
    key = hlo or base
    blob = " ".join(
        str(v) for v in (name, hlo, (args or {}).get("long_name", ""),
                         (args or {}).get("tf_op", ""))
    ).lower()
    if any(key.startswith(p) for p in _COLLECTIVE_PREFIXES):
        return "collective-comm"
    if any(h in blob for h in _ATTENTION_HINTS):
        return "attention-custom-call"
    if any(key.startswith(p) for p in _GEMM_PREFIXES):
        return "gemm"
    return "elementwise-other"


def aggregate(op_events) -> dict:
    """Bucket + per-op totals: ``{"total_us", "buckets": {bucket:
    {"us", "count"}}, "ops": {op base name: {"us", "count", "bucket"}}}``.
    Durations are trace microseconds summed across device lanes."""
    buckets = {b: {"us": 0.0, "count": 0} for b in BUCKETS}
    ops: dict[str, dict] = {}
    total = 0.0
    for e in op_events:
        dur = float(e.get("dur", 0.0))
        args = e.get("args") or {}
        name = str(args.get("hlo_op") or e.get("name") or "?")
        bucket = classify(name, args)
        base = op_base(name)
        total += dur
        buckets[bucket]["us"] += dur
        buckets[bucket]["count"] += 1
        rec = ops.setdefault(base, {"us": 0.0, "count": 0, "bucket": bucket})
        rec["us"] += dur
        rec["count"] += 1
    return {"total_us": total, "buckets": buckets, "ops": ops}


def attributed_pct(summary) -> float:
    """Share of device time in the named buckets — 100.0 by construction
    of the catch-all; printed so the guarantee is visible, not assumed."""
    total = summary["total_us"]
    if total <= 0:
        return 0.0
    named = sum(b["us"] for b in summary["buckets"].values())
    return 100.0 * named / total


# -- boundedness -------------------------------------------------------------

def anatomy_intensity(path) -> float | None:
    """Arithmetic intensity (FLOPs/byte) from the first ``anatomy`` row in
    a telemetry JSONL — the program-level ``flops_scaled/bytes_accessed``
    the static analysis recorded at bring-up."""
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("kind") != "anatomy":
                    continue
                flops = row.get("flops_scaled") or row.get("flops")
                bytes_ = row.get("bytes_accessed")
                if flops and bytes_:
                    return float(flops) / float(bytes_)
    except OSError:
        return None
    return None


def boundedness(bucket: str, ai: float | None, ridge: float) -> str:
    """compute- vs HBM-bound per bucket: collectives are interconnect-
    bound and elementwise ops HBM-bound by construction (O(1) FLOPs/byte
    is far under any ridge); GEMM/attention compare the program's
    arithmetic intensity against the ridge point, or answer "unknown"
    when no intensity was given — never a guessed verdict."""
    if bucket == "collective-comm":
        return "interconnect-bound"
    if bucket == "elementwise-other":
        return "HBM-bound"
    if ai is None:
        return "unknown (pass --anatomy or --ai)"
    return "compute-bound" if ai >= ridge else "HBM-bound"


# -- reports -----------------------------------------------------------------

def render_report(summary, *, top=10, ai=None, ridge=None,
                  out=None) -> None:
    w = (sys.stdout if out is None else out).write
    total = summary["total_us"]
    w(f"stepscope: {total / 1e3:.3f} ms device-op time, "
      f"{sum(b['count'] for b in summary['buckets'].values())} op "
      f"executions, {attributed_pct(summary):.1f}% attributed to named "
      "buckets\n")
    if ai is not None and ridge is not None:
        w(f"arithmetic intensity {ai:.1f} FLOPs/byte vs ridge "
          f"{ridge:.1f} — program is "
          f"{'compute' if ai >= ridge else 'HBM'}-bound overall\n")
    w("\nbucket                      time(ms)   share    ops   verdict\n")
    for name in BUCKETS:
        b = summary["buckets"][name]
        share = 100.0 * b["us"] / total if total > 0 else 0.0
        w(f"{name:<26}{b['us'] / 1e3:>10.3f}{share:>7.1f}%"
          f"{b['count']:>7}   "
          f"{boundedness(name, ai, ridge or float('inf'))}\n")
    w(f"\ntop {top} ops by device time:\n")
    ranked = sorted(summary["ops"].items(), key=lambda kv: -kv[1]["us"])
    for name, rec in ranked[:top]:
        share = 100.0 * rec["us"] / total if total > 0 else 0.0
        w(f"  {name:<32}{rec['us'] / 1e3:>10.3f} ms{share:>7.1f}%"
          f"  x{rec['count']:<5} {rec['bucket']}\n")


def render_diff(before, after, *, top=10, out=None) -> None:
    """Per-bucket and per-op deltas, regressions (time grew) first — the
    A/B answer to "what got slower between these two captures"."""
    w = (sys.stdout if out is None else out).write
    tb, ta = before["total_us"], after["total_us"]
    dt = ta - tb
    pct = 100.0 * dt / tb if tb > 0 else 0.0
    w(f"stepscope diff: device-op time {tb / 1e3:.3f} -> {ta / 1e3:.3f} ms "
      f"({dt / 1e3:+.3f} ms, {pct:+.1f}%)\n")
    w("\nbucket                      before(ms)  after(ms)   delta(ms)\n")
    for name in BUCKETS:
        b = before["buckets"][name]["us"]
        a = after["buckets"][name]["us"]
        w(f"{name:<26}{b / 1e3:>11.3f}{a / 1e3:>11.3f}"
          f"{(a - b) / 1e3:>+12.3f}\n")
    deltas = []
    for name in set(before["ops"]) | set(after["ops"]):
        b = before["ops"].get(name, {}).get("us", 0.0)
        a = after["ops"].get(name, {}).get("us", 0.0)
        bucket = (after["ops"].get(name) or before["ops"].get(name))["bucket"]
        deltas.append((a - b, name, b, a, bucket))
    deltas.sort(key=lambda t: -t[0])
    w(f"\ntop {top} op deltas (regressions first):\n")
    for d, name, b, a, bucket in deltas[:top]:
        w(f"  {name:<32}{b / 1e3:>9.3f} -> {a / 1e3:>9.3f} ms "
          f"({d / 1e3:+.3f})  {bucket}\n")


def summarize(path) -> dict | None:
    """Load + aggregate every trace file under ``path``; ``None`` (with a
    stderr note) when nothing parseable is there."""
    files = find_trace_files(path)
    if not files:
        print(f"stepscope: no .trace.json[.gz] under {path}",
              file=sys.stderr)
        return None
    ops = []
    for f in files:
        try:
            ops.extend(device_op_events(load_events(f)))
        except (OSError, json.JSONDecodeError, EOFError) as exc:
            print(f"stepscope: skipping unreadable {f}: {exc}",
                  file=sys.stderr)
    if not ops:
        print(f"stepscope: no device-op events in {len(files)} trace "
              f"file(s) under {path}", file=sys.stderr)
        return None
    return aggregate(ops)


# -- CLI ---------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bucket device-op time in a jax profiler capture "
        "(GEMM / collective / attention / elementwise) with compute- vs "
        "HBM-bound verdicts (docs/PERF.md §4c)"
    )
    ap.add_argument("paths", nargs="+",
                    help="trace file / profile dir / log dir "
                    "(two dirs with --diff)")
    ap.add_argument("--diff", action="store_true",
                    help="A/B mode: compare exactly two captures")
    ap.add_argument("--top", default=10, type=int,
                    help="rows in the per-op tables")
    ap.add_argument("--anatomy", default=None,
                    help="telemetry JSONL holding an `anatomy` row — the "
                    "program's FLOPs/bytes set the arithmetic intensity")
    ap.add_argument("--ai", default=None, type=float,
                    help="arithmetic intensity (FLOPs/byte) directly")
    ap.add_argument("--peak-flops", default=DEFAULT_PEAK_FLOPS, type=float,
                    help="chip peak FLOP/s for the ridge point")
    ap.add_argument("--hbm-gbps", default=DEFAULT_HBM_GBPS, type=float,
                    help="chip HBM bandwidth (GB/s) for the ridge point")
    args = ap.parse_args(argv)

    if args.diff:
        if len(args.paths) != 2:
            print("stepscope: --diff needs exactly two capture paths",
                  file=sys.stderr)
            return 2
        before = summarize(args.paths[0])
        after = summarize(args.paths[1])
        if before is None or after is None:
            return 2
        render_diff(before, after, top=args.top)
        return 0

    ai = args.ai
    if ai is None and args.anatomy:
        ai = anatomy_intensity(args.anatomy)
        if ai is None:
            print(f"stepscope: no usable anatomy row in {args.anatomy}",
                  file=sys.stderr)
    ridge = args.peak_flops / (args.hbm_gbps * 1e9)
    rc = 0
    for path in args.paths:
        summary = summarize(path)
        if summary is None:
            rc = 2
            continue
        if len(args.paths) > 1:
            print(f"== {path}")
        render_report(summary, top=args.top, ai=ai, ridge=ridge)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
