"""Pipeline parallelism: GPipe schedule ≡ sequential layer stack.

The correctness contract: running stacked blocks through the pipelined
shard_map schedule (tpudist.parallel.pp) must produce the same outputs and
gradients as a plain sequential lax.scan over the layers — the pipeline is
an execution schedule, not a numerical change. Mirrors the DP-equivalence
strategy of SURVEY.md §4 on the 8-fake-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.parallel.pp import pipeline_apply, stacked_param_shardings


def _mlp_block(p, h):
    # simple residual block: h + gelu(h @ w1) @ w2
    return h + jax.nn.gelu(h @ p["w1"]) @ p["w2"]


def _stacked_mlp_params(rng, layers, d, hidden):
    k1, k2 = jax.random.split(rng)
    scale = 1.0 / np.sqrt(d)
    return {
        "w1": jax.random.normal(k1, (layers, d, hidden)) * scale,
        "w2": jax.random.normal(k2, (layers, hidden, d)) * scale,
    }


def _sequential(params, x):
    def layer(h, p):
        return _mlp_block(p, h), None

    out, _ = jax.lax.scan(layer, x, params)
    return out


@pytest.mark.parametrize("pipe,num_micro", [(2, 4), (4, 8)])
def test_pipeline_forward_matches_sequential(pipe, num_micro):
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=8 // pipe, pipe=pipe)
    )
    layers, d, hidden = 8, 16, 32
    params = _stacked_mlp_params(jax.random.key(0), layers, d, hidden)
    x = jax.random.normal(jax.random.key(1), (16, 4, d))

    got = jax.jit(
        lambda p, x: pipeline_apply(_mlp_block, p, x, mesh, num_micro=num_micro)
    )(params, x)
    want = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential():
    pipe, num_micro = 4, 4
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=pipe))
    layers, d, hidden = 4, 8, 16
    params = _stacked_mlp_params(jax.random.key(2), layers, d, hidden)
    x = jax.random.normal(jax.random.key(3), (8, 2, d))
    y = jax.random.normal(jax.random.key(4), (8, 2, d))

    def loss_pp(p):
        return jnp.mean((pipeline_apply(_mlp_block, p, x, mesh, num_micro=num_micro) - y) ** 2)

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.grad(loss_seq)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
        ),
        g_pp, g_seq,
    )


def test_pipeline_params_actually_sharded():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    params = _stacked_mlp_params(jax.random.key(0), 8, 8, 16)
    placed = jax.device_put(params, stacked_param_shardings(params, mesh))
    # each stage holds 2 of the 8 layers: local shard = layers/pipe on dim 0
    shard = placed["w1"].addressable_shards[0]
    assert shard.data.shape == (2, 8, 16)


def test_pipelined_gpt2_train_step():
    """Full compiled train step on PipelinedGPT2 over a data×pipe mesh:
    pipe-sharded stacked blocks + Adam moments, loss finite and decreasing."""
    from tpudist.models.gpt2 import PipelinedGPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    model = PipelinedGPT2(
        mesh, num_micro=2, vocab_size=64, max_seq_len=16,
        hidden_dim=32, depth=4, num_heads=4,
    )
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((2, 16), jnp.int32), tx, mesh)
    # stacked blocks (and their Adam mirrors) must be pipe-sharded
    spec = state.params["blocks"]["qkv"]["kernel"].sharding.spec
    assert spec[0] == mesh_lib.PIPELINE_AXIS

    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    rng = np.random.Generator(np.random.PCG64(0))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pipelined_matches_plain_gpt2_shapes():
    from tpudist.models.gpt2 import PipelinedGPT2

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    model = PipelinedGPT2(
        mesh, num_micro=2, vocab_size=64, max_seq_len=16,
        hidden_dim=32, depth=4, num_heads=4,
    )
    tokens = jnp.zeros((4, 16), jnp.int32)
    variables = jax.jit(model.init)(jax.random.key(0), tokens)
    from flax import linen as nn

    logits = model.apply(nn.meta.unbox(variables), tokens)
    assert logits.shape == (4, 16, 64)
