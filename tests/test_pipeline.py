"""Pipeline parallelism: GPipe schedule ≡ sequential layer stack.

The correctness contract: running stacked blocks through the pipelined
shard_map schedule (tpudist.parallel.pp) must produce the same outputs and
gradients as a plain sequential lax.scan over the layers — the pipeline is
an execution schedule, not a numerical change. Mirrors the DP-equivalence
strategy of SURVEY.md §4 on the 8-fake-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.parallel.pp import pipeline_apply, stacked_param_shardings

_OLD_JAX_PARTIAL_MANUAL = pytest.mark.skipif(
    tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax 0.4.x XLA cannot SPMD-partition the partial-manual "
    "shard_map composition (PartitionId UNIMPLEMENTED) when the auto "
    "axes are real (>1); green on current jax — the PPxTP agreement "
    "certificate in MULTICHIP_r05.json covers the hardware contract",
)



def _mlp_block(p, h):
    # simple residual block: h + gelu(h @ w1) @ w2
    return h + jax.nn.gelu(h @ p["w1"]) @ p["w2"]


def _stacked_mlp_params(rng, layers, d, hidden):
    k1, k2 = jax.random.split(rng)
    scale = 1.0 / np.sqrt(d)
    return {
        "w1": jax.random.normal(k1, (layers, d, hidden)) * scale,
        "w2": jax.random.normal(k2, (layers, hidden, d)) * scale,
    }


def _sequential(params, x):
    def layer(h, p):
        return _mlp_block(p, h), None

    out, _ = jax.lax.scan(layer, x, params)
    return out


@pytest.mark.parametrize("pipe,num_micro", [(2, 4), (4, 8)])
@_OLD_JAX_PARTIAL_MANUAL
def test_pipeline_forward_matches_sequential(pipe, num_micro):
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=8 // pipe, pipe=pipe)
    )
    layers, d, hidden = 8, 16, 32
    params = _stacked_mlp_params(jax.random.key(0), layers, d, hidden)
    x = jax.random.normal(jax.random.key(1), (16, 4, d))

    got = jax.jit(
        lambda p, x: pipeline_apply(_mlp_block, p, x, mesh, num_micro=num_micro)
    )(params, x)
    want = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@_OLD_JAX_PARTIAL_MANUAL
def test_pipeline_grads_match_sequential():
    pipe, num_micro = 4, 4
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=pipe))
    layers, d, hidden = 4, 8, 16
    params = _stacked_mlp_params(jax.random.key(2), layers, d, hidden)
    x = jax.random.normal(jax.random.key(3), (8, 2, d))
    y = jax.random.normal(jax.random.key(4), (8, 2, d))

    def loss_pp(p):
        return jnp.mean((pipeline_apply(_mlp_block, p, x, mesh, num_micro=num_micro) - y) ** 2)

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.grad(loss_seq)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
        ),
        g_pp, g_seq,
    )


def test_pipeline_params_actually_sharded():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    params = _stacked_mlp_params(jax.random.key(0), 8, 8, 16)
    placed = jax.device_put(params, stacked_param_shardings(params, mesh))
    # each stage holds 2 of the 8 layers: local shard = layers/pipe on dim 0
    shard = placed["w1"].addressable_shards[0]
    assert shard.data.shape == (2, 8, 16)


@_OLD_JAX_PARTIAL_MANUAL
def test_pipelined_gpt2_train_step():
    """Full compiled train step on PipelinedGPT2 over a data×pipe mesh:
    pipe-sharded stacked blocks + Adam moments, loss finite and decreasing."""
    from tpudist.models.gpt2 import PipelinedGPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    model = PipelinedGPT2(
        mesh, num_micro=2, vocab_size=64, max_seq_len=16,
        hidden_dim=32, depth=4, num_heads=4,
    )
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((2, 16), jnp.int32), tx, mesh)
    # stacked blocks (and their Adam mirrors) must be pipe-sharded
    spec = state.params["blocks"]["qkv"]["kernel"].sharding.spec
    assert spec[0] == mesh_lib.PIPELINE_AXIS

    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    rng = np.random.Generator(np.random.PCG64(0))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


_GPT2_CFG = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=4,
                 num_heads=4)


@_OLD_JAX_PARTIAL_MANUAL
def test_pipelined_gpt2_matches_plain_numerically():
    """PipelinedGPT2 computes the IDENTICAL function as same-seed plain
    GPT2: init-by-conversion (stack_gpt2_params) re-layouts the same param
    leaves, and the GPipe schedule is an execution order, not a numerical
    change — so logits and loss must agree to float tolerance."""
    from flax import linen as nn

    from tpudist.models.gpt2 import GPT2, PipelinedGPT2
    from tpudist.train import lm_loss

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    plain = GPT2(**_GPT2_CFG)
    piped = PipelinedGPT2(mesh, num_micro=4, **_GPT2_CFG)
    rng = np.random.Generator(np.random.PCG64(7))
    tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)

    v_plain = nn.meta.unbox(plain.init(jax.random.key(0), tokens))
    v_piped = nn.meta.unbox(piped.init(jax.random.key(0), tokens))
    logits_plain = plain.apply(v_plain, tokens, train=False)
    logits_piped = jax.jit(
        lambda v, t: piped.apply(v, t, train=False)
    )(v_piped, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_piped), np.asarray(logits_plain),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_allclose(
        float(lm_loss(logits_piped, tokens)),
        float(lm_loss(logits_plain, tokens)), rtol=1e-5,
    )


@_OLD_JAX_PARTIAL_MANUAL
def test_pipelined_train_step_agrees_with_dp():
    """Same-seed PP and DP train steps report the same loss — the local
    mirror of the dryrun's PP agreement leg."""
    from tpudist.models.gpt2 import GPT2, PipelinedGPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(3))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}

    def first_loss(mesh, model):
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        _, metrics = step(state, batch)
        return float(metrics["loss"])

    loss_dp = first_loss(mesh_lib.create_mesh(), GPT2(**_GPT2_CFG))
    mesh_pp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    loss_pp = first_loss(
        mesh_pp, PipelinedGPT2(mesh_pp, num_micro=4, **_GPT2_CFG)
    )
    assert abs(loss_pp - loss_dp) / abs(loss_dp) < 2e-5


def test_1f1b_matches_gpipe_and_unrolled():
    """The 2-stage schedule triple the composition grid pins: 1F1B,
    GPipe, and the plain unrolled stack must agree on outputs AND
    gradients — a schedule is an execution order, not a numerical change.
    Runs on a pipe-only 2-device mesh (auto axes trivial), so it holds on
    old jax too."""
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=1, pipe=2), devices=jax.devices()[:2]
    )
    layers, d, hidden, num_micro = 4, 8, 16, 4
    params = _stacked_mlp_params(jax.random.key(5), layers, d, hidden)
    x = jax.random.normal(jax.random.key(6), (8, 2, d))
    y = jax.random.normal(jax.random.key(7), (8, 2, d))

    def loss(schedule):
        def f(p):
            out = pipeline_apply(
                _mlp_block, p, x, mesh, num_micro=num_micro,
                schedule=schedule,
            )
            return jnp.mean((out - y) ** 2)

        return f

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    l_ref, g_ref = jax.value_and_grad(loss_seq)(params)
    for schedule in ("gpipe", "1f1b"):
        l, g = jax.jit(jax.value_and_grad(loss(schedule)))(params)
        np.testing.assert_allclose(float(l), float(l_ref), rtol=2e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            ),
            g, g_ref,
        )


def test_pipelined_gpt2_1f1b_full_train_step():
    """PipelinedGPT2(schedule='1f1b') through the ordinary compiled train
    step: same-seed first loss identical to the GPipe schedule (the
    custom_vjp backward is exact), and training decreases the loss."""
    from tpudist.models.gpt2 import PipelinedGPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=1, pipe=2), devices=jax.devices()[:2]
    )
    rng = np.random.Generator(np.random.PCG64(9))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}

    def run(schedule, n_steps):
        model = PipelinedGPT2(
            mesh, num_micro=4, schedule=schedule, **_GPT2_CFG
        )
        tx = optax.adam(1e-2)
        state = create_train_state(
            model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        losses = []
        for _ in range(n_steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    l_1f1b = run("1f1b", 4)
    l_gpipe = run("gpipe", 1)
    assert abs(l_1f1b[0] - l_gpipe[0]) / abs(l_gpipe[0]) < 2e-5
    assert np.isfinite(l_1f1b).all() and l_1f1b[-1] < l_1f1b[0]


def test_pipeline_rejects_unknown_schedule():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, pipe=4))
    params = _stacked_mlp_params(jax.random.key(0), 8, 8, 16)
    x = jax.random.normal(jax.random.key(1), (8, 2, 8))
    with pytest.raises(ValueError, match="schedule"):
        pipeline_apply(
            _mlp_block, params, x, mesh, num_micro=4, schedule="2f2b"
        )


@_OLD_JAX_PARTIAL_MANUAL
def test_pipelined_gpt2_with_tensor_parallel_stages():
    """PP x TP: the pipe-manual shard_map leaves 'tensor' under GSPMD, so
    Megatron-sharded stage params must still compute the plain model's
    function (parallel/pp.py's composition claim, made real)."""
    from flax import linen as nn

    from tpudist.models.gpt2 import GPT2, PipelinedGPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=2, pipe=2, tensor=2)
    )
    model = PipelinedGPT2(mesh, num_micro=4, **_GPT2_CFG)
    tx = optax.adam(1e-3)
    state = create_train_state(
        model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh
    )
    # stage params must be BOTH pipe-sharded (layer dim) and tensor-sharded
    # (Megatron dims): qkv kernel [depth, d, 3, heads, dh] -> ('pipe', ...,
    # 'tensor', ...)
    spec = state.params["blocks"]["qkv"]["kernel"].sharding.spec
    assert spec[0] == mesh_lib.PIPELINE_AXIS
    assert mesh_lib.TENSOR_AXIS in tuple(spec)

    rng = np.random.Generator(np.random.PCG64(3))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    _, metrics = step(state, batch)
    loss_pptp = float(metrics["loss"])

    # DP reference: same seed, same batch, plain model on the pure-DP mesh
    plain = GPT2(**_GPT2_CFG)
    v_plain = nn.meta.unbox(plain.init(jax.random.key(0), batch["tokens"]))
    loss_ref = float(
        lm_loss(plain.apply(v_plain, batch["tokens"], train=False),
                batch["tokens"])
    )
    assert abs(loss_pptp - loss_ref) / abs(loss_ref) < 2e-5
