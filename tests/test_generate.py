"""KV-cache decode and generation: incremental decode must reproduce the
full (non-cached) forward exactly, for both decoder families — this pins
the cache masking, GPT-2's position-cursor, Llama's rotate-before-cache
RoPE, and GQA cache layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.generate import generate, sample_logits
from tpudist.models.gpt2 import GPT2
from tpudist.models.llama import Llama


def _tokens(b=2, s=12, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return rng.integers(0, vocab, (b, s)).astype(np.int32)


@pytest.mark.parametrize(
    "model",
    [
        GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2, num_heads=4),
        Llama(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4, num_kv_heads=2, ffn_dim=64),
        # attn_impl != "xla" routes decode through the FUSED Pallas kernel
        # (tpudist.ops.decode.decode_attention) — same contract, one launch
        GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
             num_heads=4, attn_impl="vmem"),
        Llama(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4, num_kv_heads=2, ffn_dim=64, attn_impl="vmem"),
    ],
    ids=["gpt2", "llama-gqa", "gpt2-fused", "llama-gqa-fused"],
)
def test_incremental_decode_matches_full_forward(model):
    tokens = _tokens()
    variables = model.init(jax.random.key(0), tokens, train=False)
    params = variables["params"]
    full = np.asarray(model.apply({"params": params}, jnp.asarray(tokens),
                                  train=False))

    cache = model.init(
        jax.random.key(0), jnp.zeros((2, 1), jnp.int32),
        train=False, decode=True,
    )["cache"]
    step_logits = []
    for t in range(tokens.shape[1]):
        logits, upd = model.apply(
            {"params": params, "cache": cache}, tokens[:, t : t + 1],
            train=False, decode=True, mutable=["cache"],
        )
        cache = upd["cache"]
        step_logits.append(np.asarray(logits[:, 0]))
    incremental = np.stack(step_logits, axis=1)
    np.testing.assert_allclose(incremental, full, atol=2e-4, rtol=2e-4)


def test_generate_greedy_is_deterministic_and_consistent():
    """Greedy generation equals repeatedly argmaxing the full forward."""
    model = GPT2(vocab_size=64, max_seq_len=24, hidden_dim=32, depth=1,
                 num_heads=4)
    prompt = _tokens(b=2, s=4, seed=1)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]

    out1 = generate(model, params, prompt, 6, temperature=0.0)
    out2 = generate(model, params, prompt, 6, temperature=0.0)
    np.testing.assert_array_equal(out1, out2)
    assert out1.shape == (2, 6) and out1.dtype == np.int32

    # oracle: greedy via repeated full forward (no cache)
    seq = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, jnp.asarray(seq), train=False)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
        seq = np.concatenate([seq, nxt.astype(np.int32)], axis=1)
    np.testing.assert_array_equal(out1, seq[:, 4:])


def test_generate_llama_runs_and_respects_cache_bound():
    model = Llama(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=1,
                  num_heads=4, num_kv_heads=2, ffn_dim=64)
    prompt = _tokens(b=1, s=4, seed=2)
    params = model.init(jax.random.key(2), prompt, train=False)["params"]
    out = generate(model, params, prompt, 8, temperature=0.7, top_k=10, seed=3)
    assert out.shape == (1, 8)
    assert (out >= 0).all() and (out < 64).all()
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, 13)


def test_gpt2_direct_decode_overrun_fails_loudly():
    """Direct incremental decode past max_seq_len (generate() guards its
    own entry; a direct model.apply caller used to get a silently-clamped
    wpe slice and a clobbered cache slot): eager callers get a
    ValueError, jitted loops get NaN logits for the overrunning step."""
    model = GPT2(vocab_size=64, max_seq_len=4, hidden_dim=32, depth=1,
                 num_heads=4)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 1), jnp.int32), train=False
    )["params"]
    cache = model.init(
        jax.random.key(0), jnp.zeros((1, 1), jnp.int32), train=False,
        decode=True,
    )["cache"]
    tok = jnp.ones((1, 1), jnp.int32)

    def step(cache):
        logits, upd = model.apply(
            {"params": params, "cache": cache}, tok,
            train=False, decode=True, mutable=["cache"],
        )
        return logits, upd["cache"]

    for _ in range(4):
        logits, cache = step(cache)
        assert np.isfinite(np.asarray(logits)).all()
    with pytest.raises(ValueError, match="max_seq_len"):
        step(cache)

    jit_step = jax.jit(step)
    cache = model.init(
        jax.random.key(0), jnp.zeros((1, 1), jnp.int32), train=False,
        decode=True,
    )["cache"]
    for i in range(5):
        logits, cache = jit_step(cache)
        assert np.isfinite(np.asarray(logits)).all() == (i < 4), i


def test_sample_logits_modes():
    logits = jnp.asarray([[0.0, 5.0, 1.0, -2.0]] * 3)
    greedy = sample_logits(logits, jax.random.key(0), temperature=0.0)
    np.testing.assert_array_equal(greedy, [1, 1, 1])
    # top_k=1 forces the argmax even at high temperature
    top1 = sample_logits(logits, jax.random.key(1), temperature=2.0, top_k=1)
    np.testing.assert_array_equal(top1, [1, 1, 1])
    # top_k=2 only ever yields the top-2 ids
    draws = [
        int(t)
        for i in range(20)
        for t in sample_logits(
            logits[:1], jax.random.key(i), temperature=5.0, top_k=2
        )
    ]
    assert set(draws) <= {1, 2}
    # top_k beyond the vocab clamps (HF/torch behavior) instead of crashing
    wide = sample_logits(logits, jax.random.key(3), temperature=1.0, top_k=999)
    assert wide.shape == (3,)


def test_sample_logits_top_p_nucleus():
    # probs [0.5, 0.3, 0.15, 0.05] (descending by construction): top_p=0.7
    # keeps the smallest prefix covering >= 0.7 → tokens {0, 1}
    probs = np.array([0.5, 0.3, 0.15, 0.05])
    logits = jnp.asarray(np.log(probs))[None, :]
    draws = {
        int(sample_logits(logits, jax.random.key(i), top_p=0.7)[0])
        for i in range(40)
    }
    assert draws <= {0, 1} and len(draws) == 2
    # a tiny p still keeps the single most likely token (never empties)
    only_top = {
        int(sample_logits(logits, jax.random.key(i), top_p=1e-6)[0])
        for i in range(10)
    }
    assert only_top == {0}
    # top_p=0.0 (the degenerate edge: exclusive-cum < 0 keeps NOTHING
    # without the guard — threshold +inf, categorical over all -inf) must
    # still return the most likely token, per the docstring's guarantee
    # (HF's min_tokens_to_keep=1); and identically through the top_k
    # composition, whose nucleus runs over the top-k subset
    zero_p = {
        int(sample_logits(logits, jax.random.key(i), top_p=0.0)[0])
        for i in range(10)
    }
    assert zero_p == {0}
    zero_p_k = {
        int(sample_logits(logits, jax.random.key(i), top_k=3, top_p=0.0)[0])
        for i in range(10)
    }
    assert zero_p_k == {0}
    # p=1.0 is a no-op: every token reachable at high temperature
    all_tok = {
        int(sample_logits(logits, jax.random.key(i), temperature=5.0,
                          top_p=1.0)[0])
        for i in range(200)
    }
    assert all_tok == {0, 1, 2, 3}
    # composes with top_k (HF order): k=3 renormalizes to
    # [0.526, 0.316, 0.158], so p=0.8 keeps the first two (exclusive
    # cumulative 0.842 >= 0.8 drops token 2)
    combo = {
        int(sample_logits(logits, jax.random.key(i), top_k=3, top_p=0.8)[0])
        for i in range(40)
    }
    assert combo <= {0, 1}


@pytest.mark.parametrize(
    "b,s,h,hkv,dh,pos",
    [(2, 64, 4, 4, 16, 10), (2, 64, 4, 2, 16, 0), (3, 128, 6, 1, 32, 127)],
)
def test_fused_decode_attention_matches_oracle(b, s, h, hkv, dh, pos):
    """The one-launch decode kernel ≡ masked dense attention, including
    GQA head grouping and the pos=0 single-valid-slot edge. K/V arrive in
    the cache's head-major [B, H_kv, S, dh] layout (cached_kv's contract)."""
    from tpudist.ops.attention import dot_product_attention, repeat_kv
    from tpudist.ops.decode import _fused_decode_attention, decode_attention

    rng = np.random.Generator(np.random.PCG64(7))
    q = jnp.asarray(rng.standard_normal((b, 1, h, dh)), jnp.float32)
    keys = jnp.asarray(rng.standard_normal((b, hkv, s, dh)), jnp.float32)
    values = jnp.asarray(rng.standard_normal((b, hkv, s, dh)), jnp.float32)
    out = _fused_decode_attention(q, keys, values, jnp.int32(pos))
    mask = jnp.arange(s)[None, None, None, :] <= pos
    # oracle in the models' seq-major activation layout
    kr, vr = repeat_kv(q, keys.transpose(0, 2, 1, 3),
                       values.transpose(0, 2, 1, 3))
    ref = dot_product_attention(q, kr, vr, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # and the dispatcher's own dense path agrees too (impl="xla")
    dense = decode_attention(q, keys, values, mask, jnp.int32(pos), impl="xla")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ref), atol=1e-5)


def test_sampler_topk_topp_threshold_equals_full_sort():
    """The composed top_k+top_p filter computes its nucleus threshold from
    the top-k values alone (no [B, V] sort per token). Checked BOTH ways
    against the full-sort reference formulation on tie-free float logits
    (exact k-th-value ties legitimately differ — the subset sampler keeps
    exactly k ids, the threshold form keeps every tied id):
    no over-keeping (every sampled id is reference-kept) and no
    over-filtering (every reference-kept id with non-trivial mass is
    eventually sampled)."""
    rng = np.random.Generator(np.random.PCG64(3))
    logits = jnp.asarray(rng.standard_normal((5, 512)) * 3, jnp.float32)
    top_k, top_p = 50, 0.9

    # full-sort reference (the pre-optimization formulation)
    kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
    filt = jnp.where(logits < kth, -jnp.inf, logits)
    sorted_desc = jnp.flip(jnp.sort(filt, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    excl = jnp.cumsum(probs, axis=-1) - probs
    thresh = jnp.min(
        jnp.where(excl < top_p, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    ref_kept = np.asarray(filt >= thresh)
    ref_probs = np.asarray(jax.nn.softmax(
        jnp.where(jnp.asarray(ref_kept), logits, -jnp.inf), axis=-1
    ))

    seen = set()
    for i in range(300):
        tok = sample_logits(
            logits, jax.random.key(i), temperature=1.0, top_k=top_k,
            top_p=top_p,
        )
        seen.update((r, int(t)) for r, t in enumerate(np.asarray(tok)))
    # direction 1 — no over-keeping: nothing outside the reference set
    for r, t in seen:
        assert ref_kept[r, t], (r, t)
    # direction 2 — no over-filtering: every reference-kept id carrying
    # >= 5% mass must show up in 300 draws (P(miss) <= 0.95^300 ≈ 2e-7
    # per id; an over-filtering bug — e.g. nucleus `<=` for `<`, or a
    # too-small subset — makes its dropped ids NEVER appear)
    for r in range(ref_kept.shape[0]):
        for t in np.nonzero(ref_kept[r] & (ref_probs[r] >= 0.05))[0]:
            assert (r, int(t)) in seen, (r, int(t), ref_probs[r, t])


def test_generate_eos_pads_the_tail():
    """With eos_id set, each row emits pad_id after its first EOS and the
    pre-EOS prefix is unchanged from the unconstrained run (greedy —
    deterministic, so the two runs are comparable token-for-token)."""
    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=1,
                 num_heads=4)
    prompt = _tokens(b=3, s=4, seed=21)
    params = model.init(jax.random.key(21), prompt, train=False)["params"]
    free = generate(model, params, prompt, 10, temperature=0.0)
    # pick an eos id that actually occurs mid-sequence in some row
    eos = int(free[0, 4])
    out = generate(model, params, prompt, 10, temperature=0.0, eos_id=eos,
                   pad_id=63)
    for r in range(free.shape[0]):
        hits = np.nonzero(free[r] == eos)[0]
        if hits.size == 0:
            np.testing.assert_array_equal(out[r], free[r])
        else:
            cut = hits[0]
            np.testing.assert_array_equal(out[r, :cut + 1], free[r, :cut + 1])
            assert (out[r, cut + 1:] == 63).all()


def test_generate_return_lengths():
    """return_lengths: each row's length is its first-EOS index + 1 (the
    EOS token counts), or max_new_tokens when it never stopped — the same
    per-row retirement rule the serving engine applies (eos_retire)."""
    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=1,
                 num_heads=4)
    prompt = _tokens(b=3, s=4, seed=31)
    params = model.init(jax.random.key(31), prompt, train=False)["params"]
    free = generate(model, params, prompt, 10, temperature=0.0)
    eos = int(free[0, 4])
    out, lengths = generate(model, params, prompt, 10, temperature=0.0,
                            eos_id=eos, pad_id=63, return_lengths=True)
    assert lengths.shape == (3,) and lengths.dtype == np.int32
    for r in range(3):
        hits = np.nonzero(free[r] == eos)[0]
        want = hits[0] + 1 if hits.size else 10
        assert lengths[r] == want, r
        assert (out[r, lengths[r]:] == 63).all()
    # no eos: every length is max_new_tokens
    _, full = generate(model, params, prompt, 6, temperature=0.0,
                       return_lengths=True)
    np.testing.assert_array_equal(full, [6, 6, 6])


def test_generate_bucketed_prompts_share_one_compile():
    """Prompt lengths 5, 6, 7 land in the length-8 bucket: ONE compiled
    program serves all three (the anti-recompile contract for repeated
    generate() calls under varying prompt lengths), and each bucketed run
    still matches the repeated-full-forward greedy oracle — pinning the
    pad-then-rewind cursor logic."""
    from tpudist.generate import _run, bucket_length

    assert [bucket_length(n) for n in (1, 5, 8, 9, 17)] == [8, 8, 8, 16, 32]
    assert bucket_length(9, cap=12) == 12
    # a geometry no other test uses: jit caches per (model, shape), and a
    # warm entry from another test would hide the recompile this pins
    model = GPT2(vocab_size=48, max_seq_len=32, hidden_dim=32, depth=1,
                 num_heads=2)
    params = model.init(
        jax.random.key(7), jnp.zeros((1, 8), jnp.int32), train=False
    )["params"]
    base = _run._cache_size()
    for p in (5, 6, 7):
        prompt = _tokens(b=2, s=p, vocab=48, seed=40 + p)
        out = generate(model, params, prompt, 5, temperature=0.0)
        seq = prompt
        for _ in range(5):
            logits = model.apply({"params": params}, jnp.asarray(seq),
                                 train=False)
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
            seq = np.concatenate([seq, nxt.astype(np.int32)], axis=1)
        np.testing.assert_array_equal(out, seq[:, p:])
    assert _run._cache_size() == base + 1


def test_generate_with_tensor_sharded_params():
    """Decode composes with tensor parallelism: Megatron-sharded params on
    a data x tensor mesh generate the same tokens as replicated params."""
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.train import create_train_state

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    model = GPT2(vocab_size=64, max_seq_len=24, hidden_dim=32, depth=1,
                 num_heads=4)
    state = create_train_state(
        model, 3, jnp.zeros((1, 8), jnp.int32), optax.sgd(0.1), mesh
    )
    spec = state.params["h_0"]["qkv"]["kernel"].sharding.spec
    assert "tensor" in spec, spec  # really sharded

    prompt = _tokens(b=2, s=4, seed=9)
    sharded = generate(model, state.params, prompt, 6, temperature=0.0)
    replicated = jax.tree_util.tree_map(np.asarray, state.params)
    plain = generate(model, replicated, prompt, 6, temperature=0.0)
    np.testing.assert_array_equal(sharded, plain)


def test_learned_model_continues_pattern():
    """Train on a repeating token cycle, then greedy generation must
    continue the cycle — generation and training agree end-to-end."""
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh()
    model = GPT2(vocab_size=16, max_seq_len=32, hidden_dim=32, depth=1,
                 num_heads=4)
    tx = optax.adam(5e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    # cycle 0..7 repeated; windows start at random phases
    rng = np.random.Generator(np.random.PCG64(0))
    cycle = np.arange(8, dtype=np.int32)
    for _ in range(60):
        phase = rng.integers(0, 8, 8)
        batch = np.stack([np.tile(cycle, 3)[p : p + 16] for p in phase])
        state, metrics = step(state, {"tokens": batch})
    assert float(metrics["loss"]) < 0.1

    prompt = np.tile(cycle, 2)[None, 3:11].astype(np.int32)  # 3..10 wrap
    out = generate(model, state.params, prompt, 8, temperature=0.0)
    want = np.tile(cycle, 3)[None, 11:19]
    np.testing.assert_array_equal(out, want)
