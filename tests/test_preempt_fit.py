"""The graceful-preemption story IN-PROCESS (tier-1, no subprocess
world): a chaos-injected SIGTERM at step k makes fit() finish the
in-flight step, write a synchronous emergency checkpoint at exactly k,
flush the run report with ``exit_reason="preempted"`` and a goodput
section whose components sum to wall time, and raise ``Preempted`` (the
SystemExit-75 the supervisor restarts on); a second fit() over the same
checkpoint dir — generation 1, same argv including the chaos spec —
resumes at k+1 and reproduces the uninterrupted run's loss trajectory
BIT-identically, through the int8-quantized gradient all-reduce + ZeRO-1
sharded optimizer (the paths with the most resume-sensitive state: the
error-feedback residual and the sharded Adam mirrors).

Model choice: the BN-free tiny MLP of test_dp_equivalence, not a
transformer — determinism is the point, and the resume runs cache-less
(``no_persistent_compile_cache``): this container's jax 0.4.x XLA:CPU
misexecutes cache-LOADED executables on exactly the donated-step-on-
restored-arrays pattern the resume path is made of (the same documented
wart the guard tests opt out for; fresh compiles of the MLP cost
seconds)."""

import json
import signal

import numpy as np
import optax
import pytest
from flax import linen as nn

from tpudist.checkpoint import latest_step
from tpudist.data.loader import DataLoader
from tpudist.resilience import GENERATION_ENV, Preempted
from tpudist.telemetry import TelemetryConfig
from tpudist.train import fit

GOODPUT_PARTS = ("bringup_s", "restore_s", "compile_s", "data_wait_s",
                 "checkpoint_s", "productive_step_s")


class _TinyMlp(nn.Module):
    """Non-divisible leaf sizes (37/10) so the quantized layout's
    pad-and-slice math and ZeRO-1's pad-and-reshape both exercise."""

    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(10)(nn.relu(nn.Dense(37)(x)))


def _loader(batch: int = 16):
    rng = np.random.default_rng(0)
    data = {
        "image": rng.normal(size=(64, 13)).astype(np.float32),
        "label": (rng.random(64) * 10).astype(np.int32),
    }
    return DataLoader(data, batch)


def _fit(tmp_path, job_id, ckpt_dir, *, chaos=None, epochs=4,
         telemetry=False, **kw):
    return fit(
        _TinyMlp(), optax.adam(1e-2), _loader(), epochs=epochs,
        job_id=job_id, batch_size=16, log_dir=str(tmp_path),
        telemetry=telemetry, profile=False,
        checkpoint_dir=None if ckpt_dir is None else str(ckpt_dir),
        chaos=chaos,
        # the acceptance combination: quantized AR (error-feedback
        # residual in the train state) + ZeRO-1 sharded Adam mirrors
        reduce="quantized", shard_opt_state=True, **kw,
    )


def _goodput_sums(goodput):
    parts = sum(goodput[k] for k in GOODPUT_PARTS)
    assert parts == pytest.approx(goodput["total_s"], rel=0.01), goodput


def test_chaos_sigterm_emergency_checkpoint_then_bit_identical_resume(
        tmp_path, monkeypatch, no_persistent_compile_cache):
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=4)

    # the uninterrupted reference: same model/data/optimizer/reduction —
    # and the same telemetry config, because guard_nonfinite changes the
    # COMPILED PROGRAM (the in-graph select guard) and bit-identity only
    # holds between identical programs — its own checkpoint dir, run end
    # to end: 4 epochs x 4 batches
    ref_state, ref_losses = _fit(
        tmp_path, "Ref", tmp_path / "ref_ckpt", checkpoint_every=4,
        telemetry=cfg,
    )
    assert len(ref_losses) == 16

    # generation 0: SIGTERM lands after step 6 completes (between the
    # step-based saves at 4 and 8) — fit must write the emergency
    # checkpoint AT 6, report "preempted", and exit restartable
    with pytest.raises(Preempted) as ei:
        _fit(tmp_path, "PR", tmp_path / "ckpt", chaos="sigterm@6",
             checkpoint_every=4, telemetry=cfg)
    assert ei.value.code == 75
    assert ei.value.step == 6
    assert latest_step(tmp_path / "ckpt") == 6

    report = json.loads((tmp_path / "PR_report.json").read_text())
    assert report["status"] == "preempted"
    assert report["exit_reason"] == "preempted"
    assert report["generation"] == 0
    goodput = report["goodput"]
    _goodput_sums(goodput)
    assert goodput["emergency_save_s"] > 0
    assert goodput["steps"] == 6

    # generation 1: the supervisor's relaunch — same argv (chaos spec
    # included: it is generation-0-gated and must NOT re-fire at the
    # resume step), TPUDIST_RESTART_GENERATION=1 exported
    monkeypatch.setenv(GENERATION_ENV, "1")
    state, losses = _fit(
        tmp_path, "PR", tmp_path / "ckpt", chaos="sigterm@6",
        checkpoint_every=4, telemetry=cfg,
    )
    assert int(state.step) == 16
    # resumed at k+1: exactly the 10 remaining steps, and the trajectory
    # through quantized-AR + ZeRO-1 is BIT-identical to the uninterrupted
    # run's tail — the emergency checkpoint lost nothing
    assert len(losses) == 10
    assert losses == ref_losses[6:]

    # the final report aggregates both lives of the job
    report = json.loads((tmp_path / "PR_report.json").read_text())
    assert report["exit_reason"] == "completed"
    assert report["generation"] == 1
    gens = report["goodput"]["generations"]
    assert [g["generation"] for g in gens] == [0, 1]
    assert gens[0]["exit_reason"] == "preempted"
    assert gens[1]["restore_s"] > 0  # the resume actually restored
    cum = report["goodput"]["cumulative"]
    assert cum["restart_overhead_s"] > 0
    assert cum["wall_s"] >= gens[0]["total_s"] + gens[1]["total_s"]

    # heartbeats from both generations share the append-mode stream,
    # attributable by the appended generation field
    rows = [
        json.loads(l)
        for l in (tmp_path / "PR_telemetry_0.jsonl").read_text().splitlines()
    ]
    beat_gens = {r["generation"] for r in rows if r["kind"] == "heartbeat"}
    assert beat_gens == {0, 1}


def test_preempt_without_checkpointing_still_reports_and_exits_75(
        tmp_path, monkeypatch):
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False)
    with pytest.raises(Preempted) as ei:
        fit(
            _TinyMlp(), optax.adam(1e-2), _loader(), epochs=2,
            job_id="NC", batch_size=16, log_dir=str(tmp_path),
            telemetry=cfg, profile=False, chaos="sigterm@3",
        )
    assert ei.value.code == 75
    # the checkpoint-less library caller keeps the trained state: fit's
    # would-be return value rides the exception
    assert ei.value.state is not None and int(ei.value.state.step) == 3
    assert len(ei.value.losses) == 3
    report = json.loads((tmp_path / "NC_report.json").read_text())
    assert report["exit_reason"] == "preempted"
    assert report["goodput"]["emergency_save_s"] == 0  # nothing to save to


def test_chaos_crash_runs_the_real_crash_path(tmp_path):
    from tpudist.resilience import ChaosCrash

    cfg = TelemetryConfig(sentry=False, mfu=False)
    with pytest.raises(ChaosCrash, match="step 3"):
        fit(
            _TinyMlp(), optax.adam(1e-2), _loader(), epochs=2,
            job_id="CC", batch_size=16, log_dir=str(tmp_path),
            telemetry=cfg, profile=False, chaos="crash@3",
        )
    report = json.loads((tmp_path / "CC_report.json").read_text())
    assert report["status"] == "crashed:ChaosCrash"
    assert report["exit_reason"] == "crashed:ChaosCrash"


def test_time_based_checkpoint_cadence(tmp_path):
    # checkpoint_every_s alone (no step cadence): every step takes longer
    # than the microscopic period, so every boundary saves — the
    # wall-clock knob works without the step knob
    state, losses = fit(
        _TinyMlp(), optax.adam(1e-2), _loader(), epochs=1,
        job_id="TS", batch_size=16, log_dir=str(tmp_path), profile=False,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=0,
        checkpoint_every_s=1e-6,
    )
    assert len(losses) == 4
    assert latest_step(tmp_path / "ckpt") == 4
    steps = sorted(
        int(d.name) for d in (tmp_path / "ckpt").iterdir()
        if d.is_dir() and d.name.isdigit()
    )
    # max_to_keep=3, saved at every boundary: the tail of 1..4 remains
    assert steps == [2, 3, 4]


def test_sigterm_during_stalled_input_pipeline_still_preempts_gracefully(
        tmp_path, monkeypatch):
    """The realistic worst case: the preemption notice lands while the
    loop is BLOCKED on a stalled data source. The prefetch wait polls the
    guard flag, ends the stream early, and fit takes the emergency-
    checkpoint path — instead of absorbing the signal and hanging until
    the scheduler's SIGKILL."""
    import os as _os
    import threading
    import time as _time

    monkeypatch.delenv(GENERATION_ENV, raising=False)

    stalled = threading.Event()

    class StallingLoader(DataLoader):
        """Yields 2 batches, then the source wedges (60 s ≫ the test)."""

        def __iter__(self):
            it = super().__iter__()
            for i, b in enumerate(it):
                if i == 2:
                    stalled.set()
                    _time.sleep(60)
                yield b

    def _kill_once_blocked():
        # deterministic: fire only after the stall began AND step 1's
        # cadence checkpoint is durable. The prefetch generator tops its
        # queue up BEFORE yielding the next staged batch, so once the
        # producer stalls the consumer is provably blocked inside the
        # prefetch wait (step 2 cannot have dispatched).
        stalled.wait(60)
        for _ in range(600):
            if (latest_step(tmp_path / "ckpt") or 0) >= 1:
                break
            _time.sleep(0.1)
        _os.kill(_os.getpid(), signal.SIGTERM)

    killer = threading.Thread(target=_kill_once_blocked, daemon=True)
    killer.start()
    t0 = _time.monotonic()
    with pytest.raises(Preempted) as ei:
        fit(
            _TinyMlp(), optax.adam(1e-2), StallingLoader(
                _loader().dataset, 16
            ), epochs=2, job_id="ST", batch_size=16,
            log_dir=str(tmp_path), profile=False,
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=1,
        )
    # exited within the poll cadence, not the 60 s stall
    assert _time.monotonic() - t0 < 40
    assert ei.value.code == 75
    # the one completed pre-stall step is persisted; nothing after (the
    # trip is checked before the next dispatch)
    assert int(ei.value.state.step) == 1
    assert latest_step(tmp_path / "ckpt") == 1


def test_preempt_false_keeps_default_signal_disposition(tmp_path):
    before = signal.getsignal(signal.SIGTERM)
    seen = []

    class SpyLoader(DataLoader):
        def __iter__(self):
            seen.append(signal.getsignal(signal.SIGTERM))
            return super().__iter__()

    rng = np.random.default_rng(0)
    data = {
        "image": rng.normal(size=(32, 13)).astype(np.float32),
        "label": (rng.random(32) * 10).astype(np.int32),
    }
    fit(
        _TinyMlp(), optax.adam(1e-2), SpyLoader(data, 16),
        epochs=1, job_id="NP", batch_size=16, log_dir=str(tmp_path),
        profile=False, preempt=False,
    )
    assert seen and all(h == before for h in seen)
    assert signal.getsignal(signal.SIGTERM) == before
