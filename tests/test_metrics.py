"""Log-format contract tests vs /root/reference/main.py:65-67,107-117
(BASELINE.md: "Log format contract (to be reproduced exactly)")."""

from tpudist.metrics import HEADER, MetricsLogger


def test_header_and_filename(tmp_path):
    logger = MetricsLogger("Job7", 128, 0, 8, log_dir=tmp_path)
    assert logger.file_name.name == "Job7_128_0.log"
    logger.finish()
    lines = logger.file_name.read_text().splitlines()
    assert lines[0] == HEADER.strip()
    assert lines[0] == "datetime\tg_step\tg_img\tloss_value\texamples_per_sec"


def test_rank0_rows_every_5_steps(tmp_path):
    logger = MetricsLogger("J", 64, 0, 4, log_dir=tmp_path)
    for step in range(1, 11):
        logger.log_step(step, loss_value=2.5, step_duration=0.5)
    logger.finish()
    lines = logger.file_name.read_text().splitlines()
    rows = [l for l in lines[1:] if not l.startswith("TrainTime")]
    assert len(rows) == 2  # steps 5 and 10
    f5 = rows[0].split("\t")
    # g_step = global_step*world, g_img = g_step*batch (main.py:110)
    assert f5[1] == str(5 * 4)
    assert f5[2] == str(5 * 4 * 64)
    assert f5[3] == "2.5"
    assert abs(float(f5[4]) - 64 / 0.5) < 1e-6


def test_nonzero_rank_writes_header_only(tmp_path):
    logger = MetricsLogger("J", 64, 3, 4, log_dir=tmp_path)
    for step in range(1, 11):
        logger.log_step(step, 1.0, 0.1)
    logger.finish()
    lines = logger.file_name.read_text().splitlines()
    assert lines[0].startswith("datetime")
    assert len(lines) == 2 and lines[1].startswith("TrainTime\t")


def test_hbm_row_rank0_only_and_noop_without_stats(tmp_path):
    """log_memory writes one tagged HBM row (rank 0, stats present), like
    the TrainTime footer — and never touches the reference's data-row
    contract. None/{} (CPU backends report nothing) is a silent no-op."""
    import json

    logger = MetricsLogger("J", 8, 0, 1, log_dir=tmp_path)
    logger.log_memory(None)
    logger.log_memory({})
    stats = {"bytes_in_use": 123, "bytes_limit": 456}
    logger.log_memory(stats)
    logger.finish()
    lines = logger.file_name.read_text().splitlines()
    hbm = [l for l in lines if l.startswith("HBM\t")]
    assert len(hbm) == 1
    assert json.loads(hbm[0].split("\t", 1)[1]) == stats
    # rank != 0 writes nothing
    other = MetricsLogger("J", 8, 2, 4, log_dir=tmp_path)
    other.log_memory(stats)
    other.finish()
    assert "HBM" not in other.file_name.read_text()
    # the live-stats provider contract: dict or None, never raises on CPU
    from tpudist.memory import device_memory_stats

    assert device_memory_stats() is None or isinstance(
        device_memory_stats(), dict
    )


def test_zero_duration_row_is_tagged_not_inf(tmp_path):
    """A coarse clock under a sub-resolution CPU step hands log_step a
    duration of 0: the reference's ``batch_size / step_duration`` would be
    a ZeroDivisionError. The row must land with 0.0 throughput under a
    ``ZeroDur`` tag (footer-style, so plain data rows keep the guarantee
    that examples_per_sec is a real measurement) — and mirror the same
    values into the JSONL sink in dual-sink mode."""
    import json

    class _Sink:
        rows = []

        def write(self, kind, step=None, **fields):
            self.rows.append({"kind": kind, "step": step, **fields})

    logger = MetricsLogger("J", 64, 0, 1, log_dir=tmp_path)
    logger.attach_sink(_Sink())
    logger.log_step(5, loss_value=2.5, step_duration=0.0)
    logger.log_step(10, loss_value=2.0, step_duration=0.5)
    logger.finish()
    lines = logger.file_name.read_text().splitlines()
    tagged = [l for l in lines if l.startswith("ZeroDur\t")]
    assert len(tagged) == 1
    fields = tagged[0].split("\t")
    # tag + the reference's five columns, throughput pinned to 0.0
    assert len(fields) == 6 and float(fields[5]) == 0.0
    # the clean row is untagged and keeps the real measurement
    clean = [l for l in lines[1:] if not l.startswith(("ZeroDur", "TrainTime"))]
    assert len(clean) == 1 and abs(float(clean[0].split("\t")[4]) - 128) < 1e-6
    jsonl = [r for r in _Sink.rows if r["kind"] == "throughput"]
    assert [r["zero_duration"] for r in jsonl] == [True, False]
    assert jsonl[0]["examples_per_sec"] == 0.0
    json.dumps(jsonl)  # rows stay JSON-serializable


def test_traintime_footer_format(tmp_path):
    logger = MetricsLogger("J", 1, 0, 1, log_dir=tmp_path)
    t = logger.finish()
    last = logger.file_name.read_text().splitlines()[-1]
    tag, val = last.split("\t")
    assert tag == "TrainTime"
    assert float(val) >= 0 and t >= 0
    assert "." in val  # %f formatting
