"""The self-healing repair loop (tpudist.resilience.repair): unit
coverage for the policy engine (anchor promotion/demotion, skip-streak
arithmetic, sustained-spike rule, repeat-escalation, budget
circuit-breaker), the new chaos kinds (multi-spec parse, nanburst batch
poisoning, bitflip SDC injection), keep_last retention, and the
IN-PROCESS fit() drills the acceptance demands: a chaos-poisoned run
that detects, rolls back to the anchored checkpoint, skips the window,
books the repair row, and finishes with finite loss — state-level EQUAL
to a clean reference that simply never saw the skipped window (no
stochastic consumer → the repair salt legally changes nothing).

The fit drills run cache-less (``no_persistent_compile_cache``): the
rollback path is donated-step-on-restored-arrays, the exact pattern this
container's jax 0.4.x XLA:CPU misexecutes from cache-LOADED executables
(the documented wart test_preempt_fit opts out for)."""

import json
import math

import numpy as np
import optax
import pytest
from flax import linen as nn

import jax
import jax.numpy as jnp

from tpudist import mesh as mesh_lib
from tpudist.data.loader import DataLoader
from tpudist.resilience import (
    GENERATION_ENV,
    ChaosCrash,
    ChaosInjector,
    ChaosSpec,
    RepairExhausted,
    RepairPolicy,
    RepairRestart,
    flip_param_bit,
    parse_chaos,
    resolve_policy,
)
from tpudist.resilience.repair import RepairController
from tpudist.telemetry import TelemetryConfig
from tpudist.train import fit


class _TinyMlp(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))


def _data(n=64):
    rng = np.random.default_rng(0)
    return {
        "image": rng.normal(size=(n, 13)).astype(np.float32),
        "label": (rng.random(n) * 10).astype(np.int32),
    }


# -- policy / chaos parsing --------------------------------------------------

def test_resolve_policy_coercions():
    assert resolve_policy(None) is None and resolve_policy(False) is None
    assert resolve_policy(True) == RepairPolicy()
    assert resolve_policy({"skip_window": 3}).skip_window == 3
    p = RepairPolicy(skip_streak=5)
    assert resolve_policy(p) is p
    with pytest.raises(ValueError):
        resolve_policy("yes")
    # salt 0 is the pristine seed — a never-repaired run's programs are
    # bit-identical to a repair-less one
    assert RepairPolicy().salted_seed(7, 0) == 7
    assert RepairPolicy().salted_seed(7, 2) != RepairPolicy().salted_seed(7, 1)


def test_parse_chaos_multi_and_single_compat():
    # single-spec strings parse byte-compatibly with ChaosSpec.parse
    assert parse_chaos("crash@12") == [ChaosSpec.parse("crash@12")]
    specs = parse_chaos("bitflip@10,nanburst:3@20")
    assert [s.kind for s in specs] == ["bitflip", "nanburst"]
    assert specs[0].step == 10 and specs[1].step == 20
    assert specs[1].count == 3
    # nanburst defaults to a 1-step burst; bitflip takes no ':n'
    assert parse_chaos("nanburst@4")[0].count == 1
    for bad in ("", ",", "bitflip:2@4", "nanburst:0@4", "sigterm:3@4"):
        with pytest.raises(ValueError):
            parse_chaos(bad)


def test_multi_spec_injector_fires_each_once_and_rearm():
    kills = []
    inj = ChaosInjector(
        parse_chaos("sigterm@3,crash@5@*"), generation=0,
        kill=lambda pid, sig: kills.append(sig),
    )
    assert inj.maybe_fire(3) is True and len(kills) == 1
    assert inj.maybe_fire(4) is False  # sigterm one-shot, crash not due
    with pytest.raises(ChaosCrash):
        inj.maybe_fire(5)
    assert inj.fired
    # rearm re-arms ONLY the @* deterministic-bug spec
    inj.rearm()
    assert inj.maybe_fire(3) is False  # the gen-pinned sigterm stays spent
    with pytest.raises(ChaosCrash):
        inj.maybe_fire(6)


def test_nanburst_wrap_poisons_exact_step_window():
    inj = ChaosInjector(parse_chaos("nanburst:2@6"), generation=0)
    batches = [
        {"image": np.ones((4, 3), np.float32), "label": np.zeros(4, np.int64)}
        for _ in range(8)
    ]
    # first batch trains step 5: poisoned steps are 7 and 8 only
    out = list(inj.wrap_batches(iter(batches), 5))
    poisoned = [i for i, b in enumerate(out)
                if not np.isfinite(b["image"]).all()]
    assert [5 + i for i in poisoned] == [7, 8]
    # the source batches are not mutated in place
    assert all(np.isfinite(b["image"]).all() for b in batches)
    # a generation-gated burst never poisons in generation 1
    gen1 = ChaosInjector(parse_chaos("nanburst:2@6"), generation=1)
    out1 = list(gen1.wrap_batches(iter(batches), 5))
    assert all(np.isfinite(b["image"]).all() for b in out1)


def test_nanburst_refuses_float_free_batch():
    inj = ChaosInjector(parse_chaos("nanburst@1"), generation=0)
    out = inj.wrap_batches(
        iter([{"tokens": np.zeros((2, 4), np.int32)}]), 2
    )
    with pytest.raises(ChaosCrash, match="no float"):
        list(out)


def test_flip_param_bit_visible_to_divergence_probe():
    from flax.core import FrozenDict

    from tpudist.parallel.dp import make_divergence_probe
    from tpudist.train import TrainState

    mesh = mesh_lib.create_mesh()
    repl = mesh_lib.replicated_sharding(mesh)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=jax.device_put(
            {"w": np.arange(32, dtype=np.float32)}, repl
        ),
        batch_stats=FrozenDict(), opt_state=(),
    )
    probe = make_divergence_probe(state, mesh)
    clean = {k: int(v) for k, v in probe(state).items()}
    assert clean["replica_divergence"] == 0
    flipped, info = flip_param_bit(state, mesh=mesh)
    assert info["leaf"].endswith("w") and info["flipped_locally"]
    bad = {k: int(v) for k, v in probe(flipped).items()}
    # exactly one replica disagrees — and replica 0 (the comparison
    # base) is never the corrupted one
    assert bad["replica_divergence"] == 1
    assert bad["replica_checksum"] == clean["replica_checksum"]
    # the value barely moved (one low mantissa bit): the SDC is silent
    # to every magnitude-based detector
    a = np.asarray(state.params["w"], np.float64)
    b = np.asarray(flipped.params["w"], np.float64)
    assert np.allclose(a, b, rtol=1e-5)


def test_flip_param_bit_refuses_unreplicated_state():
    from flax.core import FrozenDict

    from tpudist.train import TrainState

    mesh = mesh_lib.create_mesh()
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharded = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=jax.device_put(
            {"w": np.arange(32, dtype=np.float32)}, sharded
        ),
        batch_stats=FrozenDict(), opt_state=(),
    )
    with pytest.raises(ChaosCrash, match="no fully-replicated"):
        flip_param_bit(state, mesh=mesh)


# -- controller units --------------------------------------------------------

class _FakeCkpt:
    def __init__(self, steps=(), anchor=None):
        self.steps = sorted(steps)
        self.anchor = anchor
        self.anchor_writes = []

    def read_anchor(self):
        return self.anchor

    def write_anchor(self, step):
        self.anchor = int(step)
        self.anchor_writes.append(int(step))

    def all_steps(self):
        return list(self.steps)


def _controller(tmp_path, policy=None, ckpt=None, clock=None, gen=0):
    ctl = RepairController(
        policy or RepairPolicy(anchor_clean_steps=3, skip_streak=3,
                               skip_window=4, repeat_window=6,
                               max_repairs=3, budget_window_s=600.0),
        tmp_path, generation=gen,
        **({"clock": clock} if clock else {}),
    )
    ctl.bind(ckpt if ckpt is not None else _FakeCkpt())
    return ctl


def _clean(step):
    return step, {"loss": 1.0, "update_skipped": 0, "nonfinite_grad_count": 0}


def test_anchor_promotion_and_demotion(tmp_path):
    ckpt = _FakeCkpt()
    ctl = _controller(tmp_path, ckpt=ckpt)
    ctl.on_save(4)
    for s in (5, 6):
        ctl.observe_step(*_clean(s))
    assert ctl.anchored is None  # 2 clean steps < K=3
    ctl.observe_step(*_clean(7))
    assert ctl.anchored == 4 and ckpt.anchor == 4  # promoted + persisted
    # a save followed by an UNHEALTHY step before K clean ones is
    # demoted — a checkpoint written mid-incubating-spike can never
    # become the rollback target
    ctl.on_save(8)
    ctl.observe_step(9, {"loss": float("nan")})
    for s in (10, 11, 12, 13):
        ctl.observe_step(*_clean(s))
    assert ctl.anchored == 4  # 8 never promotes
    # the next healthy save promotes normally
    ctl.on_save(14)
    for s in (15, 16, 17):
        ctl.observe_step(*_clean(s))
    assert ctl.anchored == 14


def test_skip_streak_trigger_arithmetic(tmp_path):
    ctl = _controller(tmp_path)
    # 2 skipped steps, then clean: streak resets, no trigger
    ctl.observe_step(5, {"loss": 1.0, "update_skipped": 1})
    ctl.observe_step(6, {"loss": 1.0, "update_skipped": 1})
    ctl.observe_step(*_clean(7))
    assert ctl.triggered is None
    # 3 consecutive (streak == policy.skip_streak) trigger; a lone
    # nonfinite grad count counts toward the same streak
    ctl.observe_step(8, {"loss": 1.0, "update_skipped": 1})
    ctl.observe_step(9, {"loss": float("inf")})
    ctl.observe_step(10, {"loss": 1.0, "nonfinite_grad_count": 2})
    trig = ctl.take_trigger()
    assert trig["cause"] == "skip_streak" and trig["streak"] == 3
    assert ctl.triggered is None  # consumed


def test_sustained_spike_trigger_vs_single_spike(tmp_path):
    ctl = _controller(tmp_path, policy=RepairPolicy(
        spike_patience=2, spike_window_steps=10))
    ctl.on_detection({"detector": "sentry", "event": "loss_spike",
                      "step": 5, "loss": 9.0})
    assert ctl.triggered is None  # one spike is news, not a verdict
    # a spike outside the window ages out
    ctl.on_detection({"detector": "sentry", "event": "loss_spike",
                      "step": 40, "loss": 9.0})
    assert ctl.triggered is None
    ctl.on_detection({"detector": "sentry", "event": "loss_spike",
                      "step": 45, "loss": 9.0})
    assert ctl.take_trigger()["cause"] == "loss_spike"
    # divergence triggers immediately — an SDC has no benign reading
    ctl.on_detection({"detector": "divergence", "step": 50,
                      "replica_divergence": 1, "state_nonfinite": 0})
    assert ctl.take_trigger()["cause"] == "sdc_divergence"
    # sentry 'nonfinite' events are left to the skip-streak arithmetic
    ctl.on_detection({"detector": "sentry", "event": "nonfinite",
                      "step": 55})
    assert ctl.triggered is None


def test_plan_rollback_then_repeat_restart_and_salt(tmp_path):
    clock = lambda: 1000.0
    ckpt = _FakeCkpt(steps=[2, 4, 8], anchor=8)
    ctl = _controller(tmp_path, ckpt=ckpt, clock=clock)
    assert ctl.salt == 0
    a1 = ctl.plan({"cause": "sdc_divergence"}, 12, max_step=100)
    assert (a1.kind, a1.rollback_step, a1.anchored) == ("rollback", 8, True)
    assert (a1.skip_from, a1.skip_to, a1.salt) == (12, 16, 1)
    assert a1.discarded_steps == 4
    ctl.record(a1)
    assert ctl.salt == 1
    # a trigger within repeat_window of the resume point escalates
    a2 = ctl.plan({"cause": "sdc_divergence"}, 20, max_step=100)
    assert a2.kind == "restart" and a2.salt == 2
    ctl.record(a2)
    assert ctl.pending is not None and ctl.pending["action"] == "restart"
    # the durable record round-trips into a fresh controller (the next
    # generation's bring-up), which consumes the directive
    ctl2 = _controller(tmp_path, ckpt=ckpt, clock=clock, gen=1)
    assert ctl2.salt == 2
    d = ctl2.consume_pending()
    assert d["skip_to"] == a2.skip_to
    assert ctl2.pending is None
    ctl3 = _controller(tmp_path, ckpt=ckpt, clock=clock, gen=1)
    assert ctl3.pending is None  # consumption is durable
    # far past the repeat window, the next trigger is a fresh incident
    a3 = ctl2.plan({"cause": "loss_spike"}, 80, max_step=100)
    assert a3.kind == "rollback"
    # skip_to clamps at the end of the run
    a4 = ctl2.plan({"cause": "loss_spike"}, 99, max_step=100)
    assert a4.skip_to == 100


def test_budget_circuit_breaker(tmp_path):
    now = {"t": 1000.0}
    ckpt = _FakeCkpt(steps=[4], anchor=4)
    ctl = _controller(
        tmp_path, ckpt=ckpt, clock=lambda: now["t"],
        policy=RepairPolicy(max_repairs=2, budget_window_s=100.0,
                            repeat_window=0, skip_window=0),
    )
    ctl.record(ctl.plan({"cause": "a"}, 10, max_step=1000))
    now["t"] += 10
    ctl.record(ctl.plan({"cause": "b"}, 50, max_step=1000))
    now["t"] += 10
    with pytest.raises(RepairExhausted, match="budget exhausted"):
        ctl.plan({"cause": "c"}, 90, max_step=1000)
    # the window ROLLS: once the old entries age out, repairs resume
    now["t"] += 200
    assert ctl.plan({"cause": "d"}, 130, max_step=1000).kind == "rollback"
    # max_repairs=0 disables the breaker entirely
    ctl0 = _controller(
        tmp_path, ckpt=ckpt,
        policy=RepairPolicy(max_repairs=0, repeat_window=0, skip_window=0),
    )
    for s in (10, 50, 90, 130):
        ctl0.record(ctl0.plan({"cause": "x"}, s, max_step=1000))


def test_no_rollback_target_exhausts(tmp_path):
    ctl = _controller(tmp_path, ckpt=_FakeCkpt(steps=[]))
    with pytest.raises(RepairExhausted, match="no checkpoint"):
        ctl.plan({"cause": "sdc_divergence"}, 5, max_step=100)


def test_supervisor_handles_exit_77_and_exports_history():
    from tpudist.resilience import EXIT_HISTORY_ENV, Supervisor, exit_history

    env = {}
    seen = []

    def run_world(generation):
        seen.append((generation, env.get(EXIT_HISTORY_ENV)))
        return [77, 77, 1][generation]

    sup = Supervisor(run_world, max_restarts=0, log=lambda m: None,
                     environ=env)
    # 77 rides the restartable fast path (no crash budget consumed);
    # the terminal crash (budget-exhausted poison) ends the job
    assert sup.run() == 1
    assert sup.exit_history == [77, 77, 1]
    # each relaunched generation saw its predecessors' exit codes
    assert seen == [(0, None), (1, "77"), (2, "77,77")]
    assert exit_history({EXIT_HISTORY_ENV: "77,77"}) == [77, 77]
    assert exit_history({EXIT_HISTORY_ENV: "garbage,75"}) == [75]
    assert exit_history({}) == []


def test_goodput_repair_components_sum_exactly():
    from tpudist.resilience import GoodputTracker
    from tpudist.resilience.goodput import COMPONENTS

    class _Clock:
        now = 0.0

        def __call__(self):
            return self.now

    clk, wall = _Clock(), _Clock()
    gp = GoodputTracker(generation=0, clock=clk, wall=wall)
    gp.loop_started()
    clk.now = 1.0
    gp.step_boundary()
    gp.add_repair(0.5, 2.0)
    clk.now = 8.0
    s = gp.summary("completed")
    assert s["repair_s"] == 0.5 and s["repair_replay_s"] == 2.0
    assert s["repairs"] == 1
    parts = sum(s[k] for k in COMPONENTS) + s["productive_step_s"]
    assert parts == pytest.approx(s["total_s"], rel=1e-9)
    assert s["cumulative"]["repair_overhead_s"] == pytest.approx(2.5)


def test_keep_last_prunes_and_anchor_is_exempt(tmp_path):
    from flax.core import FrozenDict

    from tpudist.checkpoint import Checkpointer
    from tpudist.train import TrainState

    def _state(step):
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            params={"w": jnp.full((4,), float(step))},
            batch_stats=FrozenDict(), opt_state={"m": jnp.zeros(4)},
        )

    with Checkpointer(tmp_path / "ck", keep_last=2) as ckpt:
        ckpt.save(_state(1), wait=True)
        ckpt.save(_state(2), wait=True)
        ckpt.write_anchor(2)
        for s in (3, 4, 5):
            ckpt.save(_state(s), wait=True)
        # newest 2 plus the anchored step survive; 1/3 pruned
        assert ckpt.all_steps() == [2, 4, 5]
        assert ckpt.read_anchor() == 2
        restored = ckpt.restore(like=_state(0), step=2)
        assert float(restored.params["w"][0]) == 2.0


def test_keep_last_protects_anchor_candidates_until_promotion(tmp_path):
    """Regression: with a save cadence denser than keep_last x
    anchor_clean_steps, retention used to delete a save BEFORE its
    promotion window elapsed — the later promotion then stamped the
    anchor file with a step dir that no longer existed, and the first
    rollback died on a missing checkpoint instead of self-healing. The
    controller's protect hook (bind wires Checkpointer.protect_steps)
    keeps candidates alive until they promote or demote."""
    from flax.core import FrozenDict

    from tpudist.checkpoint import Checkpointer
    from tpudist.train import TrainState

    def _state(s):
        return TrainState(
            step=jnp.asarray(s, jnp.int32),
            params={"w": jnp.full((4,), float(s))},
            batch_stats=FrozenDict(), opt_state={"m": jnp.zeros(4)},
        )

    with Checkpointer(tmp_path / "ck", keep_last=2) as ckpt:
        ctl = RepairController(
            RepairPolicy(anchor_clean_steps=10), tmp_path / "ck"
        ).bind(ckpt)
        # saves every 2 steps, clean health throughout: step 2's
        # promotion window (12) outlives keep_last=2 by several saves
        for s in range(1, 15):
            if s % 2 == 0:
                ckpt.save(_state(s), wait=True)
                ctl.on_save(s)
            ctl.observe_step(*_clean(s))
        assert ctl.anchored is not None
        # the promoted anchor step (and any still-pending candidates)
        # survived retention — the rollback target is restorable
        assert ctl.anchored in ckpt.all_steps()
        ckpt.restore(like=_state(0), step=ctl.anchored)
        # a DEMOTED candidate stops being protected: the next save's
        # prune reclaims it
        ctl.observe_step(15, {"loss": float("nan")})
        ckpt.save(_state(16), wait=True)
        assert len(ckpt.all_steps()) <= 2 + 1  # newest 2 + anchor


def test_fit_repair_requires_checkpointing(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_dir"):
        fit(_TinyMlp(), optax.adam(1e-2), DataLoader(_data(), 16),
            epochs=1, job_id="RV", log_dir=str(tmp_path), profile=False,
            repair=True)
    with pytest.raises(ValueError, match="cadence"):
        fit(_TinyMlp(), optax.adam(1e-2), DataLoader(_data(), 16),
            epochs=1, job_id="RV", log_dir=str(tmp_path), profile=False,
            checkpoint_dir=str(tmp_path / "ck"), repair=True)


# -- the in-process drills ---------------------------------------------------

def _rows(path):
    return [json.loads(l) for l in path.read_text().splitlines()]


def _fit_kwargs(tmp_path, job, **over):
    kw = dict(
        epochs=8, job_id=job, batch_size=16, log_dir=str(tmp_path),
        profile=False,
        checkpoint_dir=str(tmp_path / f"{job}_ckpt"), checkpoint_every=2,
        repair={"skip_window": 4, "anchor_clean_steps": 2,
                "skip_streak": 3, "repeat_window": 8, "max_repairs": 3},
    )
    kw.update(over)
    return kw


def test_bitflip_full_loop_detect_rollback_skip_finish(
        tmp_path, monkeypatch, no_persistent_compile_cache):
    """The acceptance drill, no supervisor involved: an SDC at step 9 is
    caught by the divergence probe, state rolls back to the ANCHORED
    save, the cursor skips the window, the repair row/report/goodput all
    book it, and the run finishes with finite loss."""
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=0,
                          divergence_every=2)
    state, losses = fit(
        _TinyMlp(), optax.adam(1e-2), DataLoader(_data(), 16),
        telemetry=cfg, chaos="bitflip@9",
        **_fit_kwargs(tmp_path, "BF"),
    )
    assert int(state.step) == 32
    assert all(math.isfinite(l) for l in losses)
    rows = _rows(tmp_path / "BF_telemetry_0.jsonl")
    div = [r for r in rows if r["kind"] == "divergence"]
    rep = [r for r in rows if r["kind"] == "repair"]
    assert div and div[0]["replica_divergence"] == 1
    assert len(rep) == 1
    r = rep[0]
    assert r["action"] == "rollback"
    assert r["cause"]["cause"] == "sdc_divergence"
    assert r["anchored"] is True
    # the anchor predates the flip: a save written while the SDC
    # incubated must never be the rollback target
    assert r["rollback_step"] <= 9
    # the skip actually skips: past the trigger by the policy window
    assert r["skip_to"] == r["skip_from"] + 4
    # losses: 32 scheduled steps minus the discarded span's resolved
    # rows plus nothing double-counted — every recorded loss is finite
    report = json.loads((tmp_path / "BF_report.json").read_text())
    assert report["status"] == "completed"
    assert [e["action"] for e in report["repairs"]] == ["rollback"]
    good = report["goodput"]
    assert good["repairs"] == 1
    assert good["repair_s"] > 0
    # partition stays exact with the new components
    parts = sum(good[k] for k in (
        "bringup_s", "restore_s", "compile_s", "cache_load_s",
        "data_wait_s", "checkpoint_s", "repair_s", "repair_replay_s",
        "productive_step_s",
    ))
    assert parts == pytest.approx(good["total_s"], rel=0.01)
    # the anchored step survived keep_last retention
    from tpudist.checkpoint import Checkpointer

    with Checkpointer(tmp_path / "BF_ckpt") as ck:
        assert ck.read_anchor() in ck.all_steps()


def test_nanburst_skip_streak_repairs_and_heals(
        tmp_path, monkeypatch, no_persistent_compile_cache):
    """Three consecutive poisoned steps defeat the single-step guard
    (each one is skipped, but the streak never ends inside the burst's
    window on a replay) — the skip-streak trigger rolls back and jumps
    PAST the burst, so the repaired run never sees those batches and
    finishes clean."""
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=0)
    state, losses = fit(
        _TinyMlp(), optax.adam(1e-2), DataLoader(_data(), 16),
        telemetry=cfg, chaos="nanburst:3@6",
        **_fit_kwargs(tmp_path, "NB"),
    )
    assert int(state.step) == 32
    rep = [r for r in _rows(tmp_path / "NB_telemetry_0.jsonl")
           if r["kind"] == "repair"]
    assert len(rep) == 1
    assert rep[0]["cause"]["cause"] == "skip_streak"
    assert rep[0]["cause"]["streak"] == 3
    # the burst window [7, 9] sits inside the skipped span
    assert rep[0]["rollback_step"] <= 6
    assert rep[0]["skip_to"] > 9
    # the tail of the run is clean: every loss after the repair finite
    assert all(math.isfinite(l) for l in losses[-10:])


def test_repair_equivalence_to_clean_reference(
        tmp_path, monkeypatch, no_persistent_compile_cache):
    """A chaos-poisoned run that auto-repairs must MATCH a clean
    reference run that simply never saw the skipped window. No dropout
    and no stochastic rounding → the repair salt legally changes
    nothing, so the pin is state-level EXACT (same compiled program,
    same data sequence: batches [0, A) then [S, N))."""
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=0,
                          divergence_every=2)
    data = _data()
    state, losses = fit(
        _TinyMlp(), optax.adam(1e-2), DataLoader(data, 16),
        telemetry=cfg, chaos="bitflip@9", seed=0,
        **_fit_kwargs(tmp_path, "EQ"),
    )
    rep = [r for r in _rows(tmp_path / "EQ_telemetry_0.jsonl")
           if r["kind"] == "repair"]
    assert len(rep) == 1
    anchor, skip_to = rep[0]["rollback_step"], rep[0]["skip_to"]

    # the reference: the same compiled-step config (telemetry +
    # guard_nonfinite change the program) driven by hand over the same
    # deterministic batch order, applying steps 1..anchor then
    # skip_to+1..N — the trajectory that never saw the skipped window
    from tpudist.train import (
        create_train_state, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh()
    tx = optax.adam(1e-2)
    init_input = jnp.zeros(
        (mesh_lib.data_parallel_size(mesh), 13), jnp.float32
    )
    ref = create_train_state(_TinyMlp(), 0, init_input, tx, mesh)
    step_fn = make_train_step(
        _TinyMlp(), tx, mesh, dropout_seed=0,
        telemetry=True, guard_nonfinite=True,
        state_sharding=state_shardings_of(ref),
    )
    batches = list(DataLoader(data, 16))
    spe, total = len(batches), 8 * len(batches)
    for g in list(range(1, anchor + 1)) + list(range(skip_to + 1, total + 1)):
        ref, _ = step_fn(ref, batches[(g - 1) % spe])

    for path, a, b in zip(
        jax.tree_util.tree_leaves_with_path(state.params),
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref.params),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=str(path)
        )
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(ref.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_repeat_trigger_exits_77_and_directive_resumes(
        tmp_path, monkeypatch, no_persistent_compile_cache):
    """Rung 3 in-process: a deterministic (@*-re-armed) SDC re-fires
    inside the repaired window → fit persists the rollback-and-skip
    directive and raises RepairRestart (SystemExit 77, restartable);
    the relaunched generation consumes the directive at bring-up
    (restores the ANCHOR, not the suspect newest save, and resumes past
    the wider skip)."""
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=0,
                          divergence_every=2)
    kw = _fit_kwargs(
        tmp_path, "RT", epochs=10, telemetry=cfg, chaos="bitflip@9@*",
        repair={"skip_window": 2, "anchor_clean_steps": 2,
                "repeat_window": 8, "max_repairs": 5},
    )
    loader = DataLoader(_data(), 16)
    with pytest.raises(RepairRestart) as ei:
        fit(_TinyMlp(), optax.adam(1e-2), loader, **kw)
    assert ei.value.code == 77
    blob = json.loads(
        (tmp_path / "RT_ckpt" / "tpudist_repair.json").read_text()
    )
    assert blob["pending"]["action"] == "restart"
    assert [e["action"] for e in blob["history"]] == ["rollback", "restart"]
    report = json.loads((tmp_path / "RT_report.json").read_text())
    assert report["status"] == "repair_restart"

    # generation 1 (the supervisor's relaunch): directive consumed, the
    # @* poison refires and the run keeps repairing within budget
    monkeypatch.setenv(GENERATION_ENV, "1")
    directive = dict(blob["pending"])
    try:
        state, _ = fit(_TinyMlp(), optax.adam(1e-2), loader, **kw)
        final = int(state.step)
    except RepairRestart:
        final = None  # escalated again before the budget — also valid
    blob = json.loads(
        (tmp_path / "RT_ckpt" / "tpudist_repair.json").read_text()
    )
    # the directive was consumed durably and a resume row was booked
    rows = _rows(tmp_path / "RT_telemetry_0.jsonl")
    resumes = [r for r in rows if r["kind"] == "repair"
               and r.get("action") == "resume"]
    assert len(resumes) == 1
    assert resumes[0]["rollback_step"] == directive["rollback_step"]
    assert resumes[0]["skip_to"] == directive["skip_to"]
    if final is not None:
        assert final == 40
