"""Elastic restart: cross-world-size checkpoint resharding
(tpudist/resilience/elastic.py + Checkpointer.restore(reshard=True) +
fit(elastic=True)) and the corrupt-checkpoint fallback walk — all
in-process on sub-meshes of the 8 fake CPU devices, so the ZeRO-1
pad-and-reshape relayout, the residual flush, the meta-validation
matrix, and the commit protocol are tier-1.

Tolerance note for the end-to-end trajectory pins: a resumed world of a
DIFFERENT size runs a different psum reduction tree and (under
reduce="quantized") folds different replica indices into the stochastic-
rounding stream, so post-resume losses track the same-data-order
reference within a documented tolerance, not bitwise — the BIT-exact pin
is the state-level one (`_logical_opt_state`: the resharded optimizer
mirrors equal the checkpoint's logical values exactly)."""

import json
import math

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import Mesh

from tpudist import mesh as mesh_lib
from tpudist.checkpoint import Checkpointer, latest_step
from tpudist.data.loader import DataLoader
from tpudist.optim import _zero1_layout, shard_state
from tpudist.resilience import GENERATION_ENV, Preempted
from tpudist.resilience.elastic import (
    ElasticRefusal,
    elastic_mismatch,
    refusal_reason,
    remap_step,
)
from tpudist.telemetry import TelemetryConfig
from tpudist.train import (
    create_train_state,
    fit,
    make_train_step,
    state_shardings_of,
)


def _mesh(n: int) -> Mesh:
    devs = np.array(jax.devices()[:n])
    return Mesh(
        devs.reshape(n, 1, 1, 1, 1, 1),
        (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS, mesh_lib.PIPELINE_AXIS,
         mesh_lib.EXPERT_AXIS, mesh_lib.SEQUENCE_AXIS,
         mesh_lib.TENSOR_AXIS),
    )


class _Mlp(nn.Module):
    """Layer widths chosen so the ZeRO-1 layout matrix is fully covered
    across worlds 4 and 8: (13,96)/(96,84) shard at both (96 divides 8),
    (84,35)=2940 has no 8-divisible dim (pad@8) but 84 divides 4
    (shard@4) — the classification-change case — and (35,10)/biases stay
    replicated below min_size."""

    @nn.compact
    def __call__(self, x, train=False):
        h = nn.relu(nn.Dense(96)(x))
        h = nn.relu(nn.Dense(84)(h))
        h = nn.relu(nn.Dense(35)(h))
        return nn.Dense(10)(h)


def _data(rows: int = 64):
    rng = np.random.default_rng(0)
    return {
        "image": rng.normal(size=(rows, 13)).astype(np.float32),
        "label": (rng.random(rows) * 10).astype(np.int32),
    }


def _build(world: int, *, reduce="quantized"):
    mesh = _mesh(world)
    tx = shard_state(optax.adam(1e-2), mesh)
    state = create_train_state(
        _Mlp(), 0, jnp.zeros((world, 13)), tx, mesh
    )
    step = make_train_step(
        _Mlp(), tx, mesh, reduce=reduce,
        state_sharding=state_shardings_of(state),
    )
    if step.grad_reducer is not None:
        state = step.grad_reducer.attach_residual(state)
    return mesh, tx, state, step


def _logical_opt_state(tx, state):
    """The stored opt state un-padded back to natural shapes on host —
    the world-size-free view both sides of a reshard must agree on
    bit-for-bit."""
    refs = jax.eval_shape(tx.inner.init, state.params)
    world = int(tx.mesh.shape[mesh_lib.DATA_AXIS])

    def restore(leaf, ref):
        mode, _ = _zero1_layout(ref.shape, world, 1024)
        x = np.asarray(leaf)
        if mode != "pad":
            return x
        return x.ravel()[: math.prod(ref.shape)].reshape(ref.shape)

    return jtu.tree_map(restore, state.opt_state, refs)


def _meta(world: int, spe: int = 4, **over) -> dict:
    m = {
        "steps_per_epoch": spe, "batch_size": 16, "world_size": 8,
        "grad_accum": 1, "shard_opt_state": True, "reduce": "quantized",
        "data_world": world,
    }
    m.update(over)
    return m


def _reshard_roundtrip(tmp_path, old_world, new_world):
    mesh_o, tx_o, state_o, step_o = _build(old_world)
    batch = {k: v[:16] for k, v in _data().items()}
    for _ in range(3):
        state_o, _ = step_o(state_o, step_o.stage(batch))
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.write_meta(_meta(old_world))
        ck.save(state_o, wait=True)

    mesh_n, tx_n, like, step_n = _build(new_world)
    events = []
    with Checkpointer(tmp_path / "ckpt") as ck:
        state_n = ck.restore(
            like=like, reshard=True, run_meta=_meta(new_world),
            mesh=mesh_n, on_event=events.append,
        )
    return tx_o, state_o, tx_n, state_n, step_n, events, batch


@pytest.mark.parametrize("old_world,new_world", [(8, 4), (4, 8)])
def test_zero1_reshard_roundtrip(tmp_path, old_world, new_world):
    """The exactness pin: after an 8→4 (and 4→8) reshard, params,
    batch-stats, and the LOGICAL values of every ZeRO-1 optimizer leaf —
    pad-and-reshape leaves un-padded, classification-change leaves
    included — are bit-identical to the checkpoint's; the residual banks
    come back zeroed at the NEW world's layout; and the restored state
    steps (the shardings really landed where the new step wants them)."""
    tx_o, state_o, tx_n, state_n, step_n, events, batch = (
        _reshard_roundtrip(tmp_path, old_world, new_world)
    )
    assert jtu.tree_all(jtu.tree_map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
        state_o.params, state_n.params,
    ))
    a = _logical_opt_state(tx_o, state_o)
    b = _logical_opt_state(tx_n, state_n)
    assert jtu.tree_all(jtu.tree_map(
        lambda x, y: x.shape == y.shape and bool((x == y).all()), a, b
    ))
    # residual: world-bound → flushed to zeros at the NEW layout
    res = np.asarray(state_n.comm_residual)
    assert res.shape[0] == new_world and not res.any()
    assert int(state_n.step) == int(state_o.step)
    (ev,) = [e for e in events if e["tag"] == "reshard"]
    assert ev["old_world"] == old_world and ev["new_world"] == new_world
    assert ev["residual_flushed"] is True
    assert ev["resharded_leaves"] >= 2  # the pad-layout mu/nu leaves moved
    # and the new world actually trains on the resharded state
    state_n, metrics = step_n(state_n, step_n.stage(batch))
    assert np.isfinite(float(metrics["loss"]))


def test_reshard_handles_non_divisible_leaves(tmp_path):
    """The (84,35) kernel is pad-stored at world 8 ([8,368], 4 zeros of
    tail padding) but naturally sharded at world 4 — the classification-
    change case where the flat prefix must be the logical leaf exactly."""
    tx_o, state_o, tx_n, state_n, _, events, _ = _reshard_roundtrip(
        tmp_path, 8, 4
    )
    (ev,) = [e for e in events if e["tag"] == "reshard"]
    assert "opt_state/0/mu/Dense_2/kernel" in ev["resharded"]
    mu_o = _logical_opt_state(tx_o, state_o)[0].mu
    mu_n = _logical_opt_state(tx_n, state_n)[0].mu
    k = "Dense_2"
    assert mu_n[k]["kernel"].shape == (84, 35)
    assert (mu_o[k]["kernel"] == mu_n[k]["kernel"]).all()


def test_meta_matrix_reshard_vs_refusal():
    """The validation matrix: world-shaped differences reshard, semantic
    differences refuse, equality is not a mismatch at all."""
    base = _meta(8)
    # pure world resize (device count, world_size, steps_per_epoch,
    # batch_size): valid elastic mismatches
    assert elastic_mismatch(base, _meta(4))
    assert elastic_mismatch(base, _meta(8, spe=8, world_size=4))
    assert elastic_mismatch(base, _meta(8, batch_size=8))
    # semantic changes: refused, with the offending keys named
    assert "reduce" in refusal_reason(base, _meta(8, reduce="none"))
    assert "shard_opt_state" in refusal_reason(
        base, {k: v for k, v in _meta(8).items() if k != "shard_opt_state"}
    )
    # unknown future keys default-deny
    assert "mystery" in refusal_reason(base, dict(base, mystery=1))
    # no difference → no mismatch
    assert not elastic_mismatch(base, dict(base))
    # legacy metas predate data_world: a pre-elastic checkpoint resuming
    # at its own unchanged geometry must MATCH (no refusal, no
    # gratuitous reshard-commit), while a real resize still mismatches
    from tpudist.resilience.elastic import meta_matches

    legacy = {k: v for k, v in base.items() if k != "data_world"}
    assert meta_matches(legacy, base)
    assert not elastic_mismatch(legacy, base)
    assert not meta_matches(legacy, _meta(4, world_size=4))
    assert elastic_mismatch(legacy, _meta(4, world_size=4))


def test_expert_world_resize_default_denied():
    """The expert axis is a MODEL axis: the expert-scattered FFN stacks
    were written under their placement and have no reshard path, so an
    expert_world resize refuses with the same named hint as fsdp/tensor/
    pipe — and a legacy meta (pre expert recording) compares as 1."""
    saved = _meta(8, expert_world=2)
    reason = refusal_reason(saved, _meta(8, expert_world=4))
    assert reason is not None
    assert "expert_world 2 -> 4" in reason
    assert "only the data axis is elastic" in reason
    # legacy meta (no expert_world) at an unchanged all-dense geometry:
    # no refusal; resumed onto an expert-split mesh: default-denied
    legacy = _meta(8)
    assert refusal_reason(legacy, _meta(8, expert_world=1)) is None
    reason = refusal_reason(legacy, _meta(8, expert_world=2))
    assert reason is not None and "expert_world 1 -> 2" in reason


def test_refused_reshard_raises_elastic_refusal(tmp_path):
    """A non-resize mismatch must raise the refusal — never be mistaken
    for corruption and silently walked past by the fallback."""
    mesh_o, _, state_o, step_o = _build(8)
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.write_meta(_meta(8))
        ck.save(state_o, wait=True)
    mesh_n, _, like, _ = _build(4)
    with Checkpointer(tmp_path / "ckpt") as ck:
        with pytest.raises(ElasticRefusal, match="reduce"):
            ck.restore(
                like=like, reshard=True, mesh=mesh_n, fallback=True,
                run_meta=_meta(4, reduce="none"),
            )


def test_remap_step_cursor():
    # same steps/epoch → identity (the fixed-global-batch drill)
    assert remap_step(6, _meta(8, spe=4), _meta(4, spe=4)) == (6, True)
    # halved global batch → doubled steps/epoch → doubled counter, exact
    assert remap_step(6, _meta(8, spe=4), _meta(4, spe=8)) == (12, True)
    # doubled global batch → halved counter, exact at even steps
    assert remap_step(6, _meta(4, spe=8), _meta(8, spe=4)) == (3, True)
    # inexact ratio rounds DOWN (re-consume the partial batch, never skip)
    step, exact = remap_step(5, _meta(4, spe=8), _meta(8, spe=4))
    assert (step, exact) == (2, False)
    # missing steps_per_epoch (unsized loader) degrades to identity
    assert remap_step(7, {"steps_per_epoch": None}, _meta(8)) == (7, True)


def _fit_kwargs(tmp_path, world, job_id, **kw):
    cfg = TelemetryConfig(sentry=False, mfu=False, heartbeat_every=4)
    return dict(
        epochs=4, mesh=_mesh(world), job_id=job_id, batch_size=16,
        log_dir=str(tmp_path), telemetry=cfg, profile=False,
        reduce="quantized", shard_opt_state=True, **kw,
    )


def test_fit_elastic_resumes_8_to_4(tmp_path, monkeypatch,
                                    no_persistent_compile_cache):
    """The acceptance drill in-process: an 8-device ZeRO-1 +
    quantized-AR run is preempted at step 6; ``fit(elastic=True)`` on a
    4-device mesh reshards, commits (old-geometry step dirs replaced by
    the new-world save), and runs to completion with the post-resume
    trajectory tracking the uninterrupted 8-device reference (same data
    order; tolerance documented in the module docstring — the first
    resumed step, computed from bit-identical params, is pinned tight).
    Cache-less via no_persistent_compile_cache: this jax 0.4.x XLA:CPU
    aborts executing persistent-cache-LOADED executables on the donated-
    step-on-restored-arrays pattern (test_preempt_fit's documented
    wart)."""
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    _, ref_losses = fit(
        _Mlp(), optax.adam(1e-2), DataLoader(_data(), 16),
        **_fit_kwargs(tmp_path, 8, "Ref"),
    )
    with pytest.raises(Preempted) as ei:
        fit(
            _Mlp(), optax.adam(1e-2), DataLoader(_data(), 16),
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=4,
            chaos="sigterm@6", **_fit_kwargs(tmp_path, 8, "EL"),
        )
    assert ei.value.step == 6

    # without elastic=True the resize still refuses, now with the hint
    with pytest.raises(ValueError, match="elastic=True"):
        fit(
            _Mlp(), optax.adam(1e-2), DataLoader(_data(), 16),
            checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=4,
            **_fit_kwargs(tmp_path, 4, "EL"),
        )

    monkeypatch.setenv(GENERATION_ENV, "1")
    state, losses = fit(
        _Mlp(), optax.adam(1e-2), DataLoader(_data(), 16),
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=4,
        chaos="sigterm@6", elastic=True,
        **_fit_kwargs(tmp_path, 4, "EL"),
    )
    assert int(state.step) == 16 and len(losses) == 10
    # step 7's loss is computed from the bit-identically restored params
    # (fp reduction order across 4-vs-8 devices is the only delta)
    assert losses[0] == pytest.approx(ref_losses[6], rel=1e-5)
    np.testing.assert_allclose(losses, ref_losses[6:], rtol=0.08)

    # the reshard was recorded, and the commit replaced the old-geometry
    # steps: everything on disk is new-world from the remapped step on
    rows = [
        json.loads(l)
        for l in (tmp_path / "EL_telemetry_0.jsonl").read_text().splitlines()
    ]
    reshard_rows = [r for r in rows if r["kind"] == "reshard"]
    assert len(reshard_rows) == 1
    assert reshard_rows[0]["old_world"] == 8
    assert reshard_rows[0]["new_world"] == 4
    steps_on_disk = sorted(
        int(d.name) for d in (tmp_path / "ckpt").iterdir()
        if d.is_dir() and d.name.isdigit()
    )
    assert min(steps_on_disk) >= 6 and max(steps_on_disk) == 16
    assert not (tmp_path / "ckpt" / "_pre_reshard").exists()
    report = json.loads((tmp_path / "EL_report.json").read_text())
    gens = report["goodput"]["generations"]
    assert [g["exit_reason"] for g in gens] == ["preempted", "completed"]
    assert gens[1]["restore_s"] > 0


def test_corrupt_checkpoint_falls_back_to_previous_step(tmp_path):
    """The satellite: a truncated newest step dir (the mid-write
    preemption shape, injected via the chaos helper) makes restore walk
    back to the previous saved step, emitting a checkpoint_fallback
    event — never poisoning the resume."""
    from tpudist.resilience.chaos import corrupt_latest_checkpoint

    mesh, tx, state, step = _build(8, reduce="none")
    batch = {k: v[:16] for k, v in _data().items()}
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.save(state, step=4, wait=True)
        for _ in range(2):
            state, _ = step(state, step.stage(batch))
        ck.save(state, step=8, wait=True)
        assert corrupt_latest_checkpoint(tmp_path / "ckpt") == 8
        assert ck.latest_step() == 8  # still points at the poisoned step

        _, _, like, _ = _build(8, reduce="none")
        events = []
        restored = ck.restore(
            like=like, fallback=True, on_event=events.append
        )
        assert int(restored.step) == 0  # the step-4 save held step 0's state
        (ev,) = [e for e in events if e["tag"] == "checkpoint_fallback"]
        assert ev["failed_step"] == 8 and ev["next_step"] == 4
        # without the fallback the corruption propagates
        with pytest.raises(Exception):
            ck.restore(like=like, step=8)
        # fit's cleanup: setting the torn step ASIDE (never deleting —
        # the failure may have been transient I/O) unblocks orbax's
        # monotonic save order (a cadence save at 6 < 8 was refused
        # while the corpse held latest_step)
        assert ck.save(state, step=6, wait=True) is False
        assert ck.quarantine_failed_step(8) is True
        assert ck.latest_step() == 4
        assert (tmp_path / "ckpt" / "_failed" / "8").is_dir()  # preserved
        assert ck.save(state, step=6, wait=True) is True
        assert ck.latest_step() == 6


def test_chaos_corrupt_spec_parses_and_fires(tmp_path):
    from tpudist.resilience import ChaosCrash, ChaosSpec, make_injector

    spec = ChaosSpec.parse("corrupt@3")
    assert spec.kind == "corrupt" and spec.step == 3
    mesh, _, state, _ = _build(4, reduce="none")
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.save(state, step=3, wait=True)
    sizes_before = {
        f: f.stat().st_size
        for f in (tmp_path / "ckpt" / "3").rglob("*") if f.is_file()
    }
    inj = make_injector("corrupt@3").bind(tmp_path / "ckpt")
    inj.generation = 0
    assert inj.maybe_fire(2) is False
    with pytest.raises(ChaosCrash, match="corrupted newest checkpoint"):
        inj.maybe_fire(3)
    # every file of the newest step really was truncated
    for f, before in sizes_before.items():
        assert f.stat().st_size == before // 2
    # unbound injector refuses loudly instead of corrupting nothing
    with pytest.raises(ChaosCrash, match="checkpoint_dir"):
        make_injector("corrupt@0").maybe_fire(0)


def test_atomic_meta_write_replaces_not_truncates(tmp_path, monkeypatch):
    """write_meta goes through tmp + os.replace: a crash mid-write can
    leave a stray tmp file but NEVER a torn tpudist_meta.json."""
    import os

    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.write_meta({"world_size": 8})
        assert ck.read_meta() == {"world_size": 8}

        real_replace = os.replace
        calls = []

        def spy(src, dst):
            calls.append((str(src), str(dst)))
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", spy)
        ck.write_meta({"world_size": 4})
        assert ck.read_meta() == {"world_size": 4}
        assert any(dst.endswith("tpudist_meta.json") for _, dst in calls)
        # the interrupted-write shape: the target never sees partial text
        monkeypatch.setattr(
            os, "replace",
            lambda *a: (_ for _ in ()).throw(OSError("disk gone")),
        )
        with pytest.raises(OSError):
            ck.write_meta({"world_size": 2})
        assert ck.read_meta() == {"world_size": 4}  # old meta intact
        leftovers = list((tmp_path / "ckpt").glob(".tpudist_meta.*"))
        assert leftovers == []  # tmp cleaned up on the failure path


def test_interrupted_reshard_commit_rolls_back(tmp_path):
    """Crash-window drill for the commit protocol: quarantined old steps
    with NO new-world save yet must roll back to a restorable directory
    (recover_interrupted_reshard), and a clean directory reports no
    interrupted commit."""
    mesh, tx, state, _ = _build(4, reduce="none")
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.save(state, step=4, wait=True)
        ck.quarantine_steps(commit_meta=_meta(8))  # ... process dies here
        assert ck.latest_step() is None
    with Checkpointer(tmp_path / "ckpt") as ck:
        assert ck.recover_interrupted_reshard() == "rolled_back"
        assert ck.latest_step() == 4
        # nothing left to recover
        assert ck.recover_interrupted_reshard() is None
        _, _, like, _ = _build(4, reduce="none")
        restored = ck.restore(like=like)
        assert int(restored.step) == 0


def test_interrupted_commit_after_save_adopts_marker_meta(tmp_path):
    """The other crash window: the barrier-save LANDED but the meta flip
    did not. The next bring-up must adopt the commit marker's meta — NOT
    re-reshard the already-new-world checkpoint (which would
    double-remap the cursor and collide the quarantine rename with the
    occupied step number)."""
    mesh, tx, state, _ = _build(4, reduce="none")
    new_meta = _meta(4, world_size=4)
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.write_meta(_meta(8))  # the OLD geometry
        ck.save(state, step=4, wait=True)
        ck.quarantine_steps(commit_meta=new_meta)
        ck.save(state, step=4, wait=True)  # the new-world barrier-save
        # ... and the process dies BEFORE write_meta(new_meta)
        assert ck.read_meta() == _meta(8)
    with Checkpointer(tmp_path / "ckpt") as ck:
        assert ck.recover_interrupted_reshard() == "completed"
        # the live step is now correctly described by the marker's meta
        # and the quarantine (old dirs + marker) is gone
        assert ck.read_meta() == new_meta
        assert ck.latest_step() == 4
        assert not (tmp_path / "ckpt" / "_pre_reshard").exists()
        # a second bring-up sees a clean, consistent directory
        assert ck.recover_interrupted_reshard() is None


def test_aot_step_routes_ragged_tail_to_jit(tmp_path,
                                            no_persistent_compile_cache):
    """A drop_remainder=False loader's short final batch must not kill a
    compile_cache run: the AOT wrapper routes off-shape batches to the
    jit path per call and keeps the executable for full batches."""
    from tpudist import compile_cache as cc_mod

    mesh, tx, state, step = _build(8, reduce="none")
    full = {k: v[:16] for k, v in _data().items()}
    ragged = {k: v[:8] for k, v in _data().items()}
    staged_full = step.stage(full)
    exe = step.jitted.lower(state, staged_full).compile()
    wrapped = cc_mod.wrap_step(step, exe, expected_batch=staged_full)
    state, m1 = wrapped(state, full)     # validates the executable
    state, m2 = wrapped(state, ragged)   # off-shape → jit, not a crash
    state, m3 = wrapped(state, full)     # back on the executable
    assert all(np.isfinite(float(m["loss"])) for m in (m1, m2, m3))
    assert wrapped.aot["exe"] is not None  # never demoted
