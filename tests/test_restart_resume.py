"""The crash-recovery story end-to-end: a training process dies mid-run,
the launcher's --max_restarts relaunches the world, and fit() resumes from
the last checkpoint at the exact step — losses continue, no data is
re-trained or skipped (tpudist/launch.py + tpudist/train.py + checkpoint)."""

import json
import os
import subprocess
import sys
import textwrap
import pytest

pytestmark = pytest.mark.slow  # subprocess world: cold-compiles its own jax programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, os, sys

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpudist import create_mesh, init_from_env
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.data.loader import DataLoader
    from tpudist.models import resnet18
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = create_mesh()
    out_dir = os.environ["OUT_DIR"]
    crash_marker = os.path.join(out_dir, "crashed_once")

    from tpudist.checkpoint import latest_step

    ckpt_dir = os.path.join(out_dir, "ckpt")

    class CrashingLoader(DataLoader):
        # first generation: hard-die mid-run, deterministically AFTER a
        # checkpoint is durable on disk (gating on latest_step avoids any
        # race with the async save) and before the run completes
        def iter_from(self, start_batch):
            for i, b in enumerate(super().iter_from(start_batch), start=start_batch):
                yield b
                if (
                    not os.path.exists(crash_marker)
                    and latest_step(ckpt_dir) is not None
                ):
                    open(crash_marker, "w").close()
                    os.kill(os.getpid(), 9)  # hard kill, no cleanup

    data = synthetic_cifar(8 * 16, num_classes=10)  # 16 batches/epoch
    loader = CrashingLoader(data, 8, transform=to_tensor)
    model = resnet18(num_classes=10, small_inputs=True)
    state, losses = fit(
        model, optax.adam(1e-3), loader,
        epochs=2, mesh=mesh, profile=False,
        job_id="Crash", log_dir=out_dir,
        checkpoint_dir=ckpt_dir, checkpoint_every=4,
    )
    with open(os.path.join(out_dir, f"done_{ctx.process_index}.json"), "w") as f:
        json.dump({"final_step": int(state.step), "n_losses": len(losses)}, f)
""")


_RESILIENCE_CHILD = textwrap.dedent("""
    import json, os

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import optax
    from flax import linen as nn

    from tpudist import create_mesh, init_from_env
    from tpudist.data.loader import DataLoader
    from tpudist.telemetry import TelemetryConfig
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = create_mesh()
    out = os.environ["OUT_DIR"]

    class TinyMlp(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(10)(nn.relu(nn.Dense(37)(x)))

    rng = np.random.default_rng(0)
    data = {
        "image": rng.normal(size=(64, 13)).astype(np.float32),
        "label": (rng.random(64) * 10).astype(np.int32),
    }
    # per-process disjoint rows in a multi-process world; the full set in
    # a single-process one (16 steps either way: 4 epochs x 4 batches of
    # the global batch 16)
    rows = {k: v[ctx.process_index::ctx.process_count] for k, v in data.items()}
    loader = DataLoader(rows, 16 // ctx.process_count)
    cfg = TelemetryConfig(
        sentry=False, mfu=False, heartbeat_every=4,
        hang_timeout_s=float(os.environ.get("HANG_TIMEOUT_S", 0)) or None,
        hang_action=os.environ.get("HANG_ACTION", "report"),
        # the repair/SDC drills need the replica-divergence probe
        divergence_every=int(os.environ.get("DIV_EVERY", 0) or 0),
    )
    state, losses = fit(
        TinyMlp(), optax.adam(1e-2), loader,
        epochs=int(os.environ.get("EPOCHS", 4)), mesh=mesh, profile=False,
        job_id="SP", log_dir=out, batch_size=16,
        world_size=ctx.world_size, global_rank=ctx.process_index,
        telemetry=cfg,
        checkpoint_dir=os.path.join(out, "ckpt"),
        checkpoint_every=int(os.environ.get("CKPT_EVERY", 4)),
        chaos=os.environ.get("CHAOS") or None,
        # the self-healing drills: rollback-and-skip repair loop
        repair=(json.loads(os.environ["REPAIR"])
                if os.environ.get("REPAIR") else None),
        # the elastic/warm-start drills: cross-world resume + AOT cache
        reduce=os.environ.get("REDUCE", "none"),
        shard_opt_state=bool(os.environ.get("SHARD_OPT")),
        elastic=bool(os.environ.get("ELASTIC")),
        compile_cache=os.environ.get("COMPILE_CACHE") or None,
    )
    # only the generation that runs to completion reaches this line (a
    # preempted/hung generation exits 75/76 from inside fit)
    with open(os.path.join(out, f"done_{ctx.process_index}.json"), "w") as f:
        json.dump({
            "final_step": int(state.step),
            "n_losses": len(losses),
            "generation": int(os.environ.get("TPUDIST_RESTART_GENERATION", -1)),
            "losses": [float(l) for l in losses],
        }, f)
""")


def _launch_resilience_child(tmp_path, env_extra, launch_args, timeout=600):
    script = tmp_path / "child.py"
    script.write_text(_RESILIENCE_CHILD)
    env = dict(os.environ)
    env["OUT_DIR"] = str(tmp_path)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra)
    return subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch", *launch_args,
            f"--master_port={29500 + os.getpid() % 499 + 1}",
            str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )


def test_chaos_sigterm_supervised_resume(tmp_path):
    """The preemption drill through the REAL supervisor: generation 0
    traps the chaos SIGTERM after step 6, writes its emergency checkpoint
    and exits 75; the launcher restarts it (max_restarts=0 — the
    restartable fast path needs no crash budget) with generation=1, which
    resumes at step 7 and completes. The report aggregates both lives."""
    r = _launch_resilience_child(
        tmp_path, {"CHAOS": "sigterm@6"},
        ["--nproc_per_node=1", "--emulate-devices=4", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rc=75 (restartable); restarting generation 1" in r.stderr
    done = json.loads((tmp_path / "done_0.json").read_text())
    assert (done["final_step"], done["n_losses"], done["generation"]) == (
        16, 10, 1)

    report = json.loads((tmp_path / "SP_report.json").read_text())
    assert report["generation"] == 1
    assert report["exit_reason"] == "completed"
    gens = report["goodput"]["generations"]
    assert [g["generation"] for g in gens] == [0, 1]
    assert gens[0]["exit_reason"] == "preempted"
    assert gens[0]["emergency_save_s"] > 0
    assert report["goodput"]["cumulative"]["restart_overhead_s"] > 0
    # both lives share the append-mode telemetry stream, attributable by
    # the heartbeat generation field
    rows = [
        json.loads(l)
        for l in (tmp_path / "SP_telemetry_0.jsonl").read_text().splitlines()
    ]
    assert {r_["generation"] for r_ in rows if r_["kind"] == "heartbeat"} == {0, 1}


def test_watchdog_exit_escalation_supervised_restart(tmp_path):
    """Detection → forensics → recovery, end to end: a chaos hang at step
    5 trips the watchdog (1 s deadline), hang_action='exit' terminates the
    wedged generation with 76 AFTER the crash file lands, the supervisor
    relaunches, and generation 1 resumes from the step-4 checkpoint to
    completion."""
    r = _launch_resilience_child(
        tmp_path,
        {"CHAOS": "hang:120@5", "HANG_TIMEOUT_S": "1.0",
         "HANG_ACTION": "exit"},
        ["--nproc_per_node=1", "--emulate-devices=4", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rc=76 (restartable); restarting generation 1" in r.stderr
    crash = json.loads((tmp_path / "SP_crash_0.json").read_text())
    assert crash["trip"]["timeout_s"] == 1.0
    done = json.loads((tmp_path / "done_0.json").read_text())
    assert done["final_step"] == 16 and done["generation"] == 1
    # generation 1 resumed from the last cadence checkpoint (step 4):
    # the hung steps 5 re-ran, nothing before 4 did
    assert done["n_losses"] == 12
    report = json.loads((tmp_path / "SP_report.json").read_text())
    assert report["exit_reason"] == "completed"
    assert [g["exit_reason"] for g in report["goodput"]["generations"]] == [
        "hang", "completed"
    ]


def test_deterministic_crash_exhausts_restart_budget(tmp_path):
    """The circuit breaker: a world that dies identically every generation
    must exhaust the rolling restart budget and exit non-zero — never spin
    (even with a huge --max_restarts)."""
    script = tmp_path / "crashy.py"
    script.write_text("import sys; sys.exit(9)\n")
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch", "--nproc_per_node=1",
            "--max_restarts=100", "--restart_budget=2",
            "--restart_window=600", "--backoff_base=0.05",
            "--backoff_max=0.1", str(script),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 9
    assert r.stderr.count("restarting") == 2
    assert "restart budget exhausted" in r.stderr


# the 2-process children execute real cross-process SPMD programs, which
# jax 0.4.x's XLA:CPU refuses outright — the same container limitation
# that gates test_multiproc_fit/test_multiproc_health; green on current jax
_OLD_JAX = tuple(
    int(p) for p in __import__("jax").__version__.split(".")[:2]
) < (0, 5)


@pytest.mark.skipif(
    _OLD_JAX, reason="jax 0.4.x XLA:CPU cannot execute multi-process "
    "computations (the children die in create_train_state before any "
    "resilience code runs); current jax runs the 2-process world"
)
def test_chaos_sigterm_two_process_world_resumes(tmp_path):
    """The preemption drill on a 2-process emulated world: every rank's
    chaos injector self-SIGTERMs at the same lockstep step boundary, both
    write their shards of the emergency checkpoint, both exit 75, and the
    supervised relaunch resumes the world at k+1 to completion."""
    r = _launch_resilience_child(
        tmp_path, {"CHAOS": "sigterm@6"},
        ["--nproc_per_node=2", "--emulate-devices=2", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restarting generation 1" in r.stderr
    done = json.loads((tmp_path / "done_0.json").read_text())
    assert (done["final_step"], done["n_losses"], done["generation"]) == (
        16, 10, 1)
    report = json.loads((tmp_path / "SP_report.json").read_text())
    assert report["generation"] == 1
    assert report["goodput"]["generations"][0]["exit_reason"] == "preempted"


def test_repair_restart_escalation_and_budget_circuit_breaker(tmp_path):
    """The self-healing ladder under the REAL supervisor, against a
    DETERMINISTIC poison (``bitflip@5@*`` re-arms after every repair and
    every relaunch): generation 0 repairs in-process once (rollback to
    the anchored save + skip), the re-poisoned state re-triggers inside
    the repeat window → exit 77 with a durable rollback-and-skip
    directive; the supervisor relaunches on the restartable fast path;
    generation 1 consumes the directive, the poison bites again, and the
    rolling repair budget (max_repairs=2) circuit-breaks the job to a
    NON-ZERO exit instead of spinning forever."""
    r = _launch_resilience_child(
        tmp_path,
        {
            "CHAOS": "bitflip@5@*",
            "DIV_EVERY": "2",
            "CKPT_EVERY": "2",
            "EPOCHS": "10",
            "REPAIR": json.dumps({
                "skip_window": 2, "anchor_clean_steps": 5,
                "repeat_window": 8, "max_repairs": 2,
            }),
        },
        ["--nproc_per_node=1", "--emulate-devices=4", "--max_restarts=0"],
    )
    # the circuit breaker turned the deterministic poison into a
    # terminal non-zero exit — never rc 0, never an endless 77 loop
    assert r.returncode != 0, r.stdout + r.stderr
    assert "rc=77 (restartable); restarting generation 1" in r.stderr
    blob = json.loads(
        (tmp_path / "ckpt" / "tpudist_repair.json").read_text()
    )
    actions = [e["action"] for e in blob["history"]]
    assert "rollback" in actions and "restart" in actions
    # every rollback targeted a PRE-flip save: the anchored retention
    # never handed back a checkpoint written while the SDC incubated
    assert all(e["rollback_step"] <= 5 for e in blob["history"])
    report = json.loads((tmp_path / "SP_report.json").read_text())
    assert report["status"] == "crashed:RepairExhausted"
    assert report["generation"] == 1
    # one file reconstructs the incident timeline: the full repair
    # history plus the supervisor's per-generation exit codes
    assert [e["action"] for e in report["repairs"]] == actions
    assert report["supervisor_exit_history"] == [77]


def test_crash_restart_resumes_from_checkpoint(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["OUT_DIR"] = str(tmp_path)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch",
            "--nproc_per_node=1", "--emulate-devices=4",
            f"--master_port={29500 + os.getpid() % 499 + 1}",
            "--max_restarts=1", str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restarting (1/1)" in r.stderr
    assert (tmp_path / "crashed_once").exists()
    got = json.loads((tmp_path / "done_0.json").read_text())
    # 2 epochs × 16 batches: the full run always ends at step 32
    assert got["final_step"] == 32, got
    # the relaunched fit() resumed from a durable checkpoint (multiple of
    # checkpoint_every=4, at least step 4) — NOT a from-scratch retrain
    assert got["n_losses"] < 32, got
    assert got["n_losses"] % 4 == 0, got


def test_elastic_supervised_resume_on_halved_world(tmp_path):
    """The elastic drill: generation 0 runs ZeRO-1 + quantized-AR on 8
    emulated devices and is chaos-SIGTERM'd after step 6; the launcher's
    per-generation ``--emulate-devices=8,4`` relaunches generation 1 on a
    HALVED world, where ``fit(elastic=True)`` reshards the checkpoint
    onto the 4-device mesh and completes. Losses after the resume track
    an uninterrupted same-data-order reference run within tolerance
    (rtol 0.08 — a resized world runs a different psum tree and draws
    different stochastic-rounding bits; the tier-1 state-level pin in
    test_elastic.py is exact)."""
    # reference: the same child, uninterrupted, on the original 8 devices
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    r = _launch_resilience_child(
        ref_dir, {"REDUCE": "quantized", "SHARD_OPT": "1"},
        ["--nproc_per_node=1", "--emulate-devices=8", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    ref = json.loads((ref_dir / "done_0.json").read_text())
    assert ref["n_losses"] == 16

    r = _launch_resilience_child(
        tmp_path,
        {"CHAOS": "sigterm@6", "REDUCE": "quantized", "SHARD_OPT": "1",
         "ELASTIC": "1"},
        ["--nproc_per_node=1", "--emulate-devices=8,4", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rc=75 (restartable); restarting generation 1" in r.stderr
    done = json.loads((tmp_path / "done_0.json").read_text())
    # global batch is device-count-free here (fixed 16-row loader), so
    # the cursor remap is identity: 10 steps remain after the resume
    assert (done["final_step"], done["n_losses"], done["generation"]) == (
        16, 10, 1)
    import numpy as np

    np.testing.assert_allclose(
        done["losses"], ref["losses"][6:], rtol=0.08
    )
    # the reshard really happened (and onto the halved world)
    rows = [
        json.loads(l)
        for l in (tmp_path / "SP_telemetry_0.jsonl").read_text().splitlines()
    ]
    (reshard,) = [r_ for r_ in rows if r_["kind"] == "reshard"]
    assert reshard["old_world"] == 8 and reshard["new_world"] == 4
    assert reshard["residual_flushed"] is True
    report = json.loads((tmp_path / "SP_report.json").read_text())
    assert [g["exit_reason"] for g in report["goodput"]["generations"]] == [
        "preempted", "completed"
    ]


def test_chaos_corrupt_supervised_fallback_resume(tmp_path):
    """The corrupt@step drill end-to-end: at step 7 the injector settles
    the async saves, truncates the newest checkpoint (step 6), and
    crashes — the torn-dir shape of dying mid-write. The supervised
    relaunch (a crash, so it needs --max_restarts) finds step 6
    undeserializable, falls back to step 4 with a checkpoint_fallback
    warning row, and completes: 12 post-resume steps, nothing before 4
    re-trained."""
    r = _launch_resilience_child(
        tmp_path, {"CHAOS": "corrupt@7", "CKPT_EVERY": "2"},
        ["--nproc_per_node=1", "--emulate-devices=4", "--max_restarts=1"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restarting (1/1)" in r.stderr
    done = json.loads((tmp_path / "done_0.json").read_text())
    assert done["final_step"] == 16 and done["generation"] == 1
    assert done["n_losses"] == 12  # resumed from 4, not the corrupted 6
    rows = [
        json.loads(l)
        for l in (tmp_path / "SP_telemetry_0.jsonl").read_text().splitlines()
    ]
    fallbacks = [
        r_ for r_ in rows
        if r_["kind"] == "warning" and r_.get("tag") == "checkpoint_fallback"
    ]
    assert fallbacks and fallbacks[0]["failed_step"] == 6
    assert fallbacks[0]["next_step"] == 4


def test_warm_cache_supervised_restart_skips_compile(tmp_path):
    """The warm-restart drill: with ``compile_cache`` set, generation 0
    misses (AOT-compiles at bring-up and stores the executable) and the
    relaunched generation 1 hits — its goodput books cache_load_s with
    compile_s == 0 (iteration 1 was an ordinary step, not a mislabeled
    compile), which is the accounting the bench's cold-vs-warm A/B
    records."""
    r = _launch_resilience_child(
        tmp_path,
        {"CHAOS": "sigterm@6", "COMPILE_CACHE": str(tmp_path / "cc")},
        ["--nproc_per_node=1", "--emulate-devices=4", "--max_restarts=0"],
    )
    assert r.returncode == 0, r.stdout + r.stderr
    done = json.loads((tmp_path / "done_0.json").read_text())
    assert (done["final_step"], done["generation"]) == (16, 1)
    rows = [
        json.loads(l)
        for l in (tmp_path / "SP_telemetry_0.jsonl").read_text().splitlines()
    ]
    cc_rows = [r_ for r_ in rows if r_["kind"] == "compile_cache"]
    assert [r_["hit"] for r_ in cc_rows] == [False, True]
    assert cc_rows[1]["compile_s"] == 0 and cc_rows[1]["load_s"] > 0
    report = json.loads((tmp_path / "SP_report.json").read_text())
    gen0, gen1 = report["goodput"]["generations"]
    assert gen0["warm_start"] is False and gen0["compile_s"] > 0
    assert gen1["warm_start"] is True
    assert gen1["compile_s"] == 0
    # goodput books the non-overlapped join wait (may be ~0 when the
    # load hid entirely behind the restore); the row's load_s is the
    # deserialization itself — and it must undercut the cold compile,
    # which is the drill's whole point
    assert gen1["cache_load_s"] >= 0
    assert 0 < cc_rows[1]["load_s"] < gen0["compile_s"]
