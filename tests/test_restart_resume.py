"""The crash-recovery story end-to-end: a training process dies mid-run,
the launcher's --max_restarts relaunches the world, and fit() resumes from
the last checkpoint at the exact step — losses continue, no data is
re-trained or skipped (tpudist/launch.py + tpudist/train.py + checkpoint)."""

import json
import os
import subprocess
import sys
import textwrap
import pytest

pytestmark = pytest.mark.slow  # subprocess world: cold-compiles its own jax programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, os, sys

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpudist import create_mesh, init_from_env
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.data.loader import DataLoader
    from tpudist.models import resnet18
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = create_mesh()
    out_dir = os.environ["OUT_DIR"]
    crash_marker = os.path.join(out_dir, "crashed_once")

    from tpudist.checkpoint import latest_step

    ckpt_dir = os.path.join(out_dir, "ckpt")

    class CrashingLoader(DataLoader):
        # first generation: hard-die mid-run, deterministically AFTER a
        # checkpoint is durable on disk (gating on latest_step avoids any
        # race with the async save) and before the run completes
        def iter_from(self, start_batch):
            for i, b in enumerate(super().iter_from(start_batch), start=start_batch):
                yield b
                if (
                    not os.path.exists(crash_marker)
                    and latest_step(ckpt_dir) is not None
                ):
                    open(crash_marker, "w").close()
                    os.kill(os.getpid(), 9)  # hard kill, no cleanup

    data = synthetic_cifar(8 * 16, num_classes=10)  # 16 batches/epoch
    loader = CrashingLoader(data, 8, transform=to_tensor)
    model = resnet18(num_classes=10, small_inputs=True)
    state, losses = fit(
        model, optax.adam(1e-3), loader,
        epochs=2, mesh=mesh, profile=False,
        job_id="Crash", log_dir=out_dir,
        checkpoint_dir=ckpt_dir, checkpoint_every=4,
    )
    with open(os.path.join(out_dir, f"done_{ctx.process_index}.json"), "w") as f:
        json.dump({"final_step": int(state.step), "n_losses": len(losses)}, f)
""")


def test_crash_restart_resumes_from_checkpoint(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["OUT_DIR"] = str(tmp_path)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch",
            "--nproc_per_node=1", "--emulate-devices=4",
            f"--master_port={29500 + os.getpid() % 499 + 1}",
            "--max_restarts=1", str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restarting (1/1)" in r.stderr
    assert (tmp_path / "crashed_once").exists()
    got = json.loads((tmp_path / "done_0.json").read_text())
    # 2 epochs × 16 batches: the full run always ends at step 32
    assert got["final_step"] == 32, got
    # the relaunched fit() resumed from a durable checkpoint (multiple of
    # checkpoint_every=4, at least step 4) — NOT a from-scratch retrain
    assert got["n_losses"] < 32, got
    assert got["n_losses"] % 4 == 0, got
