"""Multi-chip serving: the tensor-sharded engine must be a *transparent*
deployment knob (docs/SERVING.md §7).

Two layers of evidence, mirroring the acceptance criteria:

- **kernel shard parity** — the paged Pallas decode-attention kernel
  wrapped in ``shard_map`` over the ``tensor`` axis against the dense
  gather-GEMM oracle, for MHA (GPT-2 shape) and GQA (Llama shape). The
  sharded kernel is exact per shard (softmax completes per head, heads
  split across chips), so the bar is the ordinary kernel-parity one.
- **engine bit-identity** — greedy continuous-batching output of a
  ``ServeEngine(mesh=...)`` on an emulated ``tensor=2`` mesh must equal
  the single-chip engine token-for-token: contiguous and paged caches,
  speculative decoding on and off, GQA, under slot pressure with real
  preemptions, and through the AOT compile cache (cold and warm).

Greedy argmax absorbs the ULP-level float differences that sharded
matmul-reduction ordering introduces, so "identical token stream" is the
honest cross-topology contract — the same one docs/SERVING.md §7 states.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from tpudist import mesh as mesh_lib
from tpudist.models.gpt2 import GPT2
from tpudist.models.llama import Llama
from tpudist.ops.decode import paged_decode_attention
from tpudist.serve import ServeEngine

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-virtual-device mesh"
)


def _mesh(tensor=2):
    """Mesh of exactly ``tensor`` devices: SPMD programs over 2 devices
    compile measurably faster than over all 8 (the leftover axes would
    only add pure replication), and the thing under test is the tensor
    split, not the data axis."""
    return mesh_lib.create_mesh(mesh_lib.MeshConfig(tensor=tensor),
                                devices=jax.devices()[:tensor])


def _gpt2(**kw):
    return GPT2(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                num_heads=4, **kw)


def _llama(num_heads=4, kv=2):
    return Llama(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                 num_heads=num_heads, num_kv_heads=kv, ffn_dim=64)


def _params(model, seed=0):
    return nn.meta.unbox(model.init(
        jax.random.key(seed), np.zeros((1, 8), np.int32), train=False,
    )["params"])


def _prompts(n, lo=3, hi=9, seed=5):
    """Mixed lengths inside ONE prefill bucket (<=8): the sharded prefill
    program is the expensive compile, and one bucket per engine keeps
    each A/B pair inside the tier-1 budget."""
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(1, 64, rng.integers(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _drive(model, params, prompts, max_new=8, **kw):
    eng = ServeEngine(model, params, max_slots=2, seed=0, **kw)
    for p in prompts:
        eng.submit(p, max_new)
    return eng.run(), eng


def _assert_identical(base, shard):
    assert set(base) == set(shard)
    for r in base:
        assert base[r] == shard[r], f"request {r}: {base[r]} != {shard[r]}"


# ---------------------------------------------------------------------------
# paged kernel shard parity: sharded Pallas vs single-chip dense oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h,h_kv", [(4, 4), (4, 2)],
                         ids=["mha-gpt2", "gqa-llama"])
def test_paged_kernel_shard_parity(kernel_parity, h, h_kv):
    """shard_map(kernel) over tensor=2 == dense gather-GEMM oracle, for
    both the MHA and the GQA head layout (heads shard, GQA ratio is
    preserved per chip)."""
    mesh = _mesh()
    rng = np.random.Generator(np.random.PCG64(3))
    b, dh, bs, nb, mb = 3, 8, 8, 16, 4
    q = jnp.asarray(rng.standard_normal((b, 1, h, dh)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((nb, h_kv, bs, dh)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((nb, h_kv, bs, dh)), jnp.float32)
    bt = rng.integers(1, nb, (b, mb)).astype(np.int32)
    pos = np.array([5, 17, 30], np.int32)
    ref = paged_decode_attention(q, kp, vp, bt, pos, impl="xla")
    out = paged_decode_attention(q, kp, vp, bt, pos, impl="paged", mesh=mesh)
    kernel_parity(out, ref)


def test_paged_kernel_mesh_fallback_when_indivisible():
    """A mesh whose tensor world does not divide the KV heads must fall
    back to the unsharded kernel path, not crash: the op is best-effort,
    the ENGINE is where the loud refusal lives."""
    mesh = _mesh(tensor=4)
    rng = np.random.Generator(np.random.PCG64(4))
    q = jnp.asarray(rng.standard_normal((2, 1, 4, 8)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((8, 2, 8, 8)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((8, 2, 8, 8)), jnp.float32)
    bt = rng.integers(1, 8, (2, 2)).astype(np.int32)
    pos = np.array([3, 9], np.int32)
    ref = paged_decode_attention(q, kp, vp, bt, pos, impl="xla")
    out = paged_decode_attention(q, kp, vp, bt, pos, impl="paged", mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# engine bit-identity: sharded vs single-chip, greedy token streams
# ---------------------------------------------------------------------------


def test_contiguous_engine_bit_identity():
    model = _gpt2(attn_impl="xla")
    params = _params(model)
    prompts = _prompts(4)
    base, _ = _drive(model, params, prompts)
    shard, eng = _drive(model, params, prompts, mesh=_mesh())
    _assert_identical(base, shard)
    assert eng.tensor_world == 2


@pytest.mark.parametrize(
    "attn_impl",
    ["paged",
     # the dense-oracle path adds a second full A/B for GSPMD-only
     # coverage the contiguous test already exercises — keep it out of
     # the tier-1 window
     pytest.param("xla", marks=pytest.mark.slow)],
)
def test_paged_engine_bit_identity(attn_impl):
    """Paged pool sharded on the KV-head dim: both the shard_map'd Pallas
    kernel path and the pure-GSPMD dense oracle path must reproduce the
    single-chip stream."""
    model = _gpt2(attn_impl=attn_impl)
    params = _params(model)
    prompts = _prompts(4)
    kw = {"paged": True, "block_size": 8, "n_blocks": 24}
    base, _ = _drive(model, params, prompts, **kw)
    shard, _ = _drive(model, params, prompts, mesh=_mesh(), **kw)
    _assert_identical(base, shard)


@pytest.mark.slow
def test_llama_gqa_paged_engine_bit_identity():
    """GQA: h_kv=2 splits one KV head per chip while h=4 splits two query
    heads per chip — the ratio the per-shard kernel relies on (the cheap
    kernel-level GQA parity test stays tier-1; this full engine A/B is
    the slow-tier double-check)."""
    model = _llama()
    params = _params(model, seed=1)
    prompts = _prompts(4)
    kw = {"paged": True, "block_size": 8, "n_blocks": 24}
    base, _ = _drive(model, params, prompts, **kw)
    shard, _ = _drive(model, params, prompts, mesh=_mesh(), **kw)
    _assert_identical(base, shard)


@pytest.mark.parametrize("paged", [False, True], ids=["contiguous", "paged"])
def test_spec_engine_bit_identity(paged):
    """Speculative decoding composes: draft and target both run sharded,
    the bulk verify sweep included, and greedy accept/reject decisions
    (hence the whole stream) match the single-chip engine."""
    model = _gpt2()
    params = _params(model)
    draft = GPT2(vocab_size=64, max_seq_len=64, hidden_dim=16, depth=1,
                 num_heads=4)
    dparams = _params(draft, seed=2)
    prompts = _prompts(4)
    kw = dict(draft_model=draft, draft_params=dparams, spec_k=3)
    if paged:
        kw.update(paged=True, block_size=8, n_blocks=24)
    base, _ = _drive(model, params, prompts, **kw)
    shard, _ = _drive(model, params, prompts, mesh=_mesh(), **kw)
    _assert_identical(base, shard)


def test_preemption_pressure_bit_identity():
    """Slot pressure with REAL preemptions: admission only reserves the
    prompt's worst case, so a tight pool with no decode watermark runs
    dry mid-decode and preempts to the queue. The sharded engine must
    preempt/replay its way to the same token streams."""
    model = _gpt2()
    params = _params(model)
    prompts = _prompts(5, lo=4, hi=9, seed=9)

    def pressure(**kw):
        eng = ServeEngine(model, params, max_slots=3, seed=0, paged=True,
                          block_size=4, n_blocks=12, prefix_cache=False,
                          watermark_blocks=0, **kw)
        for p in prompts:
            eng.submit(p, 24)
        return eng.run(), eng.stats.preemptions

    base, pre_base = pressure()
    shard, pre_shard = pressure(mesh=_mesh())
    assert pre_base > 0, "pressure config no longer preempts; tighten it"
    assert pre_shard == pre_base
    _assert_identical(base, shard)


@pytest.mark.slow
def test_aot_compile_cache_sharded(tmp_path):
    """AOT warm start composes with the mesh: example arguments lower
    with their committed NamedShardings, so a cold run populates the
    cache and a warm run replays every program — both bit-identical to
    the single-chip stream. Three engine builds (baseline, cold, warm):
    slow tier."""
    model = _gpt2()
    params = _params(model)
    prompts = _prompts(4)
    kw = {"paged": True, "block_size": 8, "n_blocks": 24}
    base, _ = _drive(model, params, prompts, **kw)
    mesh = _mesh()
    cold, ec = _drive(model, params, prompts, mesh=mesh,
                      compile_cache=str(tmp_path), **kw)
    warm, ew = _drive(model, params, prompts, mesh=mesh,
                      compile_cache=str(tmp_path), **kw)
    _assert_identical(base, cold)
    _assert_identical(base, warm)
    assert ec.compile_cache_info["misses"] > 0
    assert ew.compile_cache_info["misses"] == 0
    assert ew.compile_cache_info["hits"] == ec.compile_cache_info["misses"]


# ---------------------------------------------------------------------------
# topology keying, refusal, observability
# ---------------------------------------------------------------------------


def test_fingerprint_keys_on_mesh_topology():
    """Satellite: an AOT artifact compiled for one topology must never be
    loaded on another — the fingerprint carries the mesh axes/shape and
    the tensor world."""
    model = _gpt2()
    params = _params(model)
    e1 = ServeEngine(model, params, max_slots=2, seed=0)
    e2 = ServeEngine(model, params, max_slots=2, seed=0, mesh=_mesh())
    assert e1._fingerprint(0) != e2._fingerprint(0)
    # and two DIFFERENT topologies differ from each other too
    e4 = ServeEngine(model, params, max_slots=2, seed=0, mesh=_mesh(tensor=4))
    assert e2._fingerprint(0) != e4._fingerprint(0)


def test_head_divisibility_refusal():
    """The engine refuses loudly — at construction, before any weight
    moves — when the tensor world does not divide the head counts. GQA:
    the KV heads are the binding constraint."""
    mesh = _mesh()
    model = _llama(num_heads=3, kv=3)
    with pytest.raises(ValueError, match="tensor"):
        ServeEngine(model, _params(model), max_slots=2, mesh=mesh)
    # h=4 divides tensor=4 but h_kv=2 does not: still refused
    gqa = _llama(num_heads=4, kv=2)
    with pytest.raises(ValueError, match="KV"):
        ServeEngine(gqa, _params(gqa, seed=1), max_slots=2,
                    mesh=_mesh(tensor=4))


def test_serve_stats_tensor_world():
    """Serve telemetry labels every window row and the final snapshot
    with the tensor world so per-chip pool_occupancy is interpretable."""
    from tpudist.serve.stats import ServeStats

    st = ServeStats(slots=2, tensor_world=2)
    assert st.snapshot()["tensor_world"] == 2
    assert st._window_row(0, 0)["tensor_world"] == 2
    model = _gpt2()
    eng = ServeEngine(model, _params(model), max_slots=2, seed=0)
    assert eng.stats.snapshot()["tensor_world"] == 1
