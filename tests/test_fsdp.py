"""FSDP (ZeRO-3 sharded state) correctness: a step with params/opt-state
sharded over the ``fsdp`` axis must be numerically equivalent to the fully
replicated DP step — sharding is placement, not math (tpudist.parallel.fsdp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpudist import mesh as mesh_lib
from tpudist.data.cifar import synthetic_cifar, to_tensor
from tpudist.mesh import FSDP_AXIS
from tpudist.models import resnet18
from tpudist.parallel.fsdp import fsdp_spec, shard_state
from tpudist.train import create_train_state, make_train_step


def _batch(n=16, seed=0):
    data = synthetic_cifar(n=n, num_classes=10, seed=seed)
    return to_tensor({"image": data["image"], "label": data["label"]})


def test_fsdp_spec_picks_largest_divisible_dim():
    assert fsdp_spec((3, 3, 64, 128), 4) == P(None, None, None, FSDP_AXIS)
    assert fsdp_spec((256, 64), 4) == P(FSDP_AXIS, None)
    # too small -> replicated
    assert fsdp_spec((64,), 4) == P()
    # nothing divisible -> replicated
    assert fsdp_spec((3, 5, 7), 4, min_size=1) == P()
    # fsdp axis of 1 -> replicated
    assert fsdp_spec((256, 64), 1) == P()


@pytest.mark.skipif(
    tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax 0.4.x XLA:CPU GSPMD orders the BN/grad reductions "
    "differently enough to breach the tolerance (2.4% loss divergence); "
    "green on current jax, and the FSDP agreement certificate in "
    "MULTICHIP_r05.json covers the real-hardware contract",
)
def test_fsdp_actually_shards_and_matches_dp():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, fsdp=4))
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)

    # independent state for the DP control: shard_state's device_put aliases
    # replicated leaves, and the donating train step would delete them from
    # under the control run
    state_dp = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)

    fsdp_state, shardings = shard_state(state, mesh)
    # at least the big conv kernels must really be sharded over fsdp
    sharded = [
        s for s in jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "spec")
        )
        if FSDP_AXIS in tuple(s.spec)
    ]
    assert len(sharded) > 10

    step_fsdp = make_train_step(model, tx, mesh, state_sharding=shardings)
    step_dp = make_train_step(model, tx, mesh)

    losses_f, losses_d = [], []
    st_f, st_d = fsdp_state, state_dp
    for i in range(2):
        b = _batch(16, seed=i)
        st_f, mf = step_fsdp(st_f, b)
        st_d, md = step_dp(st_d, b)
        losses_f.append(float(mf["loss"]))
        losses_d.append(float(md["loss"]))
    np.testing.assert_allclose(losses_f, losses_d, rtol=2e-4)
    for a, b_ in zip(
        jax.tree_util.tree_leaves(st_f.params),
        jax.tree_util.tree_leaves(st_d.params),
    ):
        # after 2 Adam steps fp reduction-order noise is amplified through
        # sqrt/eps (same chaos bound as test_8dev_dp_equals_1dev step 2)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-3, rtol=1e-2)


def test_fsdp_state_memory_is_sharded():
    """Each device holds ~1/fsdp of every sharded leaf (the ZeRO memory win)."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=1, fsdp=8))
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    fsdp_state, _ = shard_state(state, mesh)
    # find a big kernel and check its per-device shard shape
    big = [
        x for x in jax.tree_util.tree_leaves(fsdp_state.params)
        if x.size >= 64 * 64 * 9
    ]
    assert big
    for x in big:
        local = x.addressable_shards[0].data
        assert local.size * 8 == x.size, (x.shape, local.shape)


def test_compose_fsdp_3d_matches_unsharded():
    """dp x fsdp x tensor composition: TP kernels keep their Megatron specs,
    replicated leaves gain fsdp specs, loss matches the 1-device run."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.parallel.fsdp import compose_fsdp
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(13))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}

    losses = {}
    for name in ("single", "3d"):
        if name == "single":
            mesh = mesh_lib.create_mesh(
                mesh_lib.MeshConfig(data=1), devices=jax.devices()[:1]
            )
        else:
            mesh = mesh_lib.create_mesh(
                mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2)
            )
        model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
                     num_heads=4)
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
        )
        if name == "3d":
            state, shardings = compose_fsdp(state, mesh, min_size=256)
            # TP annotation survives composition...
            qkv = shardings.params["h_0"]["qkv"]["kernel"].spec
            assert mesh_lib.TENSOR_AXIS in qkv, qkv
            # ...and an unannotated leaf (positional embedding) gained fsdp
            wpe = shardings.params["wpe"].spec
            assert mesh_lib.FSDP_AXIS in wpe, wpe
        else:
            shardings = state_shardings_of(state)
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=shardings,
        )
        state, metrics = step(state, batch)
        losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["single"], losses["3d"], rtol=2e-5)
