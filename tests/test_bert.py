"""BERT encoder family (tpudist/models/bert.py): bidirectional attention,
the 80/10/10 MLM corruption, the mlm_forward train-step contract, and TP
sharding metadata."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.models.bert import Bert, mlm_forward, mlm_transform
from tpudist.train import create_train_state, make_train_step


def tiny_bert(**kw):
    kw.setdefault("vocab_size", 97)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("hidden_dim", 32)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    return Bert(**kw)


def test_logits_shape_and_finite():
    model = tiny_bert()
    tokens = jnp.asarray(
        np.random.Generator(np.random.PCG64(0)).integers(0, 97, (2, 16)),
        jnp.int32,
    )
    params = model.init(jax.random.key(0), tokens, train=False)["params"]
    logits = model.apply({"params": params}, tokens, train=False)
    assert logits.shape == (2, 16, 97)
    assert np.isfinite(np.asarray(logits)).all()


def test_attention_is_bidirectional():
    """Perturbing the LAST token must change the FIRST position's logits —
    the defining difference from the causal decoder families."""
    model = tiny_bert()
    rng = np.random.Generator(np.random.PCG64(1))
    tokens = rng.integers(0, 97, (1, 16)).astype(np.int32)
    params = model.init(jax.random.key(0), jnp.asarray(tokens), train=False)[
        "params"
    ]
    base = model.apply({"params": params}, jnp.asarray(tokens), train=False)
    flipped = tokens.copy()
    flipped[0, -1] = (flipped[0, -1] + 1) % 97
    out = model.apply({"params": params}, jnp.asarray(flipped), train=False)
    assert not np.allclose(
        np.asarray(base[0, 0]), np.asarray(out[0, 0])
    ), "first-position logits ignored the last token (causal leak)"


def test_mlm_transform_recipe():
    rng = np.random.Generator(np.random.PCG64(2))
    tokens = rng.integers(5, 90, (64, 128)).astype(np.int32)
    tr = mlm_transform(vocab_size=97, mask_id=3, seed=0)
    out = tr({"tokens": tokens})
    sel = out["mlm_mask"]
    np.testing.assert_array_equal(out["targets"], tokens)
    # unselected positions pass through untouched
    np.testing.assert_array_equal(out["tokens"][~sel], tokens[~sel])
    rate = sel.mean()
    assert 0.12 < rate < 0.18, f"selection rate {rate} far from 0.15"
    masked_share = (out["tokens"][sel] == 3).mean()
    assert 0.7 < masked_share < 0.9, f"mask share {masked_share} not ~0.8"
    # ~10% of selected keep their identity
    kept = (out["tokens"][sel] == tokens[sel]).mean()
    assert 0.04 < kept < 0.2, f"keep share {kept} not ~0.1"
    # deterministic stream given the seed
    out2 = mlm_transform(vocab_size=97, mask_id=3, seed=0)({"tokens": tokens})
    np.testing.assert_array_equal(out["tokens"], out2["tokens"])


def test_mlm_training_learns():
    """A tiny BERT on a structured corpus (token i+1 follows token i, so
    context pins every masked identity) must cut its MLM loss sharply."""
    from tpudist.data.loader import DataLoader

    mesh = mesh_lib.create_mesh()
    model = tiny_bert(hidden_dim=64)
    # 4 distinct consecutive-run windows: any unmasked neighbor pins every
    # masked identity, so the loss must fall fast
    starts = np.array([0, 16, 32, 48]).repeat(64)
    windows = (starts[:, None] + np.arange(16)[None, :]) % 64 + 5
    data = {"tokens": windows.astype(np.int32)}
    loader = DataLoader(
        data, 32, transform=mlm_transform(vocab_size=97, mask_id=3, seed=1)
    )
    tx = optax.adam(3e-3)
    state = create_train_state(
        model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
    )
    step = make_train_step(
        model, tx, mesh, input_key="tokens", label_key="targets",
        forward_loss=mlm_forward(model),
    )
    losses = []
    # post-LN BERT warms up slowly: it learns the marginal distribution
    # (ln 64 ≈ 4.16) in tens of steps but needs a couple hundred to use
    # context; 30 epochs × 8 batches ≈ 75 s on the 8-device CPU mesh
    for _ in range(30):
        for batch in loader:
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_tensor_parallel_metadata_shards_params():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    model = tiny_bert(vocab_size=96)  # divisible by the tensor axis
    state = create_train_state(
        model, 0, jnp.zeros((1, 16), jnp.int32), optax.adam(1e-3), mesh
    )
    wte = state.params["wte"]
    assert wte.sharding.spec[0] == mesh_lib.TENSOR_AXIS  # vocab-sharded
    qkv = state.params["h_0"]["qkv"]["kernel"]
    assert qkv.sharding.spec[2] == mesh_lib.TENSOR_AXIS  # column-parallel
    step = make_train_step(
        model, optax.adam(1e-3), mesh, input_key="tokens",
        label_key="targets", forward_loss=mlm_forward(model),
        state_sharding=jax.tree_util.tree_map(lambda x: x.sharding, state),
    )
    rng = np.random.Generator(np.random.PCG64(4))
    tokens = rng.integers(0, 96, (8, 16)).astype(np.int32)
    batch = mlm_transform(vocab_size=96, mask_id=3, seed=2)(
        {"tokens": tokens}
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_chunked_mlm_forward_matches_full():
    """mlm_forward(chunk=...) must reproduce the full-logits loss exactly
    (same head math through MlmHead, bounded [B, chunk, V] live logits) —
    including the ragged final chunk."""
    from flax.core import FrozenDict

    from tpudist.models.bert import mlm_forward, mlm_transform

    model = tiny_bert()
    rng = np.random.Generator(np.random.PCG64(7))
    tokens = rng.integers(0, 97, (4, 16)).astype(np.int32)
    batch = {
        k: jnp.asarray(v)
        for k, v in mlm_transform(vocab_size=97, mask_id=3, seed=3)(
            {"tokens": tokens}
        ).items()
    }
    params = model.init(jax.random.key(0), batch["tokens"], train=False)[
        "params"
    ]
    full, _ = mlm_forward(model)(params, FrozenDict(), batch)
    chunked, _ = mlm_forward(model, chunk=5)(params, FrozenDict(), batch)
    np.testing.assert_allclose(
        np.asarray(chunked), np.asarray(full), rtol=1e-5, atol=1e-6
    )


def test_train_bert_example_e2e(tmp_path):
    """examples/train_bert.py end-to-end: memmap corpus -> MLM corruption ->
    fit -> masked eval, with the reserved [MASK] id above the corpus vocab."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))
    import train_bert

    binf = tmp_path / "corpus.bin"
    np.frombuffer(b"the quick brown fox jumps over the lazy dog. " * 400,
                  np.uint8).astype(np.uint16).tofile(binf)
    state, losses = train_bert.main([
        "--tokens", str(binf), "--vocab_size", "256", "--seq_len", "32",
        "--batch_size", "2", "--hidden_dim", "32", "--depth", "1",
        "--num_heads", "2", "--epochs", "2", "--lr", "3e-3",
        "--no_profiler", "--log_dir", str(tmp_path), "--JobID", "BertE2E",
        "--eval", "--chunked_ce", "16",
    ])
    assert len(losses) > 0 and np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # the reserved mask id extends the vocab by one
    assert state.params["wte"].shape[0] == 257


def test_train_bert_init_hf_warm_start(tmp_path):
    """--init_hf warm-starts from a local HF BertForMaskedLM checkpoint
    through tpudist.interop (sizes from flags, tokenizer's own [MASK] id)."""
    import sys
    from pathlib import Path

    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from safetensors.torch import save_file

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))
    import train_bert

    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2,
    )
    torch.manual_seed(11)
    hf = transformers.BertForMaskedLM(cfg)
    ckpt = tmp_path / "hf"
    ckpt.mkdir()
    # clone() breaks the tied-tensor aliases safetensors refuses to save
    save_file(
        {k: v.clone().contiguous() for k, v in hf.state_dict().items()},
        str(ckpt / "model.safetensors"),
    )

    binf = tmp_path / "corpus.bin"
    rng = np.random.Generator(np.random.PCG64(12))
    # short corpus → ~30 steps at lr 1e-4: weights stay near the warm start
    rng.integers(0, 64, 2_000).astype(np.uint16).tofile(binf)
    state, losses = train_bert.main([
        "--tokens", str(binf), "--vocab_size", "64", "--mask_id", "3",
        "--init_hf", str(ckpt),
        "--seq_len", "32", "--batch_size", "2", "--hidden_dim", "32",
        "--depth", "1", "--num_heads", "2", "--epochs", "1",
        "--no_profiler", "--log_dir", str(tmp_path), "--JobID", "BertHF",
    ])
    assert len(losses) > 0 and np.isfinite(losses).all()
    # warm start actually took: wte equals the HF table, not a fresh init
    want = hf.state_dict()["bert.embeddings.word_embeddings.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(state.params["wte"])[: want.shape[0]], want, atol=2e-2
    )


def test_classifier_fine_tunes_on_token_presence():
    """BertClassifier learns a simple sequence-level rule (does token 7
    appear?) through the standard train step — the fine-tuning surface."""
    from tpudist.models.bert import BertClassifier

    mesh = mesh_lib.create_mesh()
    model = BertClassifier(
        num_labels=2, vocab_size=32, max_seq_len=16, hidden_dim=32,
        depth=1, num_heads=2,
    )
    rng = np.random.Generator(np.random.PCG64(9))
    tokens = rng.integers(8, 32, (256, 8)).astype(np.int32)
    put = rng.random(256) < 0.5
    tokens[put, 0] = 7  # the signal token
    labels = put.astype(np.int32)
    tx = optax.adam(3e-3)
    state = create_train_state(
        model, 0, jnp.zeros((1, 8), jnp.int32), tx, mesh
    )
    step = make_train_step(model, tx, mesh, input_key="tokens",
                           label_key="label")
    batch = {"tokens": tokens, "label": labels}
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.1, losses[-1]


def test_classifier_grafts_pretrained_encoder():
    from flax import linen as nn

    from tpudist.models.bert import BertClassifier, classifier_params_from_mlm

    kw = dict(vocab_size=32, max_seq_len=16, hidden_dim=32, depth=1,
              num_heads=2)
    pre = nn.meta.unbox(
        tiny_bert(**kw).init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32), train=False
        )["params"]
    )
    cls = nn.meta.unbox(
        BertClassifier(num_labels=3, **kw).init(
            jax.random.key(2), jnp.zeros((1, 8), jnp.int32), train=False
        )["params"]
    )
    grafted = classifier_params_from_mlm(cls, pre)
    np.testing.assert_array_equal(
        np.asarray(grafted["bert"]["wte"]), np.asarray(pre["wte"])
    )
    # head stays fresh
    np.testing.assert_array_equal(
        np.asarray(grafted["classifier"]["kernel"]),
        np.asarray(cls["classifier"]["kernel"]),
    )
    # grafted tree still runs
    model = BertClassifier(num_labels=3, **kw)
    out = model.apply(
        {"params": grafted}, jnp.zeros((2, 8), jnp.int32), train=False
    )
    assert out.shape == (2, 3)


def test_ring_attention_matches_full_bidirectional():
    """Bidirectional ring attention (causal=False K/V rotation) must equal
    full attention exactly — same unrolled params, different impl."""
    kw = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4)
    mesh_sp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, seq=2))
    rng = np.random.Generator(np.random.PCG64(13))
    tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    ref_model = Bert(**kw)
    params = ref_model.init(jax.random.key(3), tokens, train=False)["params"]
    want = ref_model.apply({"params": params}, tokens, train=False)
    ring_model = Bert(attn_impl="ring", mesh=mesh_sp, **kw)
    got = ring_model.apply({"params": params}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ulysses_matches_full_bidirectional():
    kw = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4)
    mesh_sp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, seq=2))
    rng = np.random.Generator(np.random.PCG64(14))
    tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    ref_model = Bert(**kw)
    params = ref_model.init(jax.random.key(4), tokens, train=False)["params"]
    want = ref_model.apply({"params": params}, tokens, train=False)
    uly_model = Bert(attn_impl="ulysses", mesh=mesh_sp, **kw)
    got = uly_model.apply({"params": params}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow  # spawns a fresh jax world (the repo's subprocess-test convention)
def test_ring_mlm_train_step_with_sequence_sharded_batch():
    """Subprocess-contained wrapper around the real test below: under
    heavy host contention this ring-collective step has twice SIGABRT'd
    inside XLA:CPU's runtime (an environment wart — the persistent-cache
    note in tests/conftest.py has the full diagnosis). In-process, that
    abort kills the entire pytest run and every result with it; contained,
    a crash is one retried (then failed) test. One retry absorbs the
    observed flake rate."""
    import os
    import subprocess
    import sys

    cmd = [
        sys.executable, "-m", "pytest", "-q", "-x",
        f"{__file__}::test_ring_mlm_subproc_impl",
    ]
    # the child runs CACHE-LESS: the abort is in the AOT round trip of
    # this program's cached executable (measured: 2/6 child runs abort
    # with the cache, 0/6 without; capping the ISA does not help), and
    # the child's cold compile of one tiny step is ~40s — bounded
    env = dict(
        os.environ, TPUDIST_SUBPROC_TEST="1", TPUDIST_NO_JAX_CACHE="1"
    )
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, env=env
    )
    if r.returncode < 0 or r.returncode == 134:
        # killed by a signal (the SIGABRT this wrapper contains): retry
        # once, LOUDLY — the recovery must stay observable so a spreading
        # flake is noticed before both attempts die
        print(
            f"\nring MLM subprocess CRASHED (rc={r.returncode}) — the known "
            "XLA:CPU abort (tests/conftest.py); retrying once:\n"
            + r.stderr[-1500:],
            file=sys.stderr,
        )
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600, env=env
        )
    # an ordinary test failure (rc>0) reports immediately — retrying would
    # only mask a real regression and double the wall clock
    assert r.returncode == 0, (
        f"ring MLM subprocess failed (rc={r.returncode}):\n"
        + r.stdout[-2000:] + r.stderr[-2000:]
    )


@pytest.mark.subproc_only
def test_ring_mlm_subproc_impl():
    """Context-parallel MLM training: tokens/targets/mask sharded over the
    'seq' axis, ring attention inside the compiled step. Collected only
    inside the wrapper's subprocess (the subproc_only marker skips it in
    the parent run — tests/conftest.py)."""
    from jax.sharding import PartitionSpec as P

    mesh_sp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, seq=2))
    model = tiny_bert(max_seq_len=16, mesh=mesh_sp, attn_impl="ring")
    tx = optax.adam(1e-3)
    state = create_train_state(
        model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh_sp
    )
    bd = (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
    spec = P(bd, mesh_lib.SEQUENCE_AXIS)
    step = make_train_step(
        model, tx, mesh_sp, input_key="tokens", label_key="targets",
        forward_loss=mlm_forward(model),
        batch_spec={"tokens": spec, "targets": spec, "mlm_mask": spec},
        state_sharding=jax.tree_util.tree_map(lambda x: x.sharding, state),
    )
    rng = np.random.Generator(np.random.PCG64(15))
    tokens = rng.integers(0, 97, (8, 16)).astype(np.int32)
    batch = mlm_transform(vocab_size=97, mask_id=3, seed=5)({"tokens": tokens})
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_scan_layers_trains_with_stacked_params():
    mesh = mesh_lib.create_mesh()
    model = tiny_bert(depth=3, scan_layers=True, remat_layers=True)
    tx = optax.adam(1e-3)
    state = create_train_state(
        model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
    )
    # one traced layer, params stacked [depth, ...]
    assert "hs" in state.params and "h_0" not in state.params
    qkv = state.params["hs"]["block"]["qkv"]["kernel"]
    assert qkv.shape[0] == 3 and qkv.ndim == 5
    step = make_train_step(
        model, tx, mesh, input_key="tokens", label_key="targets",
        forward_loss=mlm_forward(model),
    )
    rng = np.random.Generator(np.random.PCG64(16))
    tokens = rng.integers(0, 97, (8, 16)).astype(np.int32)
    batch = mlm_transform(vocab_size=97, mask_id=3, seed=6)({"tokens": tokens})
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_stack_layers_converts_unrolled_bert_to_scanned():
    """The shared stack_layers converter (lm_utils) moves an unrolled BERT
    checkpoint into the scan layout: identical logits from both models."""
    from flax import linen as nn

    from tpudist.models.lm_utils import stack_layers, unstack_layers

    kw = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=3,
              num_heads=4)
    rng = np.random.Generator(np.random.PCG64(17))
    tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    unrolled = Bert(**kw)
    params = nn.meta.unbox(
        unrolled.init(jax.random.key(5), tokens, train=False)["params"]
    )
    want = unrolled.apply({"params": params}, tokens, train=False)

    stacked = stack_layers(params, 3, prefix="h_", dest="hs")
    scanned = Bert(scan_layers=True, **kw)
    got = scanned.apply({"params": stacked}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # and back
    back = unstack_layers(stacked, prefix="h_", dest="hs")
    again = unrolled.apply({"params": back}, tokens, train=False)
    np.testing.assert_allclose(np.asarray(again), np.asarray(want), rtol=1e-6)


def test_attention_mask_excludes_padding():
    """A right-padded batch with attention_mask must produce the SAME hidden
    states on the real positions as the unpadded sequence: padded keys are
    out of every softmax, so position i's context is identical either way."""
    model = tiny_bert()
    rng = np.random.Generator(np.random.PCG64(21))
    short = rng.integers(0, 97, (2, 12)).astype(np.int32)
    params = model.init(jax.random.key(0), jnp.asarray(short), train=False)[
        "params"
    ]
    base = model.apply({"params": params}, jnp.asarray(short), train=False)
    # pad with junk ids the model HAS embeddings for — the mask, not the pad
    # value, must make them inert
    padded = np.concatenate(
        [short, rng.integers(0, 97, (2, 4)).astype(np.int32)], axis=1
    )
    mask = np.zeros((2, 16), np.int32)
    mask[:, :12] = 1
    out = model.apply(
        {"params": params}, jnp.asarray(padded), train=False,
        attention_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(
        np.asarray(out[:, :12]), np.asarray(base), rtol=2e-5, atol=2e-5
    )
    # and without the mask the junk keys must bleed in (the failure the
    # mask exists to prevent)
    unmasked = model.apply({"params": params}, jnp.asarray(padded), train=False)
    assert not np.allclose(np.asarray(unmasked[:, :12]), np.asarray(base))


def test_attention_mask_scan_layers_matches_unrolled():
    """The mask rides nn.scan as a broadcast argument; scanned and unrolled
    layouts must agree on masked inputs (same per-layer params via
    stack_layers would be overkill — equality of masked-vs-short suffices)."""
    model = tiny_bert(depth=3, scan_layers=True)
    rng = np.random.Generator(np.random.PCG64(22))
    short = rng.integers(0, 97, (1, 10)).astype(np.int32)
    params = model.init(jax.random.key(1), jnp.asarray(short), train=False)[
        "params"
    ]
    base = model.apply({"params": params}, jnp.asarray(short), train=False)
    padded = np.concatenate(
        [short, rng.integers(0, 97, (1, 6)).astype(np.int32)], axis=1
    )
    mask = np.zeros((1, 16), np.int32)
    mask[:, :10] = 1
    out = model.apply(
        {"params": params}, jnp.asarray(padded), train=False,
        attention_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(
        np.asarray(out[:, :10]), np.asarray(base), rtol=2e-5, atol=2e-5
    )


def test_classifier_accepts_attention_mask():
    from tpudist.models.bert import BertClassifier

    model = BertClassifier(
        num_labels=3, vocab_size=97, max_seq_len=32, hidden_dim=32,
        depth=2, num_heads=4,
    )
    rng = np.random.Generator(np.random.PCG64(23))
    short = rng.integers(0, 97, (2, 9)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(short), train=False)
    base = model.apply(variables, jnp.asarray(short), train=False)
    padded = np.concatenate(
        [short, rng.integers(0, 97, (2, 7)).astype(np.int32)], axis=1
    )
    mask = np.zeros((2, 16), np.int32)
    mask[:, :9] = 1
    out = model.apply(
        variables, jnp.asarray(padded), train=False,
        attention_mask=jnp.asarray(mask),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(base), rtol=2e-5, atol=2e-5
    )


def test_mlm_random_replacement_never_injects_mask_id():
    """The 10% random-token replacement draws from the vocab EXCLUDING
    [MASK]: a random draw landing on mask_id would create a target-bearing
    position the model can only see as masked (ADVICE r2)."""
    rng = np.random.Generator(np.random.PCG64(24))
    tokens = rng.integers(0, 5, (512, 64)).astype(np.int32)
    # random_rate=1.0: every selected position becomes a random token, so a
    # single mask_id anywhere among them is the bug
    tr = mlm_transform(
        vocab_size=5, mask_id=3, random_rate=1.0, keep_rate=0.0, seed=0
    )
    out = tr({"tokens": tokens})
    sel = out["mlm_mask"]
    assert sel.sum() > 1000
    replaced = out["tokens"][sel]
    assert not (replaced == 3).any(), "random replacement produced [MASK]"
    # the other ids all remain reachable
    assert set(np.unique(replaced)) == {0, 1, 2, 4}
