"""Run-health in a REAL 2-process world (2 × 4 emulated devices via
tpudist.launch): the cross-process aggregator's in-graph gather feeding
rank 0's straggler detection against an injected slow rank (and staying
silent on a healthy fleet), and the replica-divergence probe catching a
per-replica param perturbation injected on rank 1 only — the multi-host
forms of the single-process tests in test_health.py."""

import json
import os
import subprocess
import sys
import textwrap

import jax
import pytest

# the 2-process children execute real cross-process SPMD programs, which
# jax 0.4.x's XLA:CPU refuses outright ("Multiprocess computations aren't
# implemented on the CPU backend" — the same container limitation that
# gates test_multiproc_fit's world on this jax); green on current jax
_OLD_JAX = tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5)

pytestmark = [
    pytest.mark.slow,  # subprocess world: cold-compiles its own jax programs
    pytest.mark.skipif(
        _OLD_JAX, reason="jax 0.4.x XLA:CPU cannot execute multi-process "
        "computations (the children die in create_train_state/probe before "
        "any health code runs); current jax runs the 2-process world"
    ),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_STRAGGLER_CHILD = textwrap.dedent("""
    import json, os, time

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import optax

    from tpudist import create_mesh, init_from_env
    from tpudist.data.loader import DataLoader
    from tpudist.models.gpt2 import GPT2
    from tpudist.telemetry import TelemetryConfig
    from tpudist.train import fit, lm_loss

    ctx = init_from_env()
    mesh = create_mesh()
    sleep_s = float(os.environ.get("RANK1_SLEEP_S", "0"))

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 254, (64, 16)).astype(np.int32)
    # per-host disjoint rows (the straggler signal must come from the
    # TIMING skew, not from data divergence)
    rows = tokens[ctx.process_index::ctx.process_count]
    inner = DataLoader({"tokens": rows}, 16 // ctx.process_count)

    class PerBatchSleeper:
        # rank 1's input pipeline is slow EVERY batch — the persistent
        # straggler; rank 0's is instant
        def __init__(self, inner, s):
            self.inner, self.s = inner, s
            self.batch_size = inner.batch_size
        def __len__(self):
            return len(self.inner)
        def __iter__(self):
            for b in self.inner:
                if self.s:
                    time.sleep(self.s)
                yield b

    loader = PerBatchSleeper(
        inner, sleep_s if ctx.process_index == 1 else 0.0
    )
    model = GPT2(vocab_size=256, max_seq_len=16, hidden_dim=32, depth=1,
                 num_heads=2)
    cfg = TelemetryConfig(aggregate_every=2, straggler_patience=2,
                          mfu=False, sentry=False, heartbeat_every=0)
    state, losses = fit(
        model, optax.adam(1e-3), loader, epochs=4, mesh=mesh,
        batch_size=16, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", job_id="MH", profile=False, seed=0,
        log_dir=os.environ["OUT_DIR"], telemetry=cfg,
        world_size=ctx.process_count, global_rank=ctx.process_index,
    )
    assert len(losses) == 16
""")

_DIVERGENCE_CHILD = textwrap.dedent("""
    import json, os

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax.core import FrozenDict
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpudist import create_mesh, init_from_env
    from tpudist.parallel.dp import make_divergence_probe
    from tpudist.train import TrainState
    from tpudist.utils.compat import shard_map

    ctx = init_from_env()
    mesh = create_mesh()
    repl = NamedSharding(mesh, P())
    clean_w = jax.jit(
        lambda: jnp.arange(64, dtype=jnp.float32), out_shardings=repl
    )()

    # desync ONE device's "replicated" copy inside a compiled program:
    # out_specs=P() claims replication while device 5 (a process-1 chip)
    # holds a perturbed copy — exactly the silent-desync failure mode,
    # produced the way real desync is (by device computation, not by a
    # host constructing inconsistent buffers)
    gmesh = Mesh(np.asarray(jax.devices()), ("g",))

    def perturb_device_5(x):
        i = jax.lax.axis_index("g")
        return x + jnp.float32(1e-3) * (i == 5).astype(jnp.float32)

    bad_w = jax.jit(
        shard_map(perturb_device_5, mesh=gmesh, in_specs=P(),
                  out_specs=P(), check_vma=False),
        out_shardings=NamedSharding(gmesh, P()),
    )(clean_w)

    def probe_counts(w):
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params={"w": w},
            batch_stats=FrozenDict(), opt_state=(),
        )
        probe = make_divergence_probe(state, mesh)
        return {k: int(v) for k, v in probe(state).items()}

    clean = probe_counts(clean_w)
    desynced = probe_counts(bad_w)
    out = os.path.join(
        os.environ["OUT_DIR"], f"div_{ctx.process_index}.json"
    )
    with open(out, "w") as f:
        json.dump({"clean": clean, "desynced": desynced}, f)
""")


def _launch(tmp_path, child_src, out_dir, *, env_extra=None, port_off=0):
    script = tmp_path / "child.py"
    script.write_text(child_src)
    os.makedirs(out_dir, exist_ok=True)
    env = dict(os.environ)
    env["OUT_DIR"] = str(out_dir)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    port = 29650 + (os.getpid() + port_off) % 300
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch",
            "--nproc_per_node=2", "--emulate-devices=4",
            f"--master_port={port}", str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    return r


def _rows(path):
    return [json.loads(l) for l in open(path)]


def test_straggler_fires_on_slow_rank_and_not_on_healthy(tmp_path):
    slow = tmp_path / "slow"
    _launch(tmp_path, _STRAGGLER_CHILD, slow,
            env_extra={"RANK1_SLEEP_S": "0.25"}, port_off=0)
    rows0 = _rows(slow / "MH_telemetry_0.jsonl")
    fleet = [r for r in rows0 if r["kind"] == "fleet"]
    assert fleet, rows0
    # the gathered skew stats cover both hosts, and rank 1's host-side
    # share dwarfs rank 0's (the sleep lives in ITS input pipeline;
    # lockstep collectives equalize interval_s, which is exactly why the
    # aggregator folds host_s)
    last = fleet[-1]
    assert set(last["per_rank_step"]) == {"0", "1"}
    assert last["per_rank_host_s"]["1"] > last["per_rank_host_s"]["0"]
    stragglers = [r for r in rows0 if r["kind"] == "straggler"]
    assert len(stragglers) == 1, stragglers  # one-shot
    assert stragglers[0]["rank"] == 1
    # rank 1 writes no straggler row (rank-0 fold), but shares the fleet
    rows1 = _rows(slow / "MH_telemetry_1.jsonl")
    assert not [r for r in rows1 if r["kind"] == "straggler"]
    # the end-of-run report records the event and both ranks' last steps
    report = json.loads((slow / "MH_report.json").read_text())
    assert report["straggler_events"] and \
        report["straggler_events"][0]["rank"] == 1
    assert set(report["per_rank_last_seen"]) == {"0", "1"}

    healthy = tmp_path / "healthy"
    _launch(tmp_path, _STRAGGLER_CHILD, healthy,
            env_extra={"RANK1_SLEEP_S": "0"}, port_off=1)
    rows0 = _rows(healthy / "MH_telemetry_0.jsonl")
    assert [r for r in rows0 if r["kind"] == "fleet"]
    assert not [r for r in rows0 if r["kind"] == "straggler"]
    report = json.loads((healthy / "MH_report.json").read_text())
    assert report["straggler_events"] == []


def test_divergence_probe_catches_cross_process_perturbation(tmp_path):
    out = tmp_path / "div"
    _launch(tmp_path, _DIVERGENCE_CHILD, out, port_off=2)
    for rank in (0, 1):
        res = json.loads((out / f"div_{rank}.json").read_text())
        # clean replicas agree bitwise
        assert res["clean"]["replica_divergence"] == 0
        # device 5's perturbed copy disagrees with replica 0 — every
        # process sees the same (replicated) verdict in-graph, within ONE
        # probe, without any host-side cross-rank comparison
        assert res["desynced"]["replica_divergence"] == 1
