"""Driver-contract test for ``__graft_entry__.dryrun_multichip``.

Round 1's only multi-chip artifact recorded failure (``ok=false``) because
``dryrun_multichip`` asserted 8 devices instead of provisioning them. This
test runs the function exactly the way the driver does — a fresh
interpreter with NO jax platform env vars and no conftest help — and
requires the self-provisioning path (re-exec onto a virtual CPU mesh) to
bring up all legs. Simulates the reference's multi-machine recipe
(/root/reference/README.md:17-35).
"""

import os
import subprocess
import sys
import pytest

pytestmark = pytest.mark.slow  # subprocess world: cold-compiles its own jax programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# env vars that would "help" (or hinder) the child; the driver sets none of
# them, so neither does this test
_SCRUBBED = (
    "JAX_PLATFORMS",
    "XLA_FLAGS",
    "TPUDIST_FORCE_CPU",
    "_TPUDIST_DRYRUN_INPROC",
    "JAX_PLATFORM_NAME",
)


def test_dryrun_multichip_provisions_own_mesh():
    env = {k: v for k, v in os.environ.items() if k not in _SCRUBBED}
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__; __graft_entry__.dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=880,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    # every leg of the strategy matrix must have run in the child
    for leg in (
        "DP+accum: ok",
        "CKPT(save+restore+step): ok",
        "TP: ok",
        "LLAMA(tp): ok",
        "LLAMA(scan+remat,tp): ok",
        "BERT(mlm,tp): ok",
        "PP: ok",
        "SP(ring): ok",
        "SP(ulysses): ok",
        "EP(moe): ok",
        "EP(llama-moe): ok",
        "FSDP: ok",
        "3D(dp*fsdp*tp): ok",
    ):
        assert leg in out, f"missing dryrun leg {leg!r} in output:\n{out}"
