"""tools/bench_gate.py: the rolling-baseline perf gate — record parsing
(summary JSON, JSONL metric streams, and regex salvage of the truncated
BENCH_r*.json tails), the median+MAD noise band, direction inference, and
the exit-code contract: 0 on pass, 3 (the tools/ offender convention) on
an injected regression."""

import importlib.util
import json
import pathlib

import pytest

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"
REPO = _TOOLS.parent


def _load():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", _TOOLS / "bench_gate.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_gate = _load()


# -- record parsing ----------------------------------------------------------


def test_extract_legs_from_summary_dict():
    text = json.dumps({
        "metric": "bench_summary", "value": 2.0,
        "legs": {"a_tokens_per_sec": {"value": 100.0, "unit": "u",
                                      "vs_baseline": 2.0},
                 "b_images_per_sec": {"value": 50.0}},
    })
    assert bench_gate.extract_legs(text) == {
        "a_tokens_per_sec": 100.0, "b_images_per_sec": 50.0}


def test_extract_legs_from_jsonl_metric_stream():
    text = (json.dumps({"metric": "leg_a", "value": 1.5, "unit": "u"})
            + "\n" + json.dumps({"metric": "leg_b", "value": 2.5})
            + "\nnot json at all\n")
    assert bench_gate.extract_legs(text) == {"leg_a": 1.5, "leg_b": 2.5}


def test_extract_legs_salvages_torn_round_file_tail():
    """BENCH_r*.json archives truncate stdout to the last ~2000 chars, so
    the compact-summary line is usually torn at the FRONT — json.loads
    refuses it, but the interior leg entries are regex-recoverable."""
    torn = ('ma-125M: RoPE glue text that got cut..."\n'
            '{"metric":"bench_summary_compact",...TORN...'
            '"gpt2_124m_tokens_per_sec_per_chip": {"value": 129115.2, '
            '"unit": "t", "vs_baseline": 2.58}, '
            '"vit_b16_train_images_per_sec_per_chip": {"value": 781.2, '
            '"vs_baseline": 1.1}, "failed_leg_groups": []}\n')
    round_file = json.dumps({"n": 5, "cmd": "python bench.py", "rc": 0,
                             "tail": torn})
    legs = bench_gate.extract_legs(round_file)
    assert legs == {"gpt2_124m_tokens_per_sec_per_chip": 129115.2,
                    "vit_b16_train_images_per_sec_per_chip": 781.2}


def test_extract_legs_from_committed_round_archives():
    """The real archived rounds in the repo: every BENCH_r*.json tail must
    yield at least one salvaged leg, and BENCH_SUMMARY.json all of them —
    the seed command's actual inputs."""
    summary = REPO / "BENCH_SUMMARY.json"
    legs = bench_gate.extract_legs(summary.read_text())
    assert len(legs) >= 14
    for rf in sorted(REPO.glob("BENCH_r0*.json")):
        assert bench_gate.extract_legs(rf.read_text()), rf.name


# -- direction + band --------------------------------------------------------


def test_lower_is_better_inference():
    lower = bench_gate.lower_is_better
    assert lower("gpt2_124m_anatomy_overhead_pct")
    assert lower("gpt2_124m_trace_overhead_pct")
    assert lower("preempt_recovery_s")
    assert lower("grad_sync_bytes_per_step")
    assert lower("serve_p99_latency_ms")
    # throughput names — including the _sec token — stay higher-is-better
    assert not lower("gpt2_124m_tokens_per_sec_per_chip")
    assert not lower("resnet50_train_images_per_sec_per_chip")
    assert not lower("gpt2_124m_decode_tokens_per_sec")


def test_baseline_band_widens_with_noise():
    med, band = bench_gate.baseline_of([100.0, 100.0, 100.0, 100.0])
    assert med == 100.0 and band == bench_gate.DEFAULT_BAND  # quiet: floor
    med, band = bench_gate.baseline_of([100.0, 90.0, 110.0, 80.0, 120.0])
    assert med == 100.0 and band == pytest.approx(0.30)  # 3*MAD/median


def test_judge_statuses():
    hist = [100.0] * 5
    assert bench_gate.judge("leg_tok_per_sec", 99.0, hist)["status"] \
        == "pass"
    bad = bench_gate.judge("leg_tok_per_sec", 90.0, hist)
    assert bad["status"] == "regression"
    assert bad["delta_pct"] == pytest.approx(-10.0)
    # lower-is-better: an INCREASE regresses
    assert bench_gate.judge("x_overhead_pct", 90.0, [100.0] * 5)["status"] \
        == "pass"
    assert bench_gate.judge("x_overhead_pct", 110.0, [100.0] * 5)["status"] \
        == "regression"
    # legs without enough history pass with a note, never fail
    assert bench_gate.judge("new_leg", 1.0, [])["status"] == "no-history"
    assert bench_gate.judge("new_leg", 1.0, [5.0])["status"] == "no-history"


# -- end-to-end: seed, pass, exit-3 on injected regression -------------------


def _summary_file(tmp_path, name, scale=1.0):
    legs = {"gpt2_tokens_per_sec": 100000.0 * scale,
            "anatomy_overhead_pct": 0.5 / scale}
    path = tmp_path / name
    path.write_text(json.dumps({
        "metric": "bench_summary", "value": 2.0,
        "legs": {k: {"value": v, "unit": "u", "vs_baseline": 1.0}
                 for k, v in legs.items()},
    }))
    return path


def test_gate_passes_history_and_fails_injected_regression(
        tmp_path, capsys):
    store = tmp_path / "store.json"
    history = [_summary_file(tmp_path, f"r{i}.json", scale=s)
               for i, s in enumerate([1.0, 1.01, 0.99, 1.0])]
    rc = bench_gate.main(["seed", "--store", str(store)]
                         + [str(p) for p in history])
    assert rc == 0
    assert len(json.loads(store.read_text())["gpt2_tokens_per_sec"]) == 4

    # a fresh record inside the noise band: exit 0
    fresh = _summary_file(tmp_path, "fresh.json", scale=1.005)
    assert bench_gate.main(["check", "--store", str(store),
                            str(fresh)]) == 0
    out = capsys.readouterr().out
    assert "within the noise band" in out

    # an injected 10% regression on BOTH directions: exit 3
    bad = _summary_file(tmp_path, "bad.json", scale=0.90)
    rc = bench_gate.main(["check", "--store", str(store), str(bad)])
    out = capsys.readouterr().out
    assert rc == 3  # the marker_audit/schema_audit offender convention
    assert "REGRESSION" in out
    # throughput fell AND the lower-is-better overhead leg rose
    assert out.count("REGRESSION") == 2


def test_gate_update_rolls_baseline_forward_only_on_pass(tmp_path):
    store = tmp_path / "store.json"
    for i in range(3):
        bench_gate.main(["seed", "--store", str(store),
                         str(_summary_file(tmp_path, f"r{i}.json"))])
    fresh = _summary_file(tmp_path, "fresh.json", scale=1.01)
    assert bench_gate.main(["check", "--store", str(store), "--update",
                            str(fresh)]) == 0
    assert len(json.loads(store.read_text())["gpt2_tokens_per_sec"]) == 4
    bad = _summary_file(tmp_path, "bad.json", scale=0.5)
    assert bench_gate.main(["check", "--store", str(store), "--update",
                            str(bad)]) == 3
    # the regressed values did NOT poison the store
    assert len(json.loads(store.read_text())["gpt2_tokens_per_sec"]) == 4


def test_gate_no_history_passes_with_note(tmp_path, capsys):
    store = tmp_path / "store.json"
    fresh = _summary_file(tmp_path, "fresh.json")
    assert bench_gate.main(["check", "--store", str(store),
                            str(fresh)]) == 0
    assert "no baseline yet" in capsys.readouterr().out


def test_gate_unreadable_record_exits_2(tmp_path):
    assert bench_gate.main(["check", "--store",
                            str(tmp_path / "s.json"),
                            str(tmp_path / "missing.json")]) == 2


def test_store_history_is_capped(tmp_path):
    store = tmp_path / "store.json"
    files = [str(_summary_file(tmp_path, f"r{i}.json"))
             for i in range(25)]
    bench_gate.main(["seed", "--store", str(store), "--keep", "10"]
                    + files)
    assert len(json.loads(store.read_text())["gpt2_tokens_per_sec"]) == 10


def test_bench_wires_the_gate():
    """bench.py exposes --gate (off by default) and schedules the anatomy
    overhead leg — source-level, no device work."""
    src = (REPO / "bench.py").read_text()
    assert '"--gate"' in src
    assert "bench_gate.py" in src
    assert '"anatomy": (bench_anatomy_overhead' in src
    assert '"metric": "gpt2_124m_anatomy_overhead_pct"' in src
