"""Chunked weight-tied CE (tpudist.models.gpt2.chunked_lm_forward) must be
numerically identical to the full-logits lm_loss path — it is a memory
optimization, not a math change."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.models.gpt2 import GPT2, chunked_lm_forward
from tpudist.train import (
    create_train_state, lm_loss, make_train_step, state_shardings_of,
)


def _model():
    return GPT2(vocab_size=97, max_seq_len=33, hidden_dim=32, depth=2, num_heads=4)


def _batch():
    rng = np.random.Generator(np.random.PCG64(5))
    # seq 33 → 32 predicted positions, NOT divisible by chunk 8? (32 is; use
    # chunk 7 below to exercise the padded tail)
    return {"tokens": rng.integers(0, 97, (8, 33)).astype(np.int32)}


@pytest.mark.parametrize("chunk", [7, 8, 64])
def test_chunked_matches_full_logits(chunk):
    model = _model()
    variables = jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 33), jnp.int32))
    params = variables["params"]
    batch = _batch()

    full = lm_loss(
        model.apply({"params": params}, batch["tokens"], train=True),
        batch["tokens"],
    )
    fused, _ = chunked_lm_forward(model, chunk=chunk)(params, {}, batch)
    np.testing.assert_allclose(float(full), float(fused), rtol=1e-6)


def test_chunked_grads_match():
    model = _model()
    variables = jax.jit(model.init)(jax.random.key(0), jnp.zeros((1, 33), jnp.int32))
    params = variables["params"]
    batch = _batch()

    def loss_full(p):
        return lm_loss(
            model.apply({"params": p}, batch["tokens"], train=True), batch["tokens"]
        )

    def loss_fused(p):
        return chunked_lm_forward(model, chunk=8)(p, {}, batch)[0]

    g_full = jax.grad(loss_full)(params)
    g_fused = jax.grad(loss_fused)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
        ),
        g_full, g_fused,
    )


def test_chunked_train_step_on_mesh():
    mesh = mesh_lib.create_mesh()
    model = _model()
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((1, 33), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh, input_key="tokens", label_key="tokens",
        state_sharding=state_shardings_of(state),
        forward_loss=chunked_lm_forward(model, chunk=8),
    )
    batch = _batch()
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_chunked_accepts_moe_but_rejects_jitter():
    # MoE composes with the chunked path (the aux loss rides the mutable
    # 'losses' collection — tests/test_moe.py pins the value); router
    # jitter is the one knob the fused forward can't serve
    chunked_lm_forward(GPT2(num_experts=4))
    with pytest.raises(ValueError):
        chunked_lm_forward(GPT2(num_experts=4, router_jitter=0.1))


def test_chunked_rejects_bad_chunk():
    with pytest.raises(ValueError):
        chunked_lm_forward(_model(), chunk=0)
    with pytest.raises(ValueError):
        chunked_lm_forward(_model(), chunk=-256)


def test_gpt2_scan_layers_matches_unrolled():
    """GPT-2's nn.scan'd depth == the unrolled loop given the same weights
    (moved across layouts with the shared stack_layers converter)."""
    import jax
    import numpy as np

    from tpudist.models.gpt2 import GPT2
    from tpudist.models.lm_utils import stack_layers, unstack_layers

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 64, (2, 12)).astype(np.int32)
    unrolled = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=3,
                    num_heads=4)
    variables = unrolled.init(jax.random.key(6), tokens, train=False)
    want = unrolled.apply(variables, tokens, train=False)

    stacked = stack_layers(variables["params"], 3, prefix="h_", dest="hs")
    scan_model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=3,
                      num_heads=4, scan_layers=True)
    got = scan_model.apply({"params": stacked}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    # and the inverse restores the unrolled tree exactly
    from flax import linen as nn

    back = unstack_layers(stacked, prefix="h_", dest="hs")
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(nn.meta.unbox(variables["params"])),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gpt2_remat_layers_with_dropout_trains():
    """GPT-2's scan splits a 'dropout' rng through nn.remat — the rng/remat
    interaction Llama (dropout-free) never exercises."""
    import jax
    import numpy as np
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    mesh = mesh_lib.create_mesh()
    model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
                 num_heads=4, dropout=0.1, scan_layers=True,
                 remat_layers=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    rng = np.random.Generator(np.random.PCG64(1))
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    for _ in range(2):
        state, metrics = step(state, {"tokens": tokens})
    assert np.isfinite(float(metrics["loss"]))


def test_smoothed_ce_reduces_to_plain_at_zero():
    """Label smoothing (vision recipe): eps=0 is exactly plain CE, eps>0
    penalizes overconfident one-hot logits."""
    from tpudist.train import cross_entropy_loss, smoothed_cross_entropy

    rng = np.random.Generator(np.random.PCG64(0))
    logits = jnp.asarray(rng.normal(size=(8, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    np.testing.assert_allclose(
        float(smoothed_cross_entropy(0.0)(logits, labels)),
        float(cross_entropy_loss(logits, labels)),
        rtol=1e-6,
    )
    # eps > 0 penalizes overconfidence: loss on one-hot-perfect logits rises
    sharp = jnp.where(jax.nn.one_hot(labels, 10) > 0, 50.0, 0.0)
    assert float(smoothed_cross_entropy(0.1)(sharp, labels)) > float(
        smoothed_cross_entropy(0.0)(sharp, labels)
    )
