"""Speculative decoding (tpudist.serve.spec + the engine's spec mode).

The load-bearing contract: speculation changes THROUGHPUT, never the
output distribution. Greedy speculative engine output must be
token-identical to the non-speculative engine (and hence to static
``generate()``) under staggered arrivals and slot pressure — on both
model families, contiguous and paged, through eviction/preemption
cycles. Sampled mode is pinned statistically at the acceptance-rule
level (the emitted-token marginal equals the warped target
distribution). Plus: the per-row warped log-prob helper the ratio test
shares with the sampler, the device-carried cursor ("rollback" is
bookkeeping) invariant, multi-token TokenEvent ordering, spec telemetry
counters, and the paged ``ensure_to`` / equal-HBM helpers.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.generate import (
    generate, per_row_log_probs, sample_logits_per_row,
)
from tpudist.models.gpt2 import GPT2
from tpudist.models.llama import Llama
from tpudist.serve import ServeEngine, SlotPool
from tpudist.serve.blocks import PagedSlotPool, draft_equivalent_blocks
from tpudist.serve.spec import (
    cache_bytes, early_exit_draft, speculative_accept,
)


def _gpt2(max_seq_len=64):
    return GPT2(vocab_size=64, max_seq_len=max_seq_len, hidden_dim=32,
                depth=2, num_heads=4)


def _llama(max_seq_len=64):
    return Llama(vocab_size=64, max_seq_len=max_seq_len, hidden_dim=32,
                 depth=2, num_heads=4, num_kv_heads=2, ffn_dim=64)


def _params(model, seed=0):
    return model.init(
        jax.random.key(seed), np.zeros((1, 8), np.int32), train=False
    )["params"]


def _prompts(lens, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(0, vocab, (p,)).astype(np.int32) for p in lens]


# ---------------------------------------------------------------------------
# per_row_log_probs: the warped distribution the ratio test divides by


def test_per_row_log_probs_matches_sampler_filter():
    """The log-probs must describe EXACTLY the distribution
    sample_logits_per_row draws from: temperature scaling, then the
    top-k/top-p keep-set, renormalized — and a greedy row (temp 0) is a
    point mass at the argmax (what makes greedy speculation exact)."""
    rng = np.random.Generator(np.random.PCG64(0))
    logits = jnp.asarray(rng.normal(0, 2, (3, 16)).astype(np.float32))
    temperature = jnp.asarray([0.0, 0.7, 1.3], jnp.float32)
    top_k = jnp.asarray([0, 4, 0], jnp.int32)
    top_p = jnp.asarray([1.0, 1.0, 0.8], jnp.float32)
    lp = np.asarray(per_row_log_probs(
        logits, temperature=temperature, top_k=top_k, top_p=top_p
    ))
    # row 0 greedy: point mass
    g = int(np.argmax(np.asarray(logits[0])))
    assert lp[0, g] == 0.0
    assert np.all(np.isneginf(np.delete(lp[0], g)))
    # row 1 top-k=4: mass only on the 4 largest, softmax over them
    scaled = np.asarray(logits[1]) / 0.7
    keep = np.argsort(scaled)[-4:]
    assert set(np.nonzero(np.isfinite(lp[1]))[0]) == set(keep)
    ref = np.exp(scaled[keep]) / np.exp(scaled[keep]).sum()
    np.testing.assert_allclose(
        np.exp(lp[1, keep]), ref, rtol=1e-5, atol=1e-6
    )
    # every row is a normalized distribution
    np.testing.assert_allclose(
        np.exp(lp).sum(axis=-1), 1.0, rtol=1e-5
    )
    # row 2 nucleus: the kept set is the smallest prefix covering top_p
    probs = np.exp(scaled2 := np.asarray(logits[2]) / 1.3)
    probs /= probs.sum()
    order = np.argsort(probs)[::-1]
    csum = np.cumsum(probs[order])
    n_keep = int(np.searchsorted(csum, 0.8) + 1)
    assert set(np.nonzero(np.isfinite(lp[2]))[0]) == set(order[:n_keep])


# ---------------------------------------------------------------------------
# speculative_accept: exactness (greedy) and distribution preservation


def _draft_for(d_logits, keys, temperature, top_k, top_p):
    """Draft tokens exactly the way the engine drafts them: step i
    samples from the warped draft row with salt i."""
    b, k, _ = d_logits.shape
    toks = []
    for i in range(k):
        ki = jax.vmap(lambda kk: jax.random.fold_in(kk, i))(keys)
        toks.append(sample_logits_per_row(
            d_logits[:, i], ki, temperature=temperature, top_k=top_k,
            top_p=top_p,
        ))
    return jnp.stack(toks, axis=1)


def test_speculative_accept_greedy_is_target_argmax_prefix():
    """Greedy rows: whatever the draft proposed, the emitted window is
    exactly the target's argmax chain prefix — accepted drafts matched
    the argmax, the correction/bonus IS the argmax."""
    rng = np.random.Generator(np.random.PCG64(1))
    b, k, v = 24, 3, 32
    t_logits = jnp.asarray(rng.normal(0, 1.5, (b, k + 1, v)).astype(np.float32))
    d_logits = jnp.asarray(rng.normal(0, 1.5, (b, k, v)).astype(np.float32))
    # half the rows: draft agrees with the target argmax on every step
    agree = np.asarray(t_logits[: b // 2, :k])
    d_logits = d_logits.at[: b // 2].set(jnp.asarray(agree))
    keys = jax.vmap(jax.random.fold_in, (None, 0))(
        jax.random.key(9), jnp.arange(b)
    )
    zeros = jnp.zeros(b, jnp.float32)
    d_toks = _draft_for(d_logits, keys, zeros, jnp.zeros(b, jnp.int32),
                        jnp.ones(b, jnp.float32))
    emit, n_emit = speculative_accept(
        t_logits, d_logits, d_toks, jnp.full(b, k, jnp.int32), keys,
        temperature=zeros, top_k=jnp.zeros(b, jnp.int32),
        top_p=jnp.ones(b, jnp.float32),
    )
    emit, n_emit = np.asarray(emit), np.asarray(n_emit)
    argmax = np.argmax(np.asarray(t_logits), axis=-1)
    for r in range(b):
        for j in range(n_emit[r]):
            assert emit[r, j] == argmax[r, j], (r, j)
    # agreeing drafts accept everything: K drafts + the bonus token
    assert np.all(n_emit[: b // 2] == k + 1)


def test_speculative_accept_preserves_target_distribution():
    """The acceptance identity, empirically: over many independent rows
    with the SAME logits, the first emitted token's marginal equals the
    warped target distribution (TVD well under the sampling noise floor)
    — speculation is throughput, not distribution shift."""
    rng = np.random.Generator(np.random.PCG64(7))
    b, k, v = 4000, 2, 12
    t_row = rng.normal(0, 1.2, (k + 1, v)).astype(np.float32)
    d_row = rng.normal(0, 1.2, (k, v)).astype(np.float32)
    t_logits = jnp.broadcast_to(jnp.asarray(t_row), (b, k + 1, v))
    d_logits = jnp.broadcast_to(jnp.asarray(d_row), (b, k, v))
    keys = jax.vmap(jax.random.fold_in, (None, 0))(
        jax.random.key(3), jnp.arange(b)
    )
    temperature = jnp.full(b, 0.9, jnp.float32)
    top_k = jnp.full(b, 6, jnp.int32)
    top_p = jnp.full(b, 0.92, jnp.float32)
    d_toks = _draft_for(d_logits, keys, temperature, top_k, top_p)
    emit, n_emit = speculative_accept(
        t_logits, d_logits, d_toks, jnp.full(b, k, jnp.int32), keys,
        temperature=temperature, top_k=top_k, top_p=top_p,
    )
    p0 = np.exp(np.asarray(per_row_log_probs(
        jnp.asarray(t_row[:1]), temperature=temperature[:1],
        top_k=top_k[:1], top_p=top_p[:1],
    ))[0])
    emp = np.bincount(np.asarray(emit)[:, 0], minlength=v) / b
    tvd = 0.5 * np.abs(emp - p0).sum()
    assert tvd < 0.03, tvd  # measured ~0.009; noise floor ~sqrt(v/b)~0.05
    # and speculation actually accepts: the draft shares no structure
    # with the target here, yet SOME proposals land in the overlap
    assert int(np.asarray(n_emit).max()) > 1

    # n_spec=0 rows degrade to the plain warped target draw
    emit0, n_emit0 = speculative_accept(
        t_logits, d_logits, d_toks, jnp.zeros(b, jnp.int32), keys,
        temperature=temperature, top_k=top_k, top_p=top_p,
    )
    assert np.all(np.asarray(n_emit0) == 1)
    emp0 = np.bincount(np.asarray(emit0)[:, 0], minlength=v) / b
    assert 0.5 * np.abs(emp0 - p0).sum() < 0.03


# ---------------------------------------------------------------------------
# engine: greedy bit-identity under stagger + slot pressure


def test_spec_engine_greedy_matches_static_gpt2(tmp_path):
    """GPT-2, staggered arrivals, 2 slots for 4 requests: every
    speculative-engine stream equals the static generate() row — the
    acceptance criterion's bit-identity pin. The engine writes to a
    telemetry sink so the same run pins the spec schema fields on the
    `serve` rows and the `serve_summary` (docs/OBSERVABILITY.md §1)."""
    from tpudist.telemetry import TelemetrySink

    model = _gpt2()
    prompts = np.stack(_prompts([6, 6, 6, 6], seed=1))
    params = _params(model, 1)
    draft, dparams = early_exit_draft(model, params, 1)
    static = generate(model, params, prompts, 10, temperature=0.0)

    sink = TelemetrySink(str(tmp_path / "s.jsonl"))
    eng = ServeEngine(model, params, max_slots=2, seed=0, sink=sink,
                      stats_every=1, draft_model=draft,
                      draft_params=dparams, spec_k=3)
    rids = [eng.submit(prompts[i], 10) for i in range(2)]
    for _ in range(3):  # the stagger: later requests arrive mid-decode
        eng.step()
    rids += [eng.submit(prompts[i], 10) for i in (2, 3)]
    out = eng.run()
    for i in range(4):
        np.testing.assert_array_equal(out[rids[i]], static[i])
    snap = eng.stats.snapshot()
    assert snap["spec_drafted"] > 0
    sink.close()
    rows = [json.loads(l) for l in open(tmp_path / "s.jsonl")]
    serve = [r for r in rows if r["kind"] == "serve"]
    assert serve and all(
        {"spec_drafted", "spec_accepted", "spec_acceptance_rate"}
        <= set(r) for r in serve
    )
    assert sum(r["spec_drafted"] for r in serve) > 0
    summary = [r for r in rows if r["kind"] == "serve_summary"][-1]
    assert summary["spec_drafted"] >= summary["spec_accepted"] > 0
    assert 0 < summary["spec_acceptance_rate"] <= 1.0


def test_spec_engine_greedy_matches_plain_engine_llama_with_eos():
    """Llama (per-row RoPE path), mixed lengths, per-request stop token:
    the speculative engine's streams equal the non-speculative engine's
    token for token — including eos cuts discovered mid-window."""
    model = _llama()
    params = _params(model, 2)
    prompts = _prompts([3, 6, 5, 9], seed=3)

    def run(spec_kw):
        eng = ServeEngine(model, params, max_slots=2, seed=0, **spec_kw)
        rids = [eng.submit(pr, 12, eos_id=7) for pr in prompts[:3]]
        for _ in range(2):
            eng.step()
        rids.append(eng.submit(prompts[3], 12, eos_id=7))
        return [eng.run()[r] for r in rids]

    draft, dparams = early_exit_draft(model, params, 1)
    plain = run({})
    spec = run(dict(draft_model=draft, draft_params=dparams, spec_k=4))
    for a, b in zip(plain, spec):
        assert a == b


# ---------------------------------------------------------------------------
# rollback = cursor bookkeeping; multi-token events; telemetry


def test_spec_sampled_budget_eos_and_cursor_invariant():
    """Sampled speculative serving, stepped by hand: every stream stops
    within budget and never past its stop token — and after EVERY tick,
    each owned slot's synced cursor equals prompt_len + emitted − 1 (the
    position its NEXT input token writes at). That cursor equality IS
    the draft-"rollback" contract: rejected drafts moved nothing but the
    cursor, whatever the acceptance pattern was."""
    model = _gpt2()
    params = _params(model, 1)
    draft, dparams = early_exit_draft(model, params, 1)
    eng = ServeEngine(model, params, max_slots=3, seed=5,
                      draft_model=draft, draft_params=dparams, spec_k=3)
    prompts = _prompts([4, 7, 5, 9, 6], seed=11)
    rids = [
        eng.submit(pr, 9, temperature=0.9, top_k=20, top_p=0.95, eos_id=5)
        for pr in prompts
    ]
    plens = {r: len(p) for r, p in zip(rids, prompts)}
    while eng.pending:
        eng.step()
        for slot in np.nonzero(eng.pool.active)[0]:
            rid = int(eng._req[slot])
            if rid < 0 or rid not in eng._counts:
                continue
            assert eng.pool.positions[slot] == (
                plens[rid] + eng._counts[rid] - 1
            ), (slot, rid)
    for r in rids:
        toks = eng.result(r)
        assert 1 <= len(toks) <= 9
        assert all(t != 5 for t in toks[:-1])
    assert not eng.pending


def test_spec_multi_token_events_ordered_with_full_acceptance():
    """With the draft == the target every proposal is accepted: each live
    slot emits spec_k+1 tokens per tick (the full-accept bonus path), so
    a single tick's event list carries runs of consecutive indices per
    request — in order, each its own TokenEvent, done only on the last,
    on_token seeing exactly the same sequence events() yields."""
    model = _gpt2()
    params = _params(model, 1)
    seen: list[tuple[int, int, bool]] = []
    eng = ServeEngine(
        model, params, max_slots=2, seed=0, draft_model=model,
        draft_params=params, spec_k=3,
        on_token=lambda ev: seen.append((ev.request_id, ev.index, ev.done)),
    )
    prompts = _prompts([4, 6], seed=4)
    rids = [eng.submit(pr, 9) for pr in prompts]
    streamed = list(eng.events())
    assert [(e.request_id, e.index, e.done) for e in streamed] == seen
    for r in rids:
        idx = [e.index for e in streamed if e.request_id == r]
        assert idx == list(range(9))
        dones = [e.done for e in streamed if e.request_id == r]
        assert dones == [False] * 8 + [True]
    # full acceptance on-record, and some tick really batched K+1 events
    # for one request (multi-token emission, not one-at-a-time)
    snap = eng.stats.snapshot()
    assert snap["spec_acceptance_rate"] == 1.0
    assert snap["spec_accepted"] == snap["spec_drafted"] > 0


# ---------------------------------------------------------------------------
# paged + spec: eviction / preemption torture


def test_spec_paged_preemption_torture_keeps_greedy_identity():
    """Paged speculative serving under real block starvation: a pool far
    too small for the worst case forces the whole escalation ladder
    (force-fetch, prefix eviction, preempt-to-queue with replay), and
    every stream STILL equals the plain contiguous engine's greedy
    output — speculation composes with paged memory without touching
    the replay/rng/cursor contract."""
    model = _gpt2()
    params = _params(model, 3)
    prompts = _prompts([9, 11, 8, 12, 10, 7], seed=5)
    draft, dparams = early_exit_draft(model, params, 1)

    plain = ServeEngine(model, params, max_slots=3, seed=0)
    rids = [plain.submit(pr, 20) for pr in prompts]
    want = [plain.run()[r] for r in rids]

    eng = ServeEngine(
        model, params, max_slots=3, seed=0, paged=True, block_size=4,
        n_blocks=13, watermark_blocks=1, draft_model=draft,
        draft_params=dparams, spec_k=3,
    )
    rids = [eng.submit(pr, 20) for pr in prompts]
    got = [eng.run()[r] for r in rids]
    assert got == [list(w) for w in want]
    assert eng.stats.preemptions > 0  # the torture actually tortured


def test_spec_paged_ensure_to_maps_whole_window():
    """ensure_to maps every block the conservative dispatch window needs
    in one call, reports dry pools, and never exceeds the table."""
    model = _gpt2()
    pool = PagedSlotPool(model, 2, n_blocks=6, block_size=8)
    row = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: model.init_cache(1)),
    )
    slot = pool.insert(row, 5, prompt=np.arange(5, dtype=np.int32))
    assert pool.ensure_to(slot, 20)  # 3 blocks for 20 tokens
    assert int(pool.fill[slot]) == 3
    assert pool.ensure_to(slot, 20)  # idempotent
    assert int(pool.fill[slot]) == 3
    assert not pool.ensure_to(slot, 64)  # 8 blocks > 5 usable: dry
    assert int(pool.fill[slot]) == 5  # partial progress stays mapped


def test_draft_equivalent_blocks_buys_the_draft_bytes():
    """The equal-HBM handicap: the extra target blocks the AR baseline
    gets must cover the draft pool's bytes (rounded up)."""
    model = _gpt2()
    draft = model.clone(depth=1)
    extra = draft_equivalent_blocks(model, draft, max_slots=4, block_size=8)
    per_block = cache_bytes(model, 1) // model.max_seq_len * 8
    assert extra * per_block >= cache_bytes(draft, 4)
    assert (extra - 1) * per_block < cache_bytes(draft, 4)


# ---------------------------------------------------------------------------
# construction validation + helpers


def test_spec_engine_validates_draft():
    model = _gpt2()
    params = _params(model)
    draft, dparams = early_exit_draft(model, params, 1)
    with pytest.raises(ValueError, match="draft_params"):
        ServeEngine(model, params, draft_model=draft)
    with pytest.raises(ValueError, match="spec_k"):
        ServeEngine(model, params, draft_model=draft, draft_params=dparams,
                    spec_k=0)
    with pytest.raises(ValueError, match="vocab"):
        ServeEngine(model, params, draft_model=_gpt2().clone(vocab_size=32),
                    draft_params=dparams)
    with pytest.raises(ValueError, match="max_seq_len"):
        ServeEngine(model, params, draft_model=model.clone(max_seq_len=32),
                    draft_params=dparams)


def test_early_exit_draft_slices_and_validates():
    model = _gpt2()
    params = _params(model)
    draft, dparams = early_exit_draft(model, params, 1)
    assert draft.depth == 1 and draft.vocab_size == model.vocab_size
    assert set(dparams) == {"wte", "wpe", "ln_f", "h_0"}
    # shared arrays, not copies: zero extra weight HBM
    assert all(
        a is b for a, b in zip(
            jax.tree_util.tree_leaves(dparams["wte"]),
            jax.tree_util.tree_leaves(params["wte"]),
        )
    )
    with pytest.raises(ValueError, match="depth"):
        early_exit_draft(model, params, model.depth)
    with pytest.raises(ValueError, match="unrolled"):
        early_exit_draft(model, {"wte": {}, "wpe": {}, "ln_f": {}}, 1)
    llama = _llama()
    lp = _params(llama)
    ld, ldp = early_exit_draft(llama, lp, 1)
    assert set(ldp) == {"embed", "norm", "lm_head", "layer_0"}


def test_write_row_pins_slot_and_validates_range():
    model = _gpt2()
    pool = SlotPool(model, 2)
    row = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: model.init_cache(1)),
    )
    pool.write_row(row, 1)
    assert pool.n_active == 0  # bypasses occupancy bookkeeping
    with pytest.raises(ValueError, match="slot"):
        pool.write_row(row, 2)
