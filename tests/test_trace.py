"""The span layer and live metrics exporter (tpudist.telemetry.trace,
docs/OBSERVABILITY.md §8): span row schema, run_id plumbing, the serve
tracer's exact phase telescoping (queued + prefill + decode + preempted ==
total) under preemption and speculative decoding, SLO-sample parity
(span-derived TTFT/TPOT bit-equal to the ServeStats deques), the
byte-identity contract with the features off, and the Prometheus text
endpoint."""

import json
import pathlib
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpudist.models.gpt2 import GPT2
from tpudist.resilience.exitcodes import RUN_ID_ENV, ensure_run_id, run_id
from tpudist.serve import ServeEngine
from tpudist.telemetry import TelemetrySink
from tpudist.telemetry.trace import MetricsExporter, ServeTracer, Tracer


def _gpt2(max_seq_len=64):
    return GPT2(vocab_size=64, max_seq_len=max_seq_len, hidden_dim=32,
                depth=2, num_heads=4)


def _params(model, seed=0):
    import jax

    return model.init(
        jax.random.key(seed), np.zeros((1, 8), np.int32), train=False
    )["params"]


def _prompts(lens, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(0, vocab, (p,)).astype(np.int32) for p in lens]


def _rows(path):
    return [json.loads(l) for l in pathlib.Path(path).read_text().splitlines()]


def _spans(path, name=None):
    out = [r for r in _rows(path) if r["kind"] == "span"]
    return out if name is None else [r for r in out if r["name"] == name]


# -- Tracer (train-side) -----------------------------------------------------


def test_tracer_span_and_instant_schema(tmp_path):
    sink_clock = iter([50.0, 51.0]).__next__
    sink = TelemetrySink(tmp_path / "t.jsonl", rank=2, clock=sink_clock)
    tr = Tracer(sink, cat="train", process_index=3, generation=1,
                clock=lambda: 100.0)
    tr.span("step", 0.25, step=7, data_wait_s=0.01)
    tr.instant("repair", step=8, cause="loss_spike")
    sink.close()
    rows = _rows(tmp_path / "t.jsonl")
    assert rows[0] == {
        "v": 1, "t": 50.0, "kind": "span", "rank": 2, "step": 7,
        "name": "step", "cat": "train", "ph": "X",
        "t0": 99.75, "dur_s": 0.25,  # t0 defaults to now - dur_s
        "process_index": 3, "generation": 1, "data_wait_s": 0.01,
    }
    assert rows[1]["ph"] == "i" and rows[1]["dur_s"] == 0.0
    assert rows[1]["t0"] == 100.0 and rows[1]["cause"] == "loss_spike"


# -- run_id plumbing ---------------------------------------------------------


def test_run_id_minted_once_and_inherited(monkeypatch):
    env = {}
    rid = ensure_run_id(env)
    assert env[RUN_ID_ENV] == rid and len(rid) == 12
    assert ensure_run_id(env) == rid  # idempotent — relaunches inherit
    assert run_id(env) == rid
    assert run_id({}) is None and run_id({RUN_ID_ENV: "  "}) is None


def test_sink_appends_run_id_last(tmp_path, monkeypatch):
    monkeypatch.delenv(RUN_ID_ENV, raising=False)
    clock = iter([1.0, 2.0]).__next__
    with TelemetrySink(tmp_path / "a.jsonl", clock=clock) as sink:
        sink.write("health", 1, loss=0.5)
    bare = _rows(tmp_path / "a.jsonl")[0]
    assert "run_id" not in bare  # no env, no explicit id: byte-identical

    monkeypatch.setenv(RUN_ID_ENV, "envid0000000")
    clock = iter([1.0, 2.0]).__next__
    with TelemetrySink(tmp_path / "b.jsonl", clock=clock) as sink:
        assert sink.run_id == "envid0000000"  # env fallback
        sink.write("health", 1, loss=0.5)
    row = json.loads((tmp_path / "b.jsonl").read_text())
    assert list(row)[-1] == "run_id"  # appended AFTER existing fields
    assert {k: v for k, v in row.items() if k != "run_id"} == bare


# -- serve tracer: exact phase telescoping -----------------------------------


def test_serve_tracer_phases_telescope_exactly(tmp_path):
    """Synthetic lifecycle with a preemption, on dyadic timestamps so
    float addition is exact: the four phases must sum to the total."""
    sink = TelemetrySink(tmp_path / "s.jsonl", clock=lambda: 0.0)
    tr = ServeTracer(sink)
    t = lambda k: k / 1024.0  # dyadic — exact float arithmetic
    tr.on_submit(7, t(0), lane=2)
    tr.on_admit(7, t(10), pool_occupancy=0.5)
    tr.on_first_token(7, t(30), slot=1, prefix_hit=2, prefix_lookup=4)
    tr.on_spec(7, 8, 6)
    tr.on_preempt(7, t(50), pool_occupancy=1.0)
    tr.on_resume(7, t(90), slot=0)
    tr.on_done(7, t(130), 12, pool_occupancy=0.25)
    sink.close()
    spans = _spans(tmp_path / "s.jsonl")
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert [s["name"] for s in spans] == [
        "queued", "prefill", "decode", "preempt", "preempted", "decode",
        "request",
    ]
    req = by_name["request"][0]
    assert req["queued_s"] == t(10) and req["prefill_s"] == t(20)
    assert req["decode_s"] == t(20) + t(40)  # both decode segments
    assert req["preempt_s"] == t(40) and req["preempts"] == 1
    total = req["queued_s"] + req["prefill_s"] + req["decode_s"] \
        + req["preempt_s"]
    assert total == req["dur_s"] == t(130)  # EXACT, not approx
    assert req["ttft_s"] == t(30) and req["tpot_s"] == t(100) / 11
    assert req["lane"] == 2 and req["tokens"] == 12
    assert req["spec_drafted"] == 8 and req["spec_accepted"] == 6
    assert req["prefix_hit_blocks"] == 2 and req["prefix_lookup_blocks"] == 4
    # the two decode segments individually cover the decode total
    assert sum(s["dur_s"] for s in by_name["decode"]) == req["decode_s"]


# -- engine integration ------------------------------------------------------


def test_engine_spans_reconcile_with_stats(tmp_path):
    """Real engine, traced: every retired request has a terminal span
    whose phase sum matches its total within float addition error, and
    the span-derived TTFT/TPOT samples are BIT-EQUAL to the ServeStats
    SLO deques (the tracer reuses the exact clock readings)."""
    model = _gpt2()
    params = _params(model)
    sink = TelemetrySink(tmp_path / "e.jsonl")
    eng = ServeEngine(model, params, max_slots=2, seed=0, sink=sink,
                      stats_every=5, trace=True)
    prompts = _prompts([6, 10, 4, 8], seed=3)
    rids = [eng.submit(p, 6 + i, priority=i % 2)
            for i, p in enumerate(prompts)]
    eng.run()
    sink.close()
    reqs = _spans(tmp_path / "e.jsonl", "request")
    assert sorted(r["rid"] for r in reqs) == sorted(rids)
    for r in reqs:
        phase_sum = (r["queued_s"] + r["prefill_s"] + r["decode_s"]
                     + r["preempt_s"])
        assert abs(phase_sum - r["dur_s"]) < 1e-9
    # bit-equal SLO parity: same floats, same arithmetic
    assert sorted(r["ttft_s"] for r in reqs) == sorted(eng.stats.ttft)
    assert sorted(r["tpot_s"] for r in reqs if r["tpot_s"] is not None) \
        == sorted(eng.stats.tpot)
    # percentiles derived from spans == the serve_summary percentiles
    snap = eng.stats.snapshot()
    assert snap["ttft_p50"] == round(
        float(np.percentile([r["ttft_s"] for r in reqs], 50)), 6
    )
    # queue-wait samples == the queued-phase spans of first admissions
    assert sorted(s["dur_s"] for s in _spans(tmp_path / "e.jsonl", "queued")) \
        == sorted(eng.stats.queue_wait)
    # the tick backbone exists and carries the scheduler state
    ticks = _spans(tmp_path / "e.jsonl", "tick")
    assert ticks and all("queue_depth" in s and "tokens" in s for s in ticks)


def test_engine_trace_preemption_cycle(tmp_path):
    """The paged eviction cycle (pool runs dry mid-decode), traced: the
    preempted request's span decomposition includes the preemption gap
    and still telescopes to its total."""
    model = _gpt2()
    params = _params(model, 1)
    sink = TelemetrySink(tmp_path / "p.jsonl")
    eng = ServeEngine(model, params, max_slots=3, seed=0, paged=True,
                      block_size=8, n_blocks=8, watermark_blocks=0,
                      prefix_cache=False, sink=sink, trace=True)
    for p in _prompts([6, 6, 6], seed=5):
        eng.submit(p, 12)
    eng.run()
    sink.close()
    assert eng.stats.preemptions > 0
    path = tmp_path / "p.jsonl"
    assert len(_spans(path, "preempt")) == eng.stats.preemptions
    assert len(_spans(path, "preempted")) == eng.stats.preemptions
    reqs = _spans(path, "request")
    assert len(reqs) == 3
    preempted = [r for r in reqs if r["preempts"] > 0]
    assert preempted
    for r in reqs:
        phase_sum = (r["queued_s"] + r["prefill_s"] + r["decode_s"]
                     + r["preempt_s"])
        assert abs(phase_sum - r["dur_s"]) < 1e-9
        assert (r["preempt_s"] > 0) == (r["preempts"] > 0)
    assert sorted(r["ttft_s"] for r in reqs) == sorted(eng.stats.ttft)


def test_engine_trace_speculative(tmp_path):
    """Traced speculative engine: the per-request spec accounting on the
    terminal spans sums to the ServeStats lifetime totals."""
    from tpudist.serve import early_exit_draft

    model = _gpt2()
    params = _params(model)
    draft, dparams = early_exit_draft(model, params, 1)
    sink = TelemetrySink(tmp_path / "sp.jsonl")
    eng = ServeEngine(model, params, max_slots=2, seed=0, sink=sink,
                      draft_model=draft, draft_params=dparams, spec_k=3,
                      trace=True)
    for p in _prompts([6, 9], seed=2):
        eng.submit(p, 10)
    eng.run()
    sink.close()
    reqs = _spans(tmp_path / "sp.jsonl", "request")
    assert len(reqs) == 2
    assert sum(r["spec_drafted"] for r in reqs) == eng.stats.spec_drafted
    assert sum(r["spec_accepted"] for r in reqs) == eng.stats.spec_accepted
    assert eng.stats.spec_drafted > 0
    for r in reqs:
        phase_sum = (r["queued_s"] + r["prefill_s"] + r["decode_s"]
                     + r["preempt_s"])
        assert abs(phase_sum - r["dur_s"]) < 1e-9


# -- byte-identity with the features off -------------------------------------


def test_serve_stream_byte_identical_with_trace_off(tmp_path, monkeypatch):
    """The standing telemetry contract: with tracing and metrics off the
    stream is byte-identical — and with them ON, the only difference is
    APPENDED span rows (frozen clocks make both runs deterministic)."""
    monkeypatch.delenv(RUN_ID_ENV, raising=False)
    model = _gpt2()
    params = _params(model)
    prompts = _prompts([5, 7, 4], seed=1)

    def run(path, **kw):
        sink = TelemetrySink(path, clock=lambda: 50.0)
        eng = ServeEngine(model, params, max_slots=2, seed=0, sink=sink,
                          stats_every=3, clock=lambda: 100.0, **kw)
        out = {r: eng.submit(p, 5) for r, p in enumerate(prompts)}
        eng.run()
        eng.close()
        sink.close()
        return out

    run(tmp_path / "off.jsonl")
    run(tmp_path / "off2.jsonl")
    run(tmp_path / "on.jsonl", trace=True, metrics_port=0)
    off = (tmp_path / "off.jsonl").read_bytes()
    assert off == (tmp_path / "off2.jsonl").read_bytes()  # deterministic
    on_lines = (tmp_path / "on.jsonl").read_bytes().splitlines(keepends=True)
    stripped = b"".join(
        l for l in on_lines if json.loads(l)["kind"] != "span"
    )
    assert stripped == off  # tracing only ADDS rows, never perturbs


def test_telemetry_stream_byte_identical_with_trace_off(tmp_path, monkeypatch):
    """Same contract on the train-side Telemetry driver: attaching a
    Tracer + exporter adds span rows and changes nothing else."""
    from tpudist.telemetry import Telemetry, TelemetryConfig

    monkeypatch.delenv(RUN_ID_ENV, raising=False)

    def run(path, traced):
        sink = TelemetrySink(path, clock=lambda: 9.0)
        tel = Telemetry(TelemetryConfig(), sink, log_every=2, n_chips=1)
        if traced:
            tel.tracer = Tracer(sink, clock=lambda: 77.0)
            tel.exporter = MetricsExporter(0)
        for g in range(1, 6):
            tel.on_step(g, {"loss": 1.0 / g}, epoch=0, interval_s=0.5,
                        data_wait_s=0.01, dispatch_s=0.2, device_s=0.3)
        tel.shutdown()

    run(tmp_path / "off.jsonl", traced=False)
    run(tmp_path / "on.jsonl", traced=True)
    off = (tmp_path / "off.jsonl").read_bytes()
    on_lines = (tmp_path / "on.jsonl").read_bytes().splitlines(keepends=True)
    stripped = b"".join(
        l for l in on_lines if json.loads(l)["kind"] != "span"
    )
    assert stripped == off
    # and the traced stream got a span for EVERY resolved step — the
    # timeline backbone is per-step, not log_every-thinned
    steps = [json.loads(l) for l in on_lines
             if json.loads(l)["kind"] == "span"]
    assert [s["step"] for s in steps] == [1, 2, 3, 4, 5]
    assert all(s["name"] == "step" and s["dur_s"] == 0.5 for s in steps)


def test_engine_off_constructs_nothing(tmp_path):
    model = _gpt2()
    eng = ServeEngine(model, _params(model), max_slots=2, seed=0)
    assert eng.tracer is None and eng.exporter is None
    assert eng.metrics_port is None
    with pytest.raises(ValueError):
        ServeEngine(model, _params(model), trace=True)  # needs a sink


# -- metrics exporter --------------------------------------------------------


def test_metrics_exporter_end_to_end():
    with MetricsExporter(0, host="127.0.0.1") as exp:
        assert exp.port > 0
        exp.set(step=3, mfu=0.41, update_skips_total=2, gone=1.0)
        exp.set(gone=None)  # None clears
        exp.add_collector(lambda: {"serve_queue_depth": 5,
                                   "serve_ttft_p50": None,
                                   "bad:name": 1.5})
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=10
        ).read().decode()
        assert "tpudist_step 3" in body
        assert "tpudist_mfu 0.41" in body
        assert "# TYPE tpudist_mfu gauge" in body
        # _total suffix types as counter
        assert "# TYPE tpudist_update_skips_total counter" in body
        assert "tpudist_serve_queue_depth 5" in body
        assert "gone" not in body and "ttft_p50" not in body
        assert "tpudist_bad_name 1.5" in body  # sanitized
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10
            )


def test_metrics_exporter_collector_failure_is_contained():
    with MetricsExporter(0, host="127.0.0.1") as exp:
        exp.set(ok=1.0)
        exp.add_collector(lambda: (_ for _ in ()).throw(RuntimeError("x")))
        assert "tpudist_ok 1" in exp.render()  # scrape survives


def test_engine_metrics_endpoint_serves_live_stats(tmp_path):
    model = _gpt2()
    sink = TelemetrySink(tmp_path / "m.jsonl")
    eng = ServeEngine(model, _params(model), max_slots=2, seed=0,
                      sink=sink, metrics_port=0)
    for p in _prompts([5, 6], seed=4):
        eng.submit(p, 4)
    eng.run()
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{eng.metrics_port}/metrics", timeout=10
    ).read().decode()
    assert "tpudist_serve_completed 2" in body
    assert "tpudist_serve_ttft_p50" in body
    assert "# TYPE tpudist_serve_preemptions_total counter" in body
    eng.close()
    sink.close()
    assert eng.exporter is None  # closed and detached
