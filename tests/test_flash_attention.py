"""Flash-attention kernel vs the XLA oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.ops.attention import dot_product_attention
from tpudist.ops.flash_attention import flash_attention


def _qkv(b=2, s=256, h=4, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_xla(causal, kernel_parity):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = dot_product_attention(q, k, v, causal=causal)
    kernel_parity(out, ref)


def test_forward_bf16(kernel_parity):
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    kernel_parity(out, ref)


def test_multiple_k_blocks_small_blocks():
    # exercises the online-softmax accumulation across 4 K blocks and 4 Q blocks
    q, k, v = _qkv(s=512, h=2)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_xla(causal):
    q, k, v = _qkv(b=1, s=128, h=2, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(gf, gr, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_seq_pads_and_masks(causal):
    """200 % 128 != 0: the wrapper pads to 256 and masks the padded keys —
    output and grads match the XLA oracle on the unpadded shape."""
    q, k, v = _qkv(s=200)
    out = flash_attention(q, k, v, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    assert out.shape == ref.shape
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) ** 2)

    g_fl = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(dot_product_attention), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_fl, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5, err_msg=name
        )


def test_explicit_kv_len_matches_sliced_keys():
    q, k, v = _qkv(s=256)
    ref = dot_product_attention(q, k[:, :130], v[:, :130], causal=False)
    out = flash_attention(q, k, v, kv_len=130)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_head_dim_padding():
    # head_dim 64 (GPT-2's) is zero-padded to the 128-lane tile internally
    q, k, v = _qkv(s=128, d=64)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    assert out.shape == q.shape
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_pallas_bwd_matches_scan_bwd():
    """The opt-in Pallas FA-2 backward (interpret mode here) must produce
    the same dq/dk/dv as the default blockwise-scan backward."""
    from tpudist.ops.flash_attention import (
        _bwd_blockwise, _bwd_pallas, _flash_fwd,
    )

    rng = np.random.Generator(np.random.PCG64(9))
    B, S, H, D = 2, 256, 2, 128
    sm = 1.0 / np.sqrt(D)
    for causal in (False, True):
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
            for _ in range(3)
        )
        o, lse = _flash_fwd(
            q, k, v, causal=causal, sm_scale=sm, block_q=128, block_k=128
        )
        g = jnp.asarray(rng.normal(size=o.shape), jnp.float32)
        res = (q, k, v, o, lse)
        got = _bwd_pallas(
            res, g, causal=causal, sm_scale=sm, block_q=128, block_k=128,
            interpret=True,
        )
        want = _bwd_blockwise(res, g, causal=causal, sm_scale=sm, block_k=128)
        for a, b in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )


def test_pallas_bwd_kv_len_matches_scan_bwd():
    """kv_len masking through the Pallas dq/dkv kernels (interpret mode)
    agrees with the blockwise-scan backward on the same masked problem."""
    from tpudist.ops.flash_attention import (
        _bwd_blockwise, _bwd_pallas, _flash_fwd,
    )

    rng = np.random.Generator(np.random.PCG64(11))
    B, S, H, D = 1, 256, 2, 128
    sm = 1.0 / np.sqrt(D)
    kv_len = 140  # second K block partially masked, none fully retired
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        for _ in range(3)
    )
    o, lse = _flash_fwd(
        q, k, v, causal=False, sm_scale=sm, block_q=128, block_k=128,
        kv_len=kv_len,
    )
    g = jnp.asarray(rng.normal(size=o.shape), jnp.float32)
    res = (q, k, v, o, lse)
    got = _bwd_pallas(
        res, g, causal=False, sm_scale=sm, block_q=128, block_k=128,
        kv_len=kv_len, interpret=True,
    )
    want = _bwd_blockwise(
        res, g, causal=False, sm_scale=sm, block_k=128, kv_len=kv_len
    )
    for name, a, b in zip("dq dk dv".split(), got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5, err_msg=name
        )
    # padded keys receive zero gradient
    assert np.abs(np.asarray(got[1][:, :, kv_len:])).max() == 0.0
    assert np.abs(np.asarray(got[2][:, :, kv_len:])).max() == 0.0
