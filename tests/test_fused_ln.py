"""Fused residual-add+LayerNorm/RMSNorm kernel (tpudist/ops/layernorm.py)
vs the flax reference composition, interpret mode on CPU — the parity half
of the step-fusion layer (docs/PERF.md §4c). Covers the three public
compositions (plain / post-norm / pre-norm), both norm flavors, fp32+bf16,
edge shapes (non-lane-divisible hidden, non-tile row counts), gradients,
and the four model families' ``fused_ln`` knob (identical param trees,
forward/grad parity, scan layouts, untouched decode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from tpudist.ops.layernorm import FusedLayerNorm, fused_layernorm


def _data(shape, seed=0, dtype=jnp.float32):
    rng = np.random.Generator(np.random.PCG64(seed))
    return jnp.asarray(rng.standard_normal(shape), dtype)


def _ref_ln(x, scale, bias, *, eps, dtype, rms):
    if rms:
        return nn.RMSNorm(epsilon=eps, dtype=dtype).apply(
            {"params": {"scale": scale}}, x
        )
    return nn.LayerNorm(epsilon=eps, dtype=dtype).apply(
        {"params": {"scale": scale, "bias": bias}}, x
    )


# ---- kernel-level parity ---------------------------------------------------


@pytest.mark.parametrize("d", [64, 80, 768])  # 80: non-lane-divisible
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("rms", [False, True])
def test_forward_matches_flax(d, dtype, rms, kernel_parity):
    x = _data((3, 7, d), 1, dtype)  # 21 rows: not a tile multiple either
    y = _data((3, 7, d), 2, dtype)
    scale = _data((d,), 3)
    bias = _data((d,), 4)
    ref_r = x + y
    ref_n = _ref_ln(ref_r, scale, bias, eps=1e-5, dtype=dtype, rms=rms)
    n, r = fused_layernorm(
        x, scale, None if rms else bias, residual=y, eps=1e-5, rms=rms,
        out_dtype=dtype,
    )
    assert n.dtype == jnp.dtype(dtype) and r.dtype == x.dtype
    kernel_parity(n, ref_n)
    kernel_parity(r, ref_r)


def test_plain_and_post_norm_variants(kernel_parity):
    """No-residual (first/final LN) and post-norm (BERT) compositions."""
    x = _data((5, 96), 5)
    y = _data((5, 96), 6)
    scale, bias = _data((96,), 7), _data((96,), 8)
    kernel_parity(
        fused_layernorm(x, scale, bias, eps=1e-12),
        _ref_ln(x, scale, bias, eps=1e-12, dtype=jnp.float32, rms=False),
    )
    kernel_parity(
        fused_layernorm(x, scale, bias, residual=y, eps=1e-12,
                        return_residual=False),
        _ref_ln(x + y, scale, bias, eps=1e-12, dtype=jnp.float32, rms=False),
    )


@pytest.mark.parametrize("rms", [False, True])
@pytest.mark.parametrize("d", [80, 128])
def test_grads_match_flax(rms, d, kernel_parity):
    """Pre-norm composition with BOTH outputs consumed: dx/dy/dscale/dbias
    against autodiff through the flax composition."""
    x, y = _data((4, 5, d), 10), _data((4, 5, d), 11)
    scale, bias = _data((d,), 12), _data((d,), 13)
    w = _data((d, d), 14)

    def fused_loss(x, y, scale, bias):
        n, r = fused_layernorm(x, scale, None if rms else bias, residual=y,
                               eps=1e-5, rms=rms)
        return jnp.sum((n @ w) ** 2) + jnp.sum(jnp.sin(r))

    def ref_loss(x, y, scale, bias):
        r = x + y
        n = _ref_ln(r, scale, bias, eps=1e-5, dtype=jnp.float32, rms=rms)
        return jnp.sum((n @ w) ** 2) + jnp.sum(jnp.sin(r))

    gf = jax.grad(fused_loss, argnums=(0, 1, 2, 3))(x, y, scale, bias)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(x, y, scale, bias)
    for name, a, b in zip("x y scale bias".split(), gf, gr):
        if rms and name == "bias":
            continue  # rms has no bias param; the dummy's grad is unused
        kernel_parity(a, b, atol=5e-5, rtol=5e-5)


def test_post_norm_grads_no_residual_cotangent(kernel_parity):
    """return_residual=False (post-norm): only the normed value feeds the
    loss; grads still match the reference sum+LN composition."""
    x, y = _data((8, 48), 20), _data((8, 48), 21)
    scale, bias = _data((48,), 22), _data((48,), 23)

    def fused_loss(x, y):
        n = fused_layernorm(x, scale, bias, residual=y,
                            return_residual=False, eps=1e-6)
        return jnp.sum(n ** 3)

    def ref_loss(x, y):
        return jnp.sum(
            _ref_ln(x + y, scale, bias, eps=1e-6, dtype=jnp.float32,
                    rms=False) ** 3
        )

    gf = jax.grad(fused_loss, argnums=(0, 1))(x, y)
    gr = jax.grad(ref_loss, argnums=(0, 1))(x, y)
    kernel_parity(gf[0], gr[0], atol=5e-5, rtol=5e-5)
    kernel_parity(gf[1], gr[1], atol=5e-5, rtol=5e-5)


def test_validation_errors():
    x = _data((4, 32), 0)
    with pytest.raises(ValueError, match="scale shape"):
        fused_layernorm(x, _data((16,), 1))
    with pytest.raises(ValueError, match="residual shape"):
        fused_layernorm(x, _data((32,), 1), residual=_data((4, 16), 2))
    with pytest.raises(ValueError, match="return_residual"):
        fused_layernorm(x, _data((32,), 1), return_residual=True)


def test_module_params_match_flax_modules():
    """FusedLayerNorm declares the exact nn.LayerNorm / nn.RMSNorm param
    tree — the checkpoint-compat contract the fused_ln knob relies on."""
    x = _data((2, 32), 0)
    fused = FusedLayerNorm(epsilon=1e-5).init(jax.random.key(0), x)
    flax_ln = nn.LayerNorm(epsilon=1e-5).init(jax.random.key(0), x)
    assert jax.tree_util.tree_structure(fused) == jax.tree_util.tree_structure(flax_ln)
    fused_rms = FusedLayerNorm(rms=True).init(jax.random.key(0), x)
    flax_rms = nn.RMSNorm().init(jax.random.key(0), x)
    assert jax.tree_util.tree_structure(fused_rms) == jax.tree_util.tree_structure(flax_rms)


# ---- model-family knob -----------------------------------------------------


def _gpt2(**kw):
    from tpudist.models.gpt2 import GPT2

    return GPT2(vocab_size=97, max_seq_len=32, hidden_dim=48, depth=2,
                num_heads=4, **kw)


def _llama(**kw):
    from tpudist.models.llama import Llama

    return Llama(vocab_size=97, max_seq_len=32, hidden_dim=48, depth=2,
                 num_heads=4, num_kv_heads=2, **kw)


def _bert(**kw):
    from tpudist.models.bert import Bert

    return Bert(vocab_size=97, max_seq_len=32, hidden_dim=48, depth=2,
                num_heads=4, **kw)


def _vit(**kw):
    from tpudist.models.vit import ViT

    return ViT(num_classes=10, patch_size=4, hidden_dim=48, depth=2,
               num_heads=4, mlp_dim=96, **kw)


_TOKENS = jnp.asarray(
    np.random.Generator(np.random.PCG64(0)).integers(0, 97, (2, 16)),
    jnp.int32,
)
_IMAGES = _data((2, 16, 16, 3), 99)


@pytest.mark.parametrize("build,inp", [
    (_gpt2, _TOKENS), (_llama, _TOKENS), (_bert, _TOKENS), (_vit, _IMAGES),
], ids=["gpt2", "llama", "bert", "vit"])
def test_model_fused_ln_parity(build, inp, kernel_parity):
    """Same params, same tree, same function (to kernel tolerance) — the
    fused_ln knob across all four families, forward AND grads."""
    m0, m1 = build(), build(fused_ln=True)
    v0 = m0.init(jax.random.key(0), inp, train=False)
    v1 = m1.init(jax.random.key(0), inp, train=False)
    assert jax.tree_util.tree_structure(v0) == jax.tree_util.tree_structure(v1)
    o0 = m0.apply(v0, inp, train=False)
    o1 = m1.apply(v0, inp, train=False)
    kernel_parity(o1, o0, atol=5e-5, rtol=5e-5)

    g0 = jax.grad(lambda p: jnp.mean(
        m0.apply({"params": p}, inp, train=True) ** 2))(v0["params"])
    g1 = jax.grad(lambda p: jnp.mean(
        m1.apply({"params": p}, inp, train=True) ** 2))(v0["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g0)):
        kernel_parity(a, b, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("build", [_gpt2, _llama, _bert],
                         ids=["gpt2", "llama", "bert"])
def test_model_fused_ln_scan_layout(build, kernel_parity):
    """fused_ln composes with scan_layers (the one-traced-block layout)."""
    m0 = build(scan_layers=True)
    m1 = build(scan_layers=True, fused_ln=True)
    v0 = m0.init(jax.random.key(0), _TOKENS, train=False)
    kernel_parity(
        m1.apply(v0, _TOKENS, train=False),
        m0.apply(v0, _TOKENS, train=False),
        atol=5e-5, rtol=5e-5,
    )


def test_fused_ln_decode_path_unchanged():
    """Decode keeps the reference composition: a fused_ln GPT-2 generates
    BIT-identically to the unfused one (the decode trace never touches the
    kernel — single-token norms are launch-bound, not bandwidth-bound)."""
    from tpudist.generate import generate

    m0, m1 = _gpt2(), _gpt2(fused_ln=True)
    v = m0.init(jax.random.key(0), _TOKENS, train=False)
    prompt = _TOKENS[:, :4]
    out0 = generate(m0, v["params"], prompt, max_new_tokens=6, temperature=0.0)
    out1 = generate(m1, v["params"], prompt, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
