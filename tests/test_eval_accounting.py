"""Multi-process eval accounting (tpudist.train.evaluate).

Round-1 review finding: the denominator assumed every process feeds an
identical full-copy val loader, so a per-process SHARDED loader silently
mis-scaled accuracy. The fix counts both hits and the denominator from the
global padding mask in-graph. This test launches a real 2-process world
(4 emulated devices each) and requires the replicated-loader and
sharded-loader conventions to report the SAME accuracy on the same val set.
"""

import json
import os
import subprocess
import sys
import textwrap
import pytest

pytestmark = pytest.mark.slow  # subprocess world: cold-compiles its own jax programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, os, sys

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import optax

    from tpudist import create_mesh, init_from_env
    from tpudist.data.cifar import to_tensor
    from tpudist.data.digits import load_digits_dataset
    from tpudist.data.loader import DataLoader
    from tpudist.data.sampler import DistributedSampler
    from tpudist.models import resnet18
    from tpudist.train import create_train_state, evaluate

    ctx = init_from_env()
    mesh = create_mesh()
    model = resnet18(num_classes=10, small_inputs=True)
    state = create_train_state(
        model, 0, jnp.zeros((1, 32, 32, 3)), optax.adam(1e-3), mesh
    )

    val = load_digits_dataset(train=False)  # 360 rows, divisible by 2 procs

    # convention A (the reference's): every process iterates the FULL set
    rep_loader = DataLoader(val, 60, transform=to_tensor, drop_remainder=False)
    acc_rep = evaluate(model, state, rep_loader, mesh)

    # convention B: each process iterates its own disjoint shard; same
    # number of batches per process (6) keeps the collectives in lockstep
    sampler = DistributedSampler(
        len(val["label"]), num_replicas=ctx.process_count,
        rank=ctx.process_index, shuffle=False,
    )
    sh_loader = DataLoader(
        val, 30, sampler=sampler, transform=to_tensor, drop_remainder=False
    )
    acc_sh = evaluate(model, state, sh_loader, mesh)

    if ctx.process_index == 0:
        out = {"acc_rep": acc_rep, "acc_sh": acc_sh}
        with open(os.path.join(os.environ["OUT_DIR"], "acc.json"), "w") as f:
            json.dump(out, f)
""")


def test_sharded_and_replicated_val_loaders_agree(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["OUT_DIR"] = str(tmp_path)
    # the child script lives in tmp_path, so the repo must be importable
    # via PYTHONPATH rather than sys.path[0]
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = 29500 + os.getpid() % 500  # avoid colliding with a parallel run
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch",
            "--nproc_per_node=2", "--emulate-devices=4",
            f"--master_port={port}", str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    got = json.loads((tmp_path / "acc.json").read_text())
    # same 360 rows scored once (sharded) or twice-identically (replicated):
    # identical accuracy, and both in [0, 1]
    assert got["acc_rep"] == got["acc_sh"], got
    assert 0.0 <= got["acc_rep"] <= 1.0
