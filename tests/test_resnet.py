"""ResNet model family (tpudist.models.resnet) — the reference's model
(/root/reference/main.py:40) and its depth variants."""

import jax
import jax.numpy as jnp


def test_resnet_variant_factories():
    """Depth variants build and the block math matches torchvision's layer
    counts (resnet34 basic [3,4,6,3], resnet101/152 bottleneck)."""
    from tpudist.models import resnet34, resnet101, resnet152

    assert resnet34().stage_sizes == [3, 4, 6, 3]
    assert resnet101().stage_sizes == [3, 4, 23, 3]
    assert resnet152().stage_sizes == [3, 8, 36, 3]
    m = resnet34(num_classes=10, small_inputs=True)
    variables = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    logits = m.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)
