"""ResNet model family (tpudist.models.resnet) — the reference's model
(/root/reference/main.py:40) and its depth variants."""

import jax
import jax.numpy as jnp


def test_resnet_variant_factories():
    """Depth variants build and the block math matches torchvision's layer
    counts (resnet34 basic [3,4,6,3], resnet101/152 bottleneck)."""
    from tpudist.models import resnet34, resnet101, resnet152

    assert resnet34().stage_sizes == [3, 4, 6, 3]
    assert resnet101().stage_sizes == [3, 4, 23, 3]
    assert resnet152().stage_sizes == [3, 8, 36, 3]
    m = resnet34(num_classes=10, small_inputs=True)
    variables = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    logits = m.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)


def test_space_to_depth_stem():
    """The MLPerf stem keeps the stage geometry of conv7 (same feature-map
    sizes into stage 1) and trains; odd input sizes are rejected."""
    import numpy as np
    import pytest

    from tpudist.models import resnet18

    for stem in ("conv7", "space_to_depth"):
        m = resnet18(num_classes=10, stem=stem)
        v = m.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False)
        logits = m.apply(v, jnp.zeros((2, 64, 64, 3)), train=False)
        assert logits.shape == (2, 10), stem
    s2d = resnet18(num_classes=10, stem="space_to_depth")
    with pytest.raises(ValueError, match="even H/W"):
        s2d.init(jax.random.key(0), jnp.zeros((1, 63, 63, 3)), train=False)
    with pytest.raises(ValueError, match="unknown stem"):
        resnet18(stem="wat").init(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False
        )
    # the s2d stem kernel sees 4x the input channels
    k = s2d.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False)
    assert k["params"]["conv_init_s2d"]["kernel"].shape == (4, 4, 12, 64)
    # and it trains: one SGD step moves the loss
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.train import create_train_state, make_train_step

    mesh = mesh_lib.create_mesh()
    tx = optax.sgd(0.1)
    state = create_train_state(s2d, 0, jnp.zeros((1, 64, 64, 3)), tx, mesh)
    step = make_train_step(s2d, tx, mesh)
    rng = np.random.Generator(np.random.PCG64(0))
    batch = {"image": rng.random((8, 64, 64, 3), np.float32),
             "label": rng.integers(0, 10, 8).astype(np.int32)}
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
