"""Host-side augmentation pipeline (tpudist.data.transforms)."""

import numpy as np

from tpudist.data.cifar import synthetic_cifar, to_tensor
from tpudist.data.transforms import (
    CIFAR10_MEAN, CIFAR10_STD, CIFAR100_MEAN, CIFAR100_STD, compose,
    normalize, random_crop_flip, standard_cifar_augment, standard_cifar_eval,
)


def _batch(n=16):
    return synthetic_cifar(n=n, num_classes=10)


def test_crop_flip_shapes_and_dtype():
    batch = _batch()
    out = random_crop_flip(seed=0)(batch)
    assert out["image"].shape == batch["image"].shape
    assert out["image"].dtype == batch["image"].dtype  # still uint8
    np.testing.assert_array_equal(out["label"], batch["label"])


def test_crop_zero_pad_no_flip_is_identity():
    batch = _batch()
    out = random_crop_flip(pad=0, flip=False)(batch)
    np.testing.assert_array_equal(out["image"], batch["image"])


def test_crop_preserves_pixel_population_per_row():
    """A crop with pad=0 shifts nothing; with flip the row pixel multiset is
    preserved (flip only reverses)."""
    batch = _batch(4)
    out = random_crop_flip(pad=0, flip=True, seed=3)(batch)
    a = np.sort(out["image"], axis=2)
    b = np.sort(batch["image"], axis=2)
    np.testing.assert_array_equal(a, b)


def test_normalize_statistics():
    batch = to_tensor(_batch(64))
    out = normalize()(batch)
    want = (batch["image"] - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(out["image"], want, rtol=1e-6)


def test_standard_pipeline_composes():
    batch = _batch()
    out = standard_cifar_augment(seed=0)(batch)
    assert out["image"].dtype == np.float32
    assert out["image"].shape == (16, 32, 32, 3)
    # normalized: roughly zero-centered
    assert abs(float(out["image"].mean())) < 1.5


def test_deterministic_given_seed():
    a = random_crop_flip(seed=7)(_batch())
    b = random_crop_flip(seed=7)(_batch())
    np.testing.assert_array_equal(a["image"], b["image"])


def test_trains_through_loader():
    import jax.numpy as jnp
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.data.loader import DataLoader
    from tpudist.models import resnet18
    from tpudist.train import create_train_state, make_train_step

    mesh = mesh_lib.create_mesh()
    data = _batch(32)
    loader = DataLoader(data, 16, transform=standard_cifar_augment(seed=0))
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    step = make_train_step(model, tx, mesh)
    for batch in loader:
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


def test_eval_transform_matches_train_stats():
    """standard_cifar_eval normalizes with the SAME per-dataset stats as
    standard_cifar_augment (no crop/flip)."""
    batch = _batch()
    ev = standard_cifar_eval(dataset="cifar100")(batch)
    want = (to_tensor(batch)["image"] - CIFAR100_MEAN) / CIFAR100_STD
    # the eval transform runs as ONE fused affine (x·1/(255σ) − μ/σ); the
    # reassociation differs from (x/255 − μ)/σ by float-epsilon only
    np.testing.assert_allclose(ev["image"], want, rtol=1e-4, atol=1e-6)


def test_device_normalize_matches_host_affine():
    """device_normalize (in-graph) computes the same affine as the host
    to_tensor_normalize, so a loader can switch to shipping uint8 + device
    transform without changing the numbers."""
    from tpudist.data.transforms import device_normalize, to_tensor_normalize

    batch = _batch()
    host = to_tensor_normalize(CIFAR10_MEAN, CIFAR10_STD)(batch)["image"]
    dev = np.asarray(device_normalize(CIFAR10_MEAN, CIFAR10_STD)(batch["image"]))
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_trains_on_uint8_batches_with_device_transform():
    """transform=None loader (raw uint8 over the wire) + in-graph
    device_normalize — the staging-bandwidth-lean input path."""
    import jax.numpy as jnp
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.data.loader import DataLoader
    from tpudist.data.transforms import device_normalize
    from tpudist.models import resnet18
    from tpudist.train import create_train_state, make_train_step

    mesh = mesh_lib.create_mesh()
    data = _batch(32)
    loader = DataLoader(data, 16, transform=None)
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    step = make_train_step(
        model, tx, mesh,
        input_transform=device_normalize(CIFAR10_MEAN, CIFAR10_STD),
    )
    for batch in loader:
        assert batch["image"].dtype == np.uint8
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


def test_device_random_crop_flip_step_keyed():
    """In-graph augmentation: uint8-preserving, deterministic per step,
    fresh across steps, identity population per row (crop+flip only move
    pixels)."""
    import jax.numpy as jnp

    from tpudist.data.transforms import device_random_crop_flip

    aug = device_random_crop_flip(pad=2, seed=0)
    assert aug.wants_step
    x = jnp.asarray(_batch(8)["image"])
    a1 = np.asarray(aug(x, 3))
    a2 = np.asarray(aug(x, 3))
    a3 = np.asarray(aug(x, 4))
    assert a1.dtype == np.uint8 and a1.shape == x.shape
    np.testing.assert_array_equal(a1, a2)  # same step -> same crops
    assert (a1 != a3).any()  # different step -> different crops


def test_device_compose_propagates_wants_step():
    from tpudist.data.transforms import (
        device_compose, device_normalize, device_random_crop_flip,
    )

    plain = device_compose(device_normalize(CIFAR10_MEAN, CIFAR10_STD))
    assert not plain.wants_step
    chain = device_compose(
        device_random_crop_flip(pad=2),
        device_normalize(CIFAR10_MEAN, CIFAR10_STD),
    )
    assert chain.wants_step
    import jax.numpy as jnp

    x = jnp.asarray(_batch(4)["image"])
    out = chain(x, 0)
    assert out.dtype == jnp.float32 and out.shape == x.shape


def test_augmented_device_cache_trains_and_eval_refuses_augment():
    """DeviceCachedLoader + in-graph crop/flip/normalize trains (fresh
    crops each step via the step key), and the eval path REFUSES a
    wants_step transform instead of silently scoring augmented inputs."""
    import jax.numpy as jnp
    import optax
    import pytest

    from tpudist import mesh as mesh_lib
    from tpudist.data.device_cache import DeviceCachedLoader
    from tpudist.data.transforms import (
        device_compose, device_normalize, device_random_crop_flip,
    )
    from tpudist.models import resnet18
    from tpudist.train import create_train_state, evaluate, make_train_step

    mesh = mesh_lib.create_mesh()
    data = _batch(32)
    cached = DeviceCachedLoader(data, 16, mesh=mesh)
    transform = cached.input_transform(
        device_compose(
            device_random_crop_flip(),
            device_normalize(CIFAR10_MEAN, CIFAR10_STD),
        )
    )
    assert transform.wants_step and transform.wants_batch
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    step = make_train_step(model, tx, mesh, input_transform=transform)
    for batch in cached:
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    with pytest.raises(ValueError, match="wants_step"):
        evaluate(model, state, cached, mesh, input_transform=transform)
