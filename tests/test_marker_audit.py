"""The tier-1 marker audit (tools/marker_audit.py): the offenders rule on
synthetic records, and the plugin end-to-end in a child pytest run — an
over-budget test without the ``slow`` marker fails the session (exit 3)
and is named; marking it ``slow`` passes the audit. Keeps the ``not
slow`` suite honest against the 870 s tier-1 window."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import marker_audit  # noqa: E402


def test_offenders_rule():
    records = [
        ("tests/a.py::test_fast", 0.5, False),
        ("tests/a.py::test_big_unmarked", 45.0, False),
        ("tests/a.py::test_bigger_unmarked", 90.0, False),
        ("tests/b.py::test_big_marked", 500.0, True),  # slow: exempt
    ]
    bad = marker_audit.offenders(records, budget=30.0)
    # slowest first, marked tests exempt however long they run
    assert bad == [
        ("tests/a.py::test_bigger_unmarked", 90.0),
        ("tests/a.py::test_big_unmarked", 45.0),
    ]
    assert marker_audit.offenders(records, budget=1000.0) == []


def _run_child_pytest(tmp_path, test_src, budget="0.2"):
    d = tmp_path / "suite"
    d.mkdir()
    (d / "test_child.py").write_text(test_src)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.join(REPO, "tools")
    env["TPUDIST_MARKER_BUDGET_S"] = budget
    env.pop("TPUDIST_MARKER_AUDIT", None)  # plugin loads via -p, not env
    env.pop("PYTEST_CURRENT_TEST", None)
    return subprocess.run(
        [sys.executable, "-m", "pytest", str(d), "-q", "-p", "marker_audit",
         "-p", "no:cacheprovider"],
        env=env, cwd=str(d), capture_output=True, text=True, timeout=120,
    )


def test_plugin_fails_unmarked_over_budget_test(tmp_path):
    r = _run_child_pytest(tmp_path, textwrap.dedent("""
        import time

        def test_quick():
            pass

        def test_creeping():
            time.sleep(0.5)
    """))
    assert r.returncode == marker_audit.EXIT_OFFENDERS, r.stdout + r.stderr
    assert "marker audit FAILED" in r.stdout
    assert "test_creeping" in r.stdout
    # the fast test is not named as an offender
    offenders_block = r.stdout.split("marker audit FAILED")[1]
    assert "test_quick" not in offenders_block


def test_plugin_passes_marked_slow_test(tmp_path):
    r = _run_child_pytest(tmp_path, textwrap.dedent("""
        import time
        import pytest

        @pytest.mark.slow
        def test_known_slow():
            time.sleep(0.5)

        def test_quick():
            pass
    """))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "within the" in r.stdout  # the all-clear summary line


# -- the world rule: subprocess-world tests must be marked slow --------------

# the pattern is assembled by concatenation so THIS module's source never
# contains it — the audit would otherwise flag test_marker_audit itself
_WORLD = "tpudist" + ".launch"


def test_spawns_world_and_world_offenders_rules():
    assert marker_audit.spawns_world(f'cmd = [sys.executable, "-m", "{_WORLD}"]')
    assert marker_audit.spawns_world("argv += ['--emulate" + "-devices=4']")
    # the elastic drills spawn child interpreters that build their own
    # emulated device world via the raw XLA flag, bypassing the launcher
    assert marker_audit.spawns_world(
        "env['XLA_FLAGS'] = '--xla_force_host_platform" + "_device_count=4'"
    )
    assert not marker_audit.spawns_world("import subprocess\nrun(['ls'])")
    records = [
        ("tests/w.py::test_world_unmarked", True, False),
        ("tests/w.py::test_world_marked", True, True),   # slow: exempt
        ("tests/a.py::test_plain", False, False),
    ]
    assert marker_audit.world_offenders(records) == [
        "tests/w.py::test_world_unmarked"
    ]


def test_plugin_flags_unmarked_world_test(tmp_path):
    # the child module spawns a world (by source inspection) but its test
    # is not marked slow: flagged at COLLECTION, before any cost is paid
    r = _run_child_pytest(tmp_path, textwrap.dedent(f"""
        LAUNCH = "{_WORLD}"  # would be subprocess.run([..., "-m", LAUNCH])

        def test_spawns_a_world():
            pass
    """), budget="1000")
    assert r.returncode == marker_audit.EXIT_OFFENDERS, r.stdout + r.stderr
    assert "subprocess world" in r.stdout
    assert "test_spawns_a_world" in r.stdout


def test_plugin_passes_marked_world_test(tmp_path):
    r = _run_child_pytest(tmp_path, textwrap.dedent(f"""
        import pytest

        LAUNCH = "{_WORLD}"

        pytestmark = pytest.mark.slow

        def test_spawns_a_world():
            pass
    """), budget="1000")
    assert r.returncode == 0, r.stdout + r.stderr
