"""Ring / Ulysses sequence parallelism vs single-device full attention,
on the 8 fake CPU devices (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist import mesh as mesh_lib
from tpudist.ops.attention import dot_product_attention
from tpudist.parallel.cp import ring_attention, ulysses_attention


def _mesh_seq4():
    # 2-way data x 4-way sequence over the 8 fake devices
    return mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, seq=4))


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = _mesh_seq4()
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    mesh = _mesh_seq4()
    q, k, v = _qkv()
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_grads_match_full():
    mesh = _mesh_seq4()
    q, k, v = _qkv(b=2, s=32, h=2, d=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=1e-4, rtol=1e-4)


def test_ring_under_jit_compiles_once():
    mesh = _mesh_seq4()
    q, k, v = _qkv()
    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))
    out = f(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_cp_gpt2_full_train_step_matches_unsharded():
    """GPT-2 with ring-attention context parallelism (tokens sharded over
    'seq') runs a full compiled train step and matches the plain XLA
    attention model's loss — CP changes placement, not math."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(7))
    batch = {"tokens": rng.integers(0, 64, (4, 16)).astype(np.int32)}

    losses = {}
    for name in ("xla", "ring"):
        if name == "xla":
            mesh = mesh_lib.create_mesh(
                mesh_lib.MeshConfig(data=1), devices=jax.devices()[:1]
            )
            model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32,
                         depth=2, num_heads=4)
            spec = None
        else:
            mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, seq=4))
            model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32,
                         depth=2, num_heads=4, attn_impl="ring", mesh=mesh)
            spec = {"tokens": P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
                                mesh_lib.SEQUENCE_AXIS)}
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((4, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
            batch_spec=spec,
        )
        state, metrics = step(state, batch)
        losses[name] = float(metrics["loss"])

    np.testing.assert_allclose(losses["xla"], losses["ring"], rtol=2e-5)


def test_ulysses_flash_gpt2_matches_xla():
    """attn_impl='ulysses_flash': all_to_all head re-shard + Pallas flash
    per head group ≡ plain XLA attention (same params, same loss)."""
    import optax

    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )
    from jax.sharding import PartitionSpec as P

    rng = np.random.Generator(np.random.PCG64(11))
    batch = {"tokens": rng.integers(0, 64, (4, 256)).astype(np.int32)}

    losses = {}
    for name in ("xla", "ulysses_flash"):
        if name == "xla":
            mesh = mesh_lib.create_mesh(
                mesh_lib.MeshConfig(data=1), devices=jax.devices()[:1]
            )
            spec = None
        else:
            mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, seq=4))
            spec = {"tokens": P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
                                mesh_lib.SEQUENCE_AXIS)}
        model = GPT2(vocab_size=64, max_seq_len=256, hidden_dim=32,
                     depth=1, num_heads=4, attn_impl=name,
                     mesh=mesh if name != "xla" else None)
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((4, 256), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
            batch_spec=spec,
        )
        # TWO steps: the second step's loss is computed from params updated
        # with the first step's gradients, so the flash vjp under the
        # all_to_all shard_map is numerically validated, not just executed
        run = []
        for _ in range(2):
            state, metrics = step(state, batch)
            run.append(float(metrics["loss"]))
        losses[name] = run
    np.testing.assert_allclose(losses["xla"], losses["ulysses_flash"], rtol=2e-4)
