import jax
import numpy as np
import pytest

from tpudist import mesh as mesh_lib


def test_default_mesh_is_pure_dp():
    m = mesh_lib.create_mesh()
    assert m.shape[mesh_lib.DATA_AXIS] == jax.device_count() == 8
    assert mesh_lib.data_parallel_size(m) == 8


def test_mesh_config_wildcard_and_validation():
    cfg = mesh_lib.MeshConfig(data=-1, tensor=2)
    m = mesh_lib.create_mesh(cfg)
    assert m.shape[mesh_lib.DATA_AXIS] == 4
    assert m.shape[mesh_lib.TENSOR_AXIS] == 2
    with pytest.raises(ValueError):
        mesh_lib.MeshConfig(data=3).axis_sizes(8)
    with pytest.raises(ValueError):
        mesh_lib.MeshConfig(data=-1, tensor=-1).axis_sizes(8)


def test_shard_batch_places_rows_on_devices():
    m = mesh_lib.create_mesh()
    batch = {"image": np.arange(16 * 4, dtype=np.float32).reshape(16, 4),
             "label": np.arange(16, dtype=np.int32)}
    global_batch = mesh_lib.shard_batch(batch, m)
    img = global_batch["image"]
    assert img.shape == (16, 4)
    assert len(img.sharding.device_set) == 8
    # each device holds 2 rows
    for shard in img.addressable_shards:
        assert shard.data.shape == (2, 4)
    np.testing.assert_array_equal(np.asarray(img), batch["image"])


def test_global_batch_sizes():
    m = mesh_lib.create_mesh()
    per_replica, per_process = mesh_lib.global_batch_sizes(64, m)
    assert per_replica == 8
    assert per_process == 64
    with pytest.raises(ValueError):
        mesh_lib.global_batch_sizes(30, m)


def test_topology_mesh_uses_all_devices_once():
    """Topology-aware placement is a reordering, never a resampling: every
    visible device appears exactly once regardless of mesh shape."""
    for cfg in (
        mesh_lib.MeshConfig(),
        mesh_lib.MeshConfig(data=2, tensor=4),
        mesh_lib.MeshConfig(data=2, pipe=2, seq=2),
    ):
        mesh = mesh_lib.create_mesh(cfg)
        assert sorted(d.id for d in mesh.devices.flat) == sorted(
            d.id for d in jax.devices()
        )
        assert len(set(mesh.devices.flat)) == jax.device_count()


def test_explicit_devices_keep_caller_order():
    devices = jax.devices()[:4][::-1]
    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=4), devices=devices
    )
    assert [d.id for d in mesh.devices.flat] == [d.id for d in devices]
