"""Packed (pre-decoded) image datasets — tpudist.data.packed.

The pack is the framework's answer to decode-bound streaming input
(SURVEY.md §7 hard-part #1 at BASELINE configs 2/3 scale): these tests pin
the one-time pack's bit-parity with the streaming eval loader, the memmap
round-trip, and that the packed dict drops into the existing array
pipeline (DataLoader gather, DeviceCachedLoader in-graph gather, fit).
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.data.packed import load_packed, pack_image_folder


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    """Tiny class-separable JPEG tree: 2 classes x 6 images, varied source
    sizes (the pack must resize/crop them to one shape)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    for ci, cls in enumerate(["cat", "dog"]):
        (root / cls).mkdir()
        for i in range(6):
            w, h = int(rng.integers(36, 64)), int(rng.integers(36, 64))
            base = np.full((h, w, 3), 40 + 160 * ci, np.uint8)
            noise = rng.integers(0, 40, (h, w, 3), dtype=np.uint8)
            Image.fromarray(base + noise).save(root / cls / f"{i}.jpg")
    return root


def test_pack_roundtrip(jpeg_tree, tmp_path):
    out = pack_image_folder(jpeg_tree, tmp_path / "p", image_size=24)
    assert out["n"] == 12 and out["images_per_sec"] > 0
    data = load_packed(tmp_path / "p")
    assert data["image"].shape == (12, 24, 24, 3)
    assert data["image"].dtype == np.uint8
    assert data["classes"] == ["cat", "dog"]
    np.testing.assert_array_equal(data["label"], [0] * 6 + [1] * 6)
    # memmap'd by default: pages fault in on demand
    assert isinstance(data["image"], np.memmap)


def test_pack_pixels_match_streaming_eval_loader(jpeg_tree, tmp_path):
    """Bit-parity with ImageFolderLoader(train=False): the pack is the eval
    transform applied once, not a different resample."""
    from tpudist.data.imagenet import ImageFolderLoader

    pack_image_folder(jpeg_tree, tmp_path / "p", image_size=24)
    packed = load_packed(tmp_path / "p")
    with ImageFolderLoader(
        jpeg_tree, 12, train=False, image_size=24, normalize=False,
        drop_remainder=False,
    ) as loader:
        batch = next(iter(loader))
    np.testing.assert_array_equal(np.asarray(packed["image"]), batch["image"])
    np.testing.assert_array_equal(packed["label"], batch["label"])


def test_val_pack_keyed_by_train_classes(jpeg_tree, tmp_path):
    """A val tree missing a class dir must keep the train label space
    (scan_image_folder's contract, carried through the pack CLI path)."""
    import shutil

    val_root = tmp_path / "val"
    shutil.copytree(jpeg_tree, val_root)
    shutil.rmtree(val_root / "cat")
    pack_image_folder(
        val_root, tmp_path / "v", image_size=24, classes=["cat", "dog"]
    )
    data = load_packed(tmp_path / "v")
    np.testing.assert_array_equal(data["label"], [1] * 6)  # dog stays 1
    with open(str(tmp_path / "v") + "_meta.json") as f:
        assert json.load(f)["classes"] == ["cat", "dog"]


def test_packed_streams_through_dataloader_and_device_cache(jpeg_tree, tmp_path):
    """The packed dict IS an array dataset: DataLoader gathers from the
    memmap, DeviceCachedLoader stages it to the (fake) device mesh and the
    in-graph gather reproduces the same pixels."""
    from tpudist import mesh as mesh_lib
    from tpudist.data.device_cache import DeviceCachedLoader
    from tpudist.data.loader import DataLoader
    from tpudist.data.sampler import DistributedSampler

    pack_image_folder(jpeg_tree, tmp_path / "p", image_size=24)
    data = load_packed(tmp_path / "p")
    dataset = {"image": data["image"], "label": data["label"]}

    sampler = DistributedSampler(12, num_replicas=1, rank=0, shuffle=True)
    host = next(iter(DataLoader(dataset, 8, sampler=sampler, transform=None)))
    assert host["image"].dtype == np.uint8 and host["image"].shape == (8, 24, 24, 3)

    mesh = mesh_lib.create_mesh()
    cached = DeviceCachedLoader(dataset, 8, mesh=mesh, sampler=sampler)
    batch = next(iter(cached))
    gathered = np.asarray(
        jnp.take(batch["_cache"], jnp.asarray(batch["image"]), axis=0)
    )
    np.testing.assert_array_equal(gathered, host["image"])
    np.testing.assert_array_equal(batch["label"], host["label"])


def test_pack_refuses_inconsistent_files(jpeg_tree, tmp_path):
    pack_image_folder(jpeg_tree, tmp_path / "p", image_size=24)
    np.save(str(tmp_path / "p") + "_labels.npy", np.zeros(3, np.int32))
    with pytest.raises(ValueError, match="inconsistent"):
        load_packed(tmp_path / "p")
