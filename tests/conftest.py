"""Test bring-up: 8 virtual CPU devices in one process.

The TPU-native analogue of torch's gloo-on-CPU distributed testing
(SURVEY.md §4): ``--xla_force_host_platform_device_count=8`` gives a real
8-device mesh with real XLA collectives, so DP sharding, psum gradient
equivalence, and cross-replica BN are all testable with no TPU attached.
Must run before jax initializes, hence module scope here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# some environments ship a sitecustomize that force-registers a TPU plugin
# and rewrites jax_platforms; pin it back to cpu before any backend spins up
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# persistent compilation cache (host-CPU-keyed dir, tpudist/utils/cache.py;
# opt OUT with TPUDIST_NO_JAX_CACHE=1): without it the 1-core cold suite
# runs >1h, far past any CI budget. Known environment wart: ONE program —
# the bert ring-collective train step — SIGABRTs in XLA:CPU when executed
# from a cache-loaded (AOT-deserialized) executable: measured 2/6 child
# runs abort with the cache, 0/6 without, and capping --xla_cpu_max_isa
# does not help (so it is the AOT round trip, not the ISA mismatch the
# cpu_aot_loader warnings suggest). That test runs subprocess-contained
# and CACHE-LESS (tests/test_bert.py), so a crash cannot take down a
# whole run. If aborts appear elsewhere, flip the env switch and purge
# /tmp/tpudist_jax_cache*.
if os.environ.get("TPUDIST_NO_JAX_CACHE", "").lower() not in ("1", "true", "yes"):
    from tpudist.utils.cache import host_keyed_cache_dir

    jax.config.update("jax_compilation_cache_dir", host_keyed_cache_dir())
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# The smoke tier: the fastest high-signal slice of the suite, sized for a
# COLD 1-core host (no persistent compile cache) to finish well inside a
# 10-minute budget — `pytest -m smoke`. Selection rule: every reference-
# parity layer gets at least one file (sampler shard math, metrics
# contract, mesh/shardings, DP-step equivalence, data paths, native C++
# round-trips, decode/generation), but compile-heavy model files
# (bert/t5/vit/pipeline/fsdp/moe/flash) and all subprocess tests stay out.
# Measured cold on this 1-core host: see README "Testing" for the number
# recorded at marking time.
_SMOKE_FILES = {
    "test_bench_record.py",
    "test_dp_equivalence.py",
    "test_generate.py",
    "test_lm_data.py",
    "test_lm_loss.py",
    "test_mesh.py",
    "test_metrics.py",
    "test_native.py",
    "test_packed.py",
    "test_sampler.py",
    "test_transforms.py",
}


def pytest_configure(config):
    """Opt-in tier-1 marker audit (tools/marker_audit.py): with
    ``TPUDIST_MARKER_AUDIT`` set, every executed test's call duration is
    checked against the per-test budget and the session FAILS (exit 3)
    if an over-budget test is missing the ``slow`` marker — the guard
    that keeps the ``not slow`` suite inside its 870 s tier-1 window."""
    if not os.environ.get("TPUDIST_MARKER_AUDIT"):
        return
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tools = os.path.join(repo, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import marker_audit

    # is_registered, not a name check: the same module may already be
    # loaded under its own name via `-p marker_audit` or the CLI wrapper,
    # and registering the object twice is a pytest startup error
    if not config.pluginmanager.is_registered(marker_audit):
        config.pluginmanager.register(marker_audit, "tpudist-marker-audit")


def pytest_collection_modifyitems(config, items):
    """Tests marked ``subproc_only`` run ONLY inside their wrapper's child
    process (TPUDIST_SUBPROC_TEST=1) — the containment mechanism for the
    crash-capable ring-collective test (see test_bert.py). Files in
    ``_SMOKE_FILES`` are additionally marked ``smoke`` (the cold-budget
    tier; ``slow``-marked tests inside them stay excluded via
    ``-m "smoke and not slow"`` semantics — the smoke command selects
    both)."""
    import pytest as _pytest

    if os.environ.get("TPUDIST_SUBPROC_TEST"):
        return
    skip = _pytest.mark.skip(reason="runs only inside its subprocess wrapper")
    for item in items:
        if "subproc_only" in item.keywords:
            item.add_marker(skip)
        if item.fspath.basename in _SMOKE_FILES and "slow" not in item.keywords:
            item.add_marker(_pytest.mark.smoke)


def assert_kernel_parity(got, want, *, rtol=None, atol=None):
    """The ONE interpret-mode parity bar for the Pallas kernels (flash /
    vmem attention, fused LN, fused AdamW): full-precision references get
    the flash/vmem suites' historical ``rtol=atol=2e-5``; half-precision
    references (bf16/fp16) get 2% of the reference's max magnitude —
    ≈2 ulp at the output scale, because a kernel computing its interior in
    fp32 legitimately differs from a reference that rounds intermediates
    to bf16 by up to an output-magnitude ulp. Kernel tests share this
    helper (the ``kernel_parity`` fixture) so the bar cannot drift
    per-file."""
    import numpy as np

    ref_dtype = np.asarray(want).dtype
    g = np.asarray(got, np.float32)
    w = np.asarray(want, np.float32)
    if ref_dtype.itemsize <= 2:
        scale = float(max(np.max(np.abs(w)), 1e-6))
        np.testing.assert_allclose(
            g, w, rtol=rtol or 0.0,
            atol=atol if atol is not None else 2e-2 * scale,
        )
    else:
        np.testing.assert_allclose(
            g, w, rtol=2e-5 if rtol is None else rtol,
            atol=2e-5 if atol is None else atol,
        )


@pytest.fixture
def kernel_parity():
    """Fixture handle on :func:`assert_kernel_parity` — request it in any
    Pallas-kernel test instead of hand-picking tolerances."""
    return assert_kernel_parity


def tiny_resnet():
    """2-stage/1-block/8-filter ResNet: same BN + residual + strided-stage
    topology as resnet18 at a fraction of the compile bill. The shared
    helper for compile-heavy ResNet tests — test_device_cache.py compiles
    each data path as its own program, and test_amp_optim.py's guard test
    runs cache-less every time (see no_persistent_compile_cache), so the
    geometry must stay identical between them."""
    from tpudist.models.resnet import ResNet, ResNetBlock

    return ResNet(stage_sizes=[1, 1], num_filters=8, block_cls=ResNetBlock,
                  num_classes=10, small_inputs=True)


@pytest.fixture
def no_persistent_compile_cache():
    """Disable the persistent compilation cache for ONE test.

    Second documented wart of the cache's AOT round trip on this XLA:CPU
    (the first is the bert ring-collective SIGABRT above): an executable
    LOADED from the persistent cache has been observed to misexecute the
    select-guarded optimizer-update pattern (``jnp.where(ok, new, old)``
    over donated state: the post-skip clean step leaves params frozen —
    measured failing with the cache, passing without, tpudist.telemetry's
    guard tests and test_amp_optim's), and a cache HIT emits no compile
    log at all, starving ``jax.log_compiles`` assertions. Tests touching
    either pattern opt out here; everything else keeps the >1h-saving
    cache.

    Flipping ``jax_compilation_cache_dir`` alone is NOT enough: the cache
    object is a process-lifetime singleton (``_initialize_cache`` runs at
    most once and never re-reads the config), so once any earlier test
    compiled anything, the config update is silently ignored. The
    singleton must be reset around the config change — and reset again on
    exit so the restored dir takes effect for the next test.
    """
    from jax._src import compilation_cache as _cc

    old = jax.config.jax_compilation_cache_dir
    _cc.reset_cache()
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
        _cc.reset_cache()
