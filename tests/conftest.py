"""Test bring-up: 8 virtual CPU devices in one process.

The TPU-native analogue of torch's gloo-on-CPU distributed testing
(SURVEY.md §4): ``--xla_force_host_platform_device_count=8`` gives a real
8-device mesh with real XLA collectives, so DP sharding, psum gradient
equivalence, and cross-replica BN are all testable with no TPU attached.
Must run before jax initializes, hence module scope here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# some environments ship a sitecustomize that force-registers a TPU plugin
# and rewrites jax_platforms; pin it back to cpu before any backend spins up
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# persistent compilation cache: the suite re-jits the same train steps many
# times (each fit() in its own test); caching compiled executables across
# tests and across runs cuts the suite from ~10min to ~2min on CPU.
# The dir is keyed by a hash of the host's CPU flags: XLA:CPU AOT results
# only WARN on a feature mismatch and then can SIGABRT mid-run (observed
# after a host migration under this environment's VM scheduler) — a
# per-feature-set dir turns that crash into a cold compile.
from tpudist.utils.cache import host_keyed_cache_dir  # noqa: E402

jax.config.update("jax_compilation_cache_dir", host_keyed_cache_dir())
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
