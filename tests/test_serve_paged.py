"""Paged KV cache, prefix cache, and paged decode attention
(tpudist.serve.blocks + ServeEngine(paged=True), docs/SERVING.md "Paged
memory"): greedy paged-engine output must be BIT-identical to the
contiguous engine — and hence to static ``generate()`` — under staggered
arrivals, slot pressure, mixed lengths + eos (GPT-2 and Llama GQA/RoPE),
copy-on-write prefix sharing, and a preempt-to-queue eviction cycle. Plus
the block-pool lifecycle invariants (refcount torture), the paged Pallas
kernel's parity against the gather-then-dense oracle, block-budget
admission, priority lanes, pool telemetry on the serve rows, and the
serving warm start through the AOT compile cache."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.generate import generate
from tpudist.models.gpt2 import GPT2
from tpudist.models.llama import Llama
from tpudist.ops.decode import paged_decode_attention
from tpudist.serve import BlockPool, PagedSlotPool, PrefixCache, ServeEngine
from tpudist.serve.blocks import GARBAGE_BLOCK


def _gpt2(max_seq_len=64):
    return GPT2(vocab_size=64, max_seq_len=max_seq_len, hidden_dim=32,
                depth=2, num_heads=4)


def _llama(max_seq_len=64, kv=2):
    return Llama(vocab_size=64, max_seq_len=max_seq_len, hidden_dim=32,
                 depth=2, num_heads=4, num_kv_heads=kv, ffn_dim=64)


def _params(model, seed=0):
    return model.init(
        jax.random.key(seed), np.zeros((1, 8), np.int32), train=False
    )["params"]


def _prompts(lens, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(0, vocab, (p,)).astype(np.int32) for p in lens]


def _pool_clean(engine):
    """After a full drain every block the slots held is back on the free
    list; only prefix-cache references may remain, and each of those is
    exactly one reference."""
    pool = engine.pool.blocks
    held = np.nonzero(pool.refcount > 0)[0]
    cached = (set() if engine.pool.prefix is None else
              {e.block for e in engine.pool.prefix._entries.values()})
    assert set(held.tolist()) == cached
    assert all(pool.refcount[b] == 1 for b in cached)


# ---------------------------------------------------------------------------
# equivalence: the acceptance-criterion tests


def test_paged_greedy_matches_static_under_slot_pressure():
    """GPT-2, staggered arrivals, 2 slots for 4 requests: paged greedy
    streams equal the static batch rows bit-for-bit (the same scenario
    test_serve pins for the contiguous engine)."""
    model = _gpt2()
    prompts = np.stack(_prompts([6, 6, 6, 6], seed=1))
    params = _params(model, 1)
    static = generate(model, params, prompts, 10, temperature=0.0)

    eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                      block_size=8, watermark_blocks=2)
    rids = [eng.submit(prompts[i], 10) for i in range(2)]
    for _ in range(3):
        eng.step()
    rids += [eng.submit(prompts[i], 10) for i in (2, 3)]
    out = eng.run()
    for i in range(4):
        np.testing.assert_array_equal(out[rids[i]], static[i])
    _pool_clean(eng)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_greedy_mixed_lengths_eos_sweep(family):
    """Mixed prompt lengths crossing block boundaries + per-request stop
    tokens, on both decode families (Llama = GQA + per-row RoPE): every
    paged stream equals the per-request static oracle truncated at its
    returned length."""
    model = _gpt2() if family == "gpt2" else _llama()
    params = _params(model, 2)
    prompts = _prompts([3, 6, 5, 9, 12, 17], seed=3)
    eos = 7
    oracle = {}
    for i, pr in enumerate(prompts):
        toks, lens = generate(model, params, pr[None], 12, temperature=0.0,
                              eos_id=eos, return_lengths=True)
        oracle[i] = toks[0, : lens[0]].tolist()

    eng = ServeEngine(model, params, max_slots=3, seed=0, paged=True,
                      block_size=8, watermark_blocks=2)
    rids = [eng.submit(prompts[i], 12, eos_id=eos) for i in range(3)]
    for _ in range(2):
        eng.step()
    rids += [eng.submit(prompts[i], 12, eos_id=eos) for i in (3, 4, 5)]
    out = eng.run()
    for i in range(6):
        assert out[rids[i]] == oracle[i], (family, i)
    _pool_clean(eng)


def test_paged_eviction_cycle_bit_identical():
    """A pool sized so mid-decode growth runs it dry: the engine must
    preempt a slot to the queue (blocks free NOW) and re-admit it later —
    and every request's greedy stream STILL equals the static oracle
    bit-for-bit through the eviction/replay cycle."""
    model = _gpt2()
    params = _params(model, 1)
    prompts = _prompts([6, 6, 6], seed=5)
    static = {
        i: generate(model, params, p[None], 12, temperature=0.0)[0].tolist()
        for i, p in enumerate(prompts)
    }
    # 3 slots but only 7 usable blocks of 8: three requests at ~18 tokens
    # each need 9 blocks — the third forces a preemption mid-decode
    eng = ServeEngine(model, params, max_slots=3, seed=0, paged=True,
                      block_size=8, n_blocks=8, watermark_blocks=0,
                      prefix_cache=False)
    rids = [eng.submit(p, 12) for p in prompts]
    out = eng.run()
    for i in range(3):
        assert out[rids[i]] == static[i], i
    assert eng.stats.preemptions > 0  # the cycle actually happened
    assert eng.pool.blocks.n_free == eng.pool.blocks.n_usable
    _pool_clean(eng)


def test_cow_divergence_matches_cold_runs():
    """Two requests sharing a 24-token system prompt then diverging: the
    second (cache-hit) admission's tokens are bit-identical to a cold
    run, the prefix cache actually hit, and the shared blocks are mapped
    (not copied) by both physical tables."""
    model = _gpt2()
    params = _params(model, 1)
    system = _prompts([24], seed=9)[0]
    tails = _prompts([4, 7], seed=11)
    full = [np.concatenate([system, t]) for t in tails]
    cold = {
        i: generate(model, params, p[None], 8, temperature=0.0)[0].tolist()
        for i, p in enumerate(full)
    }

    eng = ServeEngine(model, params, max_slots=4, seed=0, paged=True,
                      block_size=8, watermark_blocks=2)
    r0 = eng.submit(full[0], 8)
    out0 = eng.run()
    # the three full system-prompt blocks are now cached (refcount 1)
    assert len(eng.pool.prefix) == 3
    eng.step()  # idle tick: no admissions pending
    r1 = eng.submit(full[1], 8)
    # admit WITHOUT stepping to inspect sharing before retirement
    eng._admit()
    slot = int(np.nonzero(eng.pool.active)[0][0])
    cached_blocks = {e.block for e in eng.pool.prefix._entries.values()}
    mapped = set(eng.pool.tables[slot][: int(eng.pool.fill[slot])].tolist())
    assert len(cached_blocks & mapped) == 3  # shared, not re-written
    out1 = eng.run()
    assert out0[r0] == cold[0]
    assert out1[r1] == cold[1]
    assert eng.stats.prefix_hit_rate is not None
    assert eng.stats.prefix_hit_rate > 0
    _pool_clean(eng)


def test_engine_rerun_deterministic_across_instances():
    """Regression for the XLA:CPU host-buffer aliasing wart: device_put
    zero-copy ALIASES aligned numpy arguments, and under async dispatch
    the decode step could read positions/cursor lanes AFTER the host
    already mutated them in place — corrupting streams per-process-
    deterministically (~80% of processes before _dispatch snapshotted its
    host arrays; this exact scenario reproduced it)."""
    model = _gpt2()
    params = _params(model, 1)
    pr = _prompts([5], seed=105)[0]
    oracle = generate(model, params, pr[None], 10, temperature=0.0)[0].tolist()
    for paged in (False, True):
        for _ in range(2):
            kw = dict(paged=True, block_size=8, watermark_blocks=2) \
                if paged else {}
            eng = ServeEngine(model, params, max_slots=2, seed=0, **kw)
            r = eng.submit(pr, 10)
            assert eng.run()[r] == oracle, paged


# ---------------------------------------------------------------------------
# block pool + prefix cache lifecycle


def test_block_pool_refcount_rules():
    pool = BlockPool(6)
    assert pool.n_usable == 5
    b = pool.alloc()
    assert b != GARBAGE_BLOCK and pool.refcount[b] == 1
    pool.incref(b)
    pool.decref(b)
    assert pool.n_free == 4  # still held once
    pool.decref(b)
    assert pool.n_free == 5  # returned exactly at zero
    with pytest.raises(RuntimeError, match="double free"):
        pool.decref(b)
    with pytest.raises(RuntimeError, match="unallocated"):
        pool.incref(b)
    # exhaustion probes None, never raises
    got = [pool.alloc() for _ in range(6)]
    assert got[-1] is None and all(g is not None for g in got[:-1])


def test_block_pool_garbage_block_reserved():
    pool = BlockPool(4)
    assert GARBAGE_BLOCK not in [pool.alloc() for _ in range(3)]
    with pytest.raises(RuntimeError):
        pool.decref(GARBAGE_BLOCK)


def test_refcount_torture_interleaved_admit_retire_evict():
    """Fragmentation/refcount torture: randomized interleaved admissions
    (shared prefixes), retirements, prefix evictions, and mid-decode
    block growth across many cycles — afterwards, zero leaked and zero
    double-freed blocks, and every remaining reference is a prefix-cache
    entry at refcount exactly 1 (slot references all returned)."""
    model = _gpt2(max_seq_len=64)
    params = _params(model, 0)
    eng = ServeEngine(model, params, max_slots=4, seed=0, paged=True,
                      block_size=8, n_blocks=24, watermark_blocks=1)
    rng = np.random.Generator(np.random.PCG64(42))
    shared = _prompts([16], seed=77)[0]
    live = []
    for cycle in range(60):
        roll = rng.random()
        if roll < 0.5 and len(live) < 10:
            plen = int(rng.integers(3, 20))
            if rng.random() < 0.5:
                pr = np.concatenate(
                    [shared, rng.integers(0, 64, (plen,)).astype(np.int32)]
                )
            else:
                pr = rng.integers(0, 64, (plen,)).astype(np.int32)
            budget = int(rng.integers(1, 12))
            try:
                live.append(eng.submit(pr, budget))
            except ValueError:
                pass  # request can never fit this pool: fine
        elif roll < 0.8:
            eng.step()
        else:
            eng.pool.evict_prefix(int(rng.integers(1, 3)))
        # invariant at every point: free + referenced = usable
        pool = eng.pool.blocks
        assert pool.n_free + int((pool.refcount > 0).sum()) == pool.n_usable
    eng.run()
    _pool_clean(eng)
    # the cache's own refs die at refcount 0 too
    eng.pool.evict_prefix(len(eng.pool.prefix or ()) or 1)
    if eng.pool.prefix is not None:
        eng.pool.prefix.evict(10_000)
        assert eng.pool.blocks.n_free == eng.pool.blocks.n_usable


def test_prefix_cache_chain_hash_and_lru_leaf_eviction():
    pool = BlockPool(12)
    cache = PrefixCache(pool, block_size=4)
    toks = np.arange(12, dtype=np.int32)
    blocks = [pool.alloc() for _ in range(3)]
    cache.insert(toks, blocks, 0)
    assert len(cache) == 3
    # chained: a matching prefix hits in order; a diverging block-1 chain
    # breaks the walk after block 0
    assert cache.lookup(toks, 12) == blocks
    fork = toks.copy()
    fork[5] = 63
    assert cache.lookup(fork, 12) == blocks[:1]
    # while a "slot" (our alloc refs) maps the blocks, NOTHING evicts
    assert cache.evict(3) == 0
    for b in blocks:  # the slot releases: cache-only refs remain
        pool.decref(b)
    # eviction takes LRU LEAVES only: the chain tail goes first, a
    # mid-chain block is never freed while its child lives
    assert cache.evict(1) == 1
    assert cache.lookup(toks, 12) == blocks[:2]
    assert pool.refcount[blocks[2]] == 0
    assert pool.refcount[blocks[1]] == 1
    # a slot re-mapping a block pins it (and its ancestors) again
    pool.incref(blocks[1])
    assert cache.evict(2) == 0
    assert cache.lookup(toks, 12) == blocks[:2]
    pool.decref(blocks[1])
    assert cache.evict(2) == 2  # tail-first down the chain
    assert cache.lookup(toks, 12) == []
    assert pool.n_free == pool.n_usable


def test_prefix_lookup_caps_at_limit():
    pool = BlockPool(12)
    cache = PrefixCache(pool, block_size=4)
    toks = np.arange(8, dtype=np.int32)
    blocks = [pool.alloc() for _ in range(2)]
    cache.insert(toks, blocks, 0)
    # a 8-token prompt may only consume 7 tokens of cache (the last
    # prompt token must re-run for its logits): one full block, not two
    assert cache.lookup(toks, 7) == blocks[:1]
    assert cache.lookup(toks, 8) == blocks


# ---------------------------------------------------------------------------
# paged slot pool + admission


def test_paged_pool_utilization_reports_block_occupancy():
    """The satellite bug fix: under paged admission `utilization` must be
    BLOCK occupancy, not active/max_slots — one long request in 1 of 4
    slots can hold most of the pool's bytes."""
    model = _gpt2()
    pool = PagedSlotPool(model, 4, n_blocks=9, block_size=8,
                         prefix_cache=False)
    row = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
            train=False, decode=True)["cache"]),
    )
    pool.insert(row, 48)  # 6 of 8 usable blocks, one slot of four
    assert pool.n_active == 1
    assert pool.utilization == pytest.approx(6 / 8)   # byte truth
    assert pool.n_active / pool.max_slots == 0.25     # the old reading


def test_paged_pool_validation():
    model = _gpt2()
    with pytest.raises(ValueError, match="block_size"):
        PagedSlotPool(model, 2, n_blocks=8, block_size=7)
    with pytest.raises(ValueError, match="n_blocks"):
        PagedSlotPool(model, 2, n_blocks=1, block_size=8)


def test_submit_rejects_never_fitting_request():
    model = _gpt2()
    params = _params(model, 0)
    eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                      block_size=8, n_blocks=4)  # 3 usable blocks = 24 toks
    with pytest.raises(ValueError, match="raise n_blocks"):
        eng.submit(_prompts([20])[0], 10)


def test_block_budget_admission_stalls_then_drains():
    """Admission is block-budget, not slot-count: with slots free but the
    pool near-full, the queued request waits; decode retirements free
    blocks and it admits on a later tick — no deadlock, full drain."""
    model = _gpt2()
    params = _params(model, 0)
    eng = ServeEngine(model, params, max_slots=4, seed=0, paged=True,
                      block_size=8, n_blocks=6, watermark_blocks=1,
                      prefix_cache=False)
    a = eng.submit(_prompts([10], seed=1)[0], 6)   # 2 blocks + growth
    b = eng.submit(_prompts([10], seed=2)[0], 6)
    eng.step()
    # pool: 5 usable, slot a holds 2; b needs 2 + watermark 1 → admitted;
    # a third long prompt cannot admit until someone retires
    c = eng.submit(_prompts([16], seed=3)[0], 4)
    depths = []
    while eng.pending:
        eng.step()
        depths.append(eng.queue_depth)
    assert max(depths[:1] + [0]) <= 1  # c queued at first
    out_lens = {r: len(eng.result(r)) for r in (a, b, c)}
    assert out_lens == {a: 6, b: 6, c: 4}
    _pool_clean(eng)


def test_one_token_admission_releases_prefix_pins():
    """Regression: an admission that completes at its first sample
    (max_new_tokens=1 / instant EOS) never takes a slot — it must still
    release the refcount pins admission placed on its prefix-cache hits,
    or the hit blocks stay elevated forever (unevictable, never freed:
    the pool shrinks monotonically under one-token traffic)."""
    model = _gpt2()
    params = _params(model, 1)
    system = _prompts([16], seed=9)[0]  # two full 8-token blocks
    eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                      block_size=8)
    # seed the prefix cache with the system prompt's blocks
    first = eng.submit(np.concatenate([system, _prompts([4], seed=1)[0]]), 4)
    eng.run()
    assert len(eng.result(first)) == 4
    # a burst of one-token requests, every one hitting the cached prefix
    for s in range(5):
        rid = eng.submit(
            np.concatenate([system, _prompts([4], seed=20 + s)[0]]), 1
        )
        eng.run()
        assert len(eng.result(rid)) == 1
    assert eng.stats.prefix_hit_rate > 0  # the hits actually happened
    _pool_clean(eng)
    # and the cached blocks remain evictable: a full eviction drains the
    # pool back to empty
    eng.pool.evict_prefix(eng.pool.blocks.n_usable)
    assert eng.pool.blocks.n_free == eng.pool.blocks.n_usable


def test_idle_pool_waives_watermark():
    """Regression: a request whose need_new + watermark exceeds the pool
    must still admit when the pool is IDLE (nothing decoding, nothing to
    thrash against) — otherwise it sits at the head of its lane forever
    and run() livelocks even though submit() verified it fits."""
    model = _gpt2()
    params = _params(model, 0)
    # 7 usable blocks; request needs 3 (prompt 10 + 6 new = 16 tokens);
    # watermark 6 makes need_new + watermark = 9 > 7 on an empty pool
    eng = ServeEngine(model, params, max_slots=4, seed=0, paged=True,
                      block_size=8, n_blocks=8, watermark_blocks=6,
                      prefix_cache=False)
    rid = eng.submit(_prompts([10], seed=2)[0], 6)
    out = eng.run()  # must terminate
    assert len(out[rid]) == 6
    _pool_clean(eng)


def test_full_hit_replay_resumes_without_prefill():
    """A replay re-admission whose ENTIRE K/V (prompt + replay[:-1], a
    block multiple) is prefix-cached runs no prefill and no scatter —
    the slot maps the shared blocks directly — and the resumed stream
    still matches the static oracle's suffix. Pins the row_cache=None
    fast path in _admit."""
    from tpudist.serve.engine import Request

    model = _gpt2()
    params = _params(model, 1)
    prompt = _prompts([16], seed=11)[0]  # 2 full 8-token blocks
    static = generate(model, params, prompt[None], 12,
                      temperature=0.0)[0].tolist()

    eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                      block_size=8)
    # seed the cache with the exact 24-token kv the replay will need
    warm = eng.submit(np.concatenate([prompt, np.asarray(static[:8],
                                                         np.int32)]), 2)
    eng.run()
    assert len(eng.result(warm)) == 2
    # inject a preempted-shape request: 9 tokens already emitted, so
    # kv = prompt + static[:8] = 24 tokens = 3 blocks, all cached
    rid = eng._next_id
    eng._next_id += 1
    req = Request(rid, prompt, 12, replay_tokens=tuple(static[:9]))
    eng._lanes.setdefault(0, __import__("collections").deque()).append(req)
    eng._counts[rid] = 9
    eng._live_toks[rid] = list(static[:9])
    eng._results[rid] = list(static[:9])
    eng.stats.on_submit(rid)
    eng._t_submit[rid] = eng.stats._clock()
    out = eng.run()
    assert out[rid] == static, "replay suffix diverged"
    _pool_clean(eng)


def test_paged_kernel_engine_greedy_matches_static():
    """The whole engine through the paged Pallas KERNEL path (any
    non-"xla" attn_impl dispatches it; interpret mode on CPU): greedy
    streams still equal the static xla-model oracle bit-for-bit — the
    configuration the `paged` bench leg runs."""
    kmodel = GPT2(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                  num_heads=4, attn_impl="fused")
    params = _params(_gpt2(), 1)
    prompts = _prompts([5, 9, 12], seed=6)
    static = {
        i: generate(_gpt2(), params, p[None], 8, temperature=0.0)[0].tolist()
        for i, p in enumerate(prompts)
    }
    eng = ServeEngine(kmodel, params, max_slots=3, seed=0, paged=True,
                      block_size=8, watermark_blocks=2)
    rids = [eng.submit(p, 8) for p in prompts]
    out = eng.run()
    for i in range(3):
        assert out[rids[i]] == static[i], i
    _pool_clean(eng)


def test_priority_lanes_and_ttft_aging():
    """Higher lanes admit first; with ttft_slo_s set, an overdue lower-
    lane head jumps the queue (deadline-driven aging)."""
    model = _gpt2()
    params = _params(model, 0)
    t = [0.0]
    clock = lambda: t[0]
    eng = ServeEngine(model, params, max_slots=1, seed=0, clock=clock)
    pr = _prompts([4])[0]
    lo = eng.submit(pr, 3, priority=0)
    hi = eng.submit(pr, 3, priority=5)
    assert eng._peek_next()[1].request_id == hi
    eng.run()

    eng2 = ServeEngine(model, params, max_slots=1, seed=0, clock=clock,
                       ttft_slo_s=1.0)
    lo = eng2.submit(pr, 3, priority=0)
    t[0] += 5.0  # lo is now overdue
    hi = eng2.submit(pr, 3, priority=5)
    assert eng2._peek_next()[1].request_id == lo
    eng2.run()


# ---------------------------------------------------------------------------
# paged write + kernel


def _paged_fixture(seed, b, h, h_kv, dh, bs, n_blocks, mb, max_pos):
    rng = np.random.Generator(np.random.PCG64(seed))
    q = rng.standard_normal((b, 1, h, dh)).astype(np.float32)
    k_pool = rng.standard_normal((n_blocks, h_kv, bs, dh)).astype(np.float32)
    v_pool = rng.standard_normal((n_blocks, h_kv, bs, dh)).astype(np.float32)
    # distinct physical blocks per row, deliberately non-contiguous
    perm = rng.permutation(n_blocks - 1)[: b * mb] + 1
    tables = perm.reshape(b, mb).astype(np.int32)
    pos = rng.integers(0, max_pos, (b,)).astype(np.int32)
    return (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray(pos))


@pytest.mark.parametrize("gqa", [1, 2])
def test_paged_kernel_matches_dense_oracle(kernel_parity, gqa):
    """The paged Pallas kernel (interpret mode on CPU) against the
    gather-then-dense oracle across rows whose cursors sit at block
    starts, block ends, and mid-block — including GQA head grouping."""
    h = 4
    q, k, v, bt, pos = _paged_fixture(
        0, b=5, h=h, h_kv=h // gqa, dh=16, bs=8, n_blocks=64, mb=4,
        max_pos=31,
    )
    # pin the edge cursors explicitly: first slot of a block, last slot
    pos = pos.at[0].set(0).at[1].set(7).at[2].set(8).at[3].set(31)
    got = paged_decode_attention(q, k, v, bt, pos, impl="paged")
    want = paged_decode_attention(q, k, v, bt, pos, impl="xla")
    kernel_parity(got, want)


def test_paged_kernel_large_batch_ok(kernel_parity):
    """No FUSED_MAX_BATCH-style ceiling: the paged kernel's grid scales
    with batch (the dense path's crossover was about gather bytes the
    paged walk never reads)."""
    q, k, v, bt, pos = _paged_fixture(
        1, b=24, h=4, h_kv=2, dh=16, bs=8, n_blocks=128, mb=4, max_pos=31
    )
    got = paged_decode_attention(q, k, v, bt, pos, impl="paged")
    want = paged_decode_attention(q, k, v, bt, pos, impl="xla")
    kernel_parity(got, want)


def test_paged_write_lands_in_mapped_block():
    """cached_kv's paged branch writes each row's K/V at
    (table[pos // bs], pos % bs) in the shared pool and nowhere else —
    pinned through the model decode step by comparing a paged engine
    slot's gathered window against the contiguous engine's slot rows
    after identical traffic."""
    model = _gpt2()
    params = _params(model, 3)
    pr = _prompts([9], seed=4)[0]
    cont = ServeEngine(model, params, max_slots=2, seed=0)
    paged = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                        block_size=8, watermark_blocks=2)
    rc, rp = cont.submit(pr, 6), paged.submit(pr, 6)
    for _ in range(3):
        cont.step()
        paged.step()
    n = int(cont.pool.positions[0])
    assert n == int(paged.pool.positions[0])
    fill = int(paged.pool.fill[0])
    row = paged.pool.gather_row(
        [int(x) for x in paged.pool.tables[0][:fill]]
    )
    for lc, lp in zip(jax.tree_util.tree_leaves(cont.pool.cache),
                      jax.tree_util.tree_leaves(row)):
        if getattr(lc, "ndim", 0) == 4:
            np.testing.assert_array_equal(
                np.asarray(lc)[0, :, :n], np.asarray(lp)[0, :, :n]
            )
    cont.run(), paged.run()


# ---------------------------------------------------------------------------
# telemetry + warm start


def test_serve_rows_carry_pool_fields(tmp_path):
    from tpudist.telemetry import TelemetrySink

    model = _gpt2()
    params = _params(model, 0)
    path = tmp_path / "serve.jsonl"
    sink = TelemetrySink(str(path))
    eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                      block_size=8, sink=sink, stats_every=2)
    system = _prompts([16], seed=6)[0]
    for t in _prompts([3, 5], seed=8):
        eng.submit(np.concatenate([system, t]), 4)
    eng.run()
    sink.close()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    serve = [r for r in rows if r["kind"] == "serve"]
    summary = [r for r in rows if r["kind"] == "serve_summary"]
    assert serve and summary
    for r in serve + summary:
        assert "pool_occupancy" in r
        assert "prefix_hit_rate" in r
        assert "preemptions" in r
    assert summary[-1]["pool_occupancy"] is not None
    assert summary[-1]["prefix_hit_rate"] is not None
    assert summary[-1]["preemptions"] == 0
    # contiguous rows keep the fields (null occupancy/hit rate): one
    # schema, docs/OBSERVABILITY.md §1
    path2 = tmp_path / "serve2.jsonl"
    sink2 = TelemetrySink(str(path2))
    eng2 = ServeEngine(model, params, max_slots=2, seed=0, sink=sink2,
                       stats_every=2)
    eng2.submit(_prompts([4])[0], 4)
    eng2.run()
    sink2.close()
    rows2 = [json.loads(l) for l in path2.read_text().splitlines()]
    s2 = [r for r in rows2 if r["kind"] == "serve_summary"][-1]
    assert s2["pool_occupancy"] is None
    assert s2["prefix_hit_rate"] is None


def test_compile_cache_warm_start(tmp_path):
    """ServeEngine(compile_cache=dir): cold construction AOT-compiles and
    stores the decode + per-bucket prefill programs; a second engine with
    the same weights/geometry loads every one (hits == cold misses > 0)
    and produces bit-identical output."""
    model = _gpt2()
    params = _params(model, 1)
    pr = _prompts([5, 9], seed=7)
    outs = {}
    infos = {}
    for tag in ("cold", "warm"):
        eng = ServeEngine(model, params, max_slots=2, seed=0, paged=True,
                          block_size=8, compile_cache=str(tmp_path))
        infos[tag] = eng.compile_cache_info
        rids = [eng.submit(p, 6) for p in pr]
        out = eng.run()
        outs[tag] = [out[r] for r in rids]
    assert infos["cold"]["misses"] > 0 and infos["cold"]["hits"] == 0
    assert infos["warm"]["hits"] == infos["cold"]["misses"]
    assert infos["warm"]["misses"] == 0
    assert outs["cold"] == outs["warm"]


def test_compile_cache_misses_on_new_weights(tmp_path):
    """The fingerprint covers param VALUES: an engine over different
    weights must not load the stale executables (they embed the old
    params as closure constants)."""
    model = _gpt2()
    eng1 = ServeEngine(model, _params(model, 1), max_slots=2, seed=0,
                       compile_cache=str(tmp_path))
    assert eng1.compile_cache_info["misses"] > 0
    eng2 = ServeEngine(model, _params(model, 2), max_slots=2, seed=0,
                       compile_cache=str(tmp_path))
    assert eng2.compile_cache_info["hits"] == 0
    assert eng2.compile_cache_info["misses"] > 0
