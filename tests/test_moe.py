"""Mixture-of-experts / expert parallelism (tpudist.parallel.ep).

The reference has no MoE (SURVEY.md §2.12) — these tests pin down the
routing math and the expert-sharded execution path the same way
test_dp_equivalence pins down DP: sharded ≡ unsharded, dispatch ≡ a
per-token reference computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpudist import mesh as mesh_lib
from tpudist.parallel.ep import MoEMlp, expert_capacity, top_k_dispatch


def test_expert_capacity():
    # ceil(2*64/8)=16, ×1.25 → 20
    assert expert_capacity(64, 8, top_k=2, capacity_factor=1.25) == 20
    assert expert_capacity(3, 8, top_k=1, capacity_factor=1.0) == 1


def test_dispatch_matches_per_token_reference():
    """With ample capacity, MoE output == Σ_k gate_k · FFN_{e_k}(token)."""
    rng = np.random.Generator(np.random.PCG64(0))
    T, E, d = 16, 4, 8
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(T, E)), jnp.float32))
    capacity = T  # nothing can drop
    dispatch, combine, _ = top_k_dispatch(probs, 2, capacity)

    # every token assigned to exactly 2 experts, each in exactly one slot
    np.testing.assert_allclose(np.sum(dispatch, axis=(1, 2)), 2.0, rtol=1e-6)
    # combine weights renormalize the top-2 gates to 1
    np.testing.assert_allclose(np.sum(combine, axis=(1, 2)), 1.0, rtol=1e-5)

    # no slot double-booked
    assert np.max(np.sum(dispatch, axis=0)) <= 1.0 + 1e-6

    # dispatch→expert→combine reproduces per-token top-2 mixture
    x = jnp.asarray(rng.normal(size=(T, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(E, d, d)), jnp.float32)
    slots = jnp.einsum("tec,td->ecd", dispatch, x)
    out = jnp.einsum("ecd,edf->ecf", slots, w)
    y = jnp.einsum("tec,ecd->td", combine, out)

    top2 = np.argsort(-np.asarray(probs), axis=1)[:, :2]
    for t in range(T):
        e0, e1 = top2[t]
        g0, g1 = float(probs[t, e0]), float(probs[t, e1])
        g0, g1 = g0 / (g0 + g1), g1 / (g0 + g1)
        want = g0 * (x[t] @ w[e0]) + g1 * (x[t] @ w[e1])
        np.testing.assert_allclose(np.asarray(y[t]), np.asarray(want), rtol=2e-5, atol=1e-5)


def test_capacity_dropping():
    """Tokens beyond an expert's capacity contribute zero (not garbage)."""
    T, E = 8, 2
    # all tokens want expert 0
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.float32), (T, 1))
    dispatch, combine, _ = top_k_dispatch(probs, 1, capacity=3)
    # exactly 3 tokens land (token order), the rest drop
    assert float(jnp.sum(dispatch)) == 3.0
    np.testing.assert_allclose(
        np.sum(np.asarray(dispatch), axis=(1, 2)), [1, 1, 1, 0, 0, 0, 0, 0]
    )
    # dropped tokens have zero combine weight → residual passes them through
    assert float(jnp.sum(combine[3:])) == 0.0


def test_aux_loss_balanced_is_one():
    T, E = 64, 8
    probs = jnp.full((T, E), 1.0 / E, jnp.float32)
    # break argmax ties deterministically across experts
    probs = probs + jax.nn.one_hot(jnp.arange(T) % E, E) * 1e-4
    _, _, aux = top_k_dispatch(probs, 1, capacity=T)
    assert abs(float(aux) - 1.0) < 1e-2


def test_moe_layer_runs_and_sows_aux():
    layer = MoEMlp(num_experts=4, top_k=2, capacity_factor=2.0)
    x = jnp.asarray(
        np.random.Generator(np.random.PCG64(1)).normal(size=(2, 8, 16)), jnp.float32
    )
    variables = layer.init(jax.random.key(0), x)
    y, updates = layer.apply(variables, x, mutable=["losses"])
    assert y.shape == x.shape
    (aux,) = jax.tree_util.tree_leaves(updates["losses"])
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_expert_sharded_equals_unsharded():
    """The same MoE GPT-2 step on an expert=4 mesh and a 1-device mesh
    produces the same loss — expert parallelism changes placement, not math."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(2))
    tokens = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}

    losses = {}
    for name, cfg in {
        "single": mesh_lib.MeshConfig(data=1),
        "ep": mesh_lib.MeshConfig(data=2, expert=4),
    }.items():
        devices = jax.devices()[: 1 if name == "single" else 8]
        mesh = mesh_lib.create_mesh(cfg, devices=devices)
        model = GPT2(
            vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
            num_heads=2, num_experts=4, moe_every=1, capacity_factor=2.0,
            mesh=mesh,
        )
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        state, metrics = step(state, tokens)
        losses[name] = float(metrics["loss"])

    assert np.isfinite(losses["single"])
    np.testing.assert_allclose(losses["single"], losses["ep"], rtol=2e-5)


def test_moe_gpt2_loss_decreases():
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, expert=4))
    model = GPT2(
        vocab_size=32, max_seq_len=16, hidden_dim=32, depth=2, num_heads=2,
        num_experts=4, capacity_factor=2.0, mesh=mesh,
    )
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    from tpudist.train import state_shardings_of

    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    rng = np.random.Generator(np.random.PCG64(3))
    batch = {"tokens": rng.integers(0, 32, (8, 16)).astype(np.int32)}
    first = None
    for _ in range(20):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first, f"loss did not decrease: {first} -> {last}"
