"""Mixture-of-experts / expert parallelism (tpudist.parallel.ep).

The reference has no MoE (SURVEY.md §2.12) — these tests pin down the
routing math and the expert-sharded execution path the same way
test_dp_equivalence pins down DP: sharded ≡ unsharded, dispatch ≡ a
per-token reference computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.parallel.ep import MoEMlp, expert_capacity, top_k_dispatch


def test_expert_capacity():
    # ceil(2*64/8)=16, ×1.25 → 20
    assert expert_capacity(64, 8, top_k=2, capacity_factor=1.25) == 20
    assert expert_capacity(3, 8, top_k=1, capacity_factor=1.0) == 1


def test_dispatch_matches_per_token_reference():
    """With ample capacity, MoE output == Σ_k gate_k · FFN_{e_k}(token)."""
    rng = np.random.Generator(np.random.PCG64(0))
    T, E, d = 16, 4, 8
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(T, E)), jnp.float32))
    capacity = T  # nothing can drop
    dispatch, combine, _ = top_k_dispatch(probs, 2, capacity)

    # every token assigned to exactly 2 experts, each in exactly one slot
    np.testing.assert_allclose(np.sum(dispatch, axis=(1, 2)), 2.0, rtol=1e-6)
    # combine weights renormalize the top-2 gates to 1
    np.testing.assert_allclose(np.sum(combine, axis=(1, 2)), 1.0, rtol=1e-5)

    # no slot double-booked
    assert np.max(np.sum(dispatch, axis=0)) <= 1.0 + 1e-6

    # dispatch→expert→combine reproduces per-token top-2 mixture
    x = jnp.asarray(rng.normal(size=(T, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(E, d, d)), jnp.float32)
    slots = jnp.einsum("tec,td->ecd", dispatch, x)
    out = jnp.einsum("ecd,edf->ecf", slots, w)
    y = jnp.einsum("tec,ecd->td", combine, out)

    top2 = np.argsort(-np.asarray(probs), axis=1)[:, :2]
    for t in range(T):
        e0, e1 = top2[t]
        g0, g1 = float(probs[t, e0]), float(probs[t, e1])
        g0, g1 = g0 / (g0 + g1), g1 / (g0 + g1)
        want = g0 * (x[t] @ w[e0]) + g1 * (x[t] @ w[e1])
        np.testing.assert_allclose(np.asarray(y[t]), np.asarray(want), rtol=2e-5, atol=1e-5)


def test_capacity_dropping():
    """Tokens beyond an expert's capacity contribute zero (not garbage)."""
    T, E = 8, 2
    # all tokens want expert 0
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.float32), (T, 1))
    dispatch, combine, _ = top_k_dispatch(probs, 1, capacity=3)
    # exactly 3 tokens land (token order), the rest drop
    assert float(jnp.sum(dispatch)) == 3.0
    np.testing.assert_allclose(
        np.sum(np.asarray(dispatch), axis=(1, 2)), [1, 1, 1, 0, 0, 0, 0, 0]
    )
    # dropped tokens have zero combine weight → residual passes them through
    assert float(jnp.sum(combine[3:])) == 0.0


def test_aux_loss_balanced_is_one():
    T, E = 64, 8
    probs = jnp.full((T, E), 1.0 / E, jnp.float32)
    # break argmax ties deterministically across experts
    probs = probs + jax.nn.one_hot(jnp.arange(T) % E, E) * 1e-4
    _, _, aux = top_k_dispatch(probs, 1, capacity=T)
    assert abs(float(aux) - 1.0) < 1e-2


def test_moe_layer_runs_and_sows_aux():
    layer = MoEMlp(num_experts=4, top_k=2, capacity_factor=2.0)
    x = jnp.asarray(
        np.random.Generator(np.random.PCG64(1)).normal(size=(2, 8, 16)), jnp.float32
    )
    variables = layer.init(jax.random.key(0), x)
    y, updates = layer.apply(variables, x, mutable=["losses"])
    assert y.shape == x.shape
    (aux,) = jax.tree_util.tree_leaves(updates["losses"])
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_expert_sharded_equals_unsharded():
    """The same MoE GPT-2 step on an expert=4 mesh and a 1-device mesh
    produces the same loss — expert parallelism changes placement, not math."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(2))
    tokens = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}

    losses = {}
    for name, cfg in {
        "single": mesh_lib.MeshConfig(data=1),
        "ep": mesh_lib.MeshConfig(data=2, expert=4),
    }.items():
        devices = jax.devices()[: 1 if name == "single" else 8]
        mesh = mesh_lib.create_mesh(cfg, devices=devices)
        model = GPT2(
            vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
            num_heads=2, num_experts=4, moe_every=1, capacity_factor=2.0,
            mesh=mesh,
        )
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        state, metrics = step(state, tokens)
        losses[name] = float(metrics["loss"])

    assert np.isfinite(losses["single"])
    np.testing.assert_allclose(losses["single"], losses["ep"], rtol=2e-5)


# ---------------------------------------------------------------------------
# index dispatch: the einsum oracle is the bit-checked reference


def _layer(**kw):
    kw.setdefault("num_experts", 4)
    kw.setdefault("top_k", 2)
    kw.setdefault("capacity_factor", 2.0)
    return MoEMlp(**kw)


def _x(shape=(2, 16, 16), seed=5):
    rng = np.random.Generator(np.random.PCG64(seed))
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _unboxed_params(layer, x, seed=0):
    from flax import linen as nn

    return nn.meta.unbox(layer.init(jax.random.key(seed), x)["params"])


def test_index_dispatch_forward_parity():
    """fp32, top_k=2: dispatch and the expert FFN outputs are BIT-identical
    between impls (same slot contents, same einsums); the final gate-mix
    matches to ≤1 ulp — the einsum oracle's contraction accumulates with
    FMA (one rounding per term) where the index path's explicit
    multiply-add rounds the product first (ep._index_combine docstring)."""
    x = _x()
    ein, idx = _layer(dispatch_impl="einsum"), _layer(dispatch_impl="index")
    params = {"params": _unboxed_params(ein, x)}
    y_e = np.asarray(ein.apply(params, x))
    y_i = np.asarray(idx.apply(params, x))
    np.testing.assert_allclose(y_e, y_i, rtol=0, atol=5e-7)
    # …and the ulp-level agreement is real agreement, not a loose bar:
    # outputs are O(0.1), so 5e-7 is a handful of ulps
    assert np.max(np.abs(y_e)) > 0.05


@pytest.mark.slow
def test_index_dispatch_grad_parity():
    """Backward parity: the gather's transpose is a scatter-add, so expert
    and router grads match the einsum oracle to fp32 reduction-order
    tolerance (the loss includes the sowed aux, exercising the routing
    grads too)."""
    x = _x()

    def loss_fn(layer):
        def f(p):
            y, upd = layer.apply({"params": p}, x, mutable=["losses"])
            aux = sum(jax.tree_util.tree_leaves(upd["losses"]), 0.0)
            return jnp.sum(y * y) + aux
        return f

    ein, idx = _layer(dispatch_impl="einsum"), _layer(dispatch_impl="index")
    params = _unboxed_params(ein, x)
    g_e = jax.grad(loss_fn(ein))(params)
    g_i = jax.grad(loss_fn(idx))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_e),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


@pytest.mark.slow
def test_moe_dense_equivalence_when_experts_identical():
    """The dense-equivalence oracle: with every expert holding the SAME
    weights and capacity ample, top-2 routing is a no-op — the renormalized
    gates sum to 1 and the layer equals one dense gelu FFN."""
    x = _x((2, 8, 12), seed=7)
    for impl in ("einsum", "index"):
        layer = _layer(num_experts=4, capacity_factor=4.0,
                       dispatch_impl=impl)
        params = _unboxed_params(layer, x)
        params["w1"] = jnp.tile(params["w1"][:1], (4, 1, 1))
        params["w2"] = jnp.tile(params["w2"][:1], (4, 1, 1))
        y = layer.apply({"params": params}, x)
        want = jnp.einsum(
            "bsf,fd->bsd",
            jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, params["w1"][0])),
            params["w2"][0],
        )
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(want), rtol=2e-5, atol=2e-5
        )


@pytest.mark.slow
def test_capacity_drop_deterministic_and_impl_identical():
    """capacity_factor < 1 forces drops; both impls drop the SAME tokens
    (priority is token order — deterministic), so outputs are bit-stable
    run-to-run, agree across impls (to the combine's ulp — see the
    forward-parity test), and the dropped rate really is > 0."""
    x = _x((2, 32, 8), seed=9)
    outs = {}
    for impl in ("einsum", "index"):
        layer = _layer(num_experts=2, capacity_factor=0.5,
                       dispatch_impl=impl)
        params = {"params": _unboxed_params(layer, x)}
        y1, sown = layer.apply(params, x, mutable=["moe_stats"])
        y2 = layer.apply(params, x)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        outs[impl] = np.asarray(y1)
        (dropped,) = [
            leaf for path, leaf in
            jax.tree_util.tree_flatten_with_path(sown["moe_stats"])[0]
            if any(getattr(p, "key", None) == "dropped" for p in path)
        ]
        assert float(dropped) > 0.0
    np.testing.assert_allclose(
        outs["einsum"], outs["index"], rtol=0, atol=5e-7
    )


@pytest.mark.slow
def test_index_sharded_matches_einsum_oracle():
    """The headline composition: index dispatch under a data×expert×tensor
    mesh (the explicit shard_map all-to-all) trains the same loss as the
    single-device einsum oracle."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import (
        create_train_state, lm_loss, make_train_step, state_shardings_of,
    )

    rng = np.random.Generator(np.random.PCG64(4))
    tokens = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    losses = {}
    for name, (cfg, n_dev, impl) in {
        "oracle": (mesh_lib.MeshConfig(data=1), 1, "einsum"),
        "sharded": (mesh_lib.MeshConfig(data=2, expert=2, tensor=2), 8,
                    "index"),
    }.items():
        mesh = mesh_lib.create_mesh(cfg, devices=jax.devices()[:n_dev])
        model = GPT2(
            vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
            num_heads=2, num_experts=4, moe_every=1, capacity_factor=2.0,
            moe_dispatch=impl, mesh=mesh,
        )
        tx = optax.adam(1e-3)
        # the shard_map path runs at init too: the sample batch must
        # divide the mesh's (data, fsdp) axes, unlike the GSPMD paths'
        # usual (1, S) probe
        state = create_train_state(
            model, 0, jnp.zeros((2, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        state, metrics = step(state, tokens)
        losses[name] = float(metrics["loss"])
    assert np.isfinite(losses["oracle"])
    np.testing.assert_allclose(losses["sharded"], losses["oracle"], rtol=2e-5)


# ---------------------------------------------------------------------------
# router hardening: z-loss + jitter (off by default, byte-inert when off)


@pytest.mark.slow
def test_router_z_loss_sown_and_shrinks_logit_norms():
    x = _x()
    layer = _layer(router_z_loss=1.0)
    params = _unboxed_params(layer, x)
    # inflate the router so the z-loss has norm to shrink
    params["router"] = params["router"] * 10.0

    def zloss(p):
        _, upd = layer.apply({"params": p}, x, mutable=["losses"])
        return upd["losses"]["moe_router_z_loss"]

    before = float(zloss(params))
    assert np.isfinite(before) and before > 0
    g = jax.grad(lambda p: zloss(p))(params)
    after = float(zloss(jax.tree_util.tree_map(
        lambda a, b: a - 1e-2 * b, params, g
    )))
    assert after < before, f"z-loss did not shrink: {before} -> {after}"
    # off by default: the losses collection carries ONLY the aux loss
    off = _layer()
    _, upd = off.apply({"params": params}, x, mutable=["losses"])
    assert set(upd["losses"]) == {"moe_aux_loss"}


def test_router_jitter_gating():
    x = _x()
    jit_layer = _layer(router_jitter=0.2)
    params = {"params": _unboxed_params(jit_layer, x)}
    base = np.asarray(_layer().apply(params, x))
    # eval (deterministic=True) and the default (None): byte-identical to
    # the jitter-free layer — the knob is train-only
    np.testing.assert_array_equal(
        np.asarray(jit_layer.apply(params, x, deterministic=True)), base
    )
    np.testing.assert_array_equal(np.asarray(jit_layer.apply(params, x)), base)
    # train without an rng stream: a loud refusal, not silent determinism
    with pytest.raises(ValueError, match="dropout' rng"):
        jit_layer.apply(params, x, deterministic=False)
    # train with the stream: the routing input actually moves
    noisy = np.asarray(jit_layer.apply(
        params, x, deterministic=False, rngs={"dropout": jax.random.key(1)}
    ))
    assert not np.array_equal(noisy, base)


# ---------------------------------------------------------------------------
# composition: chunked CE, remat, step metrics


@pytest.mark.slow
def test_chunked_forward_carries_moe_aux():
    """chunked_lm_forward on an MoE model: the sowed aux loss survives the
    fused path — total == chunked-CE + aux, matching the plain forward."""
    from tpudist.models.gpt2 import GPT2, chunked_lm_forward
    from tpudist.train import lm_loss

    model = GPT2(
        vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2, num_heads=2,
        num_experts=4, moe_every=1, capacity_factor=2.0,
    )
    rng = np.random.Generator(np.random.PCG64(6))
    tokens = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
    params = model.init(jax.random.key(0), tokens, train=False)["params"]
    fwd = chunked_lm_forward(model, chunk=8)
    chunked, _ = fwd(params, {}, {"tokens": tokens})
    logits, upd = model.apply(
        {"params": params}, tokens, train=True, mutable=["losses"]
    )
    aux = sum(jax.tree_util.tree_leaves(upd["losses"]), 0.0)
    want = lm_loss(logits, tokens) + aux
    assert float(aux) > 0  # the chunked total really includes a live aux
    np.testing.assert_allclose(float(chunked), float(want), rtol=1e-5)


@pytest.mark.slow
def test_moe_composes_with_remat_policy():
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=2, expert=2), devices=jax.devices()[:4]
    )
    rng = np.random.Generator(np.random.PCG64(8))
    batch = {"tokens": rng.integers(0, 64, (8, 16)).astype(np.int32)}
    losses = {}
    for policy in (None, "dots_saveable"):
        model = GPT2(
            vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
            num_heads=2, num_experts=4, capacity_factor=2.0,
            moe_dispatch="index", remat_policy=policy, mesh=mesh,
        )
        tx = optax.adam(1e-3)
        # the shard_map dispatch runs at init too: the sample batch must
        # divide the mesh's (data, fsdp) axes.
        state = create_train_state(
            model, 0, jnp.zeros((2, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens",
        )
        state, metrics = step(state, batch)
        losses[policy] = float(metrics["loss"])
    np.testing.assert_allclose(
        losses["dots_saveable"], losses[None], rtol=1e-6
    )


@pytest.mark.slow
def test_moe_step_metrics_behind_telemetry_flag():
    """Router stats ride the step metrics ONLY under telemetry=True
    (docs/OBSERVABILITY.md §1): load is per-expert [E] summing to
    1 − dropped; with telemetry off the keys are absent entirely."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=2), devices=jax.devices()[:2]
    )
    model = GPT2(
        vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2, num_heads=2,
        num_experts=4, capacity_factor=2.0, mesh=mesh,
    )
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    rng = np.random.Generator(np.random.PCG64(11))
    batch = {"tokens": rng.integers(0, 64, (4, 16)).astype(np.int32)}
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", telemetry=True,
    )
    state, metrics = step(state, batch)  # the step donates its input state
    # depth 2, moe_every 2 → block h_1 is the MoE block
    load = np.asarray(metrics["moe/h_1/load"])
    dropped = float(metrics["moe/h_1/dropped"])
    assert load.shape == (4,)
    np.testing.assert_allclose(float(load.sum()), 1.0 - dropped, rtol=1e-5)
    assert np.isfinite(float(metrics["moe/h_1/aux"]))
    plain = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens",
    )
    _, metrics = plain(state, batch)
    assert not [k for k in metrics if k.startswith("moe/")]


def test_moe_gpt2_loss_decreases():
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, expert=4))
    model = GPT2(
        vocab_size=32, max_seq_len=16, hidden_dim=32, depth=2, num_heads=2,
        num_experts=4, capacity_factor=2.0, mesh=mesh,
    )
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    from tpudist.train import state_shardings_of

    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    rng = np.random.Generator(np.random.PCG64(3))
    batch = {"tokens": rng.integers(0, 32, (8, 16)).astype(np.int32)}
    first = None
    for _ in range(20):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first, f"loss did not decrease: {first} -> {last}"
