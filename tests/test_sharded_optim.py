"""The memory-discipline layer's correctness contracts.

1. ZeRO-1 optimizer-state sharding (``tpudist.optim.shard_state``,
   arXiv:2004.13336): the sharded-state Adam step must be NUMERICALLY the
   replicated step — sharding is placement, not math — on an emulated
   multi-device mesh, including leaves whose shapes do NOT divide the mesh
   (the pad-and-reshape path), while per-device optimizer-state bytes
   shrink ~world_size×.
2. Named remat policies (``tpudist.remat``): every policy preserves loss
   and gradients exactly, stored-residual bytes order
   ``save_nothing ≤ full ≤ dots_saveable ≤ none`` (strictly at the ends),
   and the jit-lowered cost analysis shows the complementary recompute-
   FLOP ordering.

Self-contained models (no tpudist.models import): the contracts are
framework-level; the model zoo's ``remat_policy`` wiring has its own test
in ``tests/test_remat_models.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpudist import mesh as mesh_lib
from tpudist import memory, optim
from tpudist.remat import POLICY_NAMES, checkpoint as remat_checkpoint
from tpudist.train import (
    create_train_state, make_train_step, state_shardings_of,
)


class OddMLP(nn.Module):
    """Dims chosen so the Adam mirrors hold every ZeRO-1 layout: (8, 64)
    and (64, 8) kernels divide a 4-way mesh; the (7, 5) kernel and the
    7/5-sized biases divide by NOTHING and must take the pad-and-reshape
    path; adam's count is a replicated scalar."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.tanh(nn.Dense(64, name="wide")(x))
        x = jnp.tanh(nn.Dense(7, name="odd_in")(x))
        x = jnp.tanh(nn.Dense(5, name="odd_out")(x))
        return nn.Dense(8, name="head")(x)


def _mesh4():
    return mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=4), devices=jax.devices()[:4]
    )


def _batch(i):
    rng = np.random.Generator(np.random.PCG64(i))
    return {
        "x": rng.standard_normal((16, 8)).astype(np.float32),
        "y": rng.integers(0, 8, 16).astype(np.int32),
    }


def test_shard_state_step_matches_replicated():
    """3 Adam steps, shard_state vs replicated, same data: losses and
    final params agree to fp tolerance (reduce-scatter vs all-reduce
    reduction order is the only daylight)."""
    mesh = _mesh4()
    model = OddMLP()
    x0 = jnp.zeros((4, 8))
    tx_r = optax.adam(1e-3)
    tx_s = optim.shard_state(optax.adam(1e-3), mesh, min_size=1)

    state_r = create_train_state(model, 0, x0, tx_r, mesh)
    state_s = create_train_state(model, 0, x0, tx_s, mesh)

    step_r = make_train_step(model, tx_r, mesh, input_key="x", label_key="y")
    step_s = make_train_step(
        model, tx_s, mesh, input_key="x", label_key="y",
        state_sharding=state_shardings_of(state_s),
    )
    for i in range(3):
        b = _batch(i)
        state_r, mr = step_r(state_r, b)
        state_s, ms = step_s(state_s, b)
        np.testing.assert_allclose(
            float(mr["loss"]), float(ms["loss"]), rtol=1e-5
        )
    for a, b_ in zip(
        jax.tree_util.tree_leaves(state_r.params),
        jax.tree_util.tree_leaves(state_s.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=1e-5, rtol=1e-4
        )


def test_shard_state_layout_padded_and_sharded():
    """Non-divisible leaves are stored [world, cols] over 'data'; divisible
    leaves keep their shape with the largest divisible dim sharded; the
    scalar count stays replicated. Born that way out of create_train_state
    (no replicated intermediate)."""
    mesh = _mesh4()
    model = OddMLP()
    tx = optim.shard_state(optax.adam(1e-3), mesh, min_size=1)
    state = create_train_state(model, 0, jnp.zeros((4, 8)), tx, mesh)

    mu = state.opt_state[0].mu  # ScaleByAdamState of the chained adam
    # (7, 5) kernel -> flattened 35, padded to 4x9
    odd = mu["odd_out"]["kernel"]
    assert odd.shape == (4, 9)
    assert odd.sharding.spec == P("data", None)
    # (8, 64) kernel keeps its shape, largest divisible dim sharded
    wide = mu["wide"]["kernel"]
    assert wide.shape == (8, 64)
    assert mesh_lib.DATA_AXIS in tuple(wide.sharding.spec)
    # count scalar replicated
    count = state.opt_state[0].count
    assert count.shape == ()
    assert count.sharding.spec == P()
    # pad region is zeros and stays zeros after a step (the update
    # round-trips through the natural layout)
    step = make_train_step(
        model, tx, mesh, input_key="x", label_key="y",
        state_sharding=state_shardings_of(state),
    )
    state, _ = step(state, _batch(0))
    tail = np.asarray(state.opt_state[0].mu["odd_out"]["kernel"]).reshape(-1)[35:]
    np.testing.assert_array_equal(tail, 0.0)


def test_shard_state_per_device_bytes_shrink_world_x():
    """The ZeRO-1 memory claim, measured leaf-for-leaf: per-device
    optimizer-state bytes at ~1/world of replicated (padding + the scalar
    count are the only slack)."""
    mesh = _mesh4()
    model = OddMLP()
    tx_r = optax.adam(1e-3)
    tx_s = optim.shard_state(optax.adam(1e-3), mesh, min_size=1)
    state_r = create_train_state(model, 0, jnp.zeros((4, 8)), tx_r, mesh)
    state_s = create_train_state(model, 0, jnp.zeros((4, 8)), tx_s, mesh)
    rep = memory.per_device_bytes(state_r.opt_state)
    shr = memory.per_device_bytes(state_s.opt_state)
    world = 4
    assert shr < rep / (world - 1), (rep, shr)
    # and the pre-compile budget (shapes + shardings, no arrays) agrees
    # with the placed reality
    shapes = jax.eval_shape(
        tx_s.init,
        jax.eval_shape(
            lambda: model.init(jax.random.key(0), jnp.zeros((4, 8)),
                               train=False)["params"]
        ),
    )
    predicted = memory.per_device_bytes(
        shapes,
        tx_s.state_shardings(
            jax.eval_shape(
                lambda: model.init(jax.random.key(0), jnp.zeros((4, 8)),
                                   train=False)["params"]
            )
        ),
    )
    assert predicted == shr


def test_shard_state_requires_params_at_update():
    mesh = _mesh4()
    tx = optim.shard_state(optax.adam(1e-3), mesh, min_size=1)
    params = {"w": jnp.zeros((7, 5))}
    state = tx.init(params)
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.zeros((7, 5))}, state)


# ---------------------------------------------------------------------------
# remat policies
# ---------------------------------------------------------------------------


def _policy_funcs(policy):
    """A 6-block residual MLP with per-block checkpointing under
    ``policy`` — the shape where the policies measurably differ (dots are
    4x the boundary width)."""

    def block(h, w):
        w1, w2 = w
        u = jnp.tanh(h @ w1)
        return h + jnp.tanh(u @ w2)

    lay = remat_checkpoint(block, policy)

    def f(params, x):
        h = x
        for w in params:
            h = lay(h, w)
        return (h ** 2).mean()

    return f


def _mlp_params():
    rng = np.random.Generator(np.random.PCG64(0))
    h = 64
    params = [
        (
            jnp.asarray(rng.standard_normal((h, 4 * h)) * 0.05, jnp.float32),
            jnp.asarray(rng.standard_normal((4 * h, h)) * 0.05, jnp.float32),
        )
        for _ in range(6)
    ]
    x = jnp.asarray(rng.standard_normal((32, h)), jnp.float32)
    return params, x


def test_remat_policies_preserve_values_and_grads():
    params, x = _mlp_params()
    ref_v, ref_g = jax.jit(jax.value_and_grad(_policy_funcs("none")))(params, x)
    for policy in ("full", "dots_saveable", "save_nothing", True, False):
        v, g = jax.jit(jax.value_and_grad(_policy_funcs(policy)))(params, x)
        np.testing.assert_allclose(float(v), float(ref_v), rtol=1e-6)
        for a, b in zip(
            jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(ref_g)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )


def test_remat_policy_memory_ordering():
    """The policy contract: STORED-residual bytes (jax's own
    saved-residual accounting — what autodiff will keep live for
    backward; exact and backend-independent) order
    ``save_nothing ≤ full ≤ dots_saveable ≤ none``, strictly at the ends.

    Each policy's grad is also ``jax.jit(...).lower(...).compile()``'d and
    its cost analysis read — proving every policy produces a compilable
    step with a live cost model. The OPTIMIZED-HLO numbers themselves are
    deliberately not the ordering anchor: XLA:CPU's CSE undoes remat
    recompute where it is profitable on that backend (measured: identical
    flops for none/full/save_nothing, temp bytes that move the other way),
    which is exactly why the stored-bytes contract is asserted at the
    autodiff layer where the policy actually acts.
    """
    from tpudist.utils.compat import saved_residuals

    params, x = _mlp_params()
    saved = {}
    for policy in POLICY_NAMES:
        f = _policy_funcs(policy)
        res = saved_residuals(f, params, x)
        saved[policy] = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize for a, _ in res
        )
        comp = jax.jit(jax.value_and_grad(f)).lower(params, x).compile()
        ca = comp.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        assert float(ca["flops"]) > 0, (policy, ca)
    assert (
        saved["save_nothing"] <= saved["full"]
        <= saved["dots_saveable"] <= saved["none"]
    ), saved
    assert saved["save_nothing"] < saved["dots_saveable"] < saved["none"], saved


def test_remat_policy_through_train_step():
    """make_train_step accepts every named policy (and the legacy bool)
    and produces the same loss."""
    mesh = _mesh4()
    model = OddMLP()
    tx = optax.adam(1e-3)
    b = _batch(0)
    losses = {}
    for policy in ("none", "full", "dots_saveable", "save_nothing", True):
        state = create_train_state(model, 0, jnp.zeros((4, 8)), tx, mesh)
        step = make_train_step(
            model, tx, mesh, input_key="x", label_key="y", remat=policy
        )
        _, metrics = step(state, b)
        losses[str(policy)] = float(metrics["loss"])
    ref = losses["none"]
    for k, v in losses.items():
        np.testing.assert_allclose(v, ref, rtol=1e-6, err_msg=k)


def test_remat_unknown_policy_refused():
    with pytest.raises(ValueError, match="unknown remat policy"):
        remat_checkpoint(lambda x: x, "dots")


class _ListLoader:
    """Minimal fit()-shaped loader: a fixed batch list, re-iterable."""

    def __init__(self, batches, batch_size):
        self.batches = batches
        self.batch_size = batch_size

    def __iter__(self):
        return iter(self.batches)

    def __len__(self):
        return len(self.batches)


def test_fit_shard_opt_state_end_to_end(tmp_path):
    """fit(shard_opt_state=True): the one-flag surface — trains, losses
    finite, and the returned state's big moments really live sharded over
    'data' (default min_size keeps the small leaves replicated)."""

    class WideMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = jnp.tanh(nn.Dense(256, name="wide")(x))  # (8,256) ≥ min_size
            return nn.Dense(8, name="head")(x)

    from tpudist.train import fit

    mesh = _mesh4()
    loader = _ListLoader([_batch(i) for i in range(4)], batch_size=4)
    state, losses = fit(
        WideMLP(), optax.adam(1e-3), loader, epochs=1, mesh=mesh,
        batch_size=4, input_key="x", label_key="y", shard_opt_state=True,
        profile=False, log_dir=str(tmp_path), job_id="Z1",
    )
    assert len(losses) == 4
    assert np.isfinite(losses).all()
    mu = state.opt_state[0].mu
    assert mesh_lib.DATA_AXIS in tuple(mu["wide"]["kernel"].sharding.spec)
    assert mu["head"]["bias"].sharding.spec == P()  # below min_size


def test_shard_state_composes_with_remat_step():
    """The full memory-discipline recipe in one compiled step: ZeRO-1
    state + whole-forward dots_saveable remat — still numerically the
    plain step."""
    mesh = _mesh4()
    model = OddMLP()
    tx_plain = optax.adam(1e-3)
    tx = optim.shard_state(optax.adam(1e-3), mesh, min_size=1)
    state_p = create_train_state(model, 0, jnp.zeros((4, 8)), tx_plain, mesh)
    state = create_train_state(model, 0, jnp.zeros((4, 8)), tx, mesh)
    step_p = make_train_step(model, tx_plain, mesh, input_key="x", label_key="y")
    step = make_train_step(
        model, tx, mesh, input_key="x", label_key="y",
        remat="dots_saveable", state_sharding=state_shardings_of(state),
    )
    b = _batch(3)
    state_p, mp = step_p(state_p, b)
    state, ms = step(state, b)
    np.testing.assert_allclose(float(mp["loss"]), float(ms["loss"]), rtol=1e-5)
