"""ViT-B/16 (tpudist.models.vit) — BASELINE.json config 4 coverage.

No reference counterpart (/root/reference/main.py:40 is ResNet-only); these
tests pin the transformer DP leg: shapes, bf16 policy (fp32 params, bf16
compute, fp32 logits), and the sharded train step driving loss down.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpudist import mesh as mesh_lib
from tpudist.models import vit_b16


def _tiny_vit(**kw):
    cfg = dict(
        num_classes=10, patch_size=8, hidden_dim=32, depth=2,
        num_heads=4, mlp_dim=64,
    )
    cfg.update(kw)
    return vit_b16(**cfg)


def test_vit_forward_shape_and_patch_count():
    model = _tiny_vit()
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    # 32/8 = 4x4 patches + cls token
    assert variables["params"]["pos_embedding"].shape == (1, 17, 32)


def test_vit_bf16_policy():
    """bf16 compute with fp32 master params and fp32 logits — the TPU mixed
    precision convention (tpudist.amp)."""
    model = _tiny_vit(dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32, leaf.dtype
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32


def test_vit_dp_train_step_loss_decreases():
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.train import create_train_state, make_train_step

    mesh = mesh_lib.create_mesh()
    model = _tiny_vit(dtype=jnp.bfloat16)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    step = make_train_step(model, tx, mesh)
    batch = to_tensor(synthetic_cifar(n=16, num_classes=10))
    losses = []
    for _ in range(10):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_vit_grad_accum_matches_flat_batch():
    """config-4 x config-5 composition: accumulated microbatches ≡ one flat
    batch (same global loss trajectory) for the transformer leg."""
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.train import create_train_state, make_train_step

    mesh = mesh_lib.create_mesh()
    batch = to_tensor(synthetic_cifar(n=16, num_classes=10))

    losses = {}
    for accum in (1, 2):
        model = _tiny_vit()
        tx = optax.adam(1e-3)
        state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
        step = make_train_step(model, tx, mesh, grad_accum=accum)
        state, metrics = step(state, batch)
        losses[accum] = float(metrics["loss"])
    np.testing.assert_allclose(losses[1], losses[2], rtol=1e-5)

