"""The model zoo's per-block ``remat_policy`` wiring (GPT-2, Llama) and
the ~1B-param HBM budget claim the bench leg records.

Per-block remat must be a pure memory/flop trade: identical loss and
gradients, identical param NAMES (interop/checkpoints depend on the
``h_{i}``/``layer_{i}`` layout), in both the unrolled and scanned layouts.
The budget test is the test-suite half of the bench's
``gpt2_1b_shard_state_hbm_budget`` leg: exact eval_shape state bytes at
the 1536×36 (~1.1B-param) geometry, replicated provably over 16 GB,
shard_state + remat under it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist import memory, optim
from tpudist.models.gpt2 import GPT2
from tpudist.models.llama import Llama
from tpudist.train import create_train_state, lm_loss, make_train_step


def _loss_and_grad(model, tokens):
    params = model.init(
        jax.random.key(0), tokens, train=False
    )["params"]

    @jax.jit
    def lg(p, t):
        return jax.value_and_grad(
            lambda p_: lm_loss(model.apply({"params": p_}, t, train=True), t)
        )(p)

    return params, lg(params, tokens)


@pytest.mark.parametrize("policy", ["full", "dots_saveable", "save_nothing"])
def test_gpt2_block_remat_preserves_function_and_names(policy):
    rng = np.random.Generator(np.random.PCG64(5))
    tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    kw = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4)
    p_ref, (v_ref, g_ref) = _loss_and_grad(GPT2(**kw), tokens)
    p_rm, (v_rm, g_rm) = _loss_and_grad(
        GPT2(**kw, remat_policy=policy), tokens
    )
    # same param tree (names unchanged under nn.remat)
    assert jax.tree_util.tree_structure(p_ref) == jax.tree_util.tree_structure(p_rm)
    np.testing.assert_allclose(float(v_ref), float(v_rm), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_rm)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_llama_block_remat_unrolled_and_scanned():
    """remat_policy preserves the function WITHIN each layout (scan and
    unrolled init derive per-layer rngs differently, so cross-layout
    losses legitimately differ — the remat contract is per-layout)."""
    rng = np.random.Generator(np.random.PCG64(7))
    tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
    kw = dict(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
              num_heads=4, num_kv_heads=2, ffn_dim=64)
    _, (v_ref, _) = _loss_and_grad(Llama(**kw), tokens)
    _, (v_unrolled, _) = _loss_and_grad(
        Llama(**kw, remat_policy="dots_saveable"), tokens
    )
    np.testing.assert_allclose(float(v_ref), float(v_unrolled), rtol=1e-6)
    # scanned layout: remat_policy rides the scanned body — same function
    # as the un-rematted SCANNED model, and as the legacy remat_layers
    _, (v_scan_ref, _) = _loss_and_grad(
        Llama(**kw, scan_layers=True), tokens
    )
    _, (v_scan, _) = _loss_and_grad(
        Llama(**kw, scan_layers=True, remat_policy="save_nothing"), tokens
    )
    np.testing.assert_allclose(float(v_scan_ref), float(v_scan), rtol=1e-6)
    _, (v_legacy, _) = _loss_and_grad(
        Llama(**kw, scan_layers=True, remat_layers=True), tokens
    )
    np.testing.assert_allclose(float(v_scan_ref), float(v_legacy), rtol=1e-6)


def test_gpt2_remat_policy_trains_through_step():
    """remat_policy through the full compiled train step (the fit()
    surface), composed with ZeRO-1 shard_state on a 4-dev mesh."""
    from tpudist.train import state_shardings_of

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=4), devices=jax.devices()[:4]
    )
    model = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=2,
                 num_heads=4, remat_policy="dots_saveable")
    tx = optim.shard_state(optax.adam(1e-3), mesh)
    state = create_train_state(
        model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
    )
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    # a LEARNABLE stream (repeating token) so "loss drops" is a property
    # of the step, not of luck against uniform noise
    batch = {"tokens": np.full((8, 16), 7, np.int32)}
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # it trains


@pytest.mark.slow
def test_1b_budget_replicated_over_sharded_under_16gb():
    """The acceptance claim behind the bench leg, exactly as computed
    there: GPT-2 1536×36 (~1.1B params) replicated Adam does NOT fit
    16 GB; ZeRO-1 over 8 replicas + per-block save_nothing remat does
    (measured numbers, docs/PERF.md §10: 29.8 vs 10.6 GB/chip).
    eval_shape only — no arrays are materialized (the trace of the
    36-layer model is the slow part, hence the marker)."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=8))
    model = GPT2(hidden_dim=1536, depth=36, num_heads=16)
    tokens = np.zeros((1, 16), np.int32)
    tx = optax.adam(1e-3)
    replicated = memory.train_state_budget(
        model, tx, tokens, batch=4, seq=1024, world_size=1,
        remat_policy="none",
    )
    sharded = memory.train_state_budget(
        model, optim.shard_state(tx, mesh), tokens, batch=4, seq=1024,
        world_size=8, remat_policy="save_nothing",
    )
    assert replicated["n_params"] > 1.0e9
    assert not replicated["fits"], memory.format_budget(replicated)
    assert sharded["fits"], memory.format_budget(sharded)
    # the moments really shrink ~world_size x (exact leaf accounting)
    ratio = (
        replicated["opt_state_bytes_per_chip"]
        / sharded["opt_state_bytes_per_chip"]
    )
    assert ratio > 7.0, ratio
