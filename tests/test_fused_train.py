"""Step-fusion integration (docs/PERF.md §4c): make_train_step(fused=) /
fit(fused=) — trajectory equivalence of the fully-fused step against the
unfused reference (the acceptance bar: 24-step GPT-2, composed with ZeRO-1
shard_opt_state, the quantized reducer, and guard_nonfinite in one test
each), the compile-count pin (fused= introduces no recompiles across
steps), the resolve contract, the telemetry ``fusion`` row, and the
warm-start compute-copy refresh."""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.models.gpt2 import GPT2, chunked_lm_forward
from tpudist.optim import fused_adamw, shard_state
from tpudist.train import create_train_state, fit, lm_loss, make_train_step

N_STEPS = 24


def _model(**kw):
    return GPT2(vocab_size=97, max_seq_len=32, hidden_dim=48, depth=2,
                num_heads=4, **kw)


def _batches(n=N_STEPS, rows=8, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(0, 97, (rows, 16)).astype(np.int32)
            for _ in range(n)]


def _trajectory(mesh, fused, tx, model=None, **kw):
    model = model or _model()
    state = create_train_state(
        model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh
    )
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", fused=fused, **kw,
    )
    if step.grad_reducer is not None:
        state = step.grad_reducer.attach_residual(state)
    losses = []
    for b in _batches():
        state, metrics = step(state, {"tokens": b})
        losses.append(float(metrics["loss"]))
    return np.asarray(losses), state, step


# the repo's equivalence bar for same-math trajectory pins (the quantized
# suite uses 8% for a LOSSY wire; the fused step is exact math, so the bar
# here is float32-accumulation tight): losses within 1e-4 relative. Params
# get an ABSOLUTE bar of one lr (1e-3): on near-zero-gradient coordinates
# Adam's direction is mhat/(sqrt(vhat)+eps) of two tiny numbers, so an
# ulp-level forward difference can legally swing a coordinate by up to
# ±lr per step without moving the loss — relative-to-leaf-scale bars
# false-alarm on exactly those coordinates.
def _assert_equivalent(l_ref, l_fused, s_ref, s_fused, lr=1e-3):
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s_fused.params),
                    jax.tree_util.tree_leaves(s_ref.params)):
        assert float(jnp.max(jnp.abs(a - b))) < lr


def test_fused_all_matches_unfused_24_steps():
    mesh = mesh_lib.create_mesh()
    l0, s0, _ = _trajectory(mesh, None, optax.adam(1e-3))
    l1, s1, step = _trajectory(
        mesh, "all", fused_adamw(1e-3, compute_dtype=jnp.float32)
    )
    assert step.fused == {"ln", "optimizer"}
    assert step.fused_info == {
        "ln": True, "optimizer": True, "compute_dtype": "float32",
    }
    _assert_equivalent(l0, l1, s0, s1)


def test_fused_all_with_shard_opt_state():
    """ZeRO-1 composition: the fused update runs on the sharded-state
    layout (restored in-graph); trajectory pinned to the unfused run."""
    mesh = mesh_lib.create_mesh()
    l0, s0, _ = _trajectory(mesh, None, optax.adam(1e-3))
    l1, s1, _ = _trajectory(
        mesh, "all",
        shard_state(fused_adamw(1e-3, compute_dtype=jnp.float32), mesh),
    )
    _assert_equivalent(l0, l1, s0, s1)


def test_fused_all_with_quantized_reducer():
    """Explicit int8 quantized all-reduce composition: fused vs unfused
    through the SAME lossy wire — the deltas must come from the wire, not
    the fusion, so the two quantized runs pin each other tightly."""
    mesh = mesh_lib.create_mesh()
    l0, s0, _ = _trajectory(mesh, None, optax.adam(1e-3),
                            reduce="quantized")
    l1, s1, _ = _trajectory(
        mesh, "all", fused_adamw(1e-3, compute_dtype=jnp.float32),
        reduce="quantized",
    )
    # the int8 wire's stochastic rounding resolves ulp-level gradient
    # differences into occasionally-different draws, so the param bar is a
    # few lr rather than one (the loss bar — the convergence signal —
    # stays at the exact-math tightness)
    _assert_equivalent(l0, l1, s0, s1, lr=5e-3)


def test_fused_all_with_guard_nonfinite():
    mesh = mesh_lib.create_mesh()
    l0, s0, _ = _trajectory(mesh, None, optax.adam(1e-3),
                            guard_nonfinite=True)
    l1, s1, _ = _trajectory(
        mesh, "all", fused_adamw(1e-3, compute_dtype=jnp.float32),
        guard_nonfinite=True,
    )
    _assert_equivalent(l0, l1, s0, s1)


def test_fused_chunked_ce_odd_chunk():
    """fused LN + the chunked-CE forward at a chunk that does NOT divide
    the 15 predicted positions (odd last chunk) — the rebuild hook must
    hand the fused clone to the chunked forward, and the numbers must
    match the plain fused path."""
    mesh = mesh_lib.create_mesh()
    model = _model()
    l1, s1, _ = _trajectory(
        mesh, "all", fused_adamw(1e-3, compute_dtype=jnp.float32),
        model=model,
    )
    l2, s2, step = _trajectory(
        mesh, "all", fused_adamw(1e-3, compute_dtype=jnp.float32),
        model=model, forward_loss=chunked_lm_forward(model, chunk=7),
    )
    assert "ln" in step.fused
    np.testing.assert_allclose(l2, l1, rtol=1e-4, atol=1e-5)


def test_fused_no_recompiles_across_steps():
    """Compile-count pin: fused= must not add jit cache entries beyond the
    unfused baseline's, and the count must be stable from step 2 on (no
    per-step retraces — e.g. a schedule or bias-correction scalar leaking
    in as a python value would recompile every step)."""
    mesh = mesh_lib.create_mesh()

    def count(fused, tx):
        model = _model()
        state = create_train_state(
            model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(model, tx, mesh, loss_fn=lm_loss,
                               input_key="tokens", label_key="tokens",
                               fused=fused)
        sizes = []
        for b in _batches(6):
            state, _ = step(state, {"tokens": b})
            sizes.append(step.jitted._cache_size())
        return sizes

    base = count(None, optax.adam(1e-3))
    fused = count("all", fused_adamw(1e-3, compute_dtype=jnp.float32))
    assert fused[-1] == base[-1]
    assert fused[1:] == [fused[1]] * len(fused[1:])  # stable after step 2


def test_resolve_fused_contract():
    from tpudist.train import resolve_fused

    model, ftx = _model(), fused_adamw(1e-3)
    assert resolve_fused(None, model, ftx) == frozenset()
    assert resolve_fused("none", model, ftx) == frozenset()
    assert resolve_fused("auto", model, ftx) == {"ln", "optimizer"}
    assert resolve_fused("auto", model, optax.adam(1e-3)) == {"ln"}
    assert resolve_fused("ln", model, optax.adam(1e-3)) == {"ln"}
    with pytest.raises(ValueError, match="fused_adamw"):
        resolve_fused("optimizer", model, optax.adam(1e-3))
    with pytest.raises(ValueError, match="fused_ln"):
        from tpudist.models.resnet import resnet18

        resolve_fused("ln", resnet18(), ftx)
    # a resnet under "auto" quietly fuses only what exists
    from tpudist.models.resnet import resnet18

    assert resolve_fused("auto", resnet18(), ftx) == {"optimizer"}
    with pytest.raises(ValueError, match="expected"):
        resolve_fused("everything", model, ftx)


def test_foreign_forward_loss_without_rebuild():
    """An EXPLICIT ln request with a rebuild-less forward_loss must refuse
    (running unfused against an explicit request would be a benchmark
    lying); "auto" — best-effort by contract — declines the LN side with
    a warning and keeps whatever else resolved."""
    mesh = mesh_lib.create_mesh()
    plain = lambda params, stats, batch: (jnp.float32(0.0), stats)
    with pytest.raises(ValueError, match="rebuild"):
        make_train_step(_model(), optax.adam(1e-3), mesh, fused="ln",
                        forward_loss=plain)
    with pytest.warns(UserWarning, match="declining LN fusion"):
        step = make_train_step(
            _model(), fused_adamw(1e-3), mesh, fused="auto",
            forward_loss=plain,
        )
    assert step.fused == {"optimizer"}
    assert step.fused_info["ln"] is False


def test_fit_fused_writes_fusion_row(tmp_path):
    from tpudist.data.loader import DataLoader

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 97, (32, 16)).astype(np.int32)
    state, losses = fit(
        _model(), fused_adamw(1e-3, compute_dtype=jnp.float32),
        DataLoader({"tokens": tokens}, 16),
        epochs=2, job_id="FU", batch_size=16, loss_fn=lm_loss,
        input_key="tokens", label_key="tokens", fused="all",
        log_dir=str(tmp_path), telemetry=True, profile=False,
    )
    assert len(losses) == 4 and all(np.isfinite(losses))
    rows = [json.loads(l) for l in pathlib.Path(
        tmp_path / "FU_telemetry_0.jsonl").read_text().splitlines()]
    fusion = [r for r in rows if r["kind"] == "fusion"]
    assert len(fusion) == 1
    assert fusion[0]["ln"] is True and fusion[0]["optimizer"] is True
    assert fusion[0]["compute_dtype"] == "float32"


def test_fit_unfused_stream_has_no_fusion_row(tmp_path):
    """fused=None keeps the stream byte-compatible: no fusion row."""
    from tpudist.data.loader import DataLoader

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 97, (32, 16)).astype(np.int32)
    fit(
        _model(), optax.adam(1e-3), DataLoader({"tokens": tokens}, 16),
        epochs=1, job_id="NF", batch_size=16, loss_fn=lm_loss,
        input_key="tokens", label_key="tokens",
        log_dir=str(tmp_path), telemetry=True, profile=False,
    )
    rows = [json.loads(l) for l in pathlib.Path(
        tmp_path / "NF_telemetry_0.jsonl").read_text().splitlines()]
    assert not [r for r in rows if r["kind"] == "fusion"]


def test_fit_warm_start_refreshes_compute_copy(tmp_path):
    """init_params replaces the masters AFTER tx.init cast the copy; the
    first fused step must see a copy of the WARM params, or the whole
    first step trains the discarded random init."""
    from tpudist.data.loader import DataLoader
    from tpudist.optim import fused_compute_params

    from flax import linen as nn

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 97, (16, 16)).astype(np.int32)
    model = _model()
    # unboxed, like every real warm-start source (tpudist.interop)
    warm = nn.meta.unbox(
        model.init(jax.random.key(123), tokens[:1], train=False)["params"]
    )
    state, _ = fit(
        model,
        # lr=0: params stay == the warm start, so the copy must too
        fused_adamw(0.0, compute_dtype=jnp.bfloat16),
        DataLoader({"tokens": tokens}, 16),
        epochs=1, job_id="WS", batch_size=16, loss_fn=lm_loss,
        input_key="tokens", label_key="tokens", fused="all",
        log_dir=str(tmp_path), profile=False, init_params=warm,
    )
    copy = fused_compute_params(state.opt_state, state.params)
    assert copy is not None
    for c, p in zip(jax.tree_util.tree_leaves(copy),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(
            np.asarray(c, np.float32),
            np.asarray(p.astype(jnp.bfloat16), np.float32),
        )
