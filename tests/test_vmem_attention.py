"""vmem attention (tpudist/ops/vmem_attention.py) vs the XLA oracle:
forward and gradients, aligned and ragged (ViT-shaped) sequences, causal
and bidirectional, and the multi_head_attention auto routing."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.ops.attention import dot_product_attention, multi_head_attention
from tpudist.ops.vmem_attention import vmem_attention


def _qkv(b, s, h, d, seed=0, dtype=jnp.float32):
    rng = np.random.Generator(np.random.PCG64(seed))
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle_aligned(causal, kernel_parity):
    q, k, v = _qkv(2, 256, 2, 64, seed=1)
    out = vmem_attention(q, k, v, causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    kernel_parity(out, ref)


def test_matches_oracle_ragged_vit_shape():
    """S=197 (ViT-B/16): padded to 256 internally, padded keys masked."""
    q, k, v = _qkv(2, 197, 3, 64, seed=2)
    out = vmem_attention(q, k, v, causal=False)
    ref = dot_product_attention(q, k, v, causal=False)
    assert out.shape == ref.shape == (2, 197, 3, 64)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_kv_len_masks_padded_keys():
    """Explicit kv_len ≡ slicing the keys: padded K/V rows are inert."""
    q, k, v = _qkv(1, 128, 2, 64, seed=3)
    ref = dot_product_attention(q, k[:, :100], v[:, :100], causal=False)
    out = vmem_attention(q, k, v, causal=False, kv_len=100)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal,s", [(True, 256), (False, 197)])
def test_grads_match_oracle(causal, s):
    q, k, v = _qkv(1, s, 2, 64, seed=4)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    g_vmem = jax.grad(
        functools.partial(loss, functools.partial(vmem_attention, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        functools.partial(
            loss, functools.partial(dot_product_attention, causal=causal)
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g_vmem, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
            err_msg=name,
        )


def test_refuses_long_sequences():
    q, k, v = _qkv(1, 2048, 1, 64, seed=5)
    with pytest.raises(NotImplementedError, match="flash"):
        vmem_attention(q, k, v)


def test_auto_routes_vmem_then_flash():
    """auto: short S runs the vmem kernel; long S falls through to
    flash/XLA without error."""
    q, k, v = _qkv(1, 256, 2, 64, seed=6)
    out = multi_head_attention(q, k, v, causal=True, impl="auto")
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    # long S: must not raise (flash handles 128-aligned 2048)
    q2, k2, v2 = _qkv(1, 2048, 1, 64, seed=7)
    out2 = multi_head_attention(q2, k2, v2, causal=True, impl="auto")
    assert out2.shape == q2.shape


def test_multi_head_attention_kv_len_plumbed():
    """kv_len reaches the kernel through the dispatcher, and the dense path
    builds the equivalent mask — all impls agree with sliced-K oracle."""
    q, k, v = _qkv(1, 128, 2, 64, seed=8)
    ref = dot_product_attention(q, k[:, :90], v[:, :90], causal=False)
    for impl in ("xla", "vmem", "auto"):
        out = multi_head_attention(q, k, v, impl=impl, kv_len=90)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=impl,
        )
    with pytest.raises(ValueError, match="not both"):
        multi_head_attention(
            q, k, v, impl="xla", kv_len=90,
            mask=jnp.ones((1, 1, 1, 128), bool),
        )


def test_gpt2_model_vmem_matches_xla():
    """Model-level: the bench's attn_impl='vmem' GPT-2 computes the same
    function as the XLA oracle (same params, same tokens, same loss)."""
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh()
    rng = np.random.Generator(np.random.PCG64(9))
    tokens = rng.integers(0, 97, (8, 128)).astype(np.int32)
    losses = {}
    for impl in ("xla", "vmem"):
        model = GPT2(vocab_size=97, max_seq_len=128, hidden_dim=32, depth=2,
                     num_heads=4, attn_impl=impl)
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens",
        )
        _, metrics = step(state, {"tokens": tokens})
        losses[impl] = float(metrics["loss"])
    assert abs(losses["vmem"] - losses["xla"]) < 2e-5, losses


def test_vit_model_vmem_matches_xla():
    """ViT at its ragged S (4-pixel patches on 32x32 → 65 tokens) through
    the padded+masked kernel equals the XLA path."""
    from tpudist.models import vit_b16

    rng = np.random.Generator(np.random.PCG64(10))
    images = jnp.asarray(rng.random((2, 32, 32, 3)), jnp.float32)
    outs = {}
    for impl in ("xla", "vmem"):
        model = vit_b16(patch_size=4, depth=2, attn_impl=impl)
        variables = model.init(jax.random.key(0), images[:1], train=False)
        outs[impl] = np.asarray(
            model.apply(variables, images, train=False)
        )
    np.testing.assert_allclose(outs["vmem"], outs["xla"], rtol=2e-4, atol=2e-4)


def test_multi_head_attention_kv_len_flash_impl():
    """impl='flash' + kv_len stays on the kernel path (native in-kernel
    masking, no dense fallback) and matches the sliced-K oracle."""
    import warnings

    q, k, v = _qkv(1, 256, 2, 64, seed=11)
    ref = dot_product_attention(q, k[:, :130], v[:, :130], causal=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a fallback warning = test failure
        out = multi_head_attention(q, k, v, impl="flash", kv_len=130)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("h,h_kv", [(4, 2), (6, 2), (4, 1)])
def test_gqa_matches_repeated_kv(h, h_kv):
    """Grouped K/V read natively (no repeat in HBM) equals the repeat-then-
    MHA oracle — forward and all grads, including the f32-accumulated
    dk/dv that sum each query group's contributions."""
    rng = np.random.Generator(np.random.PCG64(30 + h * 10 + h_kv))
    b, s, d = 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h_kv, d)), jnp.float32)
    rep = h // h_kv

    def oracle(q, k, v):
        return dot_product_attention(
            q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
            causal=True,
        )

    out = vmem_attention(q, k, v, causal=True)
    ref = oracle(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_vmem = jax.grad(
        loss(lambda q, k, v: vmem_attention(q, k, v, causal=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for name, a, bb in zip("dq dk dv".split(), g_vmem, g_ref):
        assert a.shape == bb.shape, name
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_gqa_through_dispatcher_and_fallback():
    """multi_head_attention takes grouped K/V on every impl: vmem reads it
    natively; the dense fallback repeats internally."""
    rng = np.random.Generator(np.random.PCG64(33))
    q = jnp.asarray(rng.standard_normal((1, 128, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.float32)
    ref = dot_product_attention(
        q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2), causal=True
    )
    for impl in ("vmem", "auto", "xla"):
        out = multi_head_attention(q, k, v, causal=True, impl=impl)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=impl,
        )


def test_mesh_shard_map_wrap_matches_unwrapped():
    """multi_head_attention(mesh=...) runs the kernel per-shard inside
    shard_map (the multi-chip Pallas path: pallas_call has no GSPMD rule);
    the wrap must be loss-exact vs the unwrapped single-program path."""
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.models.gpt2 import GPT2
    from tpudist.train import create_train_state, lm_loss, make_train_step

    mesh = mesh_lib.create_mesh()
    rng = np.random.Generator(np.random.PCG64(40))
    tokens = rng.integers(0, 97, (8, 128)).astype(np.int32)
    losses = {}
    for wrapped in (False, True):
        model = GPT2(vocab_size=97, max_seq_len=128, hidden_dim=32, depth=2,
                     num_heads=4, attn_impl="vmem",
                     mesh=mesh if wrapped else None)
        tx = optax.adam(1e-3)
        state = create_train_state(
            model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh
        )
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens",
        )
        _, metrics = step(state, {"tokens": tokens})
        losses[wrapped] = float(metrics["loss"])
    assert abs(losses[True] - losses[False]) < 2e-5, losses
