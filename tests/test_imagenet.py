"""ImageNet-style image-folder pipeline tests (SURVEY.md §4 pattern: the
reference has no tests; the build's data layer is covered like the sampler —
determinism, shard disjointness, transform shape/range contracts)."""

import numpy as np
import pytest

from tpudist.data.imagenet import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ImageFolderLoader,
    _random_resized_crop,
    _resize_center_crop,
    scan_image_folder,
    synthetic_imagenet,
)
from tpudist.data.sampler import DistributedSampler

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


@pytest.fixture(scope="module")
def folder(tmp_path_factory):
    """Tiny image-folder tree: 3 classes x 5 JPEGs of varied sizes."""
    root = tmp_path_factory.mktemp("imgnet")
    rng = np.random.Generator(np.random.PCG64(0))
    sizes = [(37, 52), (64, 64), (91, 48), (120, 80), (48, 48)]
    for cls in ["cat", "dog", "eel"]:
        d = root / cls
        d.mkdir()
        for i, (w, h) in enumerate(sizes):
            arr = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.jpg", quality=90)
    return root


def test_scan_sorted_classes_and_labels(folder):
    paths, labels, classes = scan_image_folder(folder)
    assert classes == ["cat", "dog", "eel"]
    assert len(paths) == 15 and labels.shape == (15,)
    # labels follow the sorted class order; files sorted within a class
    assert labels.tolist() == [0] * 5 + [1] * 5 + [2] * 5
    assert paths == sorted(paths)


def test_val_labels_keyed_by_train_classes(folder, tmp_path):
    """A val tree missing a class dir must not shift later labels: labels
    are positions in the TRAIN class list when it's passed in."""
    val = tmp_path / "val"
    for cls in ["cat", "eel"]:  # no "dog" — partial download
        (val / cls).mkdir(parents=True)
        arr = np.zeros((40, 40, 3), np.uint8)
        Image.fromarray(arr).save(val / cls / "x.jpg")
    train_classes = ["cat", "dog", "eel"]
    _, labels, classes = scan_image_folder(val, train_classes)
    assert classes == train_classes
    assert sorted(labels.tolist()) == [0, 2]  # eel keeps index 2
    # a val-only class not present in train raises instead of guessing
    (val / "zzz").mkdir()
    Image.fromarray(arr).save(val / "zzz" / "x.jpg")
    with pytest.raises(ValueError, match="not in the reference class list"):
        scan_image_folder(val, train_classes)


def test_scan_missing_root_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        scan_image_folder(tmp_path / "nope")


def test_train_loader_shapes_and_normalization(folder):
    loader = ImageFolderLoader(folder, 4, train=True, image_size=32, seed=1)
    batches = list(loader)
    assert len(batches) == len(loader) == 15 // 4
    for b in batches:
        assert b["image"].shape == (4, 32, 32, 3)
        assert b["image"].dtype == np.float32
        assert b["label"].dtype == np.int32
    # normalized range: (x/255 - mean)/std for x in [0,255]
    lo = (0 - IMAGENET_MEAN) / IMAGENET_STD
    hi = (1 - IMAGENET_MEAN) / IMAGENET_STD
    img = np.concatenate([b["image"] for b in batches])
    assert img.min() >= lo.min() - 1e-5 and img.max() <= hi.max() + 1e-5


def test_eval_loader_deterministic_and_full_coverage(folder):
    loader = ImageFolderLoader(
        folder, 4, train=False, image_size=32, drop_remainder=False
    )
    a = [b["image"] for b in loader]
    b = [b["image"] for b in loader]
    assert len(a) == 4  # ceil(15/4): the tail batch is kept
    assert a[-1].shape[0] == 3
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)  # eval transform has no noise
    labels = np.concatenate([bb["label"] for bb in loader])
    assert sorted(labels.tolist()) == sorted([0] * 5 + [1] * 5 + [2] * 5)


def test_train_epochs_reshuffle_but_replay_within_epoch(folder):
    loader = ImageFolderLoader(folder, 15, train=True, image_size=16, seed=7)
    loader.sampler.set_epoch(0)
    e0 = next(iter(loader))["image"]
    e0_again = next(iter(loader))["image"]
    np.testing.assert_array_equal(e0, e0_again)  # same epoch => same crops
    loader.sampler.set_epoch(1)
    e1 = next(iter(loader))["image"]
    assert not np.array_equal(e0, e1)  # new epoch => new order + new crops


def test_iter_from_matches_tail(folder):
    """Mid-epoch resume: iter_from(k) must replay exactly what an
    uninterrupted iteration would have produced from batch k."""
    loader = ImageFolderLoader(folder, 5, train=True, image_size=16, seed=3)
    full = list(loader)
    tail = list(loader.iter_from(1))
    assert len(tail) == len(full) - 1
    for a, b in zip(full[1:], tail):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_sharded_loaders_are_disjoint_and_cover(folder):
    """Two processes see disjoint shards covering the dataset — the
    DistributedSampler contract (SURVEY.md §2.6) through the image path."""
    loaders = [
        ImageFolderLoader(
            folder, 4, train=True, image_size=16,
            num_replicas=2, rank=r, seed=0, drop_remainder=False,
        )
        for r in range(2)
    ]
    shards = [list(ld.sampler.epoch_indices()) for ld in loaders]
    assert len(shards[0]) == len(shards[1]) == 8  # 15 padded to 16
    combined = sorted(shards[0] + shards[1])
    # pad duplicates exactly one head index; all 15 files covered
    assert set(combined) == set(range(15))


def test_random_resized_crop_bounds():
    img = Image.fromarray(
        np.arange(40 * 60 * 3, dtype=np.uint8).reshape(40, 60, 3) % 255
    )
    rng = np.random.Generator(np.random.PCG64(0))
    for _ in range(5):
        out = _random_resized_crop(img, 24, rng)
        assert out.size == (24, 24)


def test_center_crop_geometry():
    img = Image.fromarray(np.zeros((100, 300, 3), np.uint8))
    out = _resize_center_crop(img, 224)
    assert out.size == (224, 224)
    # short side lands at 256 before the crop
    tall = Image.fromarray(np.zeros((300, 100, 3), np.uint8))
    assert _resize_center_crop(tall, 224).size == (224, 224)


def test_synthetic_imagenet_shapes():
    d = synthetic_imagenet(8, num_classes=10, image_size=224)
    assert d["image"].shape == (8, 224, 224, 3)
    assert d["image"].dtype == np.uint8
    assert d["label"].max() < 10


def test_fit_protocol_compat(folder):
    """The streaming loader drops into fit() unchanged (one tiny epoch on
    the 8-device CPU mesh; resnet at 16px keeps the compile small)."""
    import optax

    from tpudist import mesh as mesh_lib
    from tpudist.models import resnet18
    from tpudist.train import fit

    loader = ImageFolderLoader(folder, 8, train=True, image_size=16, seed=0)
    model = resnet18(num_classes=10, small_inputs=True)
    state, losses = fit(
        model, optax.sgd(1e-2), loader,
        epochs=1, mesh=mesh_lib.create_mesh(),
        job_id="ImgNetSmoke", batch_size=1, profile=False,
        log_dir=str(folder),
    )
    assert len(losses) == len(loader) > 0
    assert np.isfinite(losses).all()
