"""tools/schema_audit.py: the emitted-kind scan and §1-table parse on
synthetic inputs, and — the tier-1 wiring the tool exists for — the REAL
audit over this repo: every ``sink.write("<kind>", ...)`` call site in
``tpudist/`` must have a row in the docs/OBSERVABILITY.md §1 schema table,
so schema drift fails the suite the same commit it appears."""

import importlib.util
import pathlib
import subprocess
import sys

_REPO = pathlib.Path(__file__).resolve().parent.parent


def _load():
    spec = importlib.util.spec_from_file_location(
        "schema_audit", _REPO / "tools" / "schema_audit.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


schema_audit = _load()


def test_emitted_kinds_literal_first_arg_only():
    src = '''
sink.write("health", step, loss=loss)
self.sink.write(
    "serve_summary",
    step,
)
f.write(line)           # file handle — variable, not a kind literal
buf.write("not_a_kind" if x else y)  # literal, still matches — fine:
                                     # a documented superset is harmless
sink.write(kind, step)  # variable kind — out of scope by design
'''
    assert schema_audit.emitted_kinds(src) \
        == {"health", "serve_summary", "not_a_kind"}


def test_documented_kinds_slices_section_one():
    md = """# Observability

## 1. The JSONL stream

| kind | fields | when |
|------|--------|------|
| `health` | loss | cadence |
| `span` | t0, dur_s | trace=True |

## 2. Something else

| `bogus` | should not count | outside §1 |
"""
    assert schema_audit.documented_kinds(md) == {"health", "span"}


def test_documented_kinds_whole_doc_fallback():
    md = "## Schema\n\n| `health` | x | y |\n| `kind` | header | row |\n"
    # no "## 1." heading → whole-document scan; header cell skipped
    assert schema_audit.documented_kinds(md) == {"health"}


def test_offenders_are_emitted_minus_documented(tmp_path):
    pkg = tmp_path / "tpudist"
    pkg.mkdir()
    (pkg / "a.py").write_text('sink.write("health", 1)\n')
    (pkg / "b.py").write_text('sink.write("mystery", 1)\n')
    emitted = schema_audit.scan_tree(pkg)
    assert emitted == {"health": {"tpudist/a.py"},
                       "mystery": {"tpudist/b.py"}}
    # documented-but-never-emitted is NOT an offense
    bad = schema_audit.offenders(emitted, {"health", "retired_kind"})
    assert bad == [("mystery", ["tpudist/b.py"])]


def test_cli_exit_codes(tmp_path):
    (tmp_path / "tpudist").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "tpudist" / "m.py").write_text(
        'sink.write("undocumented_kind", 1)\n'
    )
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "## 1. Stream\n\n| `health` | x | y |\n"
    )
    r = subprocess.run(
        [sys.executable, str(_REPO / "tools" / "schema_audit.py"),
         str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == schema_audit.EXIT_OFFENDERS == 3
    assert "undocumented_kind" in r.stdout
    # make it documented → clean exit
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "## 1. Stream\n\n| `health` | x | y |\n"
        "| `undocumented_kind` | x | y |\n"
    )
    r = subprocess.run(
        [sys.executable, str(_REPO / "tools" / "schema_audit.py"),
         str(tmp_path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0


def test_real_repo_schema_is_documented():
    """The audit this file exists to wire in: the live tree against the
    live docs. A new row kind without a §1 table row fails here."""
    assert schema_audit.audit(_REPO) == []
