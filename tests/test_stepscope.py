"""tools/stepscope.py: bucketed device-op attribution of profiler traces
(docs/PERF.md §4c) — classification rules, the total-by-construction
attribution guarantee, boundedness verdicts, diff mode, and the
acceptance integration: a REAL ``jax.profiler`` capture of a jitted
program whose device time stepscope attributes >= 95% (here: 100%, the
catch-all makes it total) into named buckets."""

import gzip
import importlib.util
import json
import pathlib

import jax
import jax.numpy as jnp
import pytest

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_stepscope():
    spec = importlib.util.spec_from_file_location(
        "stepscope", _TOOLS / "stepscope.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


stepscope = _load_stepscope()


# -- classification ----------------------------------------------------------


@pytest.mark.parametrize("name,args,bucket", [
    ("dot.3", None, "gemm"),
    ("convolution.1", None, "gemm"),
    ("%dot.7", None, "gemm"),
    ("all-reduce.2", None, "collective-comm"),
    ("reduce-scatter", None, "collective-comm"),
    ("all-gather.11", None, "collective-comm"),
    ("collective-permute.1", None, "collective-comm"),
    ("custom-call.4", {"long_name": "flash_attention kernel"},
     "attention-custom-call"),
    ("custom-call.9", {"tf_op": "pallas_call splash_mha"},
     "attention-custom-call"),
    ("fusion.12", None, "elementwise-other"),
    ("reduce.1", None, "elementwise-other"),
    ("copy.2", None, "elementwise-other"),
    ("broadcast", None, "elementwise-other"),
    # args.hlo_op wins over the event name (device lanes often carry a
    # framework label in `name` and the HLO op in args)
    ("ExecutorRun", {"hlo_op": "dot.4"}, "gemm"),
])
def test_classify(name, args, bucket):
    assert stepscope.classify(name, args) == bucket


def test_op_base_strips_suffix_and_sigil():
    assert stepscope.op_base("dot.3") == "dot"
    assert stepscope.op_base("%fusion.12") == "fusion"
    assert stepscope.op_base("all-reduce") == "all-reduce"


# -- aggregation on a synthetic trace ----------------------------------------


def _event(name, dur, pid=1, **args):
    return {"ph": "X", "pid": pid, "tid": 0, "ts": 0, "dur": dur,
            "name": name, "args": {"hlo_op": name, **args}}


def _synthetic_trace(tmp_path, fname="host.trace.json.gz", gemm_us=700,
                     coll_us=200, other_us=100):
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        _event("dot.1", gemm_us / 2), _event("dot.2", gemm_us / 2),
        _event("all-reduce.1", coll_us),
        _event("fusion.1", other_us / 2), _event("add.3", other_us / 2),
        # infra noise on the device process: must NOT count
        {"ph": "X", "pid": 1, "ts": 0, "dur": 9999,
         "name": "ThreadpoolListener", "args": {}},
        # python-tracer host event: no hlo args, non-device pid
        {"ph": "X", "pid": 99, "ts": 0, "dur": 5000, "name": "train_step",
         "args": {}},
    ]
    path = tmp_path / fname
    raw = json.dumps({"traceEvents": events}).encode()
    path.write_bytes(gzip.compress(raw) if fname.endswith(".gz") else raw)
    return path


def test_aggregate_buckets_and_excludes_infra(tmp_path):
    _synthetic_trace(tmp_path)
    summary = stepscope.summarize(tmp_path)
    assert summary["total_us"] == 1000.0  # infra + host events excluded
    assert summary["buckets"]["gemm"]["us"] == 700.0
    assert summary["buckets"]["collective-comm"]["us"] == 200.0
    assert summary["buckets"]["elementwise-other"]["us"] == 100.0
    assert stepscope.attributed_pct(summary) == 100.0
    # per-op totals merge the .N suffixes
    assert summary["ops"]["dot"]["count"] == 2
    assert summary["ops"]["dot"]["us"] == 700.0


def test_plain_json_and_gz_both_load(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    _synthetic_trace(tmp_path / "a", "h.trace.json")
    _synthetic_trace(tmp_path / "b", "h.trace.json.gz")
    sa = stepscope.summarize(tmp_path / "a")
    sb = stepscope.summarize(tmp_path / "b")
    assert sa["total_us"] == sb["total_us"] == 1000.0


def test_boundedness_verdicts():
    ridge = 240.0
    assert stepscope.boundedness("collective-comm", None, ridge) \
        == "interconnect-bound"
    assert stepscope.boundedness("elementwise-other", 500.0, ridge) \
        == "HBM-bound"
    assert stepscope.boundedness("gemm", 500.0, ridge) == "compute-bound"
    assert stepscope.boundedness("gemm", 50.0, ridge) == "HBM-bound"
    assert "unknown" in stepscope.boundedness("gemm", None, ridge)


def test_anatomy_intensity_reads_first_anatomy_row(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text(
        json.dumps({"kind": "heartbeat", "step": 1}) + "\n"
        + json.dumps({"kind": "anatomy", "program": "train_step",
                      "flops_scaled": 2.4e12, "bytes_accessed": 1e10})
        + "\n")
    assert stepscope.anatomy_intensity(p) == pytest.approx(240.0)
    empty = tmp_path / "e.jsonl"
    empty.write_text(json.dumps({"kind": "span"}) + "\n")
    assert stepscope.anatomy_intensity(empty) is None
    assert stepscope.anatomy_intensity(tmp_path / "missing.jsonl") is None


def test_report_and_cli(tmp_path, capsys):
    _synthetic_trace(tmp_path)
    rc = stepscope.main([str(tmp_path), "--ai", "500", "--top", "3"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "100.0% attributed" in out
    assert "compute-bound" in out          # gemm at ai 500 vs ridge ~240
    assert "interconnect-bound" in out
    assert "dot" in out


def test_cli_missing_trace_exits_2(tmp_path, capsys):
    assert stepscope.main([str(tmp_path / "nothing")]) == 2


def test_diff_mode_regressions_first(tmp_path, capsys):
    before, after = tmp_path / "before", tmp_path / "after"
    before.mkdir(), after.mkdir()
    _synthetic_trace(before, gemm_us=700, coll_us=200, other_us=100)
    # after: collectives tripled (the regression), gemm unchanged
    _synthetic_trace(after, gemm_us=700, coll_us=600, other_us=100)
    rc = stepscope.main(["--diff", str(before), str(after)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+0.400 ms" in out  # the 400us collective delta, sign marked
    lines = [l for l in out.splitlines() if l.strip().startswith(
        ("dot", "all-reduce", "fusion", "add"))]
    assert lines[0].strip().startswith("all-reduce")  # regressions first
    assert stepscope.main(["--diff", str(before)]) == 2  # needs two


# -- acceptance: a real profiler capture -------------------------------------


def test_real_capture_attributes_95pct(tmp_path):
    """jax.profiler on a jitted GEMM+elementwise program: stepscope's
    buckets must attribute >= 95% of device-op time (the catch-all makes
    it exactly 100%), with the GEMM bucket visibly populated."""

    @jax.jit
    def f(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((256, 256), jnp.float32)
    b = jnp.ones((256, 256), jnp.float32)
    f(a, b).block_until_ready()  # compile outside the capture
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(4):
            f(a, b).block_until_ready()
    summary = stepscope.summarize(tmp_path)
    assert summary is not None and summary["total_us"] > 0
    assert stepscope.attributed_pct(summary) >= 95.0
    assert summary["buckets"]["gemm"]["us"] > 0
    named = (summary["buckets"]["gemm"]["us"]
             + summary["buckets"]["elementwise-other"]["us"])
    assert named / summary["total_us"] > 0.5
