"""Run-health unit tests (tpudist.telemetry.health + dp.make_divergence_probe):
sink rotation segments, the crash-forensics tail buffer, thread-stack dumps,
the hang watchdog's arm/trip/one-shot contract, the straggler fold rule, and
the in-graph replica-divergence probe against a hand-desynced "replicated"
array (the single-process form of the multi-process perturbation test in
test_multiproc_health.py)."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import FrozenDict
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudist import mesh as mesh_lib
from tpudist.telemetry import TelemetrySink
from tpudist.telemetry import health as H


# -- sink rotation ----------------------------------------------------------

def test_sink_rotation_segments(tmp_path):
    """Size-capped rotation: the base path stays the live tail, sealed
    segments get increasing numbers, and the full row sequence survives
    reassembly across the chain."""
    path = tmp_path / "J_telemetry_0.jsonl"
    with TelemetrySink(path, max_bytes=220) as sink:
        for i in range(12):
            sink.write("heartbeat", i, seqno=i)
        segments = sink.segments()
    assert segments[-1] == path  # active file last
    assert len(segments) > 1  # the cap actually rotated
    assert [p.name for p in segments[:-1]] == [
        f"{path.name}.{n}" for n in range(1, len(segments))
    ]
    rows = [
        json.loads(line)
        for p in segments
        for line in p.read_text().splitlines()
    ]
    # every row strict JSON, in order, none lost at the rotation seams
    assert [r["seqno"] for r in rows] == list(range(12))
    # each sealed segment respected the cap
    for p in segments[:-1]:
        assert p.stat().st_size <= 220


def test_sink_rotation_numbering_survives_cleanup_gaps(tmp_path):
    """Deleting an old segment mid-run (routine log cleanup) must not
    make the NEWEST data inherit the OLDEST position: numbering is
    monotonic, and segments() orders numerically across the gap."""
    path = tmp_path / "J_telemetry_0.jsonl"
    with TelemetrySink(path, max_bytes=220) as sink:
        for i in range(8):
            sink.write("heartbeat", i, seqno=i)
        first = sink.segments()
        assert len(first) >= 3
        first[0].unlink()  # operator deletes the oldest sealed segment
        for i in range(8, 16):
            sink.write("heartbeat", i, seqno=i)
        segs = sink.segments()
    nums = [int(p.name.rsplit(".", 1)[1]) for p in segs[:-1]]
    assert nums == sorted(nums)
    assert first[0].name not in {p.name for p in segs}  # never reused
    # the surviving chain still reads oldest→newest
    seq = [json.loads(l)["seqno"] for p in segs for l in p.read_text().splitlines()]
    assert seq == sorted(seq)


def test_sink_rotation_cap_counts_utf8_bytes(tmp_path):
    """The cap is bytes on disk: rows with non-ASCII content (a hostname,
    an event string) must not under-count and overshoot the segment cap."""
    path = tmp_path / "J_telemetry_0.jsonl"
    with TelemetrySink(path, max_bytes=400) as sink:
        for i in range(12):
            sink.write("heartbeat", i, host="héllo-wörld-ø" * 3)
        segs = sink.segments()
    for p in segs[:-1]:
        assert p.stat().st_size <= 400


def test_sink_rotation_off_by_default(tmp_path):
    path = tmp_path / "J_telemetry_0.jsonl"
    with TelemetrySink(path) as sink:
        for i in range(50):
            sink.write("health", i)
        assert sink.segments() == [path]
    assert not list(tmp_path.glob("*.jsonl.*"))


def test_sink_tail_ring_buffer(tmp_path):
    with TelemetrySink(tmp_path / "t.jsonl") as sink:
        for i in range(300):
            sink.write("health", i)
        tail = sink.tail(5)
        assert [r["step"] for r in tail] == [295, 296, 297, 298, 299]
        # the ring is bounded at TAIL_ROWS regardless of how much was written
        assert len(sink.tail(10_000)) == TelemetrySink.TAIL_ROWS


# -- thread stacks / watchdog ----------------------------------------------

def test_thread_stacks_contains_caller():
    stacks = H.thread_stacks()
    assert any("MainThread" in k for k in stacks)
    joined = "".join(s for frames in stacks.values() for s in frames)
    assert "test_thread_stacks_contains_caller" in joined


def test_watchdog_arms_on_first_beat_and_trips_once():
    trips = []
    wd = H.HangWatchdog(0.15, trips.append, poll_s=0.03)
    try:
        # not armed before the first beat: bring-up (attach + compile) can
        # take arbitrarily long without tripping
        time.sleep(0.4)
        assert wd.tripped is None and not trips
        wd.beat(5)
        time.sleep(0.5)
        assert wd.tripped is not None
        assert wd.tripped["last_step"] == 5
        assert wd.tripped["age_s"] >= 0.15
        # one-shot: beating again never re-trips the finished monitor
        wd.beat(6)
        time.sleep(0.3)
        assert len(trips) == 1
    finally:
        wd.stop()


def test_watchdog_quiet_while_beats_flow():
    trips = []
    wd = H.HangWatchdog(0.3, trips.append, poll_s=0.03)
    try:
        for s in range(8):
            wd.beat(s)
            time.sleep(0.05)
        assert wd.tripped is None and not trips
    finally:
        wd.stop()


# -- straggler fold rule ----------------------------------------------------

def _fake_two_host_aggregator(sink, **kw):
    """An aggregator whose fold sees a fabricated 2-host / 8-device world
    (this suite runs one process), exercising the rank-0 fold rule
    without a multi-process launch."""
    agg = H.CrossProcessAggregator(sink, **kw)
    agg._slot_proc = np.asarray([0, 0, 0, 0, 1, 1, 1, 1])
    agg._procs = [0, 1]
    return agg


def _rows(step, interval, host0, host1):
    steps = np.full((8, 1), step, np.int32)
    floats = np.zeros((8, 2), np.float32)
    floats[:, 0] = interval
    floats[:4, 1] = host0
    floats[4:, 1] = host1
    return steps, floats


def test_straggler_fires_once_on_persistent_slow_rank(tmp_path):
    sink = TelemetrySink(tmp_path / "t.jsonl")
    agg = _fake_two_host_aggregator(sink, every=1, ratio=1.5, patience=3)
    # rank 1 persistently burns 80% of each step host-side; rank 0 ~2%
    for k in range(5):
        agg._fold(*_rows(k + 1, 0.5, 0.01, 0.4), k + 1)
    sink.close()
    rows = [json.loads(l) for l in (tmp_path / "t.jsonl").read_text().splitlines()]
    fleet = [r for r in rows if r["kind"] == "fleet"]
    stragglers = [r for r in rows if r["kind"] == "straggler"]
    assert len(fleet) == 5
    assert fleet[0]["per_rank_host_s"] == {"0": 0.01, "1": 0.4}
    # one-shot: fires at the patience-th consecutive fold, never again
    assert len(stragglers) == 1
    assert stragglers[0]["rank"] == 1
    assert stragglers[0]["consecutive_folds"] == 3
    assert agg.straggler_events and agg.straggler_events[0]["rank"] == 1
    assert agg.last_seen == {0: 5, 1: 5}


def test_straggler_silent_on_healthy_and_transient_fleets(tmp_path):
    sink = TelemetrySink(tmp_path / "t.jsonl")
    agg = _fake_two_host_aggregator(sink, every=1, ratio=1.5, patience=3)
    # healthy: both ranks near-zero host share
    for k in range(4):
        agg._fold(*_rows(k + 1, 0.5, 0.01, 0.012), k + 1)
    # transient: rank 1 spikes for patience-1 folds, then recovers — the
    # streak resets and nothing fires
    agg._fold(*_rows(5, 0.5, 0.01, 0.4), 5)
    agg._fold(*_rows(6, 0.5, 0.01, 0.4), 6)
    agg._fold(*_rows(7, 0.5, 0.01, 0.012), 7)
    agg._fold(*_rows(8, 0.5, 0.01, 0.4), 8)
    sink.close()
    rows = [json.loads(l) for l in (tmp_path / "t.jsonl").read_text().splitlines()]
    assert not [r for r in rows if r["kind"] == "straggler"]
    assert not agg.straggler_events


def test_aggregator_single_host_never_straggles(tmp_path):
    """A one-host fleet writes fleet rows (the skew stats are still the
    report's evidence) but has no one to straggle behind."""
    sink = TelemetrySink(tmp_path / "t.jsonl")
    agg = H.CrossProcessAggregator(sink, every=2, patience=1)
    agg.on_step(2, 0.5, 0.45)  # dispatch
    agg.on_step(4, 0.5, 0.45)  # resolves step 2, dispatches step 4
    agg.flush()
    sink.close()
    rows = [json.loads(l) for l in (tmp_path / "t.jsonl").read_text().splitlines()]
    assert [r["step"] for r in rows if r["kind"] == "fleet"] == [2, 4]
    assert not [r for r in rows if r["kind"] == "straggler"]


def test_aggregator_gather_rides_delayed_fetch(tmp_path):
    """The in-graph gather's result is read one cadence later: after ONE
    on_step nothing has folded yet (the value is still in flight on the
    async pipeline); the next cadence folds it."""
    sink = TelemetrySink(tmp_path / "t.jsonl")
    agg = H.CrossProcessAggregator(sink, every=2)
    agg.on_step(1, 0.5, 0.0)  # off-cadence: ignored entirely
    agg.on_step(2, 0.5, 0.0)
    assert agg.fleet is None and agg._pending is not None
    agg.on_step(4, 0.7, 0.0)
    assert agg.fleet is not None
    assert agg.fleet["per_rank_interval_s"] == {"0": 0.5}
    sink.close()


# -- divergence probe -------------------------------------------------------

def _replicated_state(mesh, extra_opt=()):
    from tpudist.train import TrainState

    repl = mesh_lib.replicated_sharding(mesh)
    params = jax.device_put(
        {"w": np.arange(64, dtype=np.float32), "b": np.ones(8, np.float32)},
        repl,
    )
    return TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        batch_stats=FrozenDict(), opt_state=extra_opt,
    )


def test_divergence_probe_clean_then_desynced():
    from tpudist.parallel.dp import make_divergence_probe

    mesh = mesh_lib.create_mesh()
    state = _replicated_state(mesh)
    probe = make_divergence_probe(state, mesh)
    clean = {k: int(v) for k, v in probe(state).items()}
    assert clean["replica_divergence"] == 0
    assert clean["state_nonfinite"] == 0

    # hand-build a "replicated" param whose copy on one device has a
    # single element perturbed — the silent-desync failure mode
    repl = mesh_lib.replicated_sharding(mesh)
    base = np.arange(64, dtype=np.float32)
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        arr = base.copy()
        if i == 3:
            arr[17] += 1e-3
        bufs.append(jax.device_put(arr, d))
    bad = jax.make_array_from_single_device_arrays(base.shape, repl, bufs)
    state_bad = state.replace(
        params={"w": bad, "b": state.params["b"]}
    )
    desynced = {k: int(v) for k, v in probe(state_bad).items()}
    assert desynced["replica_divergence"] == 1  # exactly the one bad replica
    # the fleet checksum itself (replica 0's view) is unchanged — the
    # signal is the cross-replica comparison, not the value
    assert desynced["replica_checksum"] == clean["replica_checksum"]


def test_divergence_probe_single_bit_flip_is_visible():
    """The checksum is over raw BITS, so a low-mantissa flip a float sum
    would bury in accumulation error still changes a replica's sum."""
    from tpudist.parallel.dp import make_divergence_probe

    mesh = mesh_lib.create_mesh()
    state = _replicated_state(mesh)
    probe = make_divergence_probe(state, mesh)
    repl = mesh_lib.replicated_sharding(mesh)
    base = np.arange(64, dtype=np.float32)
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        arr = base.copy()
        if i == 5:
            u = arr.view(np.uint32)
            u[30] ^= 1  # lowest mantissa bit
        bufs.append(jax.device_put(arr, d))
    bad = jax.make_array_from_single_device_arrays(base.shape, repl, bufs)
    out = probe(state.replace(params={"w": bad, "b": state.params["b"]}))
    assert int(out["replica_divergence"]) == 1


def test_divergence_probe_zero1_sharded_state():
    """ZeRO-1-style [world, cols] P(data) opt leaves hold a different
    shard per replica — no redundancy to compare, so they contribute the
    psum'd checksum and the non-finite corruption signal instead of
    false replica-divergence positives."""
    from tpudist.parallel.dp import make_divergence_probe

    mesh = mesh_lib.create_mesh()
    sh = NamedSharding(mesh, P("data"))
    opt = np.arange(32, dtype=np.float32).reshape(8, 4)
    leaf = jax.device_put(opt, sh)
    state = _replicated_state(mesh, extra_opt=(leaf,))
    probe = make_divergence_probe(state, mesh)
    clean = {k: int(v) for k, v in probe(state).items()}
    assert clean["replica_divergence"] == 0
    assert clean["state_nonfinite"] == 0
    assert clean["sharded_checksum"] != 0

    opt_bad = opt.copy()
    opt_bad[2, 1] = np.nan  # corruption inside one replica's shard
    state_bad = state.replace(opt_state=(jax.device_put(opt_bad, sh),))
    bad = {k: int(v) for k, v in probe(state_bad).items()}
    assert bad["replica_divergence"] == 0
    assert bad["state_nonfinite"] == 1
    assert bad["sharded_checksum"] != clean["sharded_checksum"]


def test_divergence_probe_crosses_non_data_axes():
    """A desync in a tensor column OTHER than 0 must surface in the
    fetched scalar: the per-column verdicts are psum'd across the
    non-data axes, so out_specs=P() is true rather than asserted (the
    regression where device 0's column silently spoke for the fleet)."""
    from tpudist.parallel.dp import make_divergence_probe

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    repl = mesh_lib.replicated_sharding(mesh)
    base = np.arange(64, dtype=np.float32)
    from tpudist.train import TrainState

    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params={"w": jax.device_put(base, repl)},
        batch_stats=FrozenDict(), opt_state=(),
    )
    probe = make_divergence_probe(state, mesh)
    assert int(probe(state)["replica_divergence"]) == 0

    devs = list(mesh.devices.flat)
    # flat index 3 = (data=1, tensor=1): a non-zero coordinate on BOTH
    # the compared axis and a crossed one
    bufs = []
    for i, d in enumerate(devs):
        arr = base.copy()
        if i == 3:
            arr[7] += 1e-3
        bufs.append(jax.device_put(arr, d))
    bad = jax.make_array_from_single_device_arrays(base.shape, repl, bufs)
    out = probe(state.replace(params={"w": bad}))
    assert int(out["replica_divergence"]) == 1

    # a FULLY desynced replica (every tensor column corrupted — the
    # resumed-from-wrong-step failure) counts as ONE bad replica, not
    # once per column: the cross-axis fold is a max, so the operator's
    # triage number stays a replica count
    bufs = []
    for i, d in enumerate(devs):
        arr = base.copy()
        if i in (2, 3):  # data=1: both its tensor-column devices
            arr += 1e-3
        bufs.append(jax.device_put(arr, d))
    bad_full = jax.make_array_from_single_device_arrays(
        base.shape, repl, bufs
    )
    out = probe(state.replace(params={"w": bad_full}))
    assert int(out["replica_divergence"]) == 1


def test_divergence_probe_none_on_single_replica():
    from tpudist.parallel.dp import make_divergence_probe

    mesh = mesh_lib.create_mesh(
        mesh_lib.MeshConfig(data=1, tensor=-1)
    )
    state = _replicated_state(mesh_lib.create_mesh())
    assert make_divergence_probe(state, mesh) is None


# -- report helpers ---------------------------------------------------------

def test_report_with_nan_anomaly_stays_strict_json(tmp_path):
    """The run that died of a NaN loss records that NaN in its sentry
    events; the report/crash writers must serialize it as null (the
    sink's strict-JSON contract), not a bare NaN token that breaks every
    strict consumer of exactly the forensics written for them."""
    from tpudist.telemetry import NanSentry, TelemetryConfig, TelemetrySink

    sink = TelemetrySink(tmp_path / "t.jsonl")
    cfg = TelemetryConfig(hang_timeout_s=None)
    rh = H.RunHealth(cfg, sink, job_id="NJ", log_dir=str(tmp_path))

    class _TelStub:
        sentry = NanSentry(min_steps=2)
        _comm = None

    _TelStub.sentry.observe(3, float("nan"))
    assert _TelStub.sentry.events and _TelStub.sentry.events[0]["loss"] != \
        _TelStub.sentry.events[0]["loss"]  # really a NaN in the history
    rh._tel = _TelStub()
    rh.observe_interval(3, 0.1)
    rh.finish(status="crashed:FloatingPointError")
    text = (tmp_path / "NJ_report.json").read_text()
    report = json.loads(text)  # strict parse
    assert "NaN" not in text
    assert report["anomaly_events"][0]["loss"] is None
    sink.close()


def test_percentiles_and_bounded_observation():
    p = H._percentiles(list(range(1, 101)))
    assert p["p50"] == pytest.approx(50.5)
    assert p["max"] == 100 and p["n"] == 100
    assert H._percentiles([]) is None
    xs = []
    for i in range(1000):
        H._observe_bounded(xs, float(i), cap=100)
    assert len(xs) <= 100  # multi-day runs stay bounded
