"""Tensor-parallel GPT-2: sharded params train to the same numbers as a
single-device run, on the 8 fake CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpudist import mesh as mesh_lib
from tpudist.models.gpt2 import GPT2
from tpudist.train import (
    create_train_state,
    lm_loss,
    make_train_step,
    state_shardings_of,
)


def _tiny_gpt2():
    return GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2, num_heads=4)


def _batch(b=4, s=16, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return {"tokens": rng.integers(0, vocab, (b, s)).astype(np.int32)}


def _one_step(mesh, batch):
    model = _tiny_gpt2()
    # SGD keeps the update proportional to the grad, so cross-mesh fp noise
    # stays fp-sized (adam's normalization amplifies near-zero-grad noise to
    # O(lr) and makes bitwise comparison meaningless)
    tx = optax.sgd(0.1)
    sample = jnp.zeros((1, 16), jnp.int32)
    state = create_train_state(model, 0, sample, tx, mesh)
    step = make_train_step(
        model, tx, mesh,
        loss_fn=lm_loss, input_key="tokens", label_key="tokens",
        state_sharding=state_shardings_of(state),
    )
    state, metrics = step(state, batch)
    return state, float(metrics["loss"])


def test_params_are_tensor_sharded():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, tensor=4))
    model = _tiny_gpt2()
    state = create_train_state(
        model, 0, jnp.zeros((1, 16), jnp.int32), optax.adam(1e-3), mesh
    )
    wte = state.params["wte"]
    assert tuple(wte.sharding.spec)[:1] == ("tensor",)
    qkv_kernel = state.params["h_0"]["qkv"]["kernel"]
    assert tuple(qkv_kernel.sharding.spec)[:3] == (None, None, "tensor")
    # adam moments follow the params' shardings through propagation
    mu_wte = state.opt_state[0].mu["wte"]
    assert tuple(mu_wte.sharding.spec)[:1] == ("tensor",)


def test_tp_step_matches_single_device():
    batch = _batch()
    mesh_tp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, tensor=4))
    mesh_1 = mesh_lib.create_mesh(devices=jax.devices()[:1])
    state_tp, loss_tp = _one_step(mesh_tp, batch)
    state_1, loss_1 = _one_step(mesh_1, batch)
    assert np.isfinite(loss_tp)
    np.testing.assert_allclose(loss_tp, loss_1, atol=1e-5, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state_tp.params),
        jax.tree_util.tree_leaves(state_1.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=0)


def test_tp_composes_with_grad_accum():
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=2, tensor=4))
    model = _tiny_gpt2()
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh,
        loss_fn=lm_loss, input_key="tokens", label_key="tokens",
        grad_accum=2, state_sharding=state_shardings_of(state),
    )
    state, metrics = step(state, _batch(b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_vit_tensor_parallel_matches_unsharded():
    """ViT with Megatron metadata: a data x tensor mesh produces the same
    loss as an unsharded run, and the qkv kernel is actually tensor-sharded."""
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.models import vit_b16
    from tpudist.train import create_train_state, make_train_step, state_shardings_of

    batch = to_tensor(synthetic_cifar(n=8, num_classes=10))
    losses = {}
    for name, cfg, ndev in (
        ("single", mesh_lib.MeshConfig(data=1), 1),
        ("tp", mesh_lib.MeshConfig(data=2, tensor=4), 8),
    ):
        mesh = mesh_lib.create_mesh(cfg, devices=jax.devices()[:ndev])
        model = vit_b16(
            num_classes=10, patch_size=8, hidden_dim=32, depth=2,
            num_heads=4, mlp_dim=64,
        )
        tx = optax.adam(1e-3)
        state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
        if name == "tp":
            spec = state.params["block_0"]["qkv"]["kernel"].sharding.spec
            assert mesh_lib.TENSOR_AXIS in spec, spec
        step = make_train_step(
            model, tx, mesh, state_sharding=state_shardings_of(state)
        )
        state, metrics = step(state, batch)
        losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["single"], losses["tp"], rtol=2e-5)


def test_gpt2_size_variants():
    from tpudist.models import gpt2_medium, gpt2_large

    m = gpt2_medium()
    assert (m.hidden_dim, m.depth, m.num_heads) == (1024, 24, 16)
    l = gpt2_large()
    assert (l.hidden_dim, l.depth, l.num_heads) == (1280, 36, 20)
    # overrides still win
    assert gpt2_medium(depth=2).depth == 2
