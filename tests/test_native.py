"""Tests for the native (C++) core: batch gather and the TCP store.

The reference delegates batch assembly and rendezvous to upstream C++
(DataLoader worker pool, c10d TCPStore — SURVEY.md §2.3/§2.7); these tests
pin tpudist's own native equivalents against the pure-Python semantics.
"""

import multiprocessing

import numpy as np
import pytest

from tpudist import csrc


pytestmark = pytest.mark.skipif(
    csrc.lib() is None, reason="native library unavailable (no C++ toolchain)"
)


# ---------------------------------------------------------------- batcher
def test_gather_matches_numpy_all_dtypes():
    from tpudist.data.native import NativeBatcher

    b = NativeBatcher(2)
    rng = np.random.Generator(np.random.PCG64(0))
    idx = rng.integers(0, 500, 97)
    for dtype, shape in [
        (np.uint8, (500, 32, 32, 3)),
        (np.float32, (500, 17)),
        (np.int32, (500,)),
        (np.int64, (500, 3, 5)),
    ]:
        src = rng.integers(0, 100, shape).astype(dtype)
        np.testing.assert_array_equal(b.gather(src, idx), src[idx])
    b.close()


def test_fused_gather_matches_to_tensor():
    from tpudist.data.cifar import to_tensor
    from tpudist.data.native import NativeBatcher

    b = NativeBatcher(2)
    rng = np.random.Generator(np.random.PCG64(1))
    src = rng.integers(0, 256, (300, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, 300, 64)
    fused = b.gather_u8_to_f32(src, idx, *to_tensor.native_spec["image"])
    ref = to_tensor({"image": src[idx]})["image"]
    assert fused.dtype == np.float32
    np.testing.assert_allclose(fused, ref, rtol=0, atol=1e-7)
    b.close()


def test_fused_channel_gather_matches_normalize():
    """Per-channel affine gather (ABI 2) == the to_tensor_normalize math."""
    from tpudist.data.native import NativeBatcher
    from tpudist.data.transforms import CIFAR10_MEAN, CIFAR10_STD, to_tensor_normalize

    b = NativeBatcher(2)
    rng = np.random.Generator(np.random.PCG64(4))
    src = rng.integers(0, 256, (200, 16, 16, 3)).astype(np.uint8)
    idx = rng.integers(0, 200, 48)
    t = to_tensor_normalize(CIFAR10_MEAN, CIFAR10_STD)
    scale, shift = t.native_spec["image"]
    fused = b.gather_u8_to_f32_channels(src, idx, scale, shift)
    ref = t({"image": src[idx]})["image"]
    np.testing.assert_allclose(fused, ref, rtol=0, atol=1e-6)
    # shape validation: wrong channel count is rejected, not mis-broadcast
    with pytest.raises(ValueError, match="innermost"):
        b.gather_u8_to_f32_channels(src, idx, scale[:2], shift[:2])
    b.close()


def test_dataloader_native_normalized_equals_python():
    """The fused normalize pipeline rides the C++ path and stays identical
    to the numpy path batch-for-batch."""
    from tpudist.data.cifar import synthetic_cifar
    from tpudist.data.loader import DataLoader
    from tpudist.data.sampler import DistributedSampler
    from tpudist.data.transforms import standard_cifar_eval

    data = synthetic_cifar(n=200, num_classes=10)
    mk = lambda native: DataLoader(
        data, 32,
        sampler=DistributedSampler(200, num_replicas=2, rank=0, seed=5),
        transform=standard_cifar_eval("cifar10"), native=native,
    )
    for b_native, b_py in zip(mk(True), mk(False)):
        for k in b_py:
            np.testing.assert_allclose(b_native[k], b_py[k], atol=1e-6)


def test_gather_large_parallel_path():
    # large enough to split across threads (>1 MiB of rows)
    from tpudist.data.native import NativeBatcher

    b = NativeBatcher(4)
    rng = np.random.Generator(np.random.PCG64(2))
    src = rng.integers(0, 256, (2048, 3072)).astype(np.uint8)
    idx = rng.permutation(2048)
    np.testing.assert_array_equal(b.gather(src, idx), src[idx])
    out = b.gather_u8_to_f32(src, idx, 2.0, -1.0)
    np.testing.assert_allclose(out, src[idx].astype(np.float32) * 2.0 - 1.0)
    b.close()


def test_dataloader_native_equals_python():
    """The C++ fast path must be batch-for-batch identical to the numpy
    path (same sampler order, same values)."""
    from tpudist.data.cifar import synthetic_cifar, to_tensor
    from tpudist.data.loader import DataLoader
    from tpudist.data.sampler import DistributedSampler

    data = synthetic_cifar(n=257, num_classes=10)
    mk = lambda native: DataLoader(
        data, 32,
        sampler=DistributedSampler(257, num_replicas=2, rank=1, seed=3),
        transform=to_tensor, native=native,
    )
    for b_native, b_py in zip(mk(True), mk(False)):
        assert b_native.keys() == b_py.keys()
        for k in b_py:
            np.testing.assert_allclose(b_native[k], b_py[k], atol=1e-7)


def test_dataloader_falls_back_on_opaque_transform():
    """A transform without native_spec must still be applied (Python path)."""
    from tpudist.data.cifar import synthetic_cifar
    from tpudist.data.loader import DataLoader

    data = synthetic_cifar(n=64, num_classes=10)
    flip = lambda b: {**b, "image": b["image"][:, :, ::-1]}
    batch = next(iter(DataLoader(data, 16, transform=flip, native=True)))
    assert batch["image"].dtype == np.uint8  # transform ran, no f32 conversion


# ---------------------------------------------------------------- TCP store
def test_store_set_get_add():
    from tpudist.store import TCPStore

    with TCPStore("127.0.0.1", 0, world_size=1, rank=0) as s:
        s.set("alpha", b"1")
        assert s.get("alpha") == b"1"
        s.set("alpha", "two")  # str convenience + overwrite
        assert s.get("alpha") == b"two"
        assert s.get("nope", wait=False) is None
        assert s.get("nope", timeout_ms=50) is None  # bounded wait
        assert s.add("n", 10) == 10
        assert s.add("n", -3) == 7
        assert s.get("n") == b"7"  # ADD/GET interop


def test_store_two_clients_wait():
    """A GET with a wait blocks until another client SETs the key."""
    import threading

    from tpudist.store import TCPStore

    with TCPStore("127.0.0.1", 0, world_size=1, rank=0) as server:
        other = TCPStore("127.0.0.1", server.port, world_size=1, rank=1,
                         is_server=False)
        got = {}

        def waiter():
            got["v"] = server.get("late-key", timeout_ms=5000)

        t = threading.Thread(target=waiter)
        t.start()
        other.set("late-key", b"worth-the-wait")
        t.join(timeout=10)
        assert got["v"] == b"worth-the-wait"
        other.close()


def _store_worker(rank, world, port, q):
    from tpudist.store import TCPStore

    store = TCPStore("127.0.0.1", port, world_size=world, rank=rank,
                     is_server=False, timeout_ms=20_000)
    n = store.add("hits", 1)
    store.barrier("all-in")
    # after the barrier every rank must observe the full count
    total = int(store.get("hits"))
    q.put((rank, n, total))
    store.close()


def test_store_multiprocess_barrier():
    """4 real processes rendezvous on the store — the env:// pattern
    (/root/reference/README.md:17-35) without any JAX involvement."""
    from tpudist.store import TCPStore

    world = 4
    server = TCPStore("127.0.0.1", 0, world_size=world, rank=0)
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_store_worker, args=(r, world, server.port, q))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    assert sorted(n for _, n, _ in results) == [1, 2, 3, 4]
    assert all(total == world for _, _, total in results)
    server.close()


def test_store_barrier_timeout():
    from tpudist.store import TCPStore

    with TCPStore("127.0.0.1", 0, world_size=2, rank=0) as s:
        with pytest.raises(TimeoutError):
            s.barrier("lonely", timeout_ms=100)


def test_gather_index_semantics():
    """Negative indices wrap (numpy semantics); out-of-range raises instead
    of reading out-of-bounds memory; non-contiguous sources are refused."""
    from tpudist.data.native import NativeBatcher

    b = NativeBatcher(1)
    src = np.arange(50, dtype=np.int64).reshape(10, 5)
    np.testing.assert_array_equal(b.gather(src, np.array([-1, -10, 3])),
                                  src[[-1, -10, 3]])
    with pytest.raises(IndexError):
        b.gather(src, np.array([10]))
    with pytest.raises(IndexError):
        b.gather(src, np.array([-11]))
    with pytest.raises(ValueError):
        b.gather(np.asfortranarray(np.zeros((4, 4))), np.array([0]))
    b.close()


def test_native_batch_falls_back_on_non_u8_image():
    """A spec'd key with the wrong dtype must fall back to the Python path
    (which applies the transform) — not silently skip the conversion."""
    from tpudist.data.cifar import to_tensor
    from tpudist.data.loader import DataLoader

    data = {
        "image": np.full((64, 8, 8, 3), 255.0, np.float32),  # not uint8
        "label": np.zeros(64, np.int32),
    }
    batch = next(iter(DataLoader(data, 16, transform=to_tensor, native=True)))
    np.testing.assert_allclose(batch["image"], 1.0)  # /255 was applied


def test_store_barrier_reusable():
    """The same barrier name must re-synchronize on every use, not become a
    no-op after the first generation's done-key persists."""
    from tpudist.store import TCPStore

    with TCPStore("127.0.0.1", 0, world_size=2, rank=0) as s:
        import threading

        peer = TCPStore("127.0.0.1", s.port, world_size=2, rank=1,
                        is_server=False)
        for _ in range(3):  # three generations of the same name
            t = threading.Thread(target=peer.barrier, args=("epoch",))
            t.start()
            s.barrier("epoch", timeout_ms=5000)
            t.join(timeout=10)
            assert not t.is_alive()
        # a lone arrival at generation 3 must block (not see stale done keys)
        with pytest.raises(TimeoutError):
            s.barrier("epoch", timeout_ms=100)
        peer.close()


def test_store_rejects_oversized_value():
    from tpudist.store import MAX_VALUE_BYTES, TCPStore

    with TCPStore("127.0.0.1", 0, world_size=1, rank=0) as s:
        with pytest.raises(ValueError):
            s.set("big", b"x" * (MAX_VALUE_BYTES + 1))
        s.set("ok", b"still works")  # connection not poisoned
        assert s.get("ok") == b"still works"


def test_store_broadcast():
    from tpudist.store import TCPStore

    with TCPStore("127.0.0.1", 0, world_size=1, rank=0) as s:
        assert s.broadcast("cfg", b"payload") == b"payload"   # publisher
        assert s.broadcast("cfg") == b"payload"               # subscriber
