"""Memmap LM token dataset tests — the config-5 (OpenWebText-scale) input
path: lazy window gather, .bin/.npy formats, shard semantics."""

import numpy as np
import pytest

from tpudist.data.lm import TokenWindowLoader, encode_bytes, load_token_stream


@pytest.fixture(scope="module")
def stream():
    rng = np.random.Generator(np.random.PCG64(0))
    return rng.integers(0, 50257, 10_000).astype(np.uint16)


def test_load_npy_and_bin_roundtrip(tmp_path, stream):
    npy = tmp_path / "t.npy"
    np.save(npy, stream)
    binf = tmp_path / "t.bin"
    stream.tofile(binf)
    a = load_token_stream(npy)
    b = load_token_stream(binf, dtype=np.uint16)
    np.testing.assert_array_equal(np.asarray(a), stream)
    np.testing.assert_array_equal(np.asarray(b), stream)
    # memmaps, not copies
    assert isinstance(b, np.memmap)


def test_bad_suffix_and_shape(tmp_path, stream):
    with pytest.raises(ValueError):
        load_token_stream(tmp_path / "t.tokens")
    bad = tmp_path / "twod.npy"
    np.save(bad, stream.reshape(100, 100))
    with pytest.raises(ValueError):
        load_token_stream(bad)


def test_windows_cover_stream_without_overlap(stream):
    loader = TokenWindowLoader(stream, 4, 128, shuffle=False)
    assert loader.num_windows == len(stream) // 128  # 78
    batches = list(loader)
    assert len(batches) == len(loader) == 78 // 4
    flat = np.concatenate([b["tokens"].ravel() for b in batches])
    np.testing.assert_array_equal(flat, stream[: len(flat)].astype(np.int32))


def test_targets_in_window_adds_boundary_token(stream):
    loader = TokenWindowLoader(
        stream, 2, 64, targets_in_window=True, shuffle=False
    )
    b = next(iter(loader))
    assert b["tokens"].shape == (2, 65)
    # consecutive windows share the boundary token: last target of window k
    # is the first input of window k+1
    assert b["tokens"][0, -1] == b["tokens"][1, 0]


def test_memmap_gather_reads_lazily(tmp_path):
    big = tmp_path / "big.bin"
    n = 2_000_000
    (np.arange(n, dtype=np.int64) % 65536).astype(np.uint16).tofile(big)
    loader = TokenWindowLoader(big, 2, 1024, shuffle=False)
    b = loader.gather(np.array([0, 1000]))
    assert b["tokens"].shape == (2, 1024)
    np.testing.assert_array_equal(b["tokens"][0], np.arange(1024))
    np.testing.assert_array_equal(
        b["tokens"][1], np.arange(1000 * 1024, 1000 * 1024 + 1024) % 65536
    )


def test_sharded_windows_disjoint(stream):
    loaders = [
        TokenWindowLoader(stream, 4, 100, num_replicas=2, rank=r, seed=1)
        for r in range(2)
    ]
    s0 = set(loaders[0].sampler.epoch_indices().tolist())
    s1 = set(loaders[1].sampler.epoch_indices().tolist())
    assert not (s0 & s1)
    assert s0 | s1 == set(range(loaders[0].num_windows))


def test_iter_from_resume(stream):
    loader = TokenWindowLoader(stream, 8, 64, seed=5)
    full = list(loader)
    tail = list(loader.iter_from(3))
    for a, b in zip(full[3:], tail):
        np.testing.assert_array_equal(a["tokens"], b["tokens"])


def test_vocab_guard_catches_out_of_range_tokens():
    """Out-of-range ids raise at gather time instead of letting XLA's
    embedding lookup clamp them and train silently on wrong vectors."""
    bad = np.array([0, 1, 2, 999, 4, 5, 6, 7] * 32, np.int32)
    loader = TokenWindowLoader(bad, 2, 8, vocab_size=256, shuffle=False)
    with pytest.raises(ValueError, match="token id 999"):
        list(loader)
    ok = TokenWindowLoader(bad % 256, 2, 8, vocab_size=256, shuffle=False)
    assert len(list(ok)) == len(ok)


def test_too_short_stream_raises():
    with pytest.raises(ValueError):
        TokenWindowLoader(np.arange(10, dtype=np.int32), 1, 64)


def test_encode_bytes():
    t = encode_bytes("hi\x00")
    np.testing.assert_array_equal(t, [104, 105, 0])
    assert t.dtype == np.int32


def test_train_gpt2_example_runs_with_bin_tokens(tmp_path):
    """End-to-end: the GPT-2 example trains from a raw .bin memmap."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))
    import train_gpt2

    rng = np.random.Generator(np.random.PCG64(3))
    binf = tmp_path / "corpus.bin"
    rng.integers(0, 256, 40_000).astype(np.uint16).tofile(binf)
    state, losses = train_gpt2.main([
        "--tokens", str(binf), "--vocab_size", "256", "--seq_len", "64",
        "--batch_size", "1", "--hidden_dim", "32", "--depth", "1",
        "--num_heads", "2", "--epochs", "1", "--no_profiler",
        "--log_dir", str(tmp_path), "--warmup_steps", "2",
    ])
    assert len(losses) > 0 and np.isfinite(losses).all()


def test_train_gpt2_scan_compile_fallback(tmp_path, monkeypatch, capsys):
    """A remote-compile infra failure on the nn.scan'd step retries with the
    unrolled layout instead of crashing (the documented axon-tunnel limit,
    docs/LM_TRAINING.md §3.6); the injection hook simulates the 500."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))
    import train_gpt2

    monkeypatch.setenv("TPUDIST_TEST_FAIL_SCAN_COMPILE", "1")
    rng = np.random.Generator(np.random.PCG64(4))
    binf = tmp_path / "corpus.bin"
    rng.integers(0, 256, 40_000).astype(np.uint16).tofile(binf)
    state, losses = train_gpt2.main([
        "--tokens", str(binf), "--vocab_size", "256", "--seq_len", "64",
        "--batch_size", "1", "--hidden_dim", "32", "--depth", "2",
        "--num_heads", "2", "--epochs", "1", "--no_profiler",
        "--scan_layers", "--remat_layers",
        "--log_dir", str(tmp_path), "--JobID", "Fallback",
    ])
    assert len(losses) > 0 and np.isfinite(losses).all()
    # the unrolled rebuild has per-block params, not a stacked 'layers' tree
    assert "h_0" in state.params and "layers" not in state.params
    assert "retrying with the unrolled layer layout" in capsys.readouterr().err


def test_scan_fallback_refuses_cross_layout_resume(tmp_path, monkeypatch):
    """With scan-layout checkpoints on disk, the unrolled fallback would
    resume a stacked 'layers' tree into a per-block model — refuse loudly."""
    import sys
    from pathlib import Path

    import pytest

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))
    import train_gpt2

    rng = np.random.Generator(np.random.PCG64(5))
    binf = tmp_path / "corpus.bin"
    rng.integers(0, 256, 40_000).astype(np.uint16).tofile(binf)
    common = [
        "--tokens", str(binf), "--vocab_size", "256", "--seq_len", "64",
        "--batch_size", "1", "--hidden_dim", "32", "--depth", "2",
        "--num_heads", "2", "--epochs", "1", "--no_profiler",
        "--scan_layers", "--log_dir", str(tmp_path),
        "--checkpoint_dir", str(tmp_path / "ckpt"), "--JobID", "ScanCkpt",
    ]
    train_gpt2.main(common)  # writes a scan-layout checkpoint
    monkeypatch.setenv("TPUDIST_TEST_FAIL_SCAN_COMPILE", "1")
    with pytest.raises(RuntimeError, match="unstack_layers"):
        train_gpt2.main(common)
