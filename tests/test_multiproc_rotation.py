"""Multi-process RotatingDeviceCache equivalence.

The rotation's multi-process contract: the (seed, epoch) shard plan is
global, every process stages the SAME shard pixels, and per batch each
rank contributes its stride of the global within-shard order — so a
2-process world must compute the same loss sequence as the 1-process
world on the same data (the same global batch SET per step; row order
within the device array differs, which the global-batch mean is
invariant to). Mirrors tests/test_multiproc_fit.py's strategy for the
host loaders.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # subprocess world: cold-compiles its own jax programs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, os, sys

    if os.environ.get("TPUDIST_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import optax

    from tpudist import create_mesh, init_from_env
    from tpudist.data.cifar import synthetic_cifar
    from tpudist.data.device_cache import RotatingDeviceCache
    from tpudist.models import resnet18
    from tpudist.train import fit

    ctx = init_from_env()
    mesh = create_mesh()

    data = synthetic_cifar(n=64, num_classes=10)  # deterministic (seed 0)
    per_proc_batch = 16 // ctx.process_count
    rot = RotatingDeviceCache(
        data, per_proc_batch, shard_rows=32, mesh=mesh, seed=7,
    )
    model = resnet18(num_classes=10, small_inputs=True)
    state, losses = fit(
        model, optax.adam(1e-4), rot,
        epochs=2, mesh=mesh, profile=False, seed=0,
        batch_size=per_proc_batch, job_id="ROT",
        log_dir=os.environ["OUT_DIR"],
        input_transform=rot.input_transform(
            lambda x: x.astype(np.float32) / 255.0
        ),
    )
    out = {"rank": ctx.process_index, "world": ctx.process_count,
           "losses": losses, "final_step": int(state.step)}
    with open(os.path.join(
        os.environ["OUT_DIR"], f"rot_{ctx.process_index}.json"
    ), "w") as f:
        json.dump(out, f)
""")


def _launch(tmp_path, nproc, devices_per_proc, out_dir, *, port_off=0):
    script = tmp_path / "child_rot.py"
    script.write_text(_CHILD)
    os.makedirs(out_dir, exist_ok=True)
    env = dict(os.environ)
    env["OUT_DIR"] = str(out_dir)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = 29450 + (os.getpid() + port_off) % 300
    r = subprocess.run(
        [
            sys.executable, "-m", "tpudist.launch",
            f"--nproc_per_node={nproc}",
            f"--emulate-devices={devices_per_proc}",
            f"--master_port={port}", str(script),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    return r


def test_two_process_rotation_matches_single_process(tmp_path):
    one = tmp_path / "one"
    two = tmp_path / "two"
    _launch(tmp_path, 1, 8, one, port_off=0)
    _launch(tmp_path, 2, 4, two, port_off=1)

    la = json.loads((one / "rot_0.json").read_text())["losses"]
    lb0 = json.loads((two / "rot_0.json").read_text())["losses"]
    lb1 = json.loads((two / "rot_1.json").read_text())["losses"]

    # (64 rows / 32 shard_rows) shards x (32 / 16 global batch) = 4
    # steps/epoch x 2 epochs
    assert len(la) == len(lb0) == len(lb1) == 8
    # both ranks of the 2-process world agree bitwise
    np.testing.assert_array_equal(lb0, lb1)
    # the 2-process world computes the 1-process losses: same global batch
    # SET per step (rank strides partition the same shard window), same
    # seed init — step-1 agreement is the same-function certificate,
    # trajectory agreement is numerical (fp noise amplification)
    assert abs(la[0] - lb0[0]) < 2e-5, (la[0], lb0[0])
    np.testing.assert_allclose(la, lb0, rtol=0.05, atol=1e-3)
