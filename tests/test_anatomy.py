"""Program anatomy (docs/OBSERVABILITY.md §9): XLA cost/memory
introspection normalized into telemetry rows, the FLOPs-honesty
cross-check of every model family's analytic counter against XLA's own
count, the in-run step-time regression sentinel, and the satellite
surfaces — the three-column HBM budget, the per-interval live peak on the
HBM row, and the serve engine's program rows."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.telemetry import Telemetry, TelemetryConfig, TelemetrySink
from tpudist.telemetry import anatomy
from tpudist.train import create_train_state, lm_loss, make_train_step


def _rows(path):
    return [json.loads(l) for l in path.read_text().splitlines()]


# -- cost/memory normalization (no device work) ------------------------------


class _FakeCompiled:
    def __init__(self, cost=None, mem=None, raises=False):
        self._cost, self._mem, self._raises = cost, mem, raises

    def cost_analysis(self):
        if self._raises:
            raise RuntimeError("backend says no")
        return self._cost

    def memory_analysis(self):
        if self._raises:
            raise RuntimeError("backend says no")
        return self._mem


class _FakeMemStats:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 200
    temp_size_in_bytes = 5000
    alias_size_in_bytes = 150
    generated_code_size_in_bytes = 50


def test_program_costs_accepts_dict_and_list_of_dict():
    cost = {"flops": 10.0, "bytes accessed": 40.0, "transcendentals": 2.0}
    want = {"flops": 10.0, "bytes_accessed": 40.0, "transcendentals": 2.0}
    assert anatomy.program_costs(_FakeCompiled(cost=cost)) == want
    assert anatomy.program_costs(_FakeCompiled(cost=[cost])) == want


def test_program_costs_fail_soft():
    # no flops key, raising backend, empty list: all None, never a throw
    assert anatomy.program_costs(
        _FakeCompiled(cost={"bytes accessed": 1.0})) is None
    assert anatomy.program_costs(_FakeCompiled(raises=True)) is None
    assert anatomy.program_costs(_FakeCompiled(cost=[])) is None


def test_program_memory_peak_is_resident_sum_minus_alias():
    out = anatomy.program_memory(_FakeCompiled(mem=_FakeMemStats()))
    assert out["argument_bytes"] == 1000 and out["temp_bytes"] == 5000
    # args + out + temp + code - alias
    assert out["peak_bytes"] == 1000 + 200 + 5000 + 50 - 150
    assert anatomy.program_memory(_FakeCompiled(mem=None)) is None
    assert anatomy.program_memory(_FakeCompiled(raises=True)) is None


def test_analyze_program_scales_scan_counted_flops_by_grad_accum():
    cost = {"flops": 100.0, "bytes accessed": 400.0}
    info = anatomy.analyze_program(
        "p", compiled=_FakeCompiled(cost=cost, mem=_FakeMemStats()),
        grad_accum=4,
    )
    # HLO counts the scan body ONCE; the row carries both the raw and the
    # per-step-scaled numbers so it stays auditable
    assert info["flops"] == 100.0 and info["flops_scaled"] == 400.0
    assert info["bytes_accessed"] == 1600.0
    assert info["aot"] is True and info["peak_bytes"] == 6100
    # lowered-only fallback: costs, no memory, aot False
    low = anatomy.analyze_program("p", lowered=_FakeCompiled(cost=cost))
    assert low["aot"] is False and "peak_bytes" not in low
    assert anatomy.analyze_program("p") is None


def test_flops_drift_sign_and_fail_soft():
    assert anatomy.flops_drift(100.0, 110.0) == pytest.approx(0.10)
    assert anatomy.flops_drift(100.0, 90.0) == pytest.approx(-0.10)
    assert anatomy.flops_drift(100.0, None) is None
    assert anatomy.flops_drift(0.0, 90.0) is None


# -- the regression sentinel -------------------------------------------------


def test_detector_fires_once_on_sustained_slowdown():
    det = anatomy.StepTimeRegressionDetector(
        warmup=2, baseline_steps=4, window=4, threshold=0.25, patience=3)
    verdicts = []
    for dt in [9.0, 9.0] + [0.10] * 4 + [0.20] * 10:
        verdicts.append(det.observe(dt))
    fired = [v for v in verdicts if v is not None]
    assert len(fired) == 1  # one-shot
    v = fired[0]
    assert det.baseline == pytest.approx(0.10)
    assert v["rolling_median_s"] == pytest.approx(0.20)
    assert v["slowdown_pct"] == pytest.approx(100.0)
    assert v["window"] == 4 and v["threshold"] == 0.25
    # the 9.0s warmup intervals (compile) never polluted the baseline
    assert det.observe(0.5) is None  # fired stays latched


def test_detector_ignores_single_spikes():
    det = anatomy.StepTimeRegressionDetector(
        warmup=0, baseline_steps=4, window=5, threshold=0.25, patience=3)
    intervals = [0.10] * 4 + [0.10, 0.10, 2.0, 0.10, 0.10] * 6
    assert all(det.observe(dt) is None for dt in intervals)
    assert not det.fired  # a GC pause is not a regression


def test_detector_requires_consecutive_exceedances():
    det = anatomy.StepTimeRegressionDetector(
        warmup=0, baseline_steps=2, window=2, threshold=0.6, patience=3)
    # two slow medians, then recovery, resets the patience counter
    seq = [0.1, 0.1, 0.2, 0.2, 0.1, 0.1, 0.2, 0.2, 0.1, 0.1]
    assert all(det.observe(dt) is None for dt in seq)


# -- FLOPs honesty: XLA's count vs every family's analytic counter -----------
#
# Lowering only (no compile): `Lowered.cost_analysis()` is enough for
# FLOPs. Tolerances are pinned from measured drift on these geometries
# (gpt2 -4.2%, llama -2.8%, t5 -3.7%, bert -9.3%, vit -11.6%, moe at
# capacity_factor=1.0 -10.8%/-8.1%): XLA counts what the counters
# deliberately exclude (softmax/norm FLOPs, the classifier head), which
# shrinks toward zero at production geometry (the 124M check below and
# the bench anatomy leg pin 5%). A STALE counter — a model edit that
# doubles the math — blows any of these bounds.


def _family(name):
    rng = np.random.Generator(np.random.PCG64(0))
    toks = rng.integers(0, 250, (8, 32)).astype(np.int32)
    z = jnp.zeros((1, 32), jnp.int32)
    lm = dict(loss_fn=lm_loss, input_key="tokens", label_key="tokens")
    if name == "gpt2":
        from tpudist.models.gpt2 import GPT2

        model = GPT2(vocab_size=256, max_seq_len=32, hidden_dim=64,
                     depth=2, num_heads=4)
        return model, {"tokens": toks}, z, lm, 0.10
    if name == "llama":
        from tpudist.models.llama import Llama

        model = Llama(vocab_size=256, max_seq_len=32, hidden_dim=64,
                      depth=2, num_heads=4)
        return model, {"tokens": toks}, z, lm, 0.10
    if name == "gpt2_moe":
        from tpudist.models.gpt2 import GPT2

        # capacity_factor=1.0: the dispatch computes exactly the active
        # FLOPs the counter models (higher factors add capacity padding
        # the counter rightly excludes — that's dispatch slack, not work)
        model = GPT2(vocab_size=256, max_seq_len=32, hidden_dim=64,
                     depth=2, num_heads=4, num_experts=4, moe_every=1,
                     capacity_factor=1.0)
        return model, {"tokens": toks}, z, lm, 0.18
    if name == "llama_moe":
        from tpudist.models.llama import Llama

        model = Llama(vocab_size=256, max_seq_len=32, hidden_dim=64,
                      depth=2, num_heads=4, num_experts=4, moe_every=1,
                      capacity_factor=1.0)
        return model, {"tokens": toks}, z, lm, 0.15
    if name == "bert":
        from tpudist.models.bert import Bert, mlm_forward, mlm_transform

        model = Bert(vocab_size=97, max_seq_len=32, hidden_dim=64,
                     depth=2, num_heads=4)
        batch = mlm_transform(vocab_size=97, mask_id=3, seed=1)(
            {"tokens": rng.integers(5, 69, (8, 16)).astype(np.int32)})
        kw = dict(input_key="tokens", label_key="targets",
                  forward_loss=mlm_forward(model))
        return model, batch, jnp.zeros((1, 16), jnp.int32), kw, 0.15
    if name == "t5":
        from tpudist.models.t5 import (
            T5, seq2seq_forward, span_corrupt_transform,
        )

        model = T5(vocab_size=64, hidden_dim=64, ffn_dim=128, enc_depth=2,
                   dec_depth=2, num_heads=4)
        batch = span_corrupt_transform(64, seed=5)(
            {"tokens": np.tile((np.arange(32) % 37 + 1).astype(np.int32),
                               (8, 1))})
        init = (jnp.asarray(batch["enc_tokens"][:1]),
                jnp.asarray(batch["dec_tokens"][:1]))
        kw = dict(input_key="enc_tokens", label_key="targets",
                  forward_loss=seq2seq_forward(model))
        return model, batch, init, kw, 0.10
    if name == "vit":
        from tpudist.data.cifar import synthetic_cifar, to_tensor
        from tpudist.models.vit import ViT

        # mlp_dim must be 4*hidden for the model to advertise the counter
        model = ViT(num_classes=10, patch_size=8, hidden_dim=64, depth=2,
                    num_heads=4, mlp_dim=256)
        batch = to_tensor(synthetic_cifar(n=8, num_classes=10))
        return (model, batch, jnp.zeros((1, 32, 32, 3)),
                dict(input_key="image"), 0.18)
    raise AssertionError(name)


@pytest.mark.parametrize(
    "family",
    ["gpt2", "llama", "gpt2_moe", "llama_moe", "bert", "t5", "vit"],
)
def test_flops_honesty_per_family(family):
    model, batch, init_x, step_kw, tol = _family(family)
    mesh = mesh_lib.create_mesh()
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, init_x, tx, mesh)
    step = make_train_step(model, tx, mesh, **step_kw)
    staged = step.stage(batch)
    info = anatomy.analyze_train_step(
        step, state, staged, model=model,
        input_key=step_kw.get("input_key", "image"), grad_accum=1,
    )
    assert info is not None and info["flops"] > 0
    assert info["aot"] is False  # jit path: lowered, never compiled
    assert info["bytes_accessed"] > 0
    assert info["analytic_flops"] > 0
    assert abs(info["flops_drift"]) < tol, (
        f"{family} analytic counter drifted {info['flops_drift']:+.1%} "
        f"from XLA's count — a stale counter in telemetry/flops.py")


@pytest.mark.slow
def test_flops_honesty_gpt2_124m_within_5pct():
    """The acceptance bound: at production geometry (GPT-2 124M) the
    analytic counter and XLA's count agree within 5% — the tiny-geometry
    drift above is the excluded softmax/norm terms, which vanish here."""
    from tpudist.models.gpt2 import GPT2

    mesh = mesh_lib.create_mesh()
    model = GPT2()  # 124M defaults: vocab 50257, hidden 768, depth 12
    tx = optax.adam(1e-3)
    state = create_train_state(
        model, 0, jnp.zeros((1, 64), jnp.int32), tx, mesh)
    step = make_train_step(model, tx, mesh, loss_fn=lm_loss,
                           input_key="tokens", label_key="tokens")
    rng = np.random.Generator(np.random.PCG64(0))
    staged = step.stage(
        {"tokens": rng.integers(0, 50257, (8, 1024)).astype(np.int32)})
    info = anatomy.analyze_train_step(step, state, staged, model=model,
                                      grad_accum=1)
    assert info is not None
    assert abs(info["flops_drift"]) < 0.05, info["flops_drift"]


def test_grad_accum_scaling_matches_flat_batch_count():
    """flops_scaled at grad_accum=G equals (within float noise) the flat
    batch's count: the scan body really is counted once."""
    from tpudist.models.gpt2 import GPT2

    mesh = mesh_lib.create_mesh()
    model = GPT2(vocab_size=256, max_seq_len=32, hidden_dim=64, depth=1,
                 num_heads=4)
    tx = optax.adam(1e-3)
    state = create_train_state(
        model, 0, jnp.zeros((1, 32), jnp.int32), tx, mesh)
    rng = np.random.Generator(np.random.PCG64(0))
    batch = {"tokens": rng.integers(0, 250, (32, 32)).astype(np.int32)}
    infos = {}
    for g in (1, 2):
        step = make_train_step(model, tx, mesh, loss_fn=lm_loss,
                               input_key="tokens", label_key="tokens",
                               grad_accum=g)
        infos[g] = anatomy.analyze_train_step(
            step, state, step.stage(batch), model=model, grad_accum=g)
    assert infos[2]["flops"] == pytest.approx(infos[1]["flops"] / 2,
                                              rel=0.02)
    assert infos[2]["flops_scaled"] == pytest.approx(
        infos[1]["flops_scaled"], rel=0.02)


# -- telemetry wiring --------------------------------------------------------


def test_set_anatomy_writes_row_and_stale_warning(tmp_path):
    sink = TelemetrySink(tmp_path / "t.jsonl")
    tel = Telemetry(TelemetryConfig(anatomy=True, anatomy_tolerance=0.05),
                    sink, rank=0, world_size=1, log_every=1, n_chips=1)
    tel.set_anatomy({"program": "train_step", "flops": 1e9,
                     "flops_scaled": 1e9, "grad_accum": 1, "aot": False,
                     "analytic_flops": 1.2e9, "flops_drift": 0.2,
                     "flops_counter": "gpt2"})
    tel.set_anatomy(None)  # unavailable: writes nothing, never throws
    sink.close()
    rows = _rows(tmp_path / "t.jsonl")
    kinds = [r["kind"] for r in rows]
    assert kinds.count("anatomy") == 1
    warn = next(r for r in rows if r["kind"] == "warning")
    assert warn["tag"] == "stale_flops_counter"
    assert warn["flops_counter"] == "gpt2"
    assert warn["drift"] == 0.2 and warn["tolerance"] == 0.05


def test_set_anatomy_within_tolerance_no_warning(tmp_path):
    sink = TelemetrySink(tmp_path / "t.jsonl")
    tel = Telemetry(TelemetryConfig(anatomy=True), sink, rank=0,
                    world_size=1, log_every=1, n_chips=1)
    tel.set_anatomy({"program": "train_step", "flops": 1e9,
                     "flops_scaled": 1e9, "grad_accum": 1, "aot": False,
                     "analytic_flops": 0.96e9, "flops_drift": -0.04,
                     "flops_counter": "gpt2"})
    sink.close()
    kinds = {r["kind"] for r in _rows(tmp_path / "t.jsonl")}
    assert "anatomy" in kinds and "warning" not in kinds


def test_on_step_emits_one_shot_perf_regression_row(tmp_path):
    cfg = TelemetryConfig(regression_detect=True, regression_window=4,
                          regression_threshold=0.25)
    sink = TelemetrySink(tmp_path / "t.jsonl")
    tel = Telemetry(cfg, sink, rank=0, world_size=1, log_every=100,
                    n_chips=1)
    g = 0
    # warmup 2 + baseline 8 at 10ms, then a sustained 4x slowdown
    for dt in [0.01] * 10 + [0.04] * 12:
        g += 1
        tel.on_step(g, {"loss": 1.0}, epoch=0, interval_s=dt,
                    data_wait_s=0.0)
    tel.shutdown()
    rows = [r for r in _rows(tmp_path / "t.jsonl")
            if r["kind"] == "perf_regression"]
    assert len(rows) == 1  # one-shot, like the other sentinel rows
    r = rows[0]
    assert r["baseline_s"] == pytest.approx(0.01)
    assert r["slowdown_pct"] == pytest.approx(300.0, abs=5.0)
    assert r["window"] == 4 and r["step"] > 10


def test_regression_detector_off_by_default(tmp_path):
    sink = TelemetrySink(tmp_path / "t.jsonl")
    tel = Telemetry(TelemetryConfig(), sink, rank=0, world_size=1,
                    log_every=100, n_chips=1)
    assert tel.regression is None
    for g in range(1, 25):
        tel.on_step(g, {"loss": 1.0}, epoch=0,
                    interval_s=0.01 if g < 12 else 0.08, data_wait_s=0.0)
    tel.shutdown()
    kinds = {r["kind"] for r in _rows(tmp_path / "t.jsonl")}
    assert "perf_regression" not in kinds and "anatomy" not in kinds


# -- fit() integration -------------------------------------------------------


def _fit(tmp_path, cfg, *, grad_accum=1, steps_hint=None):
    from tpudist.data.loader import DataLoader
    from tpudist.train import fit

    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 254, (64, 16)).astype(np.int32)
    from tpudist.models.gpt2 import GPT2

    model = GPT2(vocab_size=256, max_seq_len=16, hidden_dim=32, depth=1,
                 num_heads=2)
    fit(model, optax.adam(1e-3), DataLoader({"tokens": tokens}, 16),
        epochs=2, job_id="ANAT", batch_size=16, loss_fn=lm_loss,
        input_key="tokens", label_key="tokens", log_dir=str(tmp_path),
        telemetry=cfg, profile=False, grad_accum=grad_accum)
    return _rows(tmp_path / "ANAT_telemetry_0.jsonl")


def test_fit_emits_anatomy_row_with_cross_check(tmp_path):
    rows = _fit(
        tmp_path,
        TelemetryConfig(anatomy=True, run_report=False), grad_accum=2)
    anat = [r for r in rows if r["kind"] == "anatomy"]
    assert len(anat) == 1  # one-shot, at bring-up
    r = anat[0]
    assert r["program"] == "train_step" and r["grad_accum"] == 2
    # the scan body is counted once: scaled = raw * grad_accum
    assert r["flops_scaled"] == pytest.approx(r["flops"] * 2)
    assert r["analytic_flops"] > 0 and "flops_drift" in r
    assert r["flops_counter"] == "gpt2"
    assert r["activation_bytes_est"] > 0


def test_fit_anatomy_stale_counter_warning_at_tight_tolerance(tmp_path):
    # tolerance far under the tiny-geometry drift: the warning MUST fire
    rows = _fit(tmp_path, TelemetryConfig(anatomy=True,
                                          anatomy_tolerance=0.001,
                                          run_report=False))
    warns = [r for r in rows if r["kind"] == "warning"
             and r.get("tag") == "stale_flops_counter"]
    assert len(warns) == 1
    assert warns[0]["program"] == "train_step"


def test_fit_default_stream_has_no_anatomy_rows(tmp_path):
    # byte-identity contract: no knob set, no new row kinds in the stream
    rows = _fit(tmp_path, TelemetryConfig(run_report=False))
    kinds = {r["kind"] for r in rows}
    assert "anatomy" not in kinds and "perf_regression" not in kinds
    assert not any(r.get("tag") == "stale_flops_counter" for r in rows
                   if r["kind"] == "warning")


# -- serve engine program anatomy --------------------------------------------


def test_serve_engine_writes_program_anatomy_rows(tmp_path):
    from tpudist.models.gpt2 import GPT2
    from tpudist.serve import ServeEngine

    model = GPT2(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                 num_heads=4)
    params = model.init(
        jax.random.key(1), np.zeros((1, 8), np.int32), train=False
    )["params"]
    sink = TelemetrySink(tmp_path / "s.jsonl")
    eng = ServeEngine(model, params, max_slots=2, seed=0, sink=sink,
                      anatomy=True)
    rng = np.random.Generator(np.random.PCG64(3))
    eng.submit(rng.integers(0, 64, (6,)).astype(np.int32), 4)
    eng.run()
    eng.close()
    sink.close()
    rows = [r for r in _rows(tmp_path / "s.jsonl")
            if r["kind"] == "anatomy"]
    programs = {r["program"] for r in rows}
    assert "serve_decode" in programs and "serve_prefill_body" in programs
    for r in rows:
        assert r["flops"] > 0 and r["flops_scaled"] == r["flops"]
    dec = next(r for r in rows if r["program"] == "serve_decode")
    assert dec["slots"] == 2 and dec["paged"] is False
    pre = next(r for r in rows if r["program"] == "serve_prefill_body")
    assert pre["chunk"] > 0
    # the rows are also held on the engine for programmatic access
    assert {r["program"] for r in eng.anatomy_info} == programs


def test_serve_engine_anatomy_requires_sink():
    from tpudist.models.gpt2 import GPT2
    from tpudist.serve import ServeEngine

    model = GPT2(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                 num_heads=4)
    params = model.init(
        jax.random.key(1), np.zeros((1, 8), np.int32), train=False
    )["params"]
    with pytest.raises(ValueError, match="sink"):
        ServeEngine(model, params, max_slots=2, seed=0, anatomy=True)


# -- the three-column HBM budget + live-peak satellites ----------------------


def test_budget_columns_fail_soft_on_cpu():
    from tpudist import memory

    cols = memory.budget_columns({"per_chip_total_bytes": 123})
    assert cols["estimate_bytes"] == 123
    # CPU backend: no allocator stats, no compiled program given
    assert cols["xla_static_bytes"] is None
    assert cols["live_peak_bytes"] is None
    assert memory.budget_columns()["estimate_bytes"] is None


def test_xla_memory_stats_and_budget_column_from_compiled():
    from tpudist import memory

    compiled = jax.jit(lambda x: (x * x).sum()).lower(
        jnp.zeros((64, 64), jnp.float32)).compile()
    xla = memory.xla_memory_stats(compiled)
    assert xla is not None and xla["peak_bytes"] > 0
    assert xla["argument_bytes"] >= 64 * 64 * 4
    cols = memory.budget_columns({"per_chip_total_bytes": 7},
                                 compiled=compiled)
    assert cols["xla_static_bytes"] == xla["peak_bytes"]


def _budget_report():
    gb = 1024**3
    return {
        "params_bytes": gb, "opt_state_bytes_per_chip": 2 * gb,
        "opt_state_bytes_global": 2 * gb, "grad_bytes": gb,
        "activation_bytes_est": gb, "remat_policy": "none",
        "workspace_bytes_est": gb // 2, "per_chip_total_bytes": 5 * gb,
        "hbm_budget_bytes": 16 * gb, "fits": True, "bytes_per_param": 16,
        "world_size": 1,
    }


def test_format_budget_appends_measured_columns_fail_soft():
    from tpudist.memory import format_budget

    base = format_budget(_budget_report())
    # None sources (what fail-soft returns) keep the line byte-identical
    assert format_budget(_budget_report(), xla_static_bytes=None,
                         live_peak_bytes=None) == base
    both = format_budget(_budget_report(),
                         xla_static_bytes=6 * 1024**3,
                         live_peak_bytes=int(5.5 * 1024**3))
    assert both.startswith(base)
    assert "| xla-static 6.00 GB" in both
    assert "| live-peak 5.50 GB" in both


def test_log_memory_appends_interval_peak_after_existing_fields(tmp_path):
    from tpudist.metrics import MetricsLogger

    sink = TelemetrySink(tmp_path / "m.jsonl")
    logger = MetricsLogger("MEM", 16, 0, 1, log_dir=tmp_path)
    logger.attach_sink(sink)
    logger.log_memory({"bytes_in_use": 10, "peak_bytes_in_use": 50})
    logger.log_memory({"bytes_in_use": 10, "peak_bytes_in_use": 50},
                      peak_bytes_in_use=99)
    logger.finish()
    sink.close()
    hbm = [l for l in logger.file_name.read_text().splitlines()
           if l.startswith("HBM\t")]
    assert len(hbm) == 2
    # no kwarg: the raw allocator fields, byte-identical to the old row
    assert json.loads(hbm[0].split("\t", 1)[1])["peak_bytes_in_use"] == 50
    # kwarg: the per-interval peak REPLACES the lifetime high-water mark
    assert json.loads(hbm[1].split("\t", 1)[1])["peak_bytes_in_use"] == 99
    mem_rows = [r for r in _rows(tmp_path / "m.jsonl")
                if r["kind"] == "memory"]
    assert [r["peak_bytes_in_use"] for r in mem_rows] == [50, 99]
    # appended AFTER the existing fields in the JSONL row
    keys = list(mem_rows[1])
    assert keys.index("peak_bytes_in_use") > keys.index("bytes_in_use")
