"""HF checkpoint interop — and the external numerics oracle: tiny
randomly-initialized transformers models, weights converted with
tpudist.interop, logits compared against the torch implementations. This
validates attention scaling, GELU flavor, LayerNorm/RMSNorm placement,
RoPE convention, and GQA head layout against an independent codebase."""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from tpudist.interop import (  # noqa: E402
    gpt2_params_from_hf,
    gpt2_params_to_hf,
    llama_params_from_hf,
    llama_params_to_hf,
)
from tpudist.models.gpt2 import GPT2  # noqa: E402
from tpudist.models.llama import Llama  # noqa: E402


def _tokens(b=2, s=16, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return rng.integers(0, vocab, (b, s)).astype(np.int32)


def test_gpt2_logits_match_transformers():
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()
    tokens = _tokens()
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()

    params = gpt2_params_from_hf(hf.state_dict(), depth=2, num_heads=4)
    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    got = model.apply({"params": params}, jnp.asarray(tokens), train=False)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_gpt2_param_tree_matches_model_init():
    """The converted tree has exactly the structure model.init produces —
    no silently missing/extra leaves."""
    import jax
    from flax import linen as nn

    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4
    )
    torch.manual_seed(1)
    hf = transformers.GPT2LMHeadModel(cfg)
    params = gpt2_params_from_hf(hf.state_dict(), depth=2, num_heads=4)
    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    ref = nn.meta.unbox(
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                   train=False)["params"]
    )
    ref_tree = jax.tree_util.tree_structure(jax.tree_util.tree_map(lambda x: 0, ref))
    got_tree = jax.tree_util.tree_structure(jax.tree_util.tree_map(lambda x: 0, params))
    assert ref_tree == got_tree
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(ref),
        jax.tree_util.tree_leaves_with_path(params),
        strict=True,
    ):
        assert np.shape(a) == np.shape(b), (pa, np.shape(a), np.shape(b))


def test_import_accepts_bf16_checkpoints():
    """Real HF checkpoints ship/load in bf16 (numpy has no bfloat16) — the
    importer must upcast, not crash."""
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=1, n_head=4
    )
    hf = transformers.GPT2LMHeadModel(cfg).to(torch.bfloat16)
    params = gpt2_params_from_hf(hf.state_dict(), depth=1, num_heads=4)
    assert params["wte"].dtype == np.float32


def test_save_hf_checkpoint_roundtrip(tmp_path):
    """tpudist → safetensors on disk → back through the importer: byte-
    identical weights (the full ecosystem hand-off loop)."""
    import jax
    from flax import linen as nn

    from tpudist.interop import load_hf_state_dict, save_hf_checkpoint

    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=1,
                 num_heads=4)
    params = nn.meta.unbox(
        model.init(jax.random.key(4), jnp.zeros((1, 8), jnp.int32),
                   train=False)["params"]
    )
    save_hf_checkpoint(params, tmp_path / "export", arch="gpt2", depth=1)
    back = gpt2_params_from_hf(
        load_hf_state_dict(tmp_path / "export"), depth=1, num_heads=4
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a, np.float32), b)

    # the advertised hand-off: config.json + our safetensors must load via
    # transformers' own from_pretrained (requires safetensors metadata)
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=1, n_head=4
    )
    cfg.save_pretrained(tmp_path / "export")
    hf = transformers.GPT2LMHeadModel.from_pretrained(tmp_path / "export")
    np.testing.assert_array_equal(
        hf.state_dict()["transformer.wte.weight"].numpy(),
        np.asarray(params["wte"], np.float32),
    )


def test_load_hf_state_dict_formats(tmp_path):
    """Local checkpoint loading: safetensors dirs (preferred), .bin
    fallback, missing path errors."""
    from tpudist.interop import load_hf_state_dict

    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=1, n_head=4
    )
    hf = transformers.GPT2LMHeadModel(cfg)
    st_dir = tmp_path / "st"
    hf.save_pretrained(st_dir)  # writes model.safetensors
    sd = load_hf_state_dict(st_dir)
    assert any(k.endswith("wte.weight") for k in sd)

    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    torch.save(hf.state_dict(), bin_dir / "pytorch_model.bin")
    sd2 = load_hf_state_dict(bin_dir)
    got = {k.removeprefix("transformer."): v for k, v in sd2.items()}
    np.testing.assert_array_equal(
        got["wte.weight"].numpy(), hf.state_dict()["transformer.wte.weight"].numpy()
    )
    with pytest.raises(FileNotFoundError):
        load_hf_state_dict(tmp_path / "nope")


def test_gpt2_export_roundtrips_into_transformers():
    """Our randomly initialized GPT-2, exported to an HF state dict and
    loaded into transformers, produces the same logits — the other
    direction of the oracle."""
    import jax
    from flax import linen as nn

    model = GPT2(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    params = nn.meta.unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32),
                   train=False)["params"]
    )
    tokens = _tokens(seed=5)
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(tokens), train=False)
    )

    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        attn_implementation="eager",
    )
    hf = transformers.GPT2LMHeadModel(cfg).eval()
    sd = {k: torch.from_numpy(v.copy()) for k, v in
          gpt2_params_to_hf(params, depth=2).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not unexpected
    assert all("attn.bias" in k or "masked_bias" in k for k in missing), missing
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_llama_export_roundtrips_into_transformers():
    import jax
    from flax import linen as nn

    model = Llama(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                  num_heads=4, num_kv_heads=2, ffn_dim=64, norm_eps=1e-5)
    params = nn.meta.unbox(
        model.init(jax.random.key(8), jnp.zeros((1, 8), jnp.int32),
                   train=False)["params"]
    )
    tokens = _tokens(seed=6)
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(tokens), train=False)
    )

    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        max_position_embeddings=32, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    hf = transformers.LlamaForCausalLM(cfg).eval()
    sd = {k: torch.from_numpy(v.copy()) for k, v in
          llama_params_to_hf(params, depth=2).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not missing and not unexpected, (missing, unexpected)
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("kv_heads,tied", [(4, False), (2, False), (2, True)])
def test_llama_logits_match_transformers(kv_heads, tied):
    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=kv_heads,
        intermediate_size=64, max_position_embeddings=32,
        rms_norm_eps=1e-5, rope_theta=10000.0, attention_bias=False,
        tie_word_embeddings=tied, attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf = transformers.LlamaForCausalLM(cfg).eval()
    tokens = _tokens(seed=3)
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()

    params = llama_params_from_hf(
        hf.state_dict(), depth=2, num_heads=4, num_kv_heads=kv_heads
    )
    assert ("lm_head" in params) == (not tied)
    model = Llama(
        vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2, num_heads=4,
        num_kv_heads=kv_heads, ffn_dim=64, rope_theta=10000.0,
        tie_embeddings=tied, norm_eps=1e-5,
    )
    got = model.apply({"params": params}, jnp.asarray(tokens), train=False)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def _tiny_t5_config():
    return transformers.T5Config(
        vocab_size=48, d_model=32, d_kv=8, num_heads=4, d_ff=64,
        num_layers=2, num_decoder_layers=2, feed_forward_proj="gated-gelu",
        relative_attention_num_buckets=8, relative_attention_max_distance=20,
        tie_word_embeddings=False, dropout_rate=0.0,
    )


def _tiny_t5_model():
    from tpudist.models.t5 import T5

    return T5(vocab_size=48, hidden_dim=32, ffn_dim=64, enc_depth=2,
              dec_depth=2, num_heads=4, rel_buckets=8, rel_max_distance=20)


def test_t5_logits_match_transformers():
    """Import direction of the T5 numerics oracle: an HF v1.1-convention
    model's weights through t5_params_from_hf reproduce transformers'
    seq2seq logits — pinning the relative-bucket function, un-scaled
    scores, gated-gelu flavor, RMSNorm placement, and un-tied head."""
    from tpudist.interop import t5_params_from_hf

    torch.manual_seed(4)
    hf = transformers.T5ForConditionalGeneration(_tiny_t5_config()).eval()
    enc = _tokens(b=2, s=12, vocab=48, seed=11)
    dec = _tokens(b=2, s=8, vocab=48, seed=12)
    with torch.no_grad():
        want = hf(
            input_ids=torch.from_numpy(enc.astype(np.int64)),
            decoder_input_ids=torch.from_numpy(dec.astype(np.int64)),
        ).logits.numpy()

    params = t5_params_from_hf(
        hf.state_dict(), enc_depth=2, dec_depth=2, num_heads=4
    )
    got = _tiny_t5_model().apply(
        {"params": params}, jnp.asarray(enc), jnp.asarray(dec), train=False
    )
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_t5_param_tree_matches_model_init():
    import jax
    from flax import linen as nn

    from tpudist.interop import t5_params_from_hf

    torch.manual_seed(5)
    hf = transformers.T5ForConditionalGeneration(_tiny_t5_config())
    params = t5_params_from_hf(
        hf.state_dict(), enc_depth=2, dec_depth=2, num_heads=4
    )
    model = _tiny_t5_model()
    want = nn.meta.unbox(
        model.init(
            jax.random.key(0),
            (jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 6), jnp.int32)),
            train=False,
        )["params"]
    )
    got_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_flatten_with_path(params)[0]}
    want_paths = {jax.tree_util.keystr(p) for p, _ in
                  jax.tree_util.tree_flatten_with_path(want)[0]}
    assert got_paths == want_paths
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(want)[0],
    ):
        assert np.shape(a) == np.shape(b), (pa, np.shape(a), np.shape(b))


def test_t5_export_roundtrips_into_transformers():
    """Export direction: our randomly-initialized T5 through
    t5_params_to_hf loads into transformers and reproduces our logits."""
    import jax
    from flax import linen as nn

    from tpudist.interop import t5_params_to_hf

    model = _tiny_t5_model()
    enc = _tokens(b=2, s=12, vocab=48, seed=13)
    dec = _tokens(b=2, s=8, vocab=48, seed=14)
    params = nn.meta.unbox(
        model.init(
            jax.random.key(9), (jnp.asarray(enc), jnp.asarray(dec)),
            train=False,
        )["params"]
    )
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(enc), jnp.asarray(dec),
                    train=False)
    )

    hf = transformers.T5ForConditionalGeneration(_tiny_t5_config()).eval()
    sd = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in
          t5_params_to_hf(params, enc_depth=2, dec_depth=2).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not missing and not unexpected, (missing, unexpected)
    with torch.no_grad():
        theirs = hf(
            input_ids=torch.from_numpy(enc.astype(np.int64)),
            decoder_input_ids=torch.from_numpy(dec.astype(np.int64)),
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_bert_logits_match_transformers():
    from tpudist.interop import bert_params_from_hf
    from tpudist.models.bert import Bert

    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu", attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf = transformers.BertForMaskedLM(cfg).eval()
    tokens = _tokens()
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()

    params = bert_params_from_hf(hf.state_dict(), depth=2, num_heads=4)
    model = Bert(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    got = model.apply({"params": params}, jnp.asarray(tokens), train=False)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_bert_param_tree_matches_model_init():
    import jax
    from flax import linen as nn

    from tpudist.interop import bert_params_from_hf
    from tpudist.models.bert import Bert

    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2,
    )
    torch.manual_seed(3)
    hf = transformers.BertForMaskedLM(cfg)
    params = bert_params_from_hf(hf.state_dict(), depth=2, num_heads=4)
    model = Bert(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    want = nn.meta.unbox(
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                   train=False)["params"]
    )
    got_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_flatten_with_path(params)[0]}
    want_paths = {jax.tree_util.keystr(p) for p, _ in
                  jax.tree_util.tree_flatten_with_path(want)[0]}
    assert got_paths == want_paths
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(want)[0],
    ):
        assert np.shape(a) == np.shape(b), (pa, np.shape(a), np.shape(b))


def test_bert_export_roundtrips_into_transformers():
    """tpudist-trained BERT weights → save_hf_checkpoint → HF
    BertForMaskedLM reproduces our logits (the hand-off direction)."""
    import jax

    from tpudist.interop import bert_params_to_hf
    from tpudist.models.bert import Bert

    model = Bert(vocab_size=64, max_seq_len=32, hidden_dim=32, depth=2,
                 num_heads=4)
    tokens = _tokens(seed=5)
    from flax import linen as nn

    params = nn.meta.unbox(
        model.init(jax.random.key(7), jnp.asarray(tokens), train=False)[
            "params"
        ]
    )
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(tokens), train=False)
    )

    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu", attn_implementation="eager",
    )
    hf = transformers.BertForMaskedLM(cfg).eval()
    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in bert_params_to_hf(params, depth=2).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    # only non-weight buffers / the untrained pooler may be missing
    assert all("pooler" in k or "position_ids" in k for k in missing), missing
    assert not unexpected, unexpected
    with torch.no_grad():
        theirs = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)
