"""Llama decoder family: RoPE math, GQA, SwiGLU, TP sharding equivalence,
ring/Ulysses composition, chunked-CE head selection — all on the 8 fake CPU
devices (SURVEY.md §4 pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.models.llama import Llama, apply_rope, llama_125m, llama2_7b, llama3_8b
from tpudist.train import (
    create_train_state,
    lm_loss,
    make_train_step,
    state_shardings_of,
)


def _tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("hidden_dim", 32)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    return Llama(**kw)


def _batch(b=4, s=16, vocab=64, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return {"tokens": rng.integers(0, vocab, (b, s)).astype(np.int32)}


def test_rope_is_a_rotation():
    """RoPE rotates each (x1,x2) pair: norms are preserved, position 0 is
    the identity, and relative phase depends only on position distance."""
    rng = np.random.Generator(np.random.PCG64(0))
    x = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    r = apply_rope(x)
    np.testing.assert_allclose(np.asarray(r[:, 0]), np.asarray(x[:, 0]), atol=1e-6)
    pairs = np.stack([np.asarray(x[..., :8]), np.asarray(x[..., 8:])], -1)
    rpairs = np.stack([np.asarray(r[..., :8]), np.asarray(r[..., 8:])], -1)
    np.testing.assert_allclose(
        np.linalg.norm(pairs, axis=-1), np.linalg.norm(rpairs, axis=-1), atol=1e-5
    )


def test_rope_relative_position_invariance():
    """q·k after RoPE depends on (i - j), not absolute positions — the
    property that makes RoPE compose with any context length."""
    rng = np.random.Generator(np.random.PCG64(1))
    qv = jnp.asarray(rng.normal(size=(16,)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(16,)), jnp.float32)

    def score(i, j, n=32):
        q = jnp.zeros((1, n, 1, 16)).at[0, i, 0].set(qv)
        k = jnp.zeros((1, n, 1, 16)).at[0, j, 0].set(kv)
        return float(jnp.sum(apply_rope(q)[0, i, 0] * apply_rope(k)[0, j, 0]))

    np.testing.assert_allclose(score(5, 3), score(20, 18), atol=1e-4)
    np.testing.assert_allclose(score(9, 2), score(25, 18), atol=1e-4)


def test_forward_shapes_and_gqa():
    model = _tiny(num_kv_heads=2)
    tokens = _batch()["tokens"]
    variables = model.init(jax.random.key(0), tokens, train=False)
    logits = model.apply(variables, tokens, train=False)
    assert logits.shape == (4, 16, 64)
    assert logits.dtype == jnp.float32
    # GQA: K/V projections carry num_kv_heads, not num_heads
    from flax import linen as nn

    k_kernel = nn.meta.unbox(variables["params"]["layer_0"]["k_proj"]["kernel"])
    assert k_kernel.shape == (32, 2, 8)


def test_gqa_head_count_must_divide():
    model = _tiny(num_kv_heads=3)
    with pytest.raises(ValueError, match="not divisible"):
        model.init(jax.random.key(0), _batch()["tokens"], train=False)


def test_tied_embeddings_share_the_table():
    tied = _tiny(tie_embeddings=True)
    variables = tied.init(jax.random.key(0), _batch()["tokens"], train=False)
    assert "lm_head" not in variables["params"]
    untied = _tiny()
    variables = untied.init(jax.random.key(0), _batch()["tokens"], train=False)
    assert "lm_head" in variables["params"]


def test_loss_decreases_on_learnable_data():
    """DP train on the 8-device mesh: a degenerate corpus (one repeated
    token pattern) must be learned fast."""
    mesh = mesh_lib.create_mesh()
    model = _tiny()
    tx = optax.adam(1e-2)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    tokens = np.tile(np.arange(16, dtype=np.int32), (8, 1))
    first = last = None
    for _ in range(8):
        state, metrics = step(state, {"tokens": tokens})
        last = float(metrics["loss"])
        first = first if first is not None else last
    assert last < first * 0.5, (first, last)


def test_tp_step_matches_single_device():
    def one_step(mesh, batch):
        model = _tiny(num_kv_heads=2)
        tx = optax.sgd(0.1)  # sgd: fp noise stays fp-sized (see test_tensor_parallel)
        state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        state, metrics = step(state, batch)
        return state, float(metrics["loss"])

    batch = _batch()
    mesh_tp = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    state_tp, loss_tp = one_step(mesh_tp, batch)
    # TP sharding is real: q kernel's head dim over 'tensor'
    spec = state_tp.params["layer_0"]["q_proj"]["kernel"].sharding.spec
    assert mesh_lib.TENSOR_AXIS in spec, spec
    mesh_1 = mesh_lib.create_mesh(devices=jax.devices()[:1])
    state_1, loss_1 = one_step(mesh_1, batch)
    np.testing.assert_allclose(loss_tp, loss_1, atol=1e-5, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state_tp.params),
        jax.tree_util.tree_leaves(state_1.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=0)


def test_ring_attention_leg():
    """Sequence-sharded Llama (ring attention over 'seq') trains a step."""
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, seq=2))
    model = _tiny(attn_impl="ring", mesh=mesh)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh)
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
        batch_spec={
            "tokens": P((mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
                        mesh_lib.SEQUENCE_AXIS)
        },
    )
    state, metrics = step(state, _batch(b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_ring_matches_xla_attention():
    """Ring attention is numerics, not semantics: same params, same batch,
    ring == plain XLA attention forward."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, seq=2))
    tokens = _batch(b=8)["tokens"]
    plain = _tiny()
    ring = _tiny(attn_impl="ring", mesh=mesh)
    variables = plain.init(jax.random.key(0), tokens, train=False)
    out_plain = plain.apply(variables, tokens, train=False)
    out_ring = ring.apply(variables, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(out_plain), np.asarray(out_ring), atol=2e-4, rtol=2e-4
    )


def test_chunked_ce_matches_full_logits():
    """chunked_lm_forward picks the right head weight for llama (untied
    lm_head and tied embed) and reproduces lm_loss exactly."""
    from tpudist.models.gpt2 import chunked_lm_forward

    tokens = _batch(b=2, s=16)["tokens"]
    for model in (_tiny(), _tiny(tie_embeddings=True)):
        variables = model.init(jax.random.key(1), tokens, train=False)
        params = variables["params"]
        logits = model.apply(variables, tokens, train=True)
        want = float(lm_loss(logits, tokens))
        fwd = chunked_lm_forward(model, chunk=5)
        got, _ = fwd(params, {}, {"tokens": tokens})
        np.testing.assert_allclose(float(got), want, atol=1e-5, rtol=1e-5)


def test_scan_layers_matches_unrolled():
    """nn.scan'd depth == the unrolled loop given the same weights, moved
    across layouts with stack_llama_layers; unstack inverts it exactly."""
    from tpudist.models.llama import stack_llama_layers, unstack_llama_layers

    tokens = _batch(b=2, s=12)["tokens"]
    unrolled = _tiny(num_kv_heads=2, depth=3)
    variables = unrolled.init(jax.random.key(5), tokens, train=False)
    params = variables["params"]
    want = unrolled.apply(variables, tokens, train=False)

    stacked = stack_llama_layers(params, depth=3)
    scan_model = _tiny(num_kv_heads=2, depth=3, scan_layers=True)
    got = scan_model.apply({"params": stacked}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )

    from flax import linen as nn

    back = unstack_llama_layers(stacked)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(nn.meta.unbox(params)),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scan_layers_tp_sharding_and_training():
    """Stacked params keep their tensor-parallel metadata (shifted past the
    depth axis) and the compiled train step runs."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    model = _tiny(num_kv_heads=2, depth=2, scan_layers=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32), tx, mesh)
    spec = tuple(state.params["layers"]["block"]["q_proj"]["kernel"].sharding.spec)
    assert spec[0] is None and "tensor" in spec, spec  # depth axis unsharded
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    state, metrics = step(state, _batch(b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_remat_layers_same_numerics_and_trains():
    """Per-layer remat changes memory, not math: same loss and same grads
    as plain scan_layers on a training step."""
    batch = _batch(b=8)
    mesh = mesh_lib.create_mesh()

    def one_step(remat_layers):
        model = _tiny(num_kv_heads=2, depth=2, scan_layers=True,
                      remat_layers=remat_layers)
        tx = optax.sgd(0.1)
        state = create_train_state(model, 0, jnp.zeros((1, 16), jnp.int32),
                                   tx, mesh)
        step = make_train_step(
            model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
            label_key="tokens", state_sharding=state_shardings_of(state),
        )
        state, metrics = step(state, batch)
        return float(metrics["loss"]), state.params

    loss_plain, params_plain = one_step(False)
    loss_remat, params_remat = one_step(True)
    np.testing.assert_allclose(loss_remat, loss_plain, rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(params_plain),
        jax.tree_util.tree_leaves(params_remat),
        strict=True,
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_remat_layers_requires_scan():
    model = _tiny(depth=2, remat_layers=True)
    with pytest.raises(ValueError, match="requires scan_layers"):
        model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                   train=False)


def test_scan_layers_decode_rejected():
    model = _tiny(depth=2, scan_layers=True)
    with pytest.raises(ValueError, match="decode"):
        model.init(jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                   train=False, decode=True)


def test_moe_single_expert_equals_dense_swiglu():
    """A 1-expert top-1 Mixtral block with ample capacity IS the dense
    SwiGLU MLP (gate weight = softmax over one expert = 1): transplant the
    expert weights into a dense block and compare outputs."""
    tokens = _batch(b=2, s=8)["tokens"]
    moe = _tiny(num_experts=1, moe_top_k=1, capacity_factor=4.0, depth=1)
    variables = moe.init(jax.random.key(3), tokens, train=False)
    out_moe = moe.apply(variables, tokens, train=False)

    from flax import linen as nn

    p = nn.meta.unbox(variables["params"])
    layer = dict(p["layer_0"])
    expert = layer.pop("moe")
    layer["gate_proj"] = {"kernel": expert["w_gate"][0]}
    layer["up_proj"] = {"kernel": expert["w_up"][0]}
    layer["down_proj"] = {"kernel": expert["w_down"][0]}
    dense_params = {**p, "layer_0": layer}
    dense = _tiny(depth=1)
    out_dense = dense.apply({"params": dense_params}, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(out_moe), np.asarray(out_dense), atol=2e-5, rtol=2e-5
    )


def test_moe_trains_over_expert_axis():
    """Mixtral-style Llama trains a step on a data x expert mesh with the
    expert FFNs expert-sharded and the aux loss included."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, expert=2))
    model = _tiny(num_experts=2, moe_top_k=2, mesh=mesh, depth=2)
    assert model.has_aux_loss
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((8, 16), jnp.int32), tx, mesh)
    spec = state.params["layer_0"]["moe"]["w_gate"].sharding.spec
    assert "expert" in spec, spec
    step = make_train_step(
        model, tx, mesh, loss_fn=lm_loss, input_key="tokens",
        label_key="tokens", state_sharding=state_shardings_of(state),
    )
    state, metrics = step(state, _batch(b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_size_presets():
    assert llama_125m().num_kv_heads == 4
    m = llama2_7b()
    assert (m.hidden_dim, m.depth, m.ffn_dim) == (4096, 32, 11008)
    m3 = llama3_8b()
    assert (m3.num_kv_heads, m3.vocab_size, m3.rope_theta) == (8, 128256, 500000.0)
    assert llama2_7b(depth=2).depth == 2
    # auto SwiGLU sizing: 8/3*768 -> 2048 rounded up to /256
    assert _tiny(hidden_dim=768).ffn_dim is None  # field stays None
