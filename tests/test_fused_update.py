"""One-pass fused AdamW (tpudist/ops/fused_update.py, optim.fused_adamw)
pinned against the optax reference chain — bit-level in interpret mode for
the shared-formula small-leaf path, ulp-level for the kernel path — plus
the compute-copy contract, edge leaves (1-element, odd sizes), and the
skip_nonfinite / decay-mask / clip / schedule compositions."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist.optim import (
    FusedAdamWState,
    decay_mask,
    fused_adamw,
    fused_compute_params,
    find_fused,
    make_optimizer,
    refresh_fused_compute,
)


def _tree(seed=0):
    r = np.random.Generator(np.random.PCG64(seed))
    return {
        # > MIN_KERNEL_ELEMS → the Pallas sweep; odd size → pad/mask path
        "w": jnp.asarray(r.standard_normal((40, 130)), jnp.float32),
        "big": jnp.asarray(r.standard_normal(9001), jnp.float32),
        # < MIN_KERNEL_ELEMS → the shared-formula XLA path
        "b": jnp.asarray(r.standard_normal(7), jnp.float32),
        # the 1-element edge leaf
        "one": jnp.asarray(r.standard_normal(1)[0], jnp.float32),
    }


def _grads(params, seed):
    r = np.random.Generator(np.random.PCG64(seed))
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(r.standard_normal(p.shape), p.dtype) * 0.1,
        params,
    )


def _run(tx, params, n_steps=5):
    state = tx.init(params)

    @jax.jit
    def step(p, s, g):
        u, s2 = tx.update(g, s, p)
        return optax.apply_updates(p, u), s2

    for i in range(n_steps):
        params, state = step(params, state, _grads(params, 100 + i))
    return params, state


@pytest.mark.parametrize("wd,clip,sched", [
    (0.0, None, False),        # plain adam
    (0.1, None, False),        # adamw + decay mask
    (0.1, 1.0, True),          # + global-norm clip + lr schedule
], ids=["adam", "adamw_mask", "clip_sched"])
def test_matches_optax_chain(wd, clip, sched):
    params = _tree()
    lr = optax.cosine_decay_schedule(1e-2, 50) if sched else 1e-2
    ftx = fused_adamw(lr, weight_decay=wd, mask=decay_mask if wd else None,
                      clip_norm=clip)
    parts = ([optax.clip_by_global_norm(clip)] if clip else []) + [
        optax.adamw(lr, weight_decay=wd, mask=decay_mask) if wd
        else optax.adam(lr)
    ]
    rtx = optax.chain(*parts) if len(parts) > 1 else parts[0]

    fp, fs = _run(ftx, params)
    rp, rs = _run(rtx, params)
    # the small-leaf path shares the formula FUNCTION with optax-order
    # arithmetic and the kernel path runs the same math through the pallas
    # interpreter — either can differ from optax by an ulp of XLA fusion
    # reassociation across 5 compounding Adam steps, no more (the bars are
    # absolute, at ~2.0-magnitude params: ~1-4 float32 ulps)
    for key in ("b", "one"):
        np.testing.assert_allclose(
            np.asarray(fp[key]), np.asarray(rp[key]), atol=5e-7, rtol=0
        )
    for key in ("w", "big"):
        np.testing.assert_allclose(
            np.asarray(fp[key]), np.asarray(rp[key]), atol=1e-6, rtol=0
        )


def test_decay_mask_actually_masks():
    """1-D leaves (mask False) must see NO decay: pin by diffing a decayed
    vs undecayed run on a zero gradient (pure-decay signal)."""
    params = _tree()
    zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
    tx = fused_adamw(1e-2, weight_decay=0.5, mask=decay_mask)
    u, _ = tx.update(zero_g, tx.init(params), params)
    assert float(jnp.max(jnp.abs(u["b"]))) == 0.0       # masked: no decay
    assert float(jnp.max(jnp.abs(u["one"]))) == 0.0
    assert float(jnp.max(jnp.abs(u["w"]))) > 0.0        # decayed


def test_compute_copy_is_cast_of_post_update_master():
    params = _tree()
    tx = fused_adamw(1e-2, compute_dtype=jnp.bfloat16)
    state = tx.init(params)
    copy = fused_compute_params(state, params)
    for c, p in zip(jax.tree_util.tree_leaves(copy),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(
            np.asarray(c, np.float32),
            np.asarray(p.astype(jnp.bfloat16), np.float32),
        )
    new_p, new_s = _run(tx, params, n_steps=3)
    copy = fused_compute_params(new_s, new_p)
    assert copy is not None
    for c, p in zip(jax.tree_util.tree_leaves(copy),
                    jax.tree_util.tree_leaves(new_p)):
        # BIT-identical to casting the post-update master — the invariant
        # that makes the copy-forward exactly the per-op-cast forward
        np.testing.assert_array_equal(
            np.asarray(c, np.float32),
            np.asarray(p.astype(jnp.bfloat16), np.float32),
        )


def test_no_copy_state_carries_zero_extra_leaves():
    params = _tree()
    tx = fused_adamw(1e-2)
    state = tx.init(params)
    assert state.compute == ()
    assert fused_compute_params(state, params) is None
    n_params = len(jax.tree_util.tree_leaves(params))
    # count + mu + nu, nothing else
    assert len(jax.tree_util.tree_leaves(state)) == 1 + 2 * n_params


def test_skip_nonfinite_freezes_fused_state():
    from tpudist.amp import skip_nonfinite, skipped_steps

    params = _tree()
    tx = skip_nonfinite(fused_adamw(1e-2, compute_dtype=jnp.bfloat16))
    assert find_fused(tx) is not None  # detection walks the wrapper
    state = tx.init(params)
    nan_g = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.nan, p.dtype), params
    )
    u, state2 = jax.jit(tx.update)(nan_g, state, params)
    assert skipped_steps(state2) == 1
    assert all(
        bool(jnp.all(x == 0)) for x in jax.tree_util.tree_leaves(u)
    )
    for a, b in zip(jax.tree_util.tree_leaves(state2[0].mu),
                    jax.tree_util.tree_leaves(state[0].mu)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the compute copy is state too: a poisoned step must not corrupt it
    for a, b in zip(jax.tree_util.tree_leaves(state2[0].compute),
                    jax.tree_util.tree_leaves(state[0].compute)):
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)
        )


def test_refresh_fused_compute_recasts_and_declines():
    params = _tree()
    tx = fused_adamw(1e-2, compute_dtype=jnp.bfloat16)
    state = tx.init(params)
    warm = jax.tree_util.tree_map(lambda p: p + 1.0, params)
    fresh = refresh_fused_compute(state, warm)
    for c, p in zip(jax.tree_util.tree_leaves(fresh.compute),
                    jax.tree_util.tree_leaves(warm)):
        np.testing.assert_array_equal(
            np.asarray(c, np.float32),
            np.asarray(p.astype(jnp.bfloat16), np.float32),
        )
    # a foreign state passes through untouched
    foreign = optax.adam(1e-2).init(params)
    assert refresh_fused_compute(foreign, params) is foreign


def test_extraction_declines_shape_mismatch():
    """The copy is used ONLY when params-shaped leaf-for-leaf — a ZeRO-1
    pad-stored (or otherwise re-laid-out) copy must be declined whole."""
    params = _tree()
    tx = fused_adamw(1e-2, compute_dtype=jnp.bfloat16)
    state = tx.init(params)
    bad = state._replace(
        compute={**state.compute, "w": state.compute["w"].reshape(-1)}
    )
    assert fused_compute_params(bad, params) is None


def test_make_optimizer_fused_routes_and_validates():
    tx = make_optimizer(1e-3, fused=True, weight_decay=0.1, clip_norm=1.0,
                        compute_dtype=jnp.bfloat16)
    assert find_fused(tx) is not None
    tx2 = make_optimizer(1e-3, fused=True, skip_nonfinite_updates=True)
    assert find_fused(tx2) is not None
    with pytest.raises(ValueError, match="fused=True"):
        make_optimizer(1e-3, fused=True, optimizer="sgd")


def test_update_requires_params():
    tx = fused_adamw(1e-2)
    params = _tree()
    with pytest.raises(ValueError, match="requires params"):
        tx.update(_grads(params, 0), tx.init(params))


def test_boxed_init_preserves_partitioning_metadata():
    """create_train_state runs tx.init on flax-BOXED params; the moments
    and the compute copy must come out boxed with the same metadata (the
    property that lets TP/ZeRO shardings derive from the state tree)."""
    from flax import linen as nn

    boxed = {
        "w": nn.Partitioned(jnp.ones((4, 2048)), names=("tensor", None)),
        "b": jnp.zeros((3,)),
    }
    tx = fused_adamw(1e-2, compute_dtype=jnp.bfloat16)
    state = jax.eval_shape(tx.init, boxed)
    assert isinstance(state, FusedAdamWState)
    mu_w = jax.tree_util.tree_leaves(
        state.mu["w"], is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )[0]
    assert isinstance(mu_w, nn.Partitioned)
    assert mu_w.names == ("tensor", None)


def test_zero1_shard_state_composition_exact():
    """shard_state(fused_adamw) must produce the identical trajectory to
    plain fused_adamw — ZeRO-1 is a layout change, not a math change."""
    from tpudist import mesh as mesh_lib
    from tpudist.optim import shard_state

    mesh = mesh_lib.create_mesh()
    params = _tree()
    plain = fused_adamw(1e-2, weight_decay=0.1, mask=decay_mask,
                        compute_dtype=jnp.bfloat16)
    sharded = shard_state(plain, mesh, min_size=8)
    pp, _ = _run(plain, params, n_steps=4)
    sp, ss = _run(sharded, params, n_steps=4)
    for a, b in zip(jax.tree_util.tree_leaves(pp),
                    jax.tree_util.tree_leaves(sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-7, rtol=0)
