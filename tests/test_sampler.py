"""DistributedSampler semantics vs SURVEY.md §2.6: deterministic seed+epoch
shuffle, head-wrap padding, strided disjoint shards, set_epoch re-keying."""

import numpy as np
import pytest

from tpudist.data.sampler import DistributedSampler


def shards(n, world, **kw):
    return [
        DistributedSampler(n, num_replicas=world, rank=r, **kw).epoch_indices()
        for r in range(world)
    ]


def test_disjoint_and_covering_when_divisible():
    world, n = 4, 100
    parts = shards(n, world)
    allidx = np.concatenate(parts)
    assert len(allidx) == n
    assert set(allidx.tolist()) == set(range(n))


def test_padding_wraps_from_head():
    # n=10, world=4 -> num_samples=3, total=12, pad=2 repeats head of the
    # permutation (torch drop_last=False semantics)
    world, n = 4, 10
    samplers = [
        DistributedSampler(n, num_replicas=world, rank=r, shuffle=False)
        for r in range(world)
    ]
    parts = [s.epoch_indices() for s in samplers]
    flat = np.stack(parts, 1).reshape(-1)  # interleave back to padded order
    assert flat.tolist() == list(range(10)) + [0, 1]
    for s in samplers:
        assert len(s) == 3


def test_padding_exceeding_dataset_size():
    parts = shards(3, 8, shuffle=False)
    flat = np.stack(parts, 1).reshape(-1)
    assert flat.tolist() == [0, 1, 2, 0, 1, 2, 0, 1]


def test_drop_last_truncates():
    parts = shards(10, 4, drop_last=True)
    assert all(len(p) == 2 for p in parts)
    assert len(set(np.concatenate(parts).tolist())) == 8


def test_set_epoch_rekeys_shuffle_deterministically():
    s = DistributedSampler(1000, num_replicas=1, rank=0, seed=0)
    s.set_epoch(0)
    e0 = s.epoch_indices()
    s.set_epoch(1)
    e1 = s.epoch_indices()
    s.set_epoch(0)
    again = s.epoch_indices()
    assert not np.array_equal(e0, e1)
    assert np.array_equal(e0, again)
    # seed+epoch keying: seed=1/epoch=0 == seed=0/epoch=1
    s2 = DistributedSampler(1000, num_replicas=1, rank=0, seed=1)
    assert np.array_equal(s2.epoch_indices(), e1)


def test_shuffled_shards_are_disjoint():
    parts = shards(128, 8, seed=3)
    assert set(np.concatenate(parts).tolist()) == set(range(128))


def test_rank_validation():
    with pytest.raises(ValueError):
        DistributedSampler(10, num_replicas=4, rank=4)
