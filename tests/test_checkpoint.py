"""Checkpoint / resume tests.

Capability extension over the reference (which persists nothing —
SURVEY.md §5): round-trip fidelity, sharded-state restore, and exact-resume
semantics of fit() (same losses as an uninterrupted run, since the sampler
order is deterministic per epoch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpudist import mesh as mesh_lib
from tpudist.checkpoint import Checkpointer, latest_step
from tpudist.data.cifar import synthetic_cifar, to_tensor
from tpudist.data.loader import DataLoader
from tpudist.data.sampler import DistributedSampler
from tpudist.models import resnet18
from tpudist.models.gpt2 import GPT2
from tpudist.train import (
    create_train_state, fit, lm_loss, make_train_step, state_shardings_of,
)

# jax 0.4.x XLA:CPU reproducibly ABORTS (kills the interpreter, not just
# the test) stepping a donated jit on orbax-RESTORED arrays inside fit();
# current jax runs these fine. A dead process costs every later test file
# its run, so the restore-then-step tests are gated, not braved.
_OLD_JAX = tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5)
_OLD_JAX_RESUME = pytest.mark.skipif(
    _OLD_JAX, reason="aborts jax 0.4.x XLA:CPU (donated step on restored "
    "arrays); green on current jax"
)


def _tiny_state(mesh):
    model = resnet18(num_classes=10, small_inputs=True)
    tx = optax.adam(1e-3)
    state = create_train_state(model, 0, jnp.zeros((1, 32, 32, 3)), tx, mesh)
    return model, tx, state


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a, b,
    )


def test_roundtrip_identity(tmp_path):
    mesh = mesh_lib.create_mesh()
    model, tx, state = _tiny_state(mesh)
    step = make_train_step(model, tx, mesh)
    batch = to_tensor(synthetic_cifar(n=16, num_classes=10))
    state, _ = step(state, batch)

    with Checkpointer(tmp_path / "ckpt") as c:
        c.save(state, wait=True)
        assert c.latest_step() == 1
        fresh = _tiny_state(mesh)[2]  # different values, same structure
        restored = c.restore(like=fresh)
    _assert_trees_equal(restored, state)
    assert latest_step(tmp_path / "ckpt") == 1


def test_restore_respects_sharded_placement(tmp_path):
    """A TP-sharded GPT-2 state restores onto its original shardings (no
    silent all-replication)."""
    mesh = mesh_lib.create_mesh(mesh_lib.MeshConfig(data=4, tensor=2))
    lm = GPT2(vocab_size=64, max_seq_len=16, hidden_dim=32, depth=1, num_heads=2)
    tx = optax.adam(1e-3)
    state = create_train_state(lm, 0, jnp.zeros((1, 8), jnp.int32), tx, mesh)
    step = make_train_step(lm, tx, mesh, loss_fn=lm_loss, input_key="tokens",
                           label_key="tokens",
                           state_sharding=state_shardings_of(state))
    tokens = {"tokens": np.arange(8 * 8, dtype=np.int32).reshape(8, 8) % 64}
    state, _ = step(state, tokens)

    with Checkpointer(tmp_path / "tp") as c:
        c.save(state, wait=True)
        fresh = create_train_state(lm, 1, jnp.zeros((1, 8), jnp.int32), tx, mesh)
        restored = c.restore(like=fresh)
    _assert_trees_equal(restored, state)
    flat_new, _ = jax.tree_util.tree_flatten(restored)
    flat_old, _ = jax.tree_util.tree_flatten(state)
    for new, old in zip(flat_new, flat_old):
        assert new.sharding.is_equivalent_to(old.sharding, new.ndim)


def test_max_to_keep(tmp_path):
    mesh = mesh_lib.create_mesh()
    _, _, state = _tiny_state(mesh)
    with Checkpointer(tmp_path / "gc", max_to_keep=2) as c:
        for s in (1, 2, 3, 4):
            c.save(state, step=s, wait=True)
        assert c.latest_step() == 4
        steps = sorted(int(p.name) for p in (tmp_path / "gc").iterdir()
                       if p.name.isdigit())
        assert steps == [3, 4]


def _run_fit(tmp_path, epochs, ckpt_dir=None, every=0, tag="a"):
    model = resnet18(num_classes=10, small_inputs=True)
    data = synthetic_cifar(n=128, num_classes=10)
    loader = DataLoader(
        data, 32, sampler=DistributedSampler(128, num_replicas=1, rank=0),
        transform=to_tensor,
    )
    return fit(
        model, optax.adam(1e-3), loader,
        epochs=epochs, job_id=f"CK{tag}", batch_size=32,
        profile=False, log_dir=str(tmp_path),
        checkpoint_dir=None if ckpt_dir is None else str(ckpt_dir),
        checkpoint_every=every,
    )


@_OLD_JAX_RESUME
def test_fit_resume_matches_uninterrupted(tmp_path):
    """Train 1 epoch + resume for the 2nd ≡ training 2 epochs straight:
    identical per-step losses (deterministic init, sampler, and updates)."""
    full_state, full_losses = _run_fit(tmp_path / "full", epochs=2)

    ckpt = tmp_path / "resume" / "ckpt"
    _, first = _run_fit(tmp_path / "resume", epochs=1, ckpt_dir=ckpt, tag="b")
    assert latest_step(ckpt) == 4  # 128/32 steps saved at end of epoch 0
    state2, second = _run_fit(tmp_path / "resume", epochs=2, ckpt_dir=ckpt, tag="b")

    np.testing.assert_allclose(
        np.asarray(first + second), np.asarray(full_losses), rtol=2e-4, atol=2e-5
    )
    assert int(state2.step) == int(full_state.step) == 8
    _assert_trees_equal(state2.params, full_state.params)


def test_resume_rejects_changed_geometry(tmp_path):
    """Resuming with a different batch size must fail loudly: state.step
    would map to the wrong data position and silently re-train on consumed
    samples."""
    ckpt = tmp_path / "geo"
    _run_fit(tmp_path, epochs=1, ckpt_dir=ckpt, tag="g")

    model = resnet18(num_classes=10, small_inputs=True)
    data = synthetic_cifar(n=128, num_classes=10)
    loader16 = DataLoader(
        data, 16, sampler=DistributedSampler(128, num_replicas=1, rank=0),
        transform=to_tensor,
    )
    with pytest.raises(ValueError, match="geometry"):
        fit(model, optax.adam(1e-3), loader16, epochs=2, job_id="CKg2",
            batch_size=16, profile=False, log_dir=str(tmp_path),
            checkpoint_dir=str(ckpt))


def test_loader_iter_from_skips_at_index_level():
    from unittest import mock

    data = synthetic_cifar(n=96, num_classes=10)
    loader = DataLoader(
        data, 16, sampler=DistributedSampler(96, num_replicas=1, rank=0),
        transform=to_tensor,
    )
    tail = list(loader.iter_from(4))
    full = list(loader)
    assert len(tail) == 2
    for a, b in zip(tail, full[4:]):
        np.testing.assert_array_equal(a["image"], b["image"])
    # skipped batches are never materialized: the native/python gather runs
    # exactly len(tail) times
    with mock.patch("tpudist.data.native.native_batch", return_value=None) as nb:
        assert len(list(loader.iter_from(4))) == 2
        assert nb.call_count == 2


@_OLD_JAX_RESUME
def test_fit_resume_mid_epoch(tmp_path):
    """checkpoint_every mid-epoch: the resumed run skips exactly the
    consumed batches and finishes the epoch (step counts line up)."""
    ckpt = tmp_path / "mid"
    full_state, full_losses = _run_fit(tmp_path, epochs=1, ckpt_dir=ckpt,
                                       every=3, tag="c")
    # wipe nothing; resuming a finished run trains zero steps
    state, losses = _run_fit(tmp_path, epochs=1, ckpt_dir=ckpt, tag="c")
    assert losses == []
    assert int(state.step) == 4

    # drop back to the step-3 checkpoint and resume the last batch
    import shutil

    shutil.rmtree(ckpt / "4")
    state, losses = _run_fit(tmp_path, epochs=1, ckpt_dir=ckpt, tag="c")
    assert len(losses) == 1
    np.testing.assert_allclose(losses[0], full_losses[3], rtol=2e-4, atol=2e-5)
