"""tools/tracelens.py: rotation-aware segment discovery, heartbeat-based
cross-rank clock alignment, the Perfetto trace-event emission, the latency
report — and the PR's acceptance integration: a real traced fit() run plus
a traced ServeEngine drain (one preemption, one repair event, an emulated
second rank) stitched into one Perfetto-loadable trace.json whose
per-request spans reconcile with the ServeStats SLO samples."""

import importlib.util
import json
import pathlib
import sys

import numpy as np
import optax

from tpudist.telemetry import TelemetrySink
from tpudist.telemetry.trace import ServeTracer, Tracer

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_tracelens():
    spec = importlib.util.spec_from_file_location(
        "tracelens", _TOOLS / "tracelens.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tracelens = _load_tracelens()


# -- discovery / rotation ----------------------------------------------------


def test_discover_orders_segments_and_filters_job(tmp_path):
    base = tmp_path / "J_telemetry_0.jsonl"
    for name in ("J_telemetry_0.jsonl.2", "J_telemetry_0.jsonl.10",
                 "J_telemetry_0.jsonl.1", "J_telemetry_0.jsonl",
                 "OTHER_telemetry_0.jsonl", "J_report.json"):
        (tmp_path / name).write_text("")
    chains = tracelens.discover([tmp_path], job="J")
    assert list(chains) == [str(base)]
    # numeric ascending (1, 2, 10 — not lexicographic), live tail LAST
    assert [p.name for p in chains[str(base)]] == [
        "J_telemetry_0.jsonl.1", "J_telemetry_0.jsonl.2",
        "J_telemetry_0.jsonl.10", "J_telemetry_0.jsonl",
    ]


def test_rotated_stream_round_trip(tmp_path):
    """Write a traced stream through REAL sink rotation (tiny max_bytes →
    multiple sealed segments), then reassemble via tracelens: every row
    survives, in write order, and the trace builds from the union."""
    path = tmp_path / "R_telemetry_0.jsonl"
    sink = TelemetrySink(path, max_bytes=600, run_id="rid0")
    tr = Tracer(sink, clock=lambda: 1000.0)
    import time

    for s in range(1, 21):
        sink.write("heartbeat", s, epoch=0, interval_s=0.1,
                   process_index=0, host="h", mono=900.0 + s,
                   generation=0)
        tr.span("step", 0.1, t0=900.0 + s - 0.1, step=s)
    sink.close()
    segs = [p for p in tmp_path.iterdir() if ".jsonl." in p.name]
    assert len(segs) >= 2  # rotation actually happened

    chains = tracelens.discover([tmp_path], job="R")
    rows = tracelens.read_chain(chains[str(path)])
    assert len(rows) == 40
    assert [r["step"] for r in rows if r["kind"] == "span"] \
        == list(range(1, 21))  # chain order == write order
    assert all(r["run_id"] == "rid0" for r in rows)
    events = tracelens.to_trace_events(rows)
    assert len([e for e in events if e["ph"] == "X"]) == 20


def test_cross_rank_mono_alignment(tmp_path):
    """Two ranks whose monotonic clocks have wildly different epochs but
    whose heartbeats share wall time: after alignment, simultaneous spans
    land at the same trace timestamp (within the alignment's resolution),
    rather than epochs apart."""
    rows = []
    for rank, mono_epoch in ((0, 1000.0), (1, 500000.0)):
        for s in range(1, 4):
            wall = 1e9 + s  # same wall instant on both ranks
            rows.append({"v": 1, "t": wall, "kind": "heartbeat",
                         "rank": rank, "step": s, "mono": mono_epoch + s,
                         "generation": 0})
            rows.append({"v": 1, "t": wall, "kind": "span", "rank": rank,
                         "step": s, "name": "step", "cat": "train",
                         "ph": "X", "t0": mono_epoch + s - 1.0,
                         "dur_s": 1.0, "generation": 0})
    events = [e for e in tracelens.to_trace_events(rows)
              if e.get("ph") == "X"]
    by_step = {}
    for e in events:
        by_step.setdefault(e["args"]["step"], []).append(e["ts"])
    for step, stamps in by_step.items():
        assert len(stamps) == 2
        assert abs(stamps[0] - stamps[1]) < 1.0, (step, stamps)


def test_serve_spans_self_anchor(tmp_path):
    """Serve spans carry no mono heartbeat — each row's wall ``t`` is the
    span-close anchor. A constant write offset must cancel exactly."""
    sink_t = [0.0]
    sink = TelemetrySink(tmp_path / "s.jsonl", clock=lambda: sink_t[0])
    tr = ServeTracer(sink)
    tr.on_submit(1, 10.0)
    sink_t[0] = 1e6 + 12.0  # wall = span clock + 1e6, exactly
    tr.on_admit(1, 12.0)
    tr.on_first_token(1, 13.0, slot=0)
    sink_t[0] = 1e6 + 15.0
    tr.on_done(1, 15.0, 3)
    sink.close()
    rows = [json.loads(l)
            for l in (tmp_path / "s.jsonl").read_text().splitlines()]
    events = [e for e in tracelens.to_trace_events(rows)
              if e.get("ph") == "X"]
    req = next(e for e in events if e["name"] == "request")
    queued = next(e for e in events if e["name"] == "queued")
    # rebased to the earliest span: queued starts at 0, request too
    assert req["ts"] == queued["ts"] == 0.0
    assert req["dur"] == 5e6  # 5 s in µs


def test_report_tables(tmp_path, capsys):
    rows = [
        {"v": 1, "t": 1.0, "kind": "span", "rank": 0, "name": "request",
         "cat": "serve", "ph": "X", "t0": 0.0, "dur_s": 2.0, "rid": 9,
         "lane": 1, "tokens": 5, "queued_s": 0.5, "prefill_s": 0.5,
         "decode_s": 1.0, "preempt_s": 0.0, "preempts": 0},
        {"v": 1, "t": 1.0, "kind": "span", "rank": 0, "name": "request",
         "cat": "serve", "ph": "X", "t0": 0.0, "dur_s": 4.0, "rid": 3,
         "lane": 0, "tokens": 7, "queued_s": 1.0, "prefill_s": 1.0,
         "decode_s": 1.5, "preempt_s": 0.5, "preempts": 1},
    ]
    top = tracelens.request_table(rows, top=1)
    assert [r["rid"] for r in top] == [3]  # slowest first
    tracelens.render_report(rows, [tmp_path], None, top=5)
    out = capsys.readouterr().out
    assert "slowest 2 request(s)" in out and "4000.0" in out


# -- the acceptance integration ----------------------------------------------


def test_fit_plus_serve_trace_end_to_end(tmp_path, monkeypatch, capsys):
    """The PR's acceptance run: a traced fit() (rotation forced, live
    metrics endpoint on), an emulated second train rank with a repair
    event, and a traced paged ServeEngine drain with a real preemption —
    tracelens stitches all streams into a Perfetto-loadable trace.json
    whose request spans reconcile with ServeStats within float error."""
    from tpudist.models.gpt2 import GPT2
    from tpudist.data.loader import DataLoader
    from tpudist.resilience.exitcodes import RUN_ID_ENV
    from tpudist.serve import ServeEngine
    from tpudist.telemetry import Telemetry, TelemetryConfig
    from tpudist.train import fit

    monkeypatch.setenv(RUN_ID_ENV, "acceptance01")
    job = "TL"
    # -- train rank 0: a real traced fit() with rotation + divergence probe
    rng = np.random.Generator(np.random.PCG64(0))
    tokens = rng.integers(0, 254, (64, 16)).astype(np.int32)
    model = GPT2(vocab_size=256, max_seq_len=16, hidden_dim=32, depth=1,
                 num_heads=2)
    cfg = TelemetryConfig(trace=True, heartbeat_every=2,
                          divergence_every=4, jsonl_max_bytes=4096,
                          run_report=False)
    from tpudist.train import lm_loss

    fit(model, optax.adam(1e-3), DataLoader({"tokens": tokens}, 16),
        epochs=3, job_id=job, batch_size=16, loss_fn=lm_loss,
        input_key="tokens", label_key="tokens", log_dir=str(tmp_path),
        telemetry=cfg, profile=False, metrics_port=0)

    # -- train rank 1 (emulated second process): the same production
    # wiring fit uses, driven directly — including the bring-up repair
    # replay path that re-emits a repair event as a span
    sink1 = TelemetrySink(tmp_path / f"{job}_telemetry_1.jsonl", rank=1)
    tel1 = Telemetry(TelemetryConfig(trace=True), sink1, rank=1,
                     world_size=2, log_every=2, n_chips=1)
    tel1.tracer = Tracer(sink1, process_index=1)
    tel1.set_repair({"action": "rollback", "cause": "loss_spike",
                     "skip_from": 6, "skip_to": 10, "rollback_step": 4})
    for g in range(1, 7):
        tel1.on_step(g, {"loss": 2.0 / g}, epoch=0, interval_s=0.01,
                     data_wait_s=0.001)
    tel1.shutdown()

    # -- serve: traced paged engine sized to force one preemption
    smodel = GPT2(vocab_size=64, max_seq_len=64, hidden_dim=32, depth=2,
                  num_heads=4)
    import jax

    sparams = smodel.init(
        jax.random.key(1), np.zeros((1, 8), np.int32), train=False
    )["params"]
    ssink = TelemetrySink(tmp_path / f"{job}_serve_0.jsonl")
    eng = ServeEngine(smodel, sparams, max_slots=3, seed=0, paged=True,
                      block_size=8, n_blocks=8, watermark_blocks=0,
                      prefix_cache=False, sink=ssink, trace=True)
    srng = np.random.Generator(np.random.PCG64(5))
    for _ in range(3):
        eng.submit(srng.integers(0, 64, (6,)).astype(np.int32), 12)
    eng.run()
    ssink.close()
    assert eng.stats.preemptions > 0  # the preemption actually happened

    # -- stitch
    out = tmp_path / "trace.json"
    rc = tracelens.main([str(tmp_path), "--job", job, "--out", str(out),
                         "--top", "3"])
    assert rc == 0
    trace = json.loads(out.read_text())  # Perfetto-loadable strict JSON
    events = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"
    x = [e for e in events if e.get("ph") == "X"]
    names = {e["name"] for e in x}
    assert {"step", "queued", "prefill", "decode", "request",
            "tick", "preempted"} <= names
    assert any(e["ph"] == "i" and e["name"] == "repair" for e in events)
    assert any(e["ph"] == "i" and e["name"] == "preempt" for e in events)
    assert any(e["ph"] == "i" and e["name"] == "probe" for e in events)
    # both train ranks present, plus named serve slot tracks
    assert {e["pid"] for e in x if e["name"] == "step"} == {0, 1}
    tnames = {e["args"]["name"] for e in events
              if e.get("name") == "thread_name"}
    assert "steps" in tnames and "serve scheduler" in tnames
    assert any(n.startswith("serve slot") for n in tnames)
    # every event timestamp is non-negative after rebasing
    assert all(e["ts"] >= 0 for e in x)
    # per-request reconciliation with the live ServeStats SLO samples
    reqs = [e for e in x if e["name"] == "request"]
    assert len(reqs) == 3
    assert sorted(e["args"]["ttft_s"] for e in reqs) \
        == sorted(eng.stats.ttft)
    for e in reqs:
        a = e["args"]
        phase_sum = (a["queued_s"] + a["prefill_s"] + a["decode_s"]
                     + a["preempt_s"])
        assert abs(phase_sum - e["dur"] / 1e6) < 1e-6
    # rotation happened on the fit stream and the run_id groups it all
    fit_files = list(tmp_path.glob(f"{job}_telemetry_0.jsonl*"))
    assert len(fit_files) >= 2
    report = capsys.readouterr().out
    assert "run_id acceptance01" in report
    assert "slowest 3 request(s)" in report


def test_run_id_filter_splits_reused_job_dir(tmp_path, capsys):
    """Two runs reusing one job id in one log dir: --run_id must keep
    exactly the requested run's rows (row-level — rotation interleaves
    runs within a segment chain, so filenames can't split them), and an
    unknown id must exit 2 rather than emit an empty trace."""
    path = tmp_path / "RR_telemetry_0.jsonl"
    for rid, base in (("runA", 100.0), ("runB", 200.0)):
        sink = TelemetrySink(path, run_id=rid)  # append mode by default
        tr = Tracer(sink, clock=lambda: 1000.0)
        for s in range(1, 4):
            sink.write("heartbeat", s, epoch=0, interval_s=0.1,
                       process_index=0, host="h", mono=base + s,
                       generation=0)
            tr.span("step", 0.1, t0=base + s - 0.1, step=s)
        sink.close()

    out = tmp_path / "trace.json"
    rc = tracelens.main([str(tmp_path), "--job", "RR", "--out", str(out),
                         "--run_id", "runB"])
    assert rc == 0
    capsys.readouterr()
    events = json.loads(out.read_text())["traceEvents"]
    x = [e for e in events if e.get("ph") == "X"]
    assert len(x) == 3  # runA's three spans filtered out
    # and the report header names only the surviving run
    rc = tracelens.main([str(tmp_path), "--job", "RR", "--out", str(out),
                        "--run_id", "nosuchrun"])
    assert rc == 2
    assert "no rows with run_id" in capsys.readouterr().err
