"""Unit contracts of tpudist.resilience: the exit-code contract, the
supervisor's backoff/budget/decision math (pure, injected clocks/rngs),
chaos-spec parsing and firing semantics, the signal-safe preemption
guard, goodput's exact wall-time partition and cross-generation
aggregation, and the watchdog's ``hang_action="exit"`` escalation
ordering (forensics first, exit second)."""

import itertools
import json
import os
import random
import signal

import pytest

from tpudist.resilience import (
    EXIT_HANG,
    EXIT_INTERRUPT,
    EXIT_PREEMPTED,
    GENERATION_ENV,
    BackoffPolicy,
    ChaosCrash,
    ChaosInjector,
    ChaosSpec,
    GoodputTracker,
    Preempted,
    PreemptionGuard,
    RestartBudget,
    Supervisor,
    classify,
    is_restartable,
    restart_generation,
)


# -- exit codes --------------------------------------------------------------

def test_exit_code_contract():
    from tpudist.resilience import EXIT_REPAIR

    assert EXIT_PREEMPTED == 75 and EXIT_HANG == 76 and EXIT_REPAIR == 77
    assert is_restartable(75) and is_restartable(76) and is_restartable(77)
    # crashes, signal deaths (negative from Popen), and operator stops
    # are NOT deliberate checkpoint-and-exit codes
    for rc in (0, 1, 9, 130, -9, -15, 78):
        assert not is_restartable(rc)
    assert classify(0) == "ok"
    assert classify(EXIT_INTERRUPT) == "stop"
    assert classify(75) == "restartable" and classify(76) == "restartable"
    assert classify(77) == "restartable"
    assert classify(1) == "crash" and classify(-9) == "crash"


def test_restart_generation_env(monkeypatch):
    monkeypatch.delenv(GENERATION_ENV, raising=False)
    assert restart_generation() == 0
    monkeypatch.setenv(GENERATION_ENV, "3")
    assert restart_generation() == 3
    monkeypatch.setenv(GENERATION_ENV, "garbage")
    assert restart_generation() == 0  # tolerant: telemetry must not die


def test_preempted_is_systemexit_75():
    e = Preempted(signal.SIGTERM, step=12)
    assert isinstance(e, SystemExit) and e.code == EXIT_PREEMPTED
    assert "SIGTERM" in str(e) and "12" in str(e)


# -- supervisor math ---------------------------------------------------------

def test_backoff_growth_and_cap():
    policy = BackoffPolicy(base_s=1.0, max_s=8.0, jitter=0.0)
    rng = random.Random(0)
    assert [policy.delay_s(a, rng) for a in range(1, 7)] == [
        1.0, 2.0, 4.0, 8.0, 8.0, 8.0
    ]
    assert policy.delay_s(0, rng) == 0.0
    assert BackoffPolicy(base_s=0.0).delay_s(3, rng) == 0.0


def test_backoff_jitter_bounds():
    policy = BackoffPolicy(base_s=2.0, max_s=64.0, jitter=0.5)
    rng = random.Random(1)
    for attempt in range(1, 6):
        base = min(2.0 * 2 ** (attempt - 1), 64.0)
        for _ in range(50):
            d = policy.delay_s(attempt, rng)
            assert 0.5 * base <= d <= 1.5 * base


def test_restart_budget_rolling_window():
    t = {"now": 0.0}
    budget = RestartBudget(2, 100.0, clock=lambda: t["now"])
    assert budget.allow()
    budget.record()
    budget.record()
    assert not budget.allow() and budget.used() == 2
    t["now"] = 101.0  # both stamps age out of the window
    assert budget.allow() and budget.used() == 0
    # 0 = unlimited (the legacy launcher behavior)
    unlimited = RestartBudget(0, 0.0)
    for _ in range(100):
        unlimited.record()
    assert unlimited.allow()


def _supervisor(rcs, **kw):
    seen_gens = []
    it = iter(rcs)

    def run_world(generation):
        seen_gens.append(generation)
        return next(it)

    sleeps = []
    logs = []
    sup = Supervisor(
        run_world,
        sleep=sleeps.append,
        log=logs.append,
        rng=random.Random(0),
        **kw,
    )
    return sup, seen_gens, sleeps, logs


def test_supervisor_restartable_fast_path_ignores_max_restarts():
    # 75/76 mean "state durable, relaunch me": they restart with
    # max_restarts=0 and no backoff, each generation numbered
    sup, gens, sleeps, logs = _supervisor(
        [75, 76, 0], max_restarts=0, budget=RestartBudget(10, 600.0)
    )
    assert sup.run() == 0
    assert gens == [0, 1, 2]
    assert sleeps == []  # prompt relaunch, no crash backoff
    assert all("restartable" in m for m in logs)


def test_supervisor_crash_respects_max_restarts_with_backoff():
    sup, gens, sleeps, logs = _supervisor(
        [9, 9, 9], max_restarts=2,
        backoff=BackoffPolicy(1.0, 60.0, jitter=0.0),
    )
    assert sup.run() == 9
    assert gens == [0, 1, 2]  # initial world + 2 restarts, then give up
    assert sleeps == [1.0, 2.0]  # exponential
    assert any("restarting (1/2)" in m for m in logs)
    assert any("restarting (2/2)" in m for m in logs)


def test_supervisor_budget_exhausts_instead_of_spinning():
    # a deterministically-failing world must exit non-zero, not loop:
    # the rolling budget is the circuit breaker even on the restartable
    # fast path (an instantly-re-preempted job is a spin too)
    sup, gens, sleeps, logs = _supervisor(
        itertools.repeat(75), max_restarts=0,
        budget=RestartBudget(3, 600.0),
    )
    assert sup.run() == 75
    assert gens == [0, 1, 2, 3]  # initial + 3 budgeted restarts
    assert any("restart budget exhausted" in m for m in logs)


def test_supervisor_operator_stop_wins():
    stop = {"on": False}

    def run_world(generation):
        stop["on"] = True  # SIGTERM landed while the world ran
        return 75

    sup = Supervisor(run_world, stop=lambda: stop["on"],
                     budget=RestartBudget(10, 600.0), log=lambda m: None)
    assert sup.run() == 75  # no restart over an operator stop


# -- chaos -------------------------------------------------------------------

def test_chaos_spec_parse():
    assert ChaosSpec.parse("crash@12") == ChaosSpec("crash", 12)
    assert ChaosSpec.parse("sigterm@5@1") == ChaosSpec(
        "sigterm", 5, generation=1
    )
    s = ChaosSpec.parse("hang:30@7@*")
    assert (s.kind, s.step, s.duration_s, s.generation) == (
        "hang", 7, 30.0, None
    )
    for bad in ("boom@3", "crash", "crash:5@3", "crash@x"):
        with pytest.raises(ValueError):
            ChaosSpec.parse(bad)


def test_chaos_crash_fires_once_at_step():
    inj = ChaosInjector(ChaosSpec.parse("crash@5"), generation=0)
    for step in range(5):
        assert inj.maybe_fire(step) is False
    with pytest.raises(ChaosCrash, match="step 5"):
        inj.maybe_fire(5)
    assert inj.fired
    assert inj.maybe_fire(6) is False  # one-shot


def test_chaos_generation_gating():
    # default: the incident happens in generation 0 only — the relaunched
    # generation resumes AT the trigger step and must not re-fire
    gen1 = ChaosInjector(ChaosSpec.parse("crash@5"), generation=1)
    assert gen1.maybe_fire(5) is False and not gen1.fired
    # '@*' fires in every generation (a deterministic bug)
    star = ChaosInjector(ChaosSpec.parse("crash@5@*"), generation=4)
    with pytest.raises(ChaosCrash):
        star.maybe_fire(5)


def test_chaos_hang_and_sigterm_mechanics():
    slept = []
    inj = ChaosInjector(ChaosSpec.parse("hang:12@2"), generation=0,
                        sleep=slept.append)
    assert inj.maybe_fire(2) is True
    assert slept == [12.0]

    kills = []
    inj = ChaosInjector(ChaosSpec.parse("sigterm@3"), generation=0,
                        kill=lambda pid, sig: kills.append((pid, sig)))
    assert inj.maybe_fire(3) is True
    assert kills == [(os.getpid(), signal.SIGTERM)]


# -- preemption guard --------------------------------------------------------

def test_preemption_guard_traps_absorbs_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert guard.active and guard.tripped is None
        os.kill(os.getpid(), signal.SIGTERM)
        # delivered synchronously: we ARE the main thread
        assert guard.tripped == signal.SIGTERM
        # repeats are absorbed while the graceful path runs
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.tripped == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == before


def test_preemption_guard_disabled_is_inert():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=False) as guard:
        assert not guard.active and guard.tripped is None
        assert signal.getsignal(signal.SIGTERM) == before


# -- goodput -----------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_goodput_partition_sums_exactly():
    clk = _Clock()
    wall = _Clock()
    wall.now = 1000.0
    gp = GoodputTracker(generation=0, clock=clk, wall=wall)
    gp.add("restore_s", 0.5)          # measured inside bring-up
    clk.now = 2.0
    gp.loop_started()                  # bringup = 2.0 - restore = 1.5
    clk.now = 5.0
    gp.step_boundary()                 # iteration 1 = compile = 3.0
    clk.now = 6.0
    gp.step_boundary(data_wait_s=0.25)
    gp.add("checkpoint_s", 0.3)
    clk.now = 8.0
    wall.now = 1008.0
    s = gp.summary("completed")
    assert s["total_s"] == 8.0
    assert (s["bringup_s"], s["restore_s"], s["compile_s"]) == (1.5, 0.5, 3.0)
    assert (s["data_wait_s"], s["checkpoint_s"]) == (0.25, 0.3)
    # productive is the residual — the components sum EXACTLY
    parts = sum(
        s[k] for k in ("bringup_s", "restore_s", "compile_s",
                       "data_wait_s", "checkpoint_s", "productive_step_s")
    )
    assert parts == pytest.approx(s["total_s"], rel=1e-9)
    assert s["steps"] == 2
    assert s["generations"][-1]["exit_reason"] == "completed"


def test_goodput_cross_generation_aggregation(tmp_path):
    # generation 0: preempted after an emergency save
    clk0, wall0 = _Clock(), _Clock()
    wall0.now = 100.0
    g0 = GoodputTracker(generation=0, clock=clk0, wall=wall0)
    g0.loop_started()
    clk0.now = 1.0
    g0.step_boundary()
    g0.add_emergency_save(2.0)
    clk0.now = 10.0
    wall0.now = 110.0
    report = {"goodput": g0.summary("preempted")}
    path = tmp_path / "J_report.json"
    path.write_text(json.dumps(report))
    assert report["goodput"]["emergency_save_s"] == 2.0
    # emergency save is a subset of checkpoint_s (partition stays disjoint)
    assert report["goodput"]["checkpoint_s"] == 2.0

    # generation 1 relaunches 7 wall-seconds later and resumes
    clk1, wall1 = _Clock(), _Clock()
    wall1.now = 117.0
    g1 = GoodputTracker(generation=1, clock=clk1, wall=wall1)
    g1.load_previous(path)
    g1.add("restore_s", 1.0)
    clk1.now = 3.0
    g1.loop_started()                 # bringup = 2.0
    clk1.now = 7.0
    g1.step_boundary()                # compile = 4.0
    clk1.now = 12.0
    wall1.now = 129.0
    s = g1.summary("completed")
    gens = s["generations"]
    assert [g["generation"] for g in gens] == [0, 1]
    assert gens[0]["exit_reason"] == "preempted"
    cum = s["cumulative"]
    assert cum["restart_gap_s"] == pytest.approx(7.0)   # 117 - 110
    # recovery price: gap + gen1 bringup/restore/compile + emergency save
    assert cum["restart_overhead_s"] == pytest.approx(
        7.0 + (2.0 + 1.0 + 4.0) + 2.0
    )
    assert cum["wall_s"] == pytest.approx(10.0 + 12.0 + 7.0)


def test_goodput_load_previous_tolerates_garbage(tmp_path):
    gp = GoodputTracker()
    gp.load_previous(tmp_path / "missing.json")
    (tmp_path / "bad.json").write_text("{not json")
    gp.load_previous(tmp_path / "bad.json")
    assert gp.summary()["generations"][-1]["generation"] == 0


# -- watchdog escalation -----------------------------------------------------

def test_hang_action_exit_escalates_after_forensics(tmp_path):
    from tpudist.telemetry import TelemetryConfig, TelemetrySink
    from tpudist.telemetry.health import RunHealth

    sink = TelemetrySink(tmp_path / "HX_telemetry_0.jsonl")
    cfg = TelemetryConfig(hang_timeout_s=60.0, hang_action="exit")
    order = []
    health = RunHealth(cfg, sink, job_id="HX", log_dir=str(tmp_path),
                       exit_fn=lambda code: order.append(("exit", code)))
    # fit wires the checkpointer's wait here: an in-flight async save must
    # get its bounded finalize window BEFORE the process dies
    health.set_exit_drain(lambda: order.append("drain"))
    try:
        health._on_trip(
            {"last_step": 3, "age_s": 9.9, "timeout_s": 60.0, "t": 0.0}
        )
    finally:
        health.shutdown()
        sink.close()
    # escalated with the restartable hang code — but only AFTER the
    # forensics landed (crash file, report, row) and the checkpoint
    # drain ran
    assert order == ["drain", ("exit", EXIT_HANG)]
    crash = json.loads((tmp_path / "HX_crash_0.json").read_text())
    assert crash["trip"]["last_step"] == 3
    report = json.loads((tmp_path / "HX_report.json").read_text())
    assert report["status"] == "watchdog"
    assert report["exit_reason"] == "hang"
    rows = [
        json.loads(l)
        for l in (tmp_path / "HX_telemetry_0.jsonl").read_text().splitlines()
    ]
    assert any(r["kind"] == "watchdog" for r in rows)


def test_hang_action_report_does_not_exit(tmp_path):
    from tpudist.telemetry import TelemetryConfig, TelemetrySink
    from tpudist.telemetry.health import RunHealth

    sink = TelemetrySink(tmp_path / "HR_telemetry_0.jsonl")
    cfg = TelemetryConfig(hang_timeout_s=60.0)  # default action: report
    exits = []
    health = RunHealth(cfg, sink, job_id="HR", log_dir=str(tmp_path),
                       exit_fn=exits.append)
    try:
        health._on_trip(
            {"last_step": 1, "age_s": 2.0, "timeout_s": 60.0, "t": 0.0}
        )
    finally:
        health.shutdown()
        sink.close()
    assert exits == []  # non-fatal: the pre-resilience contract
